"""Leaf-wise tree growth, fully on device.

TPU-native replacement of the reference's SerialTreeLearner hot loop
(reference: src/treelearner/serial_tree_learner.cpp:158 Train, :324
FindBestSplits, :564 SplitInner) and of the Data/Feature-parallel learners'
collective hooks (src/treelearner/data_parallel_tree_learner.cpp:155). Design
differences, by intent (SURVEY.md §7):

- The whole per-tree split loop runs inside ONE jitted ``lax.while_loop`` —
  no host round-trips per split, no dynamic shapes, one compilation per
  (N, F, B, num_leaves) signature. The reference keeps this loop in C++ and
  pays a kernel launch per phase; XLA fuses ours.
- ``DataPartition`` (data_partition.hpp) index shuffling is replaced by a
  ``row_leaf`` int32 vector: a split is a masked vector update, no data
  movement.
- The smaller/larger-leaf histogram-subtraction trick
  (serial_tree_learner.cpp:418: parent − smaller = larger) is kept: one
  masked histogram pass per split round for the smaller child only.
- Distribution: rows shard over a 1-D mesh; every histogram / root-sum is
  wrapped in ``comm.psum`` so the same builder runs single-chip (no-op comm)
  or under ``shard_map`` with XLA collectives over ICI — the seam the
  reference implements with Network::ReduceScatter + SyncUpGlobalBestSplit.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import BinnedDataset
from .obs import trace_phase, track_jit
from .ops.histogram import build_histogram
from .ops.split import (
    FeatureMeta,
    SplitHyper,
    SplitInfo,
    calc_leaf_output,
    find_best_split,
)
from .tree import Tree
from .utils.log import Log


class Comm:
    """Collective seam (reference analog: static class Network,
    include/LightGBM/network.h:89, and the per-strategy hooks of the
    Data/Feature/Voting-parallel tree learners). ``axis=None`` = single
    device no-op; otherwise collectives run over the named mesh axis
    inside shard_map.

    Modes (reference: src/treelearner/tree_learner.cpp:15 factory):
    - ``serial``/``data``: rows sharded; histograms are globally reduced
      (data_parallel_tree_learner.cpp:169) and every shard computes the
      same best split — no split sync needed.
    - ``feature``: rows REPLICATED, the split SEARCH is sharded by feature
      ownership; the winning SplitInfo is argmax-synced across shards
      (feature_parallel_tree_learner.cpp:40, parallel_tree_learner.h:191
      SyncUpGlobalBestSplit).
    - ``voting``: rows sharded, histograms stay LOCAL; shards vote local
      top-k features, the global top-2k features' histograms are merged,
      and the best split comes from the merged histograms — comm volume is
      O(top_k * B) per round instead of O(F * B)
      (voting_parallel_tree_learner.cpp:151 GlobalVoting).
    """

    def __init__(self, axis: Optional[str] = None, mode: Optional[str] = None,
                 top_k: int = 20, num_machines: int = 1,
                 hist_scatter: bool = True) -> None:
        self.axis = axis
        self.mode = mode or ("data" if axis else "serial")
        self.top_k = int(top_k)
        self.num_machines = int(num_machines)
        # comm-optimal data-parallel: reduce-scatter histograms by feature
        # GROUP blocks + per-shard owned-feature search + argmax split sync
        # (reference: data_parallel_tree_learner.cpp:155-251 ReduceScatter +
        # FindBestSplits over owned features + SyncUpGlobalBestSplit).
        # Halves histogram comm bytes vs full psum and divides scan work.
        self.hist_scatter = bool(hist_scatter) and self.mode == "data" \
            and axis is not None and self.num_machines > 1

    def psum(self, x):
        if self.axis is None:
            return x
        return jax.lax.psum(x, self.axis)

    def _gpad(self, g: int) -> int:
        d = self.num_machines
        return -(-g // d) * d

    def hist(self, h):
        """Leaf-histogram reduction: reduce-scatter by group blocks for
        data-parallel (each shard owns [idx*blk, (idx+1)*blk) re-embedded
        into the full shape, zeros elsewhere); identity when rows are
        replicated (feature) or hists stay local (voting)."""
        if self.axis is None or self.mode in ("feature", "voting"):
            return h
        if self.hist_scatter:
            g = h.shape[0]
            gpad = self._gpad(g)
            blk = gpad // self.num_machines
            hp = jnp.pad(h, ((0, gpad - g),) + ((0, 0),) * (h.ndim - 1))
            sc = jax.lax.psum_scatter(hp, self.axis, scatter_dimension=0,
                                      tiled=True)
            idx = jax.lax.axis_index(self.axis)
            out = jax.lax.dynamic_update_slice(
                jnp.zeros_like(hp), sc,
                (idx * blk,) + (0,) * (h.ndim - 1))
            return out[:g]
        return jax.lax.psum(h, self.axis)

    def owned_group_mask(self, feat_group, num_groups: int):
        """(F,) bool: this shard owns feature f's histogram block (data
        mode with hist_scatter); None otherwise. ``num_groups`` must be the
        static bundled-column count so the block size matches hist()."""
        if not self.hist_scatter:
            return None
        idx = jax.lax.axis_index(self.axis)
        blk = self._gpad(num_groups) // self.num_machines
        return (feat_group >= idx * blk) & (feat_group < (idx + 1) * blk)

    def root(self, x):
        """Root gradient-sum reduction (replicated rows: identity)."""
        if self.axis is None or self.mode == "feature":
            return x
        return jax.lax.psum(x, self.axis)

    def owned_mask(self, num_feat: int):
        """Feature-parallel search ownership (reference balances by bin
        count, feature_parallel_tree_learner.cpp:40; modulo striping gives
        the same asymptotic balance)."""
        if self.mode != "feature" or self.axis is None:
            return None
        idx = jax.lax.axis_index(self.axis)
        return (jnp.arange(num_feat, dtype=jnp.int32)
                % self.num_machines) == idx

    def sync_split(self, info):
        """Broadcast the globally-best SplitInfo (SyncUpGlobalBestSplit,
        parallel_tree_learner.h:191): allgather gains, argmax (ties to the
        lowest shard), then a masked psum carries every field over. Used by
        feature-parallel and by scatter-mode data-parallel (each shard
        searched only its owned feature blocks)."""
        if self.axis is None or not (self.mode == "feature"
                                     or self.hist_scatter):
            return info
        idx = jax.lax.axis_index(self.axis)
        gains = jax.lax.all_gather(info.gain, self.axis)          # (D,)
        win = jnp.argmax(jnp.where(jnp.isnan(gains), -jnp.inf, gains))
        mine = (idx == win).astype(jnp.float32)

        def bcast(x):
            guarded = jnp.where(jnp.isfinite(x.astype(jnp.float32)),
                                x.astype(jnp.float32), 0.0) \
                if x.dtype == jnp.float32 else x.astype(jnp.float32)
            out = jax.lax.psum(guarded * mine, self.axis)
            if x.dtype == jnp.float32:
                # restore -inf gains the masking zeroed out
                neg = jax.lax.psum(
                    jnp.isneginf(x.astype(jnp.float32)).astype(jnp.float32)
                    * mine, self.axis) > 0.5
                out = jnp.where(neg, -jnp.inf, out)
            return out.astype(x.dtype)

        return jax.tree.map(bcast, info)


class TreeLog(NamedTuple):
    """Device-side record of one grown tree (host rebuilds a Tree from it)."""
    num_splits: jax.Array     # scalar i32
    split_leaf: jax.Array     # (L-1,) i32
    feature: jax.Array        # (L-1,) i32
    bin: jax.Array            # (L-1,) i32
    kind: jax.Array           # (L-1,) i32
    default_left: jax.Array   # (L-1,) bool
    gain: jax.Array           # (L-1,) f32
    left_sum: jax.Array       # (L-1, 3) f32
    right_sum: jax.Array      # (L-1, 3) f32
    go_left: jax.Array        # (L-1, B) bool
    miss_bin: jax.Array       # (L-1,) i32 movable-missing bin of the feature
    movable: jax.Array        # (L-1,) bool feature has missing-directed bin
    leaf_value: jax.Array     # (L,) f32 raw outputs (pre-shrinkage)
    leaf_sum: jax.Array       # (L, 3) f32
    row_leaf: jax.Array       # (N,) i32 final leaf of every training row


def _empty_best(num_leaves: int, num_bin: int) -> SplitInfo:
    z = jnp.zeros
    return SplitInfo(
        gain=jnp.full((num_leaves,), -jnp.inf, jnp.float32),
        feature=z((num_leaves,), jnp.int32),
        bin=z((num_leaves,), jnp.int32),
        kind=z((num_leaves,), jnp.int32),
        default_left=z((num_leaves,), bool),
        go_left=z((num_leaves, num_bin), bool),
        left_sum=z((num_leaves, 3), jnp.float32),
        right_sum=z((num_leaves, 3), jnp.float32),
        left_output=z((num_leaves,), jnp.float32),
        right_output=z((num_leaves,), jnp.float32),
    )


def _set_best(best: SplitInfo, idx, info: SplitInfo) -> SplitInfo:
    return jax.tree.map(lambda b, v: b.at[idx].set(v), best, info)



def _make_best_for(meta: FeatureMeta, hp: SplitHyper, key, feature_mask,
                   num_feat: int, feature_fraction_bynode: float,
                   extra_trees: bool, constraint_sets, extra_seed: int = 6):
    """Shared per-node split evaluation: by-node column sampling,
    extra-trees random thresholds, interaction constraints, then the
    vectorized (F, B) best-split scan."""

    def allowed_mask(used_row):
        """Interaction constraints (reference: col_sampler.hpp:94 GetByNode):
        a branch may only use features from constraint sets compatible with
        the features already used on its path."""
        if constraint_sets is None:
            return jnp.ones((num_feat,), bool)
        compat = jnp.all(~used_row[None, :] | constraint_sets, axis=1)  # (S,)
        return jnp.any(constraint_sets & compat[:, None], axis=0)

    def node_inputs(r, leaf):
        """Per-node RNG-driven feature mask and extra-trees thresholds."""
        fmask = feature_mask
        if feature_fraction_bynode < 1.0:
            k = jax.random.fold_in(key, r * 2 + 1000 + leaf)
            u = jax.random.uniform(k, (num_feat,))
            kth = max(1, int(np.ceil(feature_fraction_bynode * num_feat)))
            rank = jnp.argsort(jnp.argsort(u))
            fmask = fmask & (rank < kth)
        rand_thr = None
        if extra_trees:
            # extra_seed gives the random-threshold stream its own seed
            # (reference: config.h extra_seed)
            k = jax.random.fold_in(jax.random.fold_in(key, 2000 + extra_seed),
                                   r * 2 + 1 + leaf)
            u = jax.random.uniform(k, (num_feat,))
            rand_thr = (u * jnp.maximum(meta.num_bins - 1, 1).astype(jnp.float32)) \
                .astype(jnp.int32)
        return fmask, rand_thr

    def best_for(r, leaf, hist, parent_sum, parent_out, lower, upper,
                 used_row, extra_mask=None, want_feature_gains=False,
                 use_hp=None, cegb_delta=None, node_depth=None,
                 adv_bounds=None):
        fmask, rand_thr = node_inputs(r, leaf)
        fmask = fmask & allowed_mask(used_row)
        if extra_mask is not None:
            fmask = fmask & extra_mask
        return find_best_split(
            hist, parent_sum, meta, fmask, use_hp if use_hp is not None else hp,
            parent_output=parent_out, leaf_lower=lower, leaf_upper=upper,
            rand_threshold=rand_thr, want_feature_gains=want_feature_gains,
            cegb_delta=cegb_delta, node_depth=node_depth,
            adv_bounds=adv_bounds)

    return best_for


def build_tree(
    bins: jax.Array,          # (N, F) uint8/16 — row shard on this device
    ghc: jax.Array,           # (N, 3) f32 (grad, hess, inbag) — masked already
    meta: FeatureMeta,
    feature_mask: jax.Array,  # (F,) bool, per-tree column sample
    key: jax.Array,           # PRNG for by-node sampling / extra-trees
    cegb_used: jax.Array,     # (F,) bool — accepted for signature parity
    hp: SplitHyper,           # (CEGB needs tree_builder=partition)
    *,
    num_leaves: int,
    num_bin: int,
    max_depth: int = -1,
    feature_fraction_bynode: float = 1.0,
    extra_trees: bool = False,
    comm: Comm = Comm(),
    hist_chunk: int = 2048,
    constraint_sets: Optional[jax.Array] = None,   # (S, F) bool, static presence
    forced: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    # forced = (leaf (R,), feature (R,), bin (R,)) BFS-ordered forced splits
    mxu_bf16: bool = False,
    extra_seed: int = 6,
) -> TreeLog:
    """Grow one leaf-wise tree entirely on device. jit/shard_map once."""
    n, num_feat = bins.shape
    max_splits = num_leaves - 1
    n_forced = 0 if forced is None else int(forced[0].shape[0])

    def hist_of_leaf(row_leaf, leaf_id):
        """Histogram of the rows currently on ``leaf_id`` (all rows when
        leaf_id < 0): masked one-hot matmul over the full row set."""
        mask = (jnp.asarray(leaf_id) < 0) | (row_leaf == leaf_id)
        h = build_histogram(bins, ghc * mask[:, None].astype(jnp.float32),
                            num_bin, hist_chunk, mxu_bf16=mxu_bf16)
        return comm.psum(h)

    best_for = _make_best_for(meta, hp, key, feature_mask, num_feat,
                              feature_fraction_bynode, extra_trees,
                              constraint_sets, extra_seed)

    # ---- init: root ----
    root_sum = comm.psum(jnp.sum(ghc, axis=0))
    root_hist = hist_of_leaf(jnp.zeros((n,), jnp.int32), jnp.int32(-1))
    hist_pool = jnp.zeros((num_leaves, num_feat, num_bin, 3), jnp.float32)
    hist_pool = hist_pool.at[0].set(root_hist)
    leaf_sum = jnp.zeros((num_leaves, 3), jnp.float32).at[0].set(root_sum)
    leaf_out = jnp.zeros((num_leaves,), jnp.float32).at[0].set(
        calc_leaf_output(root_sum[0], root_sum[1], hp))
    leaf_depth = jnp.zeros((num_leaves,), jnp.int32)
    leaf_lower = jnp.full((num_leaves,), -jnp.inf, jnp.float32)
    leaf_upper = jnp.full((num_leaves,), jnp.inf, jnp.float32)
    leaf_used = jnp.zeros((num_leaves, num_feat), bool)
    best = _empty_best(num_leaves, num_bin)
    best = _set_best(best, 0, best_for(0, jnp.int32(0), root_hist, root_sum,
                                       leaf_out[0], leaf_lower[0], leaf_upper[0],
                                       leaf_used[0], node_depth=jnp.int32(0)))
    row_leaf = jnp.zeros((n,), jnp.int32)
    log = TreeLog(
        num_splits=jnp.int32(0),
        split_leaf=jnp.zeros((max_splits,), jnp.int32),
        feature=jnp.zeros((max_splits,), jnp.int32),
        bin=jnp.zeros((max_splits,), jnp.int32),
        kind=jnp.zeros((max_splits,), jnp.int32),
        default_left=jnp.zeros((max_splits,), bool),
        gain=jnp.zeros((max_splits,), jnp.float32),
        left_sum=jnp.zeros((max_splits, 3), jnp.float32),
        right_sum=jnp.zeros((max_splits, 3), jnp.float32),
        go_left=jnp.zeros((max_splits, num_bin), bool),
        miss_bin=jnp.zeros((max_splits,), jnp.int32),
        movable=jnp.zeros((max_splits,), bool),
        leaf_value=leaf_out,
        leaf_sum=leaf_sum,
        row_leaf=row_leaf,
    )

    def depth_ok(depth):
        if max_depth <= 0:
            return jnp.bool_(True)
        return depth < max_depth

    force_live = jnp.bool_(n_forced > 0)
    carry0 = (jnp.int32(0), row_leaf, hist_pool, leaf_sum, leaf_out,
              leaf_depth, leaf_lower, leaf_upper, best, log, leaf_used,
              force_live)

    def cond(carry):
        r = carry[0]
        best = carry[8]
        log = carry[9]
        force_live = carry[11]
        forcing = force_live & (r < n_forced) if n_forced else False
        return (log.num_splits < max_splits) & (r < max_splits + n_forced) \
            & ((jnp.max(best.gain) > 0.0) | forcing)

    def body(carry):
        (r, row_leaf, hist_pool, leaf_sum, leaf_out, leaf_depth,
         leaf_lower, leaf_upper, best, log, leaf_used, force_live) = carry
        leaf = jnp.argmax(best.gain).astype(jnp.int32)
        info: SplitInfo = jax.tree.map(lambda a: a[leaf], best)
        if n_forced:
            # forced splits (reference: serial_tree_learner.cpp:450
            # ForceSplits — BFS-ordered (leaf, feature, bin) applied before
            # gain-driven growth; an invalid forced split aborts forcing)
            f_leaf, f_feat, f_bin = forced

            def pick_forced(_):
                ri = jnp.minimum(r, n_forced - 1)
                fl = f_leaf[ri]
                fi = find_best_split(
                    hist_pool[fl], leaf_sum[fl], meta,
                    jnp.arange(num_feat) == f_feat[ri], hp,
                    parent_output=leaf_out[fl], leaf_lower=leaf_lower[fl],
                    leaf_upper=leaf_upper[fl],
                    rand_threshold=jnp.full((num_feat,), f_bin[ri], jnp.int32),
                    node_depth=leaf_depth[fl])
                ok = fi.gain > -jnp.inf
                return (jnp.where(ok, fl, leaf),
                        jax.tree.map(lambda a, b: jnp.where(ok, a, b), fi, info),
                        ok)

            use_forced = force_live & (r < n_forced)
            leaf, info, force_live = jax.lax.cond(
                use_forced, pick_forced,
                lambda _: (leaf, info, jnp.bool_(False)), operand=None)
        valid = info.gain > -jnp.inf
        s = log.num_splits
        new_leaf = s + 1

        prev = (row_leaf, hist_pool, leaf_sum, leaf_out, leaf_depth,
                leaf_lower, leaf_upper, best, log, leaf_used)

        # ---- apply split to the row partition (DataPartition::Split analog) ----
        bins_col = jnp.take(bins, info.feature, axis=1).astype(jnp.int32)
        go_left_rows = info.go_left[bins_col]
        on_leaf = row_leaf == leaf
        row_leaf = jnp.where(on_leaf & ~go_left_rows, new_leaf, row_leaf)

        # ---- record ----
        log = log._replace(
            num_splits=new_leaf,
            split_leaf=log.split_leaf.at[s].set(leaf),
            feature=log.feature.at[s].set(info.feature),
            bin=log.bin.at[s].set(info.bin),
            kind=log.kind.at[s].set(info.kind),
            default_left=log.default_left.at[s].set(info.default_left),
            gain=log.gain.at[s].set(info.gain),
            left_sum=log.left_sum.at[s].set(info.left_sum),
            right_sum=log.right_sum.at[s].set(info.right_sum),
            go_left=log.go_left.at[s].set(info.go_left),
            miss_bin=log.miss_bin.at[s].set(meta.missing_bin[info.feature]),
            movable=log.movable.at[s].set(meta.movable_missing[info.feature]),
        )

        # ---- stats bookkeeping ----
        leaf_sum = leaf_sum.at[leaf].set(info.left_sum).at[new_leaf].set(info.right_sum)
        leaf_out = leaf_out.at[leaf].set(info.left_output) \
                           .at[new_leaf].set(info.right_output)
        d = leaf_depth[leaf] + 1
        leaf_depth = leaf_depth.at[leaf].set(d).at[new_leaf].set(d)
        if hp.has_monotone:
            mono = meta.monotone[info.feature]
            mid = (info.left_output + info.right_output) * 0.5
            lo_l, up_l = leaf_lower[leaf], leaf_upper[leaf]
            new_up_l = jnp.where(mono > 0, jnp.minimum(up_l, mid), up_l)
            new_lo_r = jnp.where(mono > 0, jnp.maximum(lo_l, mid), lo_l)
            new_lo_l = jnp.where(mono < 0, jnp.maximum(lo_l, mid), lo_l)
            new_up_r = jnp.where(mono < 0, jnp.minimum(up_l, mid), up_l)
            leaf_lower = leaf_lower.at[leaf].set(new_lo_l).at[new_leaf].set(new_lo_r)
            leaf_upper = leaf_upper.at[leaf].set(new_up_l).at[new_leaf].set(new_up_r)

        # ---- histograms: masked pass for the smaller child, subtract for the
        # larger (serial_tree_learner.cpp:418) ----
        left_smaller = info.left_sum[2] <= info.right_sum[2]
        small_id = jnp.where(left_smaller, leaf, new_leaf)
        hist_small = hist_of_leaf(row_leaf, small_id)
        parent_hist = hist_pool[leaf]
        hist_large = parent_hist - hist_small
        hist_left = jnp.where(left_smaller, hist_small, hist_large)
        hist_right = jnp.where(left_smaller, hist_large, hist_small)
        hist_pool = hist_pool.at[leaf].set(hist_left).at[new_leaf].set(hist_right)

        # ---- refresh best splits for the two children ----
        # interaction-constraint bookkeeping: children inherit path features
        used_new = leaf_used[leaf].at[info.feature].set(True)
        leaf_used = leaf_used.at[leaf].set(used_new).at[new_leaf].set(used_new)

        info_l = best_for(r, leaf, hist_left, info.left_sum,
                          leaf_out[leaf], leaf_lower[leaf], leaf_upper[leaf],
                          used_new, node_depth=leaf_depth[leaf])
        info_r = best_for(r, new_leaf, hist_right, info.right_sum,
                          leaf_out[new_leaf], leaf_lower[new_leaf],
                          leaf_upper[new_leaf], used_new,
                          node_depth=leaf_depth[new_leaf])
        gate_l = depth_ok(leaf_depth[leaf])
        gate_r = depth_ok(leaf_depth[new_leaf])
        info_l = info_l._replace(gain=jnp.where(gate_l, info_l.gain, -jnp.inf))
        info_r = info_r._replace(gain=jnp.where(gate_r, info_r.gain, -jnp.inf))
        best = _set_best(best, leaf, info_l)
        best = _set_best(best, new_leaf, info_r)

        new = (row_leaf, hist_pool, leaf_sum, leaf_out, leaf_depth,
               leaf_lower, leaf_upper, best, log, leaf_used)
        # an invalid round (forced split impossible and no positive-gain
        # split) advances the round counter but commits nothing
        committed = jax.tree.map(lambda a, b: jnp.where(valid, a, b), new, prev)
        return (r + 1,) + committed + (force_live,)

    carry = jax.lax.while_loop(cond, body, carry0)
    (_, row_leaf, _, leaf_sum, leaf_out, _, _, _, _, log, _, _) = carry
    return log._replace(leaf_value=leaf_out, leaf_sum=leaf_sum, row_leaf=row_leaf)




# ---------------------------------------------------------------------------
# Advanced monotone constraints (reference: monotone_constraints.hpp:856
# AdvancedLeafConstraints). The reference walks the tree per split to
# collect piecewise per-threshold bounds; the TPU-native form keeps DENSE
# state — per-leaf per-feature per-bin bound arrays (L, F, B) plus bin-range
# boxes (L, F) — and refreshes ALL leaves vectorized at every commit: each
# new child broadcasts its output as a bound to every leaf whose box
# overlaps the child's in all other features, over the bins beyond the
# child's own range in each monotone dimension. Candidate-threshold bounds
# then come from prefix/suffix extrema of the bin arrays, so the split scan
# sees per-threshold constraints exactly where the reference recomputes
# them. Sound by construction (every committed output is sandwiched against
# all earlier overlapping neighbors); tighter than `intermediate`, which
# collapses each leaf's constraints to two scalars.


def _adv_boxes_init(num_leaves: int, num_feat: int, meta):
    """(L, F) bin-range boxes — all intermediate mode needs."""
    rng_lo = jnp.zeros((num_leaves, num_feat), jnp.int32)
    rng_hi = jnp.broadcast_to(meta.num_bins[None, :],
                              (num_leaves, num_feat)).astype(jnp.int32)
    return (rng_lo, rng_hi)


def _adv_init(num_leaves: int, num_feat: int, num_bin: int, meta):
    cons_lo = jnp.full((num_leaves, num_feat, num_bin), -jnp.inf, jnp.float32)
    cons_hi = jnp.full((num_leaves, num_feat, num_bin), jnp.inf, jnp.float32)
    return (cons_lo, cons_hi) + _adv_boxes_init(num_leaves, num_feat, meta)


def _adv_bounds_of(adv, leaf):
    """Per-candidate child bounds (lo_l, up_l, lo_r, up_r), each (F, B):
    entry [f, t] bounds the child of a split on feature f at threshold t
    (left = bins <= t)."""
    cons_lo, cons_hi, rng_lo, rng_hi = adv
    lo = cons_lo[leaf]
    hi = cons_hi[leaf]                                # (F, B)
    rlo = rng_lo[leaf]
    rhi = rng_hi[leaf]                                # (F,)
    num_bin = lo.shape[1]
    b = jnp.arange(num_bin, dtype=jnp.int32)[None, :]
    inr = (b >= rlo[:, None]) & (b < rhi[:, None])
    hi_m = jnp.where(inr, hi, jnp.inf)
    lo_m = jnp.where(inr, lo, -jnp.inf)
    hi_f = jnp.min(hi_m, axis=1)                      # (F,) whole-range bound
    lo_f = jnp.max(lo_m, axis=1)
    # min/max over all features EXCEPT f (two-extremum trick; the +/-inf
    # sentinel makes the "no other features" case — F == 1 — unconstrained)
    hi_s = jnp.sort(jnp.concatenate([hi_f, jnp.array([jnp.inf])]))
    hi1, hi2 = hi_s[0], hi_s[1]
    hi_exc = jnp.where((hi_f == hi1) & (jnp.sum(hi_f == hi1) == 1), hi2, hi1)
    lo_s = jnp.sort(jnp.concatenate([lo_f, jnp.array([-jnp.inf])]))
    lo1, lo2 = lo_s[-1], lo_s[-2]
    lo_exc = jnp.where((lo_f == lo1) & (jnp.sum(lo_f == lo1) == 1), lo2, lo1)
    # prefix extrema cover the left child's bins [0, t]; suffix (shifted
    # one left) the right child's bins (t, B)
    pre_hi = jax.lax.cummin(hi_m, axis=1)
    pre_lo = jax.lax.cummax(lo_m, axis=1)
    suf_hi = jnp.flip(jax.lax.cummin(jnp.flip(hi_m, 1), axis=1), 1)
    suf_lo = jnp.flip(jax.lax.cummax(jnp.flip(lo_m, 1), axis=1), 1)
    inf_c = jnp.full((hi_m.shape[0], 1), jnp.inf)
    suf_hi = jnp.concatenate([suf_hi[:, 1:], inf_c], axis=1)
    suf_lo = jnp.concatenate([suf_lo[:, 1:], -inf_c], axis=1)
    up_l = jnp.minimum(hi_exc[:, None], pre_hi)
    lo_l = jnp.maximum(lo_exc[:, None], pre_lo)
    up_r = jnp.minimum(hi_exc[:, None], suf_hi)
    lo_r = jnp.maximum(lo_exc[:, None], suf_lo)
    return lo_l, up_l, lo_r, up_r


def _adv_child_boxes(rng_lo, rng_hi, sel, leaf, new_leaf, info):
    """Split the parent's bin box along a numerical winner's feature and
    commit the children's boxes. Returns the updated (rng_lo, rng_hi) plus
    the two child boxes (left keeps the parent's slot)."""
    is_num = info.kind == 0
    fs = info.feature
    t1 = info.bin + 1
    p_rlo = rng_lo[leaf]
    p_rhi = rng_hi[leaf]
    rhi_l = p_rhi.at[fs].set(jnp.where(is_num, t1, p_rhi[fs]))
    rlo_r = p_rlo.at[fs].set(jnp.where(is_num, t1, p_rlo[fs]))
    rng_lo = rng_lo.at[new_leaf].set(sel(rlo_r, rng_lo[new_leaf]))
    rng_hi = rng_hi.at[leaf].set(sel(rhi_l, p_rhi)) \
        .at[new_leaf].set(sel(p_rhi, rng_hi[new_leaf]))
    return rng_lo, rng_hi, (p_rlo, rhi_l), (rlo_r, p_rhi)


def _adv_overlap_except(rng_lo, rng_hi, c_rlo, c_rhi):
    """(L, F) mask: leaf boxes overlapping child box C in every feature BUT
    the column's own (the dimension a bound would apply along)."""
    ov = (rng_lo < c_rhi[None, :]) & (c_rlo[None, :] < rng_hi)
    nfalse = jnp.sum(~ov, axis=1)
    return (nfalse == 0)[:, None] | ((nfalse == 1)[:, None] & ~ov)


def _adv_commit(adv, meta, sel, leaf, new_leaf, info, num_bin: int):
    """Split commit: children inherit the parent's constraint entries, the
    split feature's box tightens (numerical winners), and both children
    broadcast their outputs as bounds to every box-overlapping leaf:

    - along each MONOTONE dimension, at the bins beyond the child's own
      range (the original dense analog of the reference's per-threshold
      constraints, monotone_constraints.hpp:856);
    - along each OTHER dimension f', at the bins INSIDE the child's
      f'-range, for leaves wholly ordered against the child in some
      monotone dimension. This second write is what separates `advanced`
      from `intermediate`: without it, a neighbor whose bound only applies
      to part of a leaf's f'-range (because the neighbor is itself split
      on f') degenerates to a whole-leaf scalar clamp. The (L, F, B)
      per-dimension representation cannot express joint restrictions over
      several dimensions, so these writes are CONSERVATIVE (sound: only
      ever tighter than the reference's re-searched bounds, never looser
      than monotonicity requires)."""
    cons_lo, cons_hi, rng_lo, rng_hi = adv
    cons_lo = cons_lo.at[new_leaf].set(sel(cons_lo[leaf], cons_lo[new_leaf]))
    cons_hi = cons_hi.at[new_leaf].set(sel(cons_hi[leaf], cons_hi[new_leaf]))
    rng_lo, rng_hi, box_l, box_r = _adv_child_boxes(
        rng_lo, rng_hi, sel, leaf, new_leaf, info)
    b = jnp.arange(num_bin, dtype=jnp.int32)[None, None, :]
    mono = meta.monotone[None, :]
    inc = (mono > 0)[:, :, None]
    dec = (mono < 0)[:, :, None]
    incv = mono > 0
    decv = mono < 0
    valid_b = sel(jnp.bool_(True), jnp.bool_(False))
    for (c_rlo, c_rhi), out in ((box_l, info.left_output),
                                (box_r, info.right_output)):
        # along-m writes apply a BLANKET per-m-bin bound over the whole
        # leaf; that claim is precise only when C covers the leaf's box in
        # every other dimension (always true at F == 1). When C is
        # restricted in some free dimension, the free-dimension writes
        # below carry the bound with its restriction instead — gating the
        # blanket here is what lets a split on a free dimension escape a
        # neighbor's bound outside that neighbor's range (the reference's
        # motivating per-threshold case).
        cover = (c_rlo[None, :] <= rng_lo) & (rng_hi <= c_rhi[None, :])
        ncov = jnp.sum(~cover, axis=1)                             # (L,)
        cov_exc = (ncov == 0)[:, None] | ((ncov == 1)[:, None] & ~cover)
        below = b < c_rlo[None, :, None]
        above = b >= c_rhi[None, :, None]
        hi_upd = (inc & below) | (dec & above)
        lo_upd = (inc & above) | (dec & below)
        gate = cov_exc[:, :, None] & valid_b
        cons_hi = jnp.where(gate & hi_upd, jnp.minimum(cons_hi, out), cons_hi)
        cons_lo = jnp.where(gate & lo_upd, jnp.maximum(cons_lo, out), cons_lo)

        # ---- free-dimension writes (restricted to C's own bin range) ----
        ov = (rng_lo < c_rhi[None, :]) & (c_rlo[None, :] < rng_hi)  # (L, F)
        nonov = (~ov).astype(jnp.int32)
        nov = jnp.sum(nonov, axis=1)                               # (L,)
        # leaf wholly ordered against C in monotone dim m: C bounds it
        # from above (ub) or below (lb) in value space
        ub_ord = (incv & (rng_hi <= c_rlo[None, :])) \
            | (decv & (rng_lo >= c_rhi[None, :]))                  # (L, F)
        lb_ord = (incv & (rng_lo >= c_rhi[None, :])) \
            | (decv & (rng_hi <= c_rlo[None, :]))
        # exists an ordering dim m != f' (each ordered m is disjoint, so
        # requiring overlap in all dims except {m, f'} is nov-nonov[f']==1)
        ub_any = (jnp.sum(ub_ord, axis=1)[:, None]
                  - ub_ord.astype(jnp.int32)) > 0                  # (L, F)
        lb_any = (jnp.sum(lb_ord, axis=1)[:, None]
                  - lb_ord.astype(jnp.int32)) > 0
        free_gate = (nov[:, None] - nonov) == 1                    # (L, F)
        in_rng = (b >= c_rlo[None, :, None]) & (b < c_rhi[None, :, None])
        g_ub = (ub_any & free_gate)[:, :, None] & in_rng & valid_b
        g_lb = (lb_any & free_gate)[:, :, None] & in_rng & valid_b
        cons_hi = jnp.where(g_ub, jnp.minimum(cons_hi, out), cons_hi)
        cons_lo = jnp.where(g_lb, jnp.maximum(cons_lo, out), cons_lo)
    return (cons_lo, cons_hi, rng_lo, rng_hi)


def build_tree_partitioned(
    bins: jax.Array,          # (N, F) uint8 — row shard on this device
    ghc: jax.Array,           # (N, 3) f32 (grad, hess, inbag) — masked already
    meta: FeatureMeta,
    feature_mask: jax.Array,  # (F,) bool, per-tree column sample
    key: jax.Array,           # PRNG for by-node sampling / extra-trees
    cegb_used: jax.Array,     # (F,) bool — features already used by the model
    hp: SplitHyper,
    *,
    num_leaves: int,
    num_bin: int,
    max_depth: int = -1,
    feature_fraction_bynode: float = 1.0,
    extra_trees: bool = False,
    extra_seed: int = 6,
    comm: Comm = Comm(),
    hist_chunk: int = 2048,
    part_chunk: int = 2048,
    hist_mode: str = "hilo",  # hilo (bf16-pair) | bf16 | int8 (quantized)
    hist_lo: int = 0,         # hi/lo einsum split width (0 = auto by F)
    num_bin_hist: Optional[int] = None,   # bundled-column bins (defaults num_bin)
    bundle: Optional[dict] = None,        # EFB maps (dataset.bundle_maps)
    constraint_sets: Optional[jax.Array] = None,   # (S, F) bool
    forced: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    part_kernel: str = "xla",  # xla | pallas (fused DMA kernel, TPU only)
    hist_kernel: str = "xla",  # xla (einsum) | pallas (in-VMEM, TPU only)
    split_kernel: str = "off",  # off (three launches: partition, child
    # histogram, split scan) | on (ONE pallas_call per split running all
    # three phases; planes family + serial training only — bit-identical
    # trees, the off path is the parity oracle)
    work_buf: Optional[jax.Array] = None,  # carried (2, Npad, W) u8 buffer
    return_work: bool = False,
    bins_t: Optional[jax.Array] = None,    # (F, N) transposed bins — pass a
    # block-hoisted copy when building many trees (the transpose costs
    # ~20 ms at 2M x 28; assign_leaves needs the transposed layout)
    work_layout: str = "rows",  # rows ((2, Npad, W) row-major) | planes
    # ((2, W, Npad) feature-major: 128-lane tiles carry 128 rows of ONE
    # byte column, and the root histogram folds into the pack pass) |
    # resident (planes family: bin planes live once in bins_res and the
    # slim 17-plane work buffer moves only route/ridx/g/h/c per split)
    bins_res: Optional[jax.Array] = None,  # (F, Npad) resident bin planes
    # (work_layout=resident) — pass a block-hoisted copy when building
    # many trees; derived in-graph from ``bins`` when None
    goss_compact_rows: int = 0,  # static compact row count M (tpu_goss_compact):
    # when 0 < M < N, the inbag mask is turned into a device gather that
    # packs the surviving rows to the top and the WHOLE tree build runs
    # over M rows; GOSS warmup iterations (all rows in-bag) and the rare
    # margin overflow fall back to the verbatim dense-mask build inside
    # the same jitted graph (lax.cond) — bit-identical trees either way
    route_bins: Optional[Tuple[jax.Array, Optional[jax.Array]]] = None,
    # (bins_full, bins_t_full): route ALL original rows through the grown
    # tree in assign_leaves (set by the compaction wrapper so row_leaf
    # keeps the full (N,) shape the score update expects)
    root_sum_in: Optional[jax.Array] = None,  # (3,) precomputed local root
    # (g, h, cnt) sums. The compaction wrapper computes them over the
    # DENSE ghc: XLA's row reduce uses strided accumulators, so summing
    # the compacted array would regroup the f32 additions (+/-1 ulp) —
    # histogram matmuls accumulate sequentially over rows and are immune
    hist_mxu: str = "off",  # off | on: one-hot MXU histogram kernel
    # (ops/histogram.py hist_mxu_segment — rows layout; serves both the
    # f32 hi/lo and the int8 quantized path from one kernel body)
) -> TreeLog:
    """Grow one leaf-wise tree with a physical row partition.

    The scaling-correct builder (reference contract:
    src/treelearner/serial_tree_learner.cpp:324 FindBestSplits over the
    smaller leaf + histogram subtraction, src/treelearner/data_partition.hpp
    :101 Split): per split, the parent's rows are stably partitioned into
    leaf-contiguous segments (ops/partition.py) and only the SMALLER child's
    segment is histogrammed (ops/histogram.py hist16_segment); the larger
    child's histogram is parent - smaller. Per-split cost is O(parent rows),
    per-histogram cost O(child rows) — round 1 paid O(N) for both, ~100x
    more arithmetic at 255 leaves.

    Same in/out contract as ``build_tree``; runs identically single-device
    or under shard_map (all collectives go through ``comm``).
    """
    if goss_compact_rows and 0 < goss_compact_rows < bins.shape[0]:
        # ---- GOSS device compaction (tpu_goss_compact=on) ----
        # Gather the in-bag rows to the top and build the tree over a
        # STATIC M-row prefix; removed rows carry exact (+/-0.0, 0) ghc so
        # the compact build's sums, partitions and histograms match the
        # dense-mask build bit-for-bit. The in-graph cond keeps the dense
        # path for GOSS warmup iterations (sampler emits all-ones inbag,
        # so C = N > M) and for binomial overflow beyond the 4-sigma
        # margin. Both branches route ALL N original rows in
        # assign_leaves, so row_leaf (and the score update) are
        # shape-identical either way.
        from .ops.partition import compact_rows_by_inbag
        if return_work and work_buf is None:
            raise ValueError("goss_compact_rows with return_work=True needs "
                             "a carried work_buf (its M-sized shape is the "
                             "cond's common work signature)")
        m = goss_compact_rows
        bins_c, ghc_c, c_in = compact_rows_by_inbag(bins, ghc, m)
        sub = dict(
            num_leaves=num_leaves, num_bin=num_bin, max_depth=max_depth,
            feature_fraction_bynode=feature_fraction_bynode,
            extra_trees=extra_trees, extra_seed=extra_seed, comm=comm,
            hist_chunk=hist_chunk, part_chunk=part_chunk,
            hist_mode=hist_mode, hist_lo=hist_lo,
            num_bin_hist=num_bin_hist, bundle=bundle,
            constraint_sets=constraint_sets, forced=forced,
            part_kernel=part_kernel, hist_kernel=hist_kernel,
            split_kernel=split_kernel, work_layout=work_layout,
            goss_compact_rows=0, hist_mxu=hist_mxu,
            return_work=return_work)

        def _compact(_):
            # root sums come from the DENSE ghc: the row reduce's strided
            # accumulators would regroup f32 additions over the compacted
            # array (+/-1 ulp — enough to flip near-tie splits)
            return build_tree_partitioned(
                bins_c, ghc_c, meta, feature_mask, key, cegb_used, hp,
                work_buf=work_buf, bins_t=None, bins_res=None,
                route_bins=(bins, bins_t),
                root_sum_in=jnp.sum(ghc, axis=0), **sub)

        def _dense(_):
            # fresh internal N-sized buffers; the carried M-sized work_buf
            # passes through untouched so both cond branches return the
            # same work signature
            out = build_tree_partitioned(
                bins, ghc, meta, feature_mask, key, cegb_used, hp,
                work_buf=None, bins_t=bins_t, bins_res=bins_res,
                route_bins=route_bins, **dict(sub, return_work=False))
            return (out, work_buf) if return_work else out

        return jax.lax.cond(c_in <= m, _compact, _dense, 0)

    from .ops.histogram import (hist16_segment, hist16_segment_planes,
                                hist16_segment_q, hist16_segment_resident,
                                hist_mxu_segment, hist_pallas_segment,
                                hist_pallas_segment_planes)
    from .ops.partition import (one_kernel_split_planes,
                                pack_planes_fold_root,
                                pack_resident_fold_root, pack_rows,
                                pack_rows_quantized, partition_segment,
                                partition_segment_fused,
                                partition_segment_planes,
                                partition_segment_planes_fused, planes_npad,
                                resident_bin_planes, write_route_plane)

    n, num_grp = bins.shape
    num_feat = int(meta.num_bins.shape[0])
    max_splits = num_leaves - 1
    n_forced = 0 if forced is None else int(forced[0].shape[0])
    fused_part = part_kernel == "pallas"
    quantized = hist_mode == "int8"
    resident = work_layout == "resident"
    planes = work_layout == "planes" or resident
    from .ops.partition import work_spec
    guard, buf_width = work_spec(num_grp, quantized, part_kernel,
                                 part_chunk, hist_chunk, layout=work_layout)
    bm = num_bin_hist if num_bin_hist is not None else num_bin
    one_kernel = split_kernel == "on"
    if one_kernel:
        # the fused kernel inlines the scan verbatim under these premises
        # (serial comm => hist/sync_split identity; bundle None => group ==
        # feature and route_table identity; no CEGB / by-node RNG /
        # extra-trees / constraint sets => best_raw reduces to a plain
        # find_best_split over fmask_search; scalar monotone bounds only)
        bad = []
        if not planes or not fused_part:
            bad.append("needs the fused pallas planes/resident layout")
        if quantized:
            bad.append("int8 histograms unsupported")
        if bundle is not None or bm != num_bin:
            bad.append("EFB feature bundling unsupported")
        if comm.axis is not None:
            bad.append("multi-device comm unsupported")
        if hp.use_cegb:
            bad.append("CEGB penalties unsupported")
        if hp.has_monotone and (hp.mono_intermediate or hp.mono_advanced):
            bad.append("intermediate/advanced monotone unsupported")
        if feature_fraction_bynode < 1.0 or extra_trees:
            bad.append("by-node sampling / extra-trees unsupported")
        if constraint_sets is not None:
            bad.append("interaction constraint sets unsupported")
        if hist_chunk % 128:
            bad.append("hist_chunk must be a multiple of 128")
        if bad:
            raise ValueError("tpu_split_kernel=on is not eligible here: "
                             + "; ".join(bad))
    if hist_mxu == "on":
        bad = []
        if planes:
            bad.append("needs the rows work layout")
        if not fused_part:
            bad.append("needs part_kernel=pallas (128-lane work rows)")
        if hist_chunk % 32:
            bad.append("hist_chunk must be a multiple of 32")
        if bad:
            raise ValueError("tpu_hist_mxu=on is not eligible here: "
                             + "; ".join(bad))

    # ---- packed ping-pong working buffers with guard rows ----
    # the matrix columns are EFB bundles (== features when no bundling)
    if planes:
        if quantized:
            raise ValueError("tpu_work_layout=planes does not support int8 "
                             "quantized training (the learner gate keeps "
                             "auto on rows for int8)")
        # transposed (2, W, Npad) plane pair. The pack pass ALSO produces
        # the root histogram — iteration 0 never re-reads the full matrix
        # (stale bytes in a carried buffer's guard lanes are never consumed:
        # partitions only commit valid rows and histograms mask by count)
        if work_buf is not None:
            work = work_buf
        else:
            work = jnp.zeros(
                (2, buf_width, planes_npad(n, guard, part_kernel)),
                jnp.uint8)
        base_part = partition_segment_planes_fused if fused_part \
            else partition_segment_planes
        if resident:
            # bin planes live ONCE (original row order, never partitioned);
            # the slim work buffer carries route/ridx/g/h/c only
            if bins_res is None:
                bins_res = resident_bin_planes(bins, guard, work.shape[2])
            with trace_phase("lgbtpu/pack"):
                work, root_hist_loc = pack_resident_fold_root(
                    work, bins, ghc, guard, num_bins=bm,
                    exact=hist_mode != "bf16", chunk=hist_chunk,
                    lo_w=hist_lo)

            def part_fn(work, plane, start, cnt, feat, table, *, ch):
                # gather the split feature's resident bin bytes through the
                # permuted row-index plane into the route plane, then
                # stream the slim payload through the UNCHANGED planes
                # partition (XLA or fused Mosaic) routing on plane 0 — the
                # gathered column equals the planes path's leaf-order bin
                # column value-for-value, so dest arithmetic (and trees)
                # stay bit-identical
                work = write_route_plane(work, bins_res, plane, start, cnt,
                                         feat, ch=ch)
                return base_part(work, plane, start, cnt, jnp.int32(0),
                                 table, ch=ch)
        else:
            with trace_phase("lgbtpu/pack"):
                work, root_hist_loc = pack_planes_fold_root(
                    work, bins, ghc, guard, num_bins=bm,
                    exact=hist_mode != "bf16", chunk=hist_chunk,
                    lo_w=hist_lo)
            part_fn = base_part
    else:
        pad = ((guard, guard), (0, 0))
        if quantized:
            # per-tree local quantization scales; histograms dequantize
            # before any collective, so shards may scale independently
            gscale = 127.0 / (jnp.max(jnp.abs(ghc[:, 0])) + 1e-12)
            hscale = 127.0 / (jnp.max(jnp.abs(ghc[:, 1])) + 1e-12)
            with trace_phase("lgbtpu/pack"):
                work0 = pack_rows_quantized(
                    jnp.pad(bins, pad), jnp.pad(ghc, pad),
                    jax.random.fold_in(key, 987123), gscale, hscale)
        else:
            with trace_phase("lgbtpu/pack"):
                work0 = pack_rows(jnp.pad(bins, pad), jnp.pad(ghc, pad))
        if work_buf is not None:
            # reuse the caller's ping-pong pair (fused blocks carry it
            # across trees): only plane 0's used columns need writing —
            # stale bytes elsewhere are never consumed (blends commit only
            # valid rows, and the histogram/route reads touch only the
            # used columns)
            work = work_buf.at[0, :, :work0.shape[1]].set(work0)
        else:
            if work0.shape[1] < buf_width:
                # the fused kernel DMAs whole 128-lane tiles; pad row width
                work0 = jnp.pad(work0,
                                ((0, 0), (0, buf_width - work0.shape[1])))
            work = jnp.stack([work0, jnp.zeros_like(work0)])  # (2, Npad, W)
        part_fn = partition_segment_fused if fused_part else partition_segment

    def hist_of(work, plane, start, cnt):
        """-> ((G, Bm, 3) reduced histogram, work). Callers must continue
        with the RETURNED work: the pallas kernel aliases the buffer
        through the call (identical bytes) so XLA never copies it."""
        if resident:
            # unit-stride gather over the resident bin planes through the
            # permuted row-index plane; same chunking and f32 accumulation
            # order as the planes path
            h = hist16_segment_resident(work, bins_res, plane, start, cnt,
                                        num_bins=bm, num_feat=num_grp,
                                        exact=hist_mode != "bf16",
                                        chunk=hist_chunk, lo_w=hist_lo)
        elif planes and hist_kernel == "pallas":
            h, work = hist_pallas_segment_planes(work, plane, start, cnt,
                                                 num_bins=bm,
                                                 num_feat=num_grp,
                                                 exact=hist_mode != "bf16",
                                                 chunk=hist_chunk,
                                                 lo_w=hist_lo)
        elif planes:
            h = hist16_segment_planes(work, plane, start, cnt, num_bins=bm,
                                      num_feat=num_grp,
                                      exact=hist_mode != "bf16",
                                      chunk=hist_chunk, lo_w=hist_lo)
        elif quantized and hist_mxu == "on":
            # int8 one-hots x int8 channels -> i32 on the MXU; integer
            # accumulation makes parity with hist16_segment_q exact
            h, work = hist_mxu_segment(work, plane, start, cnt,
                                       num_bins=bm, num_feat=num_grp,
                                       quantized=True, gscale=gscale,
                                       hscale=hscale, chunk=hist_chunk,
                                       lo_w=hist_lo)
        elif quantized:
            h = hist16_segment_q(work, plane, start, cnt, gscale, hscale,
                                 num_bins=bm, num_feat=num_grp,
                                 chunk=hist_chunk, lo_w=hist_lo)
        elif hist_mxu == "on":
            h, work = hist_mxu_segment(work, plane, start, cnt,
                                       num_bins=bm, num_feat=num_grp,
                                       quantized=False,
                                       exact=hist_mode != "bf16",
                                       chunk=hist_chunk, lo_w=hist_lo)
        elif hist_kernel == "pallas":
            # in-VMEM chunk loop + accumulator: one streamed read of the
            # segment, none of the XLA loop's per-chunk parasitic fusions
            h, work = hist_pallas_segment(work, plane, start, cnt,
                                          num_bins=bm, num_feat=num_grp,
                                          exact=hist_mode != "bf16",
                                          chunk=hist_chunk, lo_w=hist_lo)
        else:
            h = hist16_segment(work, plane, start, cnt, num_bins=bm,
                               num_feat=num_grp, exact=hist_mode != "bf16",
                               chunk=hist_chunk, lo_w=hist_lo)
        return comm.hist(h), work                         # (G, Bm, 3)

    def feat_view(hg, total_sum):
        """Bundled (G, Bm, 3) histogram -> per-feature (F, B, 3) view.

        Each sub-feature's own bundle slots are gathered; its shared default
        bin is recovered as total - sum(own slots) — the reference's
        FixHistogram contract (include/LightGBM/dataset.h:503).
        """
        if bundle is None:
            return hg
        flat = hg.reshape(num_grp * bm, 3)
        fh = jnp.take(flat, bundle["proj"].reshape(-1), axis=0) \
            .reshape(num_feat, num_bin, 3)
        fh = fh * bundle["valid"][:, :, None]
        rest = total_sum[None, :] - jnp.sum(fh, axis=1)          # (F, 3)
        dpos_oh = (jnp.arange(num_bin, dtype=jnp.int32)[None, :]
                   == bundle["dpos"][:, None])                    # (F, B)
        put = dpos_oh[:, :, None] & bundle["has_rest"][:, None, None]
        return jnp.where(put, rest[:, None, :], fh)

    def route_table(info):
        """Feature-space (B,) routing table -> bundle-column (Bm,) table
        (alien sub-features' slots and the shared zero follow the feature's
        default-bin direction)."""
        if bundle is None:
            return info.go_left
        row = bundle["map_fb"][info.feature]                      # (Bm,)
        oh = row[:, None] == jnp.arange(num_bin, dtype=jnp.int32)[None, :]
        return (oh.astype(jnp.float32)
                @ info.go_left.astype(jnp.float32)) > 0.5

    fmask_search = feature_mask
    owned = comm.owned_mask(num_feat)
    if owned is not None:
        fmask_search = feature_mask & owned
    grp_of_feat = bundle["group"] if bundle is not None \
        else jnp.arange(num_feat, dtype=jnp.int32)
    owned_g = comm.owned_group_mask(grp_of_feat, num_grp)
    if owned_g is not None:
        # scatter-mode data-parallel: search only the features whose
        # reduced histogram block this shard owns; sync_split broadcasts
        # the global winner afterwards
        fmask_search = fmask_search & owned_g
    best_raw = _make_best_for(meta, hp, key, fmask_search, num_feat,
                              feature_fraction_bynode, extra_trees,
                              constraint_sets, extra_seed)
    voting = comm.mode == "voting"
    if voting:
        d = float(max(comm.num_machines, 1))
        # local vote constraints are scaled by 1/num_machines
        # (reference: voting_parallel_tree_learner.cpp:62-64)
        hp_loc = hp._replace(
            min_data_in_leaf=hp.min_data_in_leaf / d,
            min_sum_hessian_in_leaf=hp.min_sum_hessian_in_leaf / d)

    def cegb_penalty(tot_g, tree_used):
        """Per-feature CEGB gain penalty (reference:
        cost_effective_gradient_boosting.hpp:66 DetlaGain): split penalty
        scales with the leaf's row count; coupled feature penalties apply
        until the model first uses the feature."""
        if not hp.use_cegb:
            return None
        return hp.cegb_tradeoff * (
            hp.cegb_penalty_split * tot_g[2]
            + meta.cegb_coupled * (~tree_used).astype(jnp.float32))

    def node_best(r, leaf, hg, tot_g, tot_l, parent_out, lower, upper,
                  used_row, tree_used, depth, adv_b=None):
        """Best split for a node under the active comm strategy. ``hg`` is
        the (bundled) histogram — global for serial/data/feature, LOCAL for
        voting; ``tot_g``/``tot_l`` the node's global/local (g,h,cnt)."""
        delta = cegb_penalty(tot_g, tree_used)
        if not voting:
            info = best_raw(r, leaf, feat_view(hg, tot_g), tot_g, parent_out,
                            lower, upper, used_row, cegb_delta=delta,
                            node_depth=depth, adv_bounds=adv_b)
            return comm.sync_split(info)
        # ---- voting parallel (reference: GlobalVoting,
        # voting_parallel_tree_learner.cpp:151,322) ----
        fv_loc = feat_view(hg, tot_l)
        fg = best_raw(r, leaf, fv_loc, tot_l, parent_out, lower, upper,
                      used_row, want_feature_gains=True, use_hp=hp_loc,
                      node_depth=depth)
        k = min(comm.top_k, num_feat)
        k2 = min(2 * comm.top_k, num_feat)
        _, top_idx = jax.lax.top_k(fg, k)
        votes = jnp.zeros((num_feat,), jnp.float32).at[top_idx].add(1.0)
        votes = comm.psum(votes)
        # deterministic global top-2k (ties resolve to the lowest index)
        bias = -jnp.arange(num_feat, dtype=jnp.float32) * 1e-6
        _, sel = jax.lax.top_k(votes + bias, k2)
        selmat = (sel[:, None]
                  == jnp.arange(num_feat, dtype=jnp.int32)[None, :]) \
            .astype(jnp.float32)                               # (k2, F)
        flat = fv_loc.reshape(num_feat, -1)
        merged = comm.psum(selmat @ flat)                      # (k2, B*3)
        full = (selmat.T @ merged).reshape(fv_loc.shape)       # voted rows only
        selmask = jnp.any(selmat > 0.5, axis=0)
        return best_raw(r, leaf, full, tot_g, parent_out, lower, upper,
                        used_row, extra_mask=selmask, cegb_delta=delta,
                        node_depth=depth, adv_bounds=adv_b)

    # ---- init: root ----
    root_sum_loc = jnp.sum(ghc, axis=0) if root_sum_in is None \
        else root_sum_in
    root_sum = comm.root(root_sum_loc)
    if planes:
        # folded into the pack pass above (bit-identical accumulation to
        # hist_of over the root segment: same chunking, same einsum order)
        root_hist = comm.hist(root_hist_loc)
    else:
        with trace_phase("lgbtpu/root_hist"):
            root_hist, work = hist_of(work, jnp.int32(0), jnp.int32(guard),
                                      jnp.int32(n))
    # the pool is kept FLAT per leaf: 4-D pools make XLA's layout
    # assignment disagree between the while carry and the gather/update
    # consumers, inserting a full pool copy per split (measured 2x430 us at
    # F=137); a 2-D (L, G*B*3) pool has one canonical layout
    hist_pool = jnp.zeros((num_leaves, num_grp * bm * 3), jnp.float32)
    hist_pool = hist_pool.at[0].set(root_hist.reshape(-1))
    leaf_sum = jnp.zeros((num_leaves, 3), jnp.float32).at[0].set(root_sum)
    leaf_sum_loc = jnp.zeros((num_leaves, 3), jnp.float32).at[0].set(
        root_sum_loc)
    leaf_out = jnp.zeros((num_leaves,), jnp.float32).at[0].set(
        calc_leaf_output(root_sum[0], root_sum[1], hp))
    leaf_depth = jnp.zeros((num_leaves,), jnp.int32)
    leaf_lower = jnp.full((num_leaves,), -jnp.inf, jnp.float32)
    leaf_upper = jnp.full((num_leaves,), jnp.inf, jnp.float32)
    leaf_used = jnp.zeros((num_leaves, num_feat), bool)
    leaf_start = jnp.zeros((num_leaves,), jnp.int32).at[0].set(guard)
    leaf_cnt = jnp.zeros((num_leaves,), jnp.int32).at[0].set(n)
    leaf_parity = jnp.zeros((num_leaves,), jnp.int32)
    tree_used0 = cegb_used.astype(bool)
    if hp.mono_advanced:
        adv0 = _adv_init(num_leaves, num_feat, num_bin, meta)
    elif hp.has_monotone and hp.mono_intermediate:
        # intermediate's neighbor refresh needs only the (L, F) bin boxes
        adv0 = _adv_boxes_init(num_leaves, num_feat, meta)
    else:
        adv0 = ()
    if hp.mono_advanced:
        node_best_pair = jax.vmap(
            node_best, in_axes=(None, 0, 0, 0, 0, 0, 0, 0, None, None,
                                None, 0))
    else:
        node_best_pair = jax.vmap(
            node_best, in_axes=(None, 0, 0, 0, 0, 0, 0, 0, None, None, None))

    # the root's initial search rides the SAME batched callable as the
    # per-round two-child refresh (batch of 1): one traced split-scan chain
    # serves every node_best call instead of compiling a second unbatched
    # variant of the whole reduce-window/select pipeline
    root_ix = jnp.array([0], jnp.int32)
    best = _empty_best(num_leaves, num_bin)
    with trace_phase("lgbtpu/split_scan"):
        root_info = node_best_pair(
            0, root_ix, root_hist[None], root_sum[None], root_sum_loc[None],
            leaf_out[:1], leaf_lower[:1], leaf_upper[:1], leaf_used[0],
            tree_used0, jnp.int32(0),
            *((jax.tree.map(lambda a: a[None],
                            _adv_bounds_of(adv0, jnp.int32(0))),)
              if hp.mono_advanced else ()))
    best = jax.tree.map(lambda b, v: b.at[root_ix].set(v), best, root_info)
    log = TreeLog(
        num_splits=jnp.int32(0),
        split_leaf=jnp.zeros((max_splits,), jnp.int32),
        feature=jnp.zeros((max_splits,), jnp.int32),
        bin=jnp.zeros((max_splits,), jnp.int32),
        kind=jnp.zeros((max_splits,), jnp.int32),
        default_left=jnp.zeros((max_splits,), bool),
        gain=jnp.zeros((max_splits,), jnp.float32),
        left_sum=jnp.zeros((max_splits, 3), jnp.float32),
        right_sum=jnp.zeros((max_splits, 3), jnp.float32),
        go_left=jnp.zeros((max_splits, num_bin), bool),
        miss_bin=jnp.zeros((max_splits,), jnp.int32),
        movable=jnp.zeros((max_splits,), bool),
        leaf_value=leaf_out,
        leaf_sum=leaf_sum,
        row_leaf=jnp.zeros((n,), jnp.int32),
    )

    def depth_ok(depth):
        if max_depth <= 0:
            return jnp.bool_(True)
        return depth < max_depth

    force_live = jnp.bool_(n_forced > 0)
    carry0 = (jnp.int32(0), work, leaf_start, leaf_cnt, leaf_parity,
              hist_pool, leaf_sum, leaf_sum_loc, leaf_out, leaf_depth,
              leaf_lower, leaf_upper, best, log, leaf_used, tree_used0,
              force_live, adv0)

    def cond(carry):
        r, best, log, force_live = carry[0], carry[12], carry[13], carry[16]
        forcing = force_live & (r < n_forced) if n_forced else False
        return (log.num_splits < max_splits) & (r < max_splits + n_forced) \
            & ((jnp.max(best.gain) > 0.0) | forcing)

    def body(carry):
        (r, work, leaf_start, leaf_cnt, leaf_parity, hist_pool, leaf_sum,
         leaf_sum_loc, leaf_out, leaf_depth, leaf_lower, leaf_upper, best,
         log, leaf_used, tree_used, force_live, adv) = carry
        leaf = jnp.argmax(best.gain).astype(jnp.int32)
        info: SplitInfo = jax.tree.map(lambda a: a[leaf], best)
        if n_forced:
            # forced splits (reference: serial_tree_learner.cpp:450
            # ForceSplits) — same protocol as build_tree
            f_leaf, f_feat, f_bin = forced

            def pick_forced(_):
                ri = jnp.minimum(r, n_forced - 1)
                fl = f_leaf[ri]
                # voting keeps hist_pool LOCAL; a forced split must still be
                # identical on every shard (default_left/gain derive from
                # missing mass), so globalize the leaf histogram first. The
                # cond predicate is replicated, so the psum is uniform.
                hg_forced = comm.psum(hist_pool[fl]) \
                    if (voting or comm.hist_scatter) else hist_pool[fl]
                hg_forced = hg_forced.reshape(num_grp, bm, 3)
                fi = find_best_split(
                    feat_view(hg_forced, leaf_sum[fl]),
                    leaf_sum[fl], meta,
                    jnp.arange(num_feat) == f_feat[ri], hp,
                    parent_output=leaf_out[fl], leaf_lower=leaf_lower[fl],
                    leaf_upper=leaf_upper[fl],
                    rand_threshold=jnp.full((num_feat,), f_bin[ri], jnp.int32),
                    node_depth=leaf_depth[fl],
                    adv_bounds=(_adv_bounds_of(adv, fl)
                                if hp.mono_advanced else None))
                ok = fi.gain > -jnp.inf
                return (jnp.where(ok, fl, leaf),
                        jax.tree.map(lambda a, b: jnp.where(ok, a, b), fi, info),
                        ok)

            use_forced = force_live & (r < n_forced)
            leaf, info, force_live = jax.lax.cond(
                use_forced, pick_forced,
                lambda _: (leaf, info, jnp.bool_(False)), operand=None)
        s = log.num_splits
        new_leaf = s + 1

        if hp.has_monotone and (hp.mono_intermediate or hp.mono_advanced):
            # the stored best split was evaluated under the bounds current
            # at the leaf's LAST evaluation; neighbor refreshes may have
            # tightened them since. The reference re-searches affected
            # leaves (GoDownToFindLeavesToUpdate -> RecomputeBestSplit);
            # we keep the chosen split but re-clamp its outputs against the
            # parent's CURRENT bounds and re-enforce sibling order — the
            # committed values then respect every earlier neighbor, which
            # is what the soundness induction needs.
            mono_f = meta.monotone[info.feature]
            if hp.mono_advanced:
                lo_l, up_l, lo_r, up_r = _adv_bounds_of(adv, leaf)
                wl = jnp.clip(info.left_output,
                              lo_l[info.feature, info.bin],
                              up_l[info.feature, info.bin])
                wr = jnp.clip(info.right_output,
                              lo_r[info.feature, info.bin],
                              up_r[info.feature, info.bin])
            else:
                lo_p, up_p = leaf_lower[leaf], leaf_upper[leaf]
                wl = jnp.clip(info.left_output, lo_p, up_p)
                wr = jnp.clip(info.right_output, lo_p, up_p)
            swap = ((mono_f > 0) & (wl > wr)) | ((mono_f < 0) & (wl < wr))
            wl, wr = jnp.where(swap, wr, wl), jnp.where(swap, wl, wr)
            info = info._replace(left_output=wl, right_output=wr)

        if n_forced:
            valid = info.gain > -jnp.inf

            def sel(a, b):
                """Commit only when the round produced a valid split."""
                return jnp.where(valid, a, b)
        else:
            # Without forced splits the loop cond guarantees the picked
            # leaf's gain is positive, so every round commits. Skipping the
            # where() means no update reads the OLD pool value after the
            # write — without this, XLA cannot prove the dynamic-update-
            # slices on the 22 MB hist_pool in-place and inserts two full
            # copies per split (~72 ms/tree at 255 leaves, profiled).
            valid = jnp.bool_(True)

            def sel(a, b):
                return a

        # ---- physical partition of the parent's segment ----
        # (invalid rounds write garbage into dead regions of the other
        # plane — harmless, since parity/segments only commit when valid)
        start = leaf_start[leaf]
        cnt = leaf_cnt[leaf]
        parity = leaf_parity[leaf]
        split_col = bundle["group"][info.feature] if bundle is not None \
            else info.feature
        # smaller child by GLOBAL in-bag count, so all shards agree
        # (serial_tree_learner.cpp:418) — known BEFORE the partition runs,
        # which is what lets the one-kernel path histogram the right child
        # inside the same launch
        left_smaller = info.left_sum[2] <= info.right_sum[2]
        if not one_kernel:
            with trace_phase("lgbtpu/partition"):
                work, lt = part_fn(work, parity, start, cnt, split_col,
                                   route_table(info), ch=part_chunk)
        new_parity = 1 - parity

        # ---- record ----
        log = log._replace(
            num_splits=sel(new_leaf, log.num_splits),
            split_leaf=log.split_leaf.at[s].set(sel(leaf, log.split_leaf[s])),
            feature=log.feature.at[s].set(sel(info.feature, log.feature[s])),
            bin=log.bin.at[s].set(sel(info.bin, log.bin[s])),
            kind=log.kind.at[s].set(sel(info.kind, log.kind[s])),
            default_left=log.default_left.at[s].set(
                sel(info.default_left, log.default_left[s])),
            gain=log.gain.at[s].set(sel(info.gain, log.gain[s])),
            left_sum=log.left_sum.at[s].set(sel(info.left_sum, log.left_sum[s])),
            right_sum=log.right_sum.at[s].set(
                sel(info.right_sum, log.right_sum[s])),
            go_left=log.go_left.at[s].set(sel(info.go_left, log.go_left[s])),
            miss_bin=log.miss_bin.at[s].set(
                sel(meta.missing_bin[info.feature], log.miss_bin[s])),
            movable=log.movable.at[s].set(
                sel(meta.movable_missing[info.feature], log.movable[s])),
        )

        # ---- segment bookkeeping ----
        def seg_update(lt, leaf_start, leaf_cnt, leaf_parity):
            leaf_start = leaf_start.at[new_leaf].set(
                sel(start + lt, leaf_start[new_leaf]))
            leaf_cnt = leaf_cnt.at[leaf].set(sel(lt, cnt)) \
                .at[new_leaf].set(sel(cnt - lt, leaf_cnt[new_leaf]))
            leaf_parity = leaf_parity.at[leaf].set(sel(new_parity, parity)) \
                .at[new_leaf].set(sel(new_parity, leaf_parity[new_leaf]))
            return leaf_start, leaf_cnt, leaf_parity

        if not one_kernel:
            leaf_start, leaf_cnt, leaf_parity = seg_update(
                lt, leaf_start, leaf_cnt, leaf_parity)

        # ---- stats bookkeeping ----
        leaf_sum = leaf_sum.at[leaf].set(sel(info.left_sum, leaf_sum[leaf])) \
            .at[new_leaf].set(sel(info.right_sum, leaf_sum[new_leaf]))
        leaf_out = leaf_out.at[leaf].set(sel(info.left_output, leaf_out[leaf])) \
            .at[new_leaf].set(sel(info.right_output, leaf_out[new_leaf]))
        d = leaf_depth[leaf] + 1
        leaf_depth = leaf_depth.at[leaf].set(sel(d, leaf_depth[leaf])) \
            .at[new_leaf].set(sel(d, leaf_depth[new_leaf]))
        if hp.has_monotone and hp.mono_advanced:
            pass  # per-threshold bounds handled via _adv_commit below
        elif hp.has_monotone and hp.mono_intermediate:
            # intermediate: children inherit the parent's scalar bounds,
            # then BOTH children broadcast their committed outputs as
            # bounds to every box-overlapping leaf wholly below/above them
            # in each monotone dimension. The broadcast includes the
            # sibling constraint (left is wholly below right on the split
            # feature) AND the reference's neighbor refresh
            # (monotone_constraints.hpp:463 GoDownToFindLeavesToUpdate) —
            # without which a neighbor's later sub-split can drop below an
            # earlier committed output (observed monotonicity violations).
            lo_p, up_p = leaf_lower[leaf], leaf_upper[leaf]
            leaf_lower = leaf_lower.at[new_leaf].set(
                sel(lo_p, leaf_lower[new_leaf]))
            leaf_upper = leaf_upper.at[new_leaf].set(
                sel(up_p, leaf_upper[new_leaf]))
            rng_lo, rng_hi = adv
            rng_lo, rng_hi, box_l, box_r = _adv_child_boxes(
                rng_lo, rng_hi, sel, leaf, new_leaf, info)
            adv = (rng_lo, rng_hi)
            monov = meta.monotone[None, :]                  # (1, F)
            inc = monov > 0
            dec = monov < 0
            valid_b = sel(jnp.bool_(True), jnp.bool_(False))
            for (c_rlo, c_rhi), out in ((box_l, info.left_output),
                                        (box_r, info.right_output)):
                ov_exc = _adv_overlap_except(rng_lo, rng_hi, c_rlo, c_rhi)
                below = rng_hi <= c_rlo[None, :]            # wholly below C
                above = rng_lo >= c_rhi[None, :]            # wholly above C
                hi_m = jnp.any(ov_exc & ((inc & below) | (dec & above)),
                               axis=1) & valid_b            # (L,)
                lo_m = jnp.any(ov_exc & ((inc & above) | (dec & below)),
                               axis=1) & valid_b
                leaf_upper = jnp.where(hi_m, jnp.minimum(leaf_upper, out),
                                       leaf_upper)
                leaf_lower = jnp.where(lo_m, jnp.maximum(leaf_lower, out),
                                       leaf_lower)
        elif hp.has_monotone:
            # basic bounds both children by the split midpoint (reference:
            # monotone_constraints.hpp:327 BasicLeafConstraints)
            mono = meta.monotone[info.feature]
            bl = br = (info.left_output + info.right_output) * 0.5
            lo_l, up_l = leaf_lower[leaf], leaf_upper[leaf]
            new_up_l = jnp.where(mono > 0, jnp.minimum(up_l, bl), up_l)
            new_lo_r = jnp.where(mono > 0, jnp.maximum(lo_l, br), lo_l)
            new_lo_l = jnp.where(mono < 0, jnp.maximum(lo_l, bl), lo_l)
            new_up_r = jnp.where(mono < 0, jnp.minimum(up_l, br), up_l)
            leaf_lower = leaf_lower.at[leaf].set(sel(new_lo_l, lo_l)) \
                .at[new_leaf].set(sel(new_lo_r, leaf_lower[new_leaf]))
            leaf_upper = leaf_upper.at[leaf].set(sel(new_up_l, up_l)) \
                .at[new_leaf].set(sel(new_up_r, leaf_upper[new_leaf]))

        # ---- histograms: the smaller child gets a fresh pass over its
        # contiguous segment; the larger child is parent - smaller ----
        parent_hist = hist_pool[leaf].reshape(num_grp, bm, 3)
        pair = jnp.stack([leaf, new_leaf])
        if one_kernel:
            # ONE launch: partition + smaller-child histogram + both-child
            # split scan. Inputs match what the oracle's hist_of +
            # node_best_pair would see (bounds/outputs already updated
            # above); outputs are bit-identical by construction.
            if resident:
                work = write_route_plane(work, bins_res, parity, start, cnt,
                                         split_col, ch=part_chunk)
            with trace_phase("lgbtpu/one_kernel_split"):
                work, lt, hist_left, hist_right, infos = \
                    one_kernel_split_planes(
                        work, parity, start, cnt,
                        jnp.int32(0) if resident else split_col,
                        info.go_left, left_smaller, d, parent_hist, meta,
                        fmask_search,
                        jnp.stack([info.left_sum, info.right_sum]),
                        leaf_out[pair], leaf_lower[pair], leaf_upper[pair],
                        hp, num_bins=bm, num_feat=num_grp,
                        exact=hist_mode != "bf16", ch=part_chunk,
                        hist_chunk=hist_chunk, lo_w=hist_lo,
                        resident_planes=bins_res if resident else None)
            leaf_start, leaf_cnt, leaf_parity = seg_update(
                lt, leaf_start, leaf_cnt, leaf_parity)
        else:
            small_start = jnp.where(left_smaller, start, start + lt)
            small_cnt = jnp.where(left_smaller, lt, cnt - lt)
            with trace_phase("lgbtpu/histogram"):
                hist_small, work = hist_of(work, new_parity, small_start,
                                           small_cnt)
            hist_large = parent_hist - hist_small
            hist_left = jnp.where(left_smaller, hist_small, hist_large)
            hist_right = jnp.where(left_smaller, hist_large, hist_small)
        if n_forced:
            old_right = hist_pool[new_leaf].reshape(num_grp, bm, 3)
            pool_val = jnp.stack([sel(hist_left, parent_hist),
                                  sel(hist_right, old_right)])
        else:
            pool_val = jnp.stack([hist_left, hist_right])
        hist_pool = hist_pool.at[pair].set(pool_val.reshape(2, -1))
        # local (g,h,cnt) totals per child (voting mode votes with these;
        # any group's bins partition the rows, so group 0 sums the leaf)
        loc_parent = leaf_sum_loc[leaf]
        loc_left = jnp.sum(hist_left[0], axis=0)
        loc_right = loc_parent - loc_left
        leaf_sum_loc = leaf_sum_loc.at[leaf].set(sel(loc_left, loc_parent)) \
            .at[new_leaf].set(sel(loc_right, leaf_sum_loc[new_leaf]))

        # ---- refresh best splits for the two children ----
        used_new = leaf_used[leaf].at[info.feature].set(True)
        leaf_used = leaf_used.at[leaf].set(sel(used_new, leaf_used[leaf])) \
            .at[new_leaf].set(sel(used_new, leaf_used[new_leaf]))
        tree_used = tree_used.at[info.feature].set(
            sel(jnp.bool_(True), tree_used[info.feature]))

        # one vmapped search over both children: the scan ops are tiny at
        # (F, B), so two separate calls pay the per-op dispatch cost twice
        # (one-kernel rounds already scanned inside the fused launch)
        if not one_kernel:
            extra_pair = ()
            if hp.mono_advanced:
                adv = _adv_commit(adv, meta, sel, leaf, new_leaf, info,
                                  num_bin)
                ab_l = _adv_bounds_of(adv, leaf)
                ab_r = _adv_bounds_of(adv, new_leaf)
                extra_pair = (jax.tree.map(lambda a, b: jnp.stack([a, b]),
                                           ab_l, ab_r),)
            with trace_phase("lgbtpu/split_scan"):
                infos = node_best_pair(
                    r, pair, jnp.stack([hist_left, hist_right]),
                    jnp.stack([info.left_sum, info.right_sum]),
                    jnp.stack([loc_left, loc_right]), leaf_out[pair],
                    leaf_lower[pair], leaf_upper[pair], used_new, tree_used,
                    d, *extra_pair)
        gates = jnp.stack([depth_ok(leaf_depth[leaf]),
                           depth_ok(leaf_depth[new_leaf])]) & valid
        infos = infos._replace(gain=jnp.where(gates, infos.gain, -jnp.inf))
        if n_forced:
            olds = jax.tree.map(lambda a: a[pair], best)
            infos = jax.tree.map(
                lambda a, b: jnp.where(valid, a, b), infos, olds)
        best = jax.tree.map(lambda b, v: b.at[pair].set(v), best, infos)

        return (r + 1, work, leaf_start, leaf_cnt, leaf_parity, hist_pool,
                leaf_sum, leaf_sum_loc, leaf_out, leaf_depth, leaf_lower,
                leaf_upper, best, log, leaf_used, tree_used, force_live, adv)

    carry = jax.lax.while_loop(cond, body, carry0)
    (_, work_fin, _, _, _, _, leaf_sum, _, leaf_out, _, _, _, _, log, _, _,
     _, _) = carry
    rb, rbt = (bins, bins_t) if route_bins is None else route_bins
    row_leaf = assign_leaves(rb, log, has_categorical=hp.has_categorical,
                             bundle=bundle, bins_t=rbt)
    log = log._replace(leaf_value=leaf_out, leaf_sum=leaf_sum,
                       row_leaf=row_leaf)
    if return_work:
        return log, work_fin
    return log


@partial(jax.jit, static_argnames=("has_categorical",))
def assign_leaves(bins: jax.Array, log: TreeLog,
                  has_categorical: bool = True,
                  bundle: Optional[dict] = None,
                  bins_t: Optional[jax.Array] = None) -> jax.Array:
    """Route binned rows through a tree's split log (device analog of
    Tree::PredictLeafIndex over pre-binned data; used for valid-set score
    updates, mirroring ScoreUpdater's use of the data partition,
    score_updater.hpp:88).

    Numerical splits route arithmetically (bin <= threshold, with the
    movable-missing bin overridden to the recorded default direction) —
    no table gathers, which are slow on TPU. With EFB bundles the matrix
    columns are bundle-bin coded: the sub-feature's slots translate back
    to feature bins arithmetically and all alien slots follow the shared
    default bin's direction. Categorical splits need the full (B,) routing
    table; when the dataset has no categorical features (static
    ``has_categorical=False``) that path is skipped entirely.
    """
    n = bins.shape[0]
    max_splits = log.split_leaf.shape[0]
    # fast path: numerical(-or-bundled) trees route in ONE streaming Pallas
    # pass (ops/route.py) — the fori form below re-reads the matrix and the
    # leaf vector once per round (~30 ms/tree at 2M x 28 vs ~5 ms)
    if not has_categorical:
        from .ops.route import (ROUTE_BLOCK_ROWS, build_route_table,
                                route_rows, pltpu)
        if pltpu is not None and jax.default_backend() in ("tpu", "axon"):
            if bins_t is not None and bins_t.ndim == 3:
                btr = bins_t   # pre-padded (F, npad/128, 128) block form
            else:
                bt = bins_t if bins_t is not None else bins.T
                rb = ROUTE_BLOCK_ROWS
                npad = ((n + rb - 1) // rb) * rb
                if npad != n:
                    bt = jnp.pad(bt, ((0, 0), (0, npad - n)))
                btr = bt.reshape(bins.shape[1], npad // 128, 128)
            table = build_route_table(log, None, bundle)
            return route_rows(btr, table, log.num_splits, n)[:n]
    # the routing state is pure HBM traffic (a full-N read-modify-write per
    # round): u8 leaf ids cut it 4x whenever they fit (num_leaves <= 256 —
    # always true for the partitioned builder's default shapes)
    small = max_splits + 1 <= 256
    ldt = jnp.uint8 if small else jnp.int32
    row_leaf = jnp.zeros((n,), ldt)
    # one transpose up front: each routing round then reads ONE contiguous
    # (N,) row instead of gathering a strided column from the row-major
    # matrix (the column gather re-streams the whole matrix per round —
    # measured ~30 ms/tree at 2M x 28; transposed rounds are ~6 ms total).
    # Callers building many trees pass a hoisted bins_t (the u8 transpose
    # itself costs ~20 ms at 2M x 28).
    if bins_t is None:
        bins_t = bins.T

    def body(r, row_leaf):
        active = r < log.num_splits
        leaf = log.split_leaf[r]
        fid = log.feature[r]
        col_idx = bundle["group"][fid] if bundle is not None else fid
        col = jax.lax.dynamic_index_in_dim(
            bins_t, col_idx, axis=0, keepdims=False).astype(jnp.int32)

        def go_numerical(col):
            if bundle is not None:
                off = bundle["offset"][fid]
                d = bundle["dpos"][fid]
                rest_dir = log.go_left[r][d]
                rank = col - off
                fb = rank + (rank >= d)
                in_range = bundle["has_rest"][fid] \
                    & (col >= off) & (col < off + bundle["nbm1"][fid])
                plain = ~bundle["has_rest"][fid]
                eff = jnp.where(plain, col, fb)
                go = eff <= log.bin[r]
                go = jnp.where(log.movable[r] & (eff == log.miss_bin[r]),
                               log.default_left[r], go)
                return jnp.where(plain | in_range, go, rest_dir)
            go = col <= log.bin[r]
            return jnp.where(log.movable[r] & (col == log.miss_bin[r]),
                             log.default_left[r], go)

        if has_categorical:
            num_bin = log.go_left.shape[1]

            def go_categorical(col):
                oh = (col[:, None]
                      == jnp.arange(num_bin, dtype=jnp.int32)[None, :])
                return (oh.astype(jnp.float32)
                        @ log.go_left[r].astype(jnp.float32)) > 0.5

            # only the winning branch runs: numerical rounds skip the
            # O(N*B) one-hot entirely
            go = jax.lax.cond(log.kind[r] > 0, go_categorical, go_numerical,
                              col)
        else:
            go = go_numerical(col)
        upd = jnp.where((row_leaf == leaf.astype(ldt)) & ~go,
                        (r + 1).astype(ldt), row_leaf)
        return jnp.where(active, upd, row_leaf)

    out = jax.lax.fori_loop(0, max_splits, body, row_leaf)
    return out.astype(jnp.int32)


def leaf_values_by_row(leaf_value: jax.Array, row_leaf: jax.Array,
                       num_leaves: int, chunk: int = 65536) -> jax.Array:
    """(L,) leaf outputs + (N,) leaf ids -> (N,) per-row values.

    TPU element gathers run at ~60ns/row (latency-bound); a chunked one-hot
    contraction is bandwidth-bound instead (~50x faster at N=2M). Exact:
    f32 HIGHEST matmul with a 0/1 operand.
    """
    n = row_leaf.shape[0]
    iota = jnp.arange(num_leaves, dtype=row_leaf.dtype)
    lv = leaf_value.astype(jnp.float32)

    def one(rl_c):
        oh = (rl_c[:, None] == iota[None, :]).astype(jnp.float32)
        return jax.lax.dot(oh, lv[:, None],
                           precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)[:, 0]

    if n <= chunk:
        # no padding below one chunk — serving buckets sit far under the
        # chunk size and must not pay a 65536-row contraction for 256 rows
        return one(row_leaf)
    pad = (-n) % chunk
    rl = jnp.pad(row_leaf, (0, pad)) if pad else row_leaf
    out = jax.lax.map(one, rl.reshape(-1, chunk))
    return out.reshape(-1)[:n]


# --------------------------------------------------------------------------
# Host wrapper
# --------------------------------------------------------------------------

class SerialTreeLearner:
    """Host orchestration around the jitted device builder
    (reference analog: SerialTreeLearner + the factory at
    src/treelearner/tree_learner.cpp:15 — device offload is the default
    here, so the 4×3 learner matrix collapses to {serial, data-parallel}
    over the same builder)."""

    def __init__(self, config: Config, dataset: BinnedDataset,
                 comm_axis: Optional[str] = None) -> None:
        self.config = config
        self.dataset = dataset
        self.num_leaves = max(2, int(config.num_leaves))
        nb = dataset.feature_num_bins()
        self.num_bin = int(max(2, nb.max() if len(nb) else 2))
        from .ops.binning import BIN_CATEGORICAL, MISSING_NAN, MISSING_ZERO
        mono = np.zeros(dataset.num_features, dtype=np.int8)
        if dataset.monotone_constraints is not None:
            mono = dataset.monotone_constraints.astype(np.int8)
        pen = np.ones(dataset.num_features, dtype=np.float32)
        if dataset.feature_penalty is not None:
            pen = dataset.feature_penalty.astype(np.float32)
        cegb_coupled = np.zeros(dataset.num_features, dtype=np.float32)
        if config.cegb_penalty_feature_coupled:
            for i, f in enumerate(dataset.used_feature_indices):
                if f < len(config.cegb_penalty_feature_coupled):
                    cegb_coupled[i] = config.cegb_penalty_feature_coupled[f]
        if config.cegb_penalty_feature_lazy:
            Log.warning("cegb_penalty_feature_lazy is not supported; "
                        "use cegb_penalty_feature_coupled")
        self.meta = FeatureMeta(
            num_bins=jnp.asarray(nb, jnp.int32),
            movable_missing=jnp.asarray(
                [m.missing_type in (MISSING_NAN, MISSING_ZERO)
                 and m.bin_type != BIN_CATEGORICAL
                 for m in dataset.bin_mappers], bool),
            missing_bin=jnp.asarray([m.missing_bin for m in dataset.bin_mappers], jnp.int32),
            is_categorical=jnp.asarray(
                [m.bin_type == BIN_CATEGORICAL for m in dataset.bin_mappers], bool),
            monotone=jnp.asarray(mono),
            penalty=jnp.asarray(pen),
            cegb_coupled=jnp.asarray(cegb_coupled),
        )
        self.hp = SplitHyper(
            lambda_l1=float(config.lambda_l1),
            lambda_l2=float(config.lambda_l2),
            min_data_in_leaf=float(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(config.min_sum_hessian_in_leaf),
            min_gain_to_split=float(config.min_gain_to_split),
            max_delta_step=float(config.max_delta_step),
            cat_smooth=float(config.cat_smooth),
            cat_l2=float(config.cat_l2),
            max_cat_threshold=int(config.max_cat_threshold),
            max_cat_to_onehot=int(config.max_cat_to_onehot),
            min_data_per_group=float(config.min_data_per_group),
            path_smooth=float(config.path_smooth),
            has_categorical=any(m.bin_type == BIN_CATEGORICAL for m in dataset.bin_mappers),
            has_monotone=dataset.monotone_constraints is not None,
            mono_intermediate=config.monotone_constraints_method
            in ("intermediate", "advanced"),
            mono_advanced=(config.monotone_constraints_method == "advanced"
                           and dataset.monotone_constraints is not None),
            monotone_penalty=float(config.monotone_penalty),
            cegb_tradeoff=float(config.cegb_tradeoff),
            cegb_penalty_split=float(config.cegb_penalty_split),
            # gate on an actually non-zero penalty: cegb_tradeoff alone is a
            # multiplier with nothing to multiply, and enabling CEGB forces
            # the partitioned builder for runs that would train identically
            use_cegb=bool(config.cegb_penalty_split > 0
                          or config.cegb_penalty_feature_coupled),
        )
        self.bins = dataset.device_bins()
        self.num_bin_hist = int(max(2, dataset.group_num_bins().max()
                                    if dataset.num_groups else 2))
        self.bundle = None
        if dataset.has_bundles:
            self.bundle = {k: jnp.asarray(v)
                           for k, v in dataset.bundle_maps().items()}
        if self.hp.mono_advanced and not self.use_partition():
            Log.warning("monotone_constraints_method=advanced needs the "
                        "partitioned builder (max_bin <= 256); the dense "
                        "builder applies the basic (midpoint) method")
            self.hp = self.hp._replace(mono_advanced=False)
        if self.hp.use_cegb and not self.use_partition():
            Log.fatal("CEGB penalties require the partitioned builder "
                      "(max_bin <= 256, tree_builder != dense)")
        if (config.use_quantized_grad
                or config.tpu_hist_precision == "int8") \
                and not self.use_partition():
            Log.fatal("use_quantized_grad requires the partitioned builder "
                      "(max_bin <= 256, tree_builder != dense)")
        self.comm = self._make_comm(comm_axis)
        self._build = track_jit("learner/build", jax.jit(self.make_build_fn()))

    def _make_comm(self, axis: Optional[str]) -> Comm:
        return Comm(axis)

    def use_partition(self) -> bool:
        """Partitioned (leaf-contiguous) builder unless disabled or the bin
        count exceeds the packed-u8 layout (max_bin > 256 -> u16 bins)."""
        mode = self.config.tree_builder
        if mode == "dense":
            if self.bundle is not None:
                Log.fatal("tree_builder=dense does not support EFB bundles; "
                          "set enable_bundle=false or use the partitioned "
                          "builder")
            return False
        ok = self.num_bin <= 256 and self.num_bin_hist <= 256 \
            and self.bins.dtype == jnp.uint8
        if mode == "partition" and not ok:
            Log.fatal(
                "tree_builder=partition requires max_bin <= 256 (uint8 "
                "bins); got %d bins. Use tree_builder=dense or lower "
                "max_bin.", self.num_bin)
        if not ok and self.bundle is not None:
            Log.fatal("EFB bundles require the partitioned builder "
                      "(max_bin <= 256)")
        return ok

    def make_build_fn(self):
        """The tree-builder callable with static arguments closed over —
        shared by the serial, data-parallel and fused training paths."""
        if self.use_partition():
            return partial(build_tree_partitioned, **self.build_kwargs())
        return partial(build_tree, **self.build_kwargs())

    def build_kwargs(self) -> dict:
        config = self.config
        kw = dict(
            hp=self.hp,
            num_leaves=self.num_leaves,
            num_bin=self.num_bin,
            max_depth=int(config.max_depth),
            feature_fraction_bynode=float(config.feature_fraction_bynode),
            extra_trees=bool(config.extra_trees),
            extra_seed=int(config.extra_seed),
            comm=self.comm,
            constraint_sets=self._constraint_sets(),
            forced=self._forced_splits(),
        )
        if self.use_partition():
            from .obs import telemetry
            mode = config.tpu_hist_precision
            if config.use_quantized_grad:
                mode = "int8"
            backend = jax.default_backend()
            # Ledger preresolution (ROADMAP self-calibration): a previous
            # run on this (machine, dataset-shape, config) key already
            # resolved the auto knobs; reuse its answers instead of
            # re-deriving them, recording under ledger_preresolution so
            # the knob set still persists forward (and the acceptance
            # test can assert ZERO new auto_resolution records). Values
            # come from a JSON file: sanitize here, and every validation
            # gate below still applies to them.
            pre = {}
            if config.obs_ledger:
                from . import obs_ledger
                raw = obs_ledger.preresolve(config, self.dataset.num_data,
                                            self.dataset.num_features)
                valid = {"tpu_partition_kernel": ("pallas", "xla"),
                         "tpu_hist_kernel": ("pallas", "xla"),
                         "tpu_work_layout": ("planes", "rows"),
                         "tpu_resident_state": ("resident", "off"),
                         "tpu_split_kernel": ("on", "off"),
                         "tpu_forest_kernel": ("on", "off"),
                         "tpu_goss_compact": ("on", "off"),
                         "tpu_hist_mxu": ("on", "off")}
                for k, v in raw.items():
                    if k in valid and v in valid[k]:
                        pre[k] = v
                    elif k in ("tpu_part_chunk", "tpu_hist_chunk") \
                            and isinstance(v, int) and v > 0:
                        pre[k] = v

            def _pre(knob):
                """Consume a preresolved knob value (records + counts)."""
                v = pre[knob]
                telemetry.record("ledger_preresolution",
                                 dedupe_key=(knob, v), knob=knob,
                                 configured="auto", value=v,
                                 reason="preresolved from run ledger")
                telemetry.count("ledger/preresolved_knobs")
                return v

            part_kernel = config.tpu_partition_kernel
            auto_kernel = part_kernel == "auto"
            part_why = ""
            if auto_kernel and "tpu_partition_kernel" in pre:
                part_kernel = _pre("tpu_partition_kernel")
                auto_kernel = False   # resolved; no fresh record below
            elif auto_kernel:
                # the fused DMA kernel needs Mosaic; CPU test meshes and
                # non-TPU backends use the portable XLA pipeline
                part_kernel = "pallas" if backend in ("tpu", "axon") else "xla"
                part_why = ("backend %s has Mosaic: fused DMA kernel"
                            % backend if part_kernel == "pallas" else
                            "backend %s has no Mosaic: portable XLA pipeline"
                            % backend)
            from .ops.partition import GH_BYTES, GH_BYTES_Q
            row_w = self.bins.shape[1] + (GH_BYTES_Q if mode == "int8"
                                          else GH_BYTES)
            if part_kernel == "pallas" and row_w > 512:
                # 512 bytes = 4 DMA lane-tiles; beyond that the permutation
                # matmul and VMEM scratch stop paying for themselves
                if not auto_kernel:
                    Log.warning(
                        "tpu_partition_kernel=pallas needs packed rows "
                        "<= 512 bytes (got %d); using the XLA kernel",
                        row_w)
                part_kernel = "xla"
                part_why = ("packed row %d B exceeds the 512 B pallas DMA "
                            "window" % row_w)
            part_chunk = int(config.tpu_part_chunk)
            auto_part_chunk = part_chunk <= 0
            if auto_part_chunk and "tpu_part_chunk" in pre:
                part_chunk = _pre("tpu_part_chunk")
                auto_part_chunk = False
            elif auto_part_chunk:
                # measured on v5e: the XLA path optimum is 2048 (per-op
                # overhead vs O(ch^2) compaction matmul); the pallas kernel
                # has no per-op overhead, so 1024 halves the matmul work
                part_chunk = 1024 if part_kernel == "pallas" else 2048
            if part_kernel == "pallas" and (
                    part_chunk % 32
                    or (part_chunk > 256 and part_chunk % 256)):
                Log.fatal("tpu_part_chunk must be a multiple of 32 and, "
                          "above 256, a multiple of the 256-row compaction "
                          "sub-block (got %d)", part_chunk)
            hist_chunk = int(config.tpu_hist_chunk)
            auto_hist_chunk = hist_chunk <= 0
            if auto_hist_chunk and "tpu_hist_chunk" in pre:
                hist_chunk = _pre("tpu_hist_chunk")
                auto_hist_chunk = False
            elif auto_hist_chunk:
                # measured on v5e (lo_w-tuned einsum): 4096-row chunks win
                # at F<=64; wide matrices spill VMEM — 1024 is ~8% faster
                # than 2048 at F=137
                hist_chunk = 4096 if self.bins.shape[1] <= 64 else 1024
            hist_kernel = config.tpu_hist_kernel
            auto_hist = hist_kernel == "auto"
            if auto_hist and "tpu_hist_kernel" in pre:
                hist_kernel = _pre("tpu_hist_kernel")
                auto_hist = False
            elif auto_hist:
                # auto = xla: the in-VMEM pallas kernel is bit-identical
                # and ~6x faster standalone, but in-situ (alternating with
                # the partition kernel inside the tree while-loop) the axon
                # runtime puts it on a slow dispatch path (+100 ms/iter,
                # wall-measured A/B) that no spec variant avoided. Kept
                # selectable for future runtimes.
                hist_kernel = "xla"
            elif hist_kernel == "pallas" and (part_kernel != "pallas"
                                              or mode == "int8"):
                Log.warning("tpu_hist_kernel=pallas needs the pallas "
                            "partition layout and a non-quantized mode; "
                            "using the XLA einsum")
                hist_kernel = "xla"
            if hist_kernel == "pallas" and hist_chunk % 32:
                # the kernel re-derives DMA offsets as (x // 32) * 32; a
                # misaligned chunk would double-count the rows between the
                # aligned offset and the true chunk start — silently wrong
                # histograms (ADVICE: refuse loudly, like part_chunk % 32)
                Log.fatal("tpu_hist_chunk must be a multiple of 32 with "
                          "the pallas histogram kernel (got %d)", hist_chunk)
            layout = config.tpu_work_layout
            auto_layout = layout == "auto"
            layout_why = ""
            if auto_layout and "tpu_work_layout" in pre:
                layout = _pre("tpu_work_layout")
                auto_layout = False
            elif auto_layout:
                # planes pay off when a packed row wastes most of a
                # 128-lane DMA tile; at > 256 B row-major tiles are already
                # >= 2-tile efficient. int8 keeps rows (no quantized planes
                # pack pass yet)
                layout = "planes" if (
                    backend in ("tpu", "axon")
                    and row_w <= 256 and mode != "int8") else "rows"
                if layout == "planes":
                    layout_why = ("packed row %d B <= 256 B on %s: plane "
                                  "tiles waste fewer DMA lanes" % (row_w,
                                                                   backend))
                elif backend not in ("tpu", "axon"):
                    layout_why = "backend %s: row-major default" % backend
                elif mode == "int8":
                    layout_why = "int8 mode has no quantized planes pack"
                else:
                    layout_why = ("packed row %d B > 256 B: row tiles "
                                  "already >= 2-tile efficient" % row_w)
            elif layout == "planes" and mode == "int8":
                Log.warning("tpu_work_layout=planes does not support int8 "
                            "quantized training; using rows")
                layout = "rows"
            rs = config.tpu_resident_state
            auto_rs = rs == "auto"
            if rs == "on":
                if config.tpu_work_layout == "rows":
                    Log.fatal("tpu_resident_state=on requires the planes "
                              "work layout (got tpu_work_layout=rows)")
                if mode == "int8":
                    Log.fatal("tpu_resident_state=on does not support int8 "
                              "quantized training (plane-family layouts "
                              "are hilo/bf16 only)")
                layout = "resident"
            elif auto_rs and "tpu_resident_state" in pre:
                if _pre("tpu_resident_state") == "resident" \
                        and layout == "planes":
                    layout = "resident"
                auto_rs = False
            elif auto_rs and layout == "planes" \
                    and backend in ("tpu", "axon"):
                # resident state strictly reduces partition traffic where
                # the planes layout already wins, and trees stay
                # bit-identical; CPU meshes keep plain planes (the gather
                # has no payoff without HBM bandwidth pressure)
                layout = "resident"
            if layout == "resident" and hist_kernel == "pallas":
                Log.warning("tpu_hist_kernel=pallas has no resident gather "
                            "path; using the XLA gather einsum")
                hist_kernel = "xla"
            if layout == "planes" and hist_kernel == "pallas" \
                    and hist_chunk % 128:
                # the planes kernel re-derives lane DMA offsets as
                # (x // 128) * 128 — a misaligned chunk double-counts rows
                Log.fatal("tpu_hist_chunk must be a multiple of 128 with "
                          "the planes pallas histogram kernel (got %d)",
                          hist_chunk)
            if layout in ("planes", "resident") and part_kernel == "pallas" \
                    and (part_chunk % 128
                         or (part_chunk > 256 and part_chunk % 256)):
                Log.fatal("planes layout needs tpu_part_chunk a multiple "
                          "of 128 and, above 256, of the 256-row "
                          "compaction sub-block (got %d)", part_chunk)
            sk = config.tpu_split_kernel
            auto_sk = sk == "auto"
            sk_why = ""
            if auto_sk and "tpu_split_kernel" in pre:
                sk = _pre("tpu_split_kernel")
                auto_sk = False
            elif auto_sk:
                # auto = off: the one-kernel split's bit-parity is proven
                # under the pallas interpreter, but the Mosaic lowering of
                # its scan tail is unvalidated on real hardware (no TPU
                # reachable since round 5). The first v5e session runs
                # scripts/split_bisect.py and flips the knob — or lets the
                # run ledger carry the measured answer forward.
                sk = "off"
                sk_why = ("one-kernel split parity proven under interpret "
                          "only; Mosaic scan tail unmeasured on TPU — run "
                          "scripts/split_bisect.py to validate, then "
                          "enable via knob or ledger")
            if sk == "on":
                bad = []
                if layout not in ("planes", "resident") \
                        or part_kernel != "pallas":
                    bad.append("needs the fused pallas planes/resident "
                               "layout")
                if mode == "int8":
                    bad.append("int8 histograms unsupported")
                if self.bundle is not None \
                        or self.num_bin_hist != self.num_bin:
                    bad.append("EFB feature bundling unsupported")
                if self.comm.axis is not None:
                    bad.append("multi-device comm unsupported")
                if self.hp.use_cegb:
                    bad.append("CEGB penalties unsupported")
                if self.hp.has_monotone and (self.hp.mono_intermediate
                                             or self.hp.mono_advanced):
                    bad.append("intermediate/advanced monotone unsupported")
                if float(config.feature_fraction_bynode) < 1.0 \
                        or bool(config.extra_trees):
                    bad.append("by-node sampling / extra-trees unsupported")
                if kw.get("constraint_sets") is not None:
                    bad.append("interaction constraint sets unsupported")
                if hist_chunk % 128:
                    bad.append("hist_chunk must be a multiple of 128")
                if bad:
                    Log.warning("tpu_split_kernel=on is not eligible here "
                                "(%s); using the three-launch path",
                                "; ".join(bad))
                    sk = "off"
                    if auto_sk:
                        sk_why = "structurally ineligible: " + "; ".join(bad)
            fk = config.tpu_forest_kernel
            auto_fk = fk == "auto"
            fk_why = ""
            if auto_fk and "tpu_forest_kernel" in pre:
                fk = _pre("tpu_forest_kernel")
                auto_fk = False
            elif auto_fk:
                # auto = off: the forest-at-once serving kernel's bit
                # parity with the per-depth-gather predict is proven under
                # the pallas interpreter, but its Mosaic lowering (one
                # launch per row tile, resident node tables) is
                # unvalidated on real hardware. The first TPU session runs
                # scripts/forest_bisect.py and flips the knob — or lets
                # the run ledger carry the measured answer forward.
                fk = "off"
                fk_why = ("forest kernel parity proven under interpret "
                          "only; Mosaic lowering unmeasured on TPU — run "
                          "scripts/forest_bisect.py to validate, then "
                          "enable via knob or ledger")
            # serve-time eligibility (train_set present, tables within the
            # VMEM budget) is per-model state — boosting._forest_model
            # re-checks it on every pack; only the knob resolves here
            self._forest_kernel = fk
            from .ops.partition import goss_compact_rows as _gcr
            n_rows = int(self.bins.shape[0])
            goss_active = (config.data_sample_strategy == "goss"
                           and float(config.top_rate)
                           + float(config.other_rate) < 1.0)
            m_rows = _gcr(n_rows, float(config.top_rate),
                          float(config.other_rate)) if goss_active else 0
            gc = config.tpu_goss_compact
            auto_gc = gc == "auto"
            gc_why = ""
            if auto_gc and "tpu_goss_compact" in pre:
                gc = _pre("tpu_goss_compact")
                auto_gc = False
            elif auto_gc:
                # auto = off: compaction's bit-parity with the dense-mask
                # path is proven under the CPU interpreter, but the gather
                # + compact-build wall-clock win is unmeasured on hardware.
                gc = "off"
                if goss_active:
                    gc_why = ("GOSS compaction parity proven under "
                              "interpret only; gather + compact-build "
                              "unmeasured on TPU — run "
                              "scripts/goss_bisect.py to validate, then "
                              "enable via knob or ledger")
                else:
                    gc_why = ("no GOSS sampling in this config "
                              "(data_sample_strategy=%s)"
                              % config.data_sample_strategy)
            if gc == "on":
                bad = []
                if not goss_active:
                    bad.append("no GOSS sampling in this config")
                if mode == "int8":
                    bad.append("int8 stochastic-rounding draws are "
                               "row-position seeded (compaction would "
                               "change the quantization stream)")
                if self.comm.axis is not None:
                    bad.append("multi-device comm unsupported (per-shard "
                               "compact/dense cond would diverge)")
                if goss_active and m_rows >= n_rows:
                    bad.append("sample rates leave no rows to drop")
                if bad:
                    Log.warning("tpu_goss_compact=on is not eligible here "
                                "(%s); using the dense-mask path",
                                "; ".join(bad))
                    gc = "off"
                    if auto_gc:
                        gc_why = "structurally ineligible: " + "; ".join(bad)
            hm = config.tpu_hist_mxu
            auto_hm = hm == "auto"
            hm_why = ""
            if auto_hm and "tpu_hist_mxu" in pre:
                hm = _pre("tpu_hist_mxu")
                auto_hm = False
            elif auto_hm:
                # auto = off: the one-hot MXU kernel's bit-parity is proven
                # under the CPU interpreter, but its Mosaic/MXU lowering
                # (int8 x int8 -> i32 dots especially) is unvalidated on
                # real hardware.
                hm = "off"
                hm_why = ("one-hot MXU histogram parity proven under "
                          "interpret only; MXU lowering unmeasured on TPU "
                          "— run scripts/hist_mxu_bisect.py to validate, "
                          "then enable via knob or ledger")
            if hm == "on":
                bad = []
                if layout in ("planes", "resident"):
                    bad.append("needs the rows work layout")
                if part_kernel != "pallas":
                    bad.append("needs part_kernel=pallas (128-lane work "
                               "rows)")
                if hist_chunk % 32:
                    bad.append("hist_chunk must be a multiple of 32")
                if bad:
                    Log.warning("tpu_hist_mxu=on is not eligible here "
                                "(%s); using the XLA einsum path",
                                "; ".join(bad))
                    hm = "off"
                    if auto_hm:
                        hm_why = "structurally ineligible: " + "; ".join(bad)
            # auto-knob resolution records: what auto chose and why
            # (deduped, so repeated build_kwargs calls keep one record per
            # distinct resolution)
            def _rec(knob, value, reason):
                telemetry.record("auto_resolution",
                                 dedupe_key=(knob, value, reason),
                                 knob=knob, configured="auto",
                                 value=value, reason=reason)

            if auto_kernel:
                _rec("tpu_partition_kernel", part_kernel, part_why)
            if auto_hist:
                _rec("tpu_hist_kernel", hist_kernel,
                     "in-situ pallas hits the slow axon dispatch path; "
                     "the XLA einsum wins wall-clock")
            if auto_layout:
                _rec("tpu_work_layout", layout if layout != "resident"
                     else "planes", layout_why)
            if auto_rs:
                _rec("tpu_resident_state",
                     "resident" if layout == "resident" else "off",
                     "planes layout on %s: resident gather strictly "
                     "reduces partition traffic" % backend
                     if layout == "resident" else
                     "layout %s on %s: resident gather has no payoff"
                     % (layout, backend))
            if auto_part_chunk:
                _rec("tpu_part_chunk", part_chunk,
                     "%s kernel default chunk" % part_kernel)
            if auto_hist_chunk:
                _rec("tpu_hist_chunk", hist_chunk,
                     "packed width %d default chunk" % self.bins.shape[1])
            if auto_sk:
                _rec("tpu_split_kernel", sk, sk_why)
            if auto_fk:
                _rec("tpu_forest_kernel", fk, fk_why)
            if auto_gc:
                _rec("tpu_goss_compact", gc, gc_why)
            if auto_hm:
                _rec("tpu_hist_mxu", hm, hm_why)
            kw.update(
                hist_chunk=hist_chunk,
                part_chunk=part_chunk,
                hist_mode=mode,
                hist_lo=int(config.tpu_hist_lo),
                num_bin_hist=self.num_bin_hist,
                bundle=self.bundle,
                part_kernel=part_kernel,
                hist_kernel=hist_kernel,
                split_kernel=sk,
                work_layout=layout,
                goss_compact_rows=m_rows if gc == "on" else 0,
                hist_mxu=hm,
            )
        else:
            kw.update(
                hist_chunk=min(int(config.tpu_rows_per_chunk), 8192),
                # measured on v5e: XLA fuses the f32 HIGHEST one-hot matmul
                # better than the bf16 hi/lo two-dot variant
                mxu_bf16=False,
            )
        return kw

    def _constraint_sets(self) -> Optional[jax.Array]:
        """Parse interaction_constraints "[0,1],[2,3]" into (S, F) bool over
        inner feature indices (reference: col_sampler.hpp:27)."""
        spec = self.config.interaction_constraints
        if not spec:
            return None
        import re
        groups = re.findall(r"\[([^\]]*)\]", str(spec))
        if not groups:
            return None
        F = self.dataset.num_features
        sets = np.zeros((len(groups), F), dtype=bool)
        for s, grp in enumerate(groups):
            for tok in grp.split(","):
                tok = tok.strip()
                if tok == "":
                    continue
                inner = self.dataset.inner_feature_index(int(tok))
                if inner >= 0:
                    sets[s, inner] = True
        return jnp.asarray(sets)

    def _forced_splits(self):
        """Load forcedsplits_filename JSON into BFS (leaf, feature, bin)
        arrays (reference: serial_tree_learner.cpp:450 ForceSplits)."""
        fname = self.config.forcedsplits_filename
        if not fname:
            return None
        import json as _json
        import os
        if not os.path.exists(fname):
            Log.warning("forced splits file %s not found", fname)
            return None
        with open(fname) as f:
            root = _json.load(f)
        leaves, feats, bins_ = [], [], []
        queue = [(root, 0)]
        n_created = 0
        while queue and n_created < self.num_leaves - 1:
            node, leaf = queue.pop(0)
            if not node or "feature" not in node:
                continue
            inner = self.dataset.inner_feature_index(int(node["feature"]))
            if inner < 0:
                continue
            mapper = self.dataset.bin_mappers[inner]
            tbin = int(mapper.value_to_bin(
                np.asarray([float(node["threshold"])]))[0])
            tbin = min(tbin, mapper.num_bins - 2) if mapper.num_bins > 1 else 0
            leaves.append(leaf)
            feats.append(inner)
            bins_.append(tbin)
            n_created += 1
            new_leaf = n_created
            if "left" in node and node["left"]:
                queue.append((node["left"], leaf))
            if "right" in node and node["right"]:
                queue.append((node["right"], new_leaf))
        if not leaves:
            return None
        return (jnp.asarray(leaves, jnp.int32), jnp.asarray(feats, jnp.int32),
                jnp.asarray(bins_, jnp.int32))

    def work_buf_spec(self):
        """(shape, dtype) of the carried work buffer for the partitioned
        builder, or None (fused blocks preallocate it once per block instead
        of paying a fresh 2x(N,W) alloc+zero per tree)."""
        if not self.use_partition():
            return None
        from .ops.partition import planes_npad, work_spec
        kw = self.build_kwargs()
        guard, w = work_spec(self.bins.shape[1],
                             kw["hist_mode"] == "int8", kw["part_kernel"],
                             kw["part_chunk"], kw["hist_chunk"],
                             layout=kw["work_layout"])
        n = self.bins.shape[0]
        m = kw.get("goss_compact_rows", 0)
        if 0 < m < n:
            # GOSS compaction: the carried buffer serves the compact
            # branch (the dense warmup/overflow branch allocates its own
            # N-sized buffers in-graph)
            n = m
        if kw["work_layout"] in ("planes", "resident"):
            return ((2, w, planes_npad(n, guard, kw["part_kernel"])),
                    jnp.uint8)
        return ((2, n + 2 * guard, w), jnp.uint8)

    def resident_spec(self):
        """(guard, npad) of the resident bin-plane buffer, or None when the
        resolved layout is not resident. Shared by the fused trainer's
        per-block hoist and the dataset's version-token device cache."""
        if not self.use_partition():
            return None
        from .ops.partition import planes_npad, work_spec
        kw = self.build_kwargs()
        if kw["work_layout"] != "resident":
            return None
        guard, _ = work_spec(self.bins.shape[1],
                             kw["hist_mode"] == "int8", kw["part_kernel"],
                             kw["part_chunk"], kw["hist_chunk"],
                             layout=kw["work_layout"])
        return guard, planes_npad(self.bins.shape[0], guard,
                                  kw["part_kernel"])

    def traffic_spec(self):
        """Deterministic bytes-moved accounting of the per-split hot loop
        for the resolved config (bench observability; PERF.md traffic
        tables). Per PARENT ROW per split: the partition reads the src
        chunk and writes the dst chunk at the moved work width (plus the
        resident route pre-pass: 4 ridx read + 1 gather read + 1 route
        write); the smaller-child histogram reads the payload planes plus,
        for resident, the F gathered bin bytes."""
        if not self.use_partition():
            return None
        from .ops.partition import RST_GH_OFF, work_spec
        kw = self.build_kwargs()
        layout = kw["work_layout"]
        _, w = work_spec(self.bins.shape[1], kw["hist_mode"] == "int8",
                         kw["part_kernel"], kw["part_chunk"],
                         kw["hist_chunk"], layout=layout)
        f = self.bins.shape[1]
        part = 2 * w
        if layout == "resident":
            part += RST_GH_OFF + 1      # route pre-pass gather traffic
            hist = w + f                # slim payload + gathered bin bytes
        elif layout == "planes":
            hist = w
        else:
            hist = w                    # row-major reads the packed row
        one_kernel = kw.get("split_kernel", "off") == "on"
        n = int(self.bins.shape[0])
        m = int(kw.get("goss_compact_rows", 0))
        return {"work_layout": layout, "work_width": int(w),
                "partition_bytes_per_row": int(part),
                "hist_bytes_per_row": int(hist),
                "split_kernel": kw.get("split_kernel", "off"),
                "hist_mxu": kw.get("hist_mxu", "off"),
                # rows every downstream pass scans per tree: the GOSS
                # compact prefix when compaction resolved on, else N
                "effective_rows": m if 0 < m < n else n,
                "goss_compact": "on" if 0 < m < n else "off",
                # device launches per split on this config: partition +
                # child histogram + split scan, or the fused one-kernel
                "launches_per_split": 1 if one_kernel else 3}

    def train(self, ghc: jax.Array, feature_mask: jax.Array, key: jax.Array,
              cegb_used: Optional[jax.Array] = None) -> TreeLog:
        """One tree from (grad, hess, inbag) channels. Returns the device log."""
        if cegb_used is None:
            cegb_used = jnp.zeros((self.dataset.num_features,), bool)
        rspec = getattr(self, "_rspec_cache", False)
        if rspec is False:
            rspec = self._rspec_cache = self.resident_spec()
        if rspec is not None:
            # one cached device copy of the resident bin planes per dataset
            # (original row order, training-invariant) instead of an
            # in-graph transpose per tree
            return self._build(
                self.bins, ghc, self.meta, feature_mask, key, cegb_used,
                bins_res=self.dataset.device_resident_planes(*rspec))
        return self._build(self.bins, ghc, self.meta, feature_mask, key,
                           cegb_used)

    def log_to_tree(self, log: TreeLog) -> Tree:
        """Pull the split log to host and rebuild the Tree model.

        One batched transfer: per-field np.asarray would cost a blocking
        device->host round-trip each (~15x the latency over a TPU tunnel).
        ``row_leaf`` (O(rows)) stays on device — only O(leaves) data moves.
        """
        (num_splits, split_leaf, feature, bin_, default_left, gain, left_sum,
         right_sum, leaf_value, kind, go_left) = jax.device_get(
            (log.num_splits, log.split_leaf, log.feature, log.bin,
             log.default_left, log.gain, log.left_sum, log.right_sum,
             log.leaf_value, log.kind, log.go_left))
        return Tree.from_split_log(
            int(num_splits),
            split_leaf, feature, bin_, default_left, gain, left_sum, right_sum,
            leaf_value,
            bin_mappers=self.dataset.bin_mappers,
            real_feature_index=self.dataset.used_feature_indices,
            go_left_table=go_left,
            is_categorical=kind > 0,
        )
