"""Binned training dataset: the device-resident training matrix.

TPU-native equivalent of the reference ``Dataset`` + ``DatasetLoader`` +
``Metadata`` (reference: include/LightGBM/dataset.h:41,282,
src/io/dataset.cpp:318 Construct, src/io/dataset_loader.cpp). Differences by
design:

- The binned matrix is a single dense ``(rows, features)`` uint8/uint16 array
  destined for HBM (row-sharded over the device mesh), instead of per-group
  column bins (dense_bin.hpp / sparse_bin.hpp). All features share one padded
  bin axis; per-feature bin counts mask the tail during the split scan.
- EFB (reference dataset.cpp:239 FastFeatureBundling) folds mutually-exclusive
  sparse features into shared columns before the matrix is materialized.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .ops.binning import (
    BIN_CATEGORICAL,
    BIN_NUMERICAL,
    MISSING_NAN,
    MISSING_NONE,
    MISSING_ZERO,
    BinMapper,
    find_bin,
)
from .utils.log import Log


class Metadata:
    """Labels, weights, query boundaries, init scores
    (reference: include/LightGBM/dataset.h:41, src/io/metadata.cpp)."""

    def __init__(
        self,
        num_data: int,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
    ) -> None:
        self.num_data = num_data
        self.label = None if label is None else np.ascontiguousarray(label, dtype=np.float32).ravel()
        self.weight = None if weight is None else np.ascontiguousarray(weight, dtype=np.float32).ravel()
        self.init_score = None if init_score is None else np.ascontiguousarray(init_score, dtype=np.float64)
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_id: Optional[np.ndarray] = None
        if group is not None:
            group = np.asarray(group).ravel().astype(np.int64)
            # LightGBM semantics: `group` is per-query sizes summing to
            # num_data (reference src/io/metadata.cpp SetQuery). A per-row
            # query-id vector is also accepted (sklearn-API convenience) but
            # only when it cannot be a sizes vector and ids are contiguous.
            if group.sum() == num_data:
                sizes = group
                if np.any(sizes <= 0):
                    Log.fatal("group sizes must be positive")
                self.query_boundaries = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
            elif len(group) == num_data:
                qid = group
                change = np.flatnonzero(np.diff(qid)) + 1
                boundaries = np.concatenate([[0], change, [num_data]]).astype(np.int64)
                # reject non-contiguous ids (same id reappearing later)
                first_vals = qid[boundaries[:-1]]
                if len(np.unique(first_vals)) != len(first_vals):
                    Log.fatal("per-row query ids must be contiguous (sorted by query)")
                self.query_boundaries = boundaries
            else:
                Log.fatal("sum of group sizes (%d) != num_data (%d)", group.sum(), num_data)
            qb = self.query_boundaries
            qid = np.zeros(num_data, dtype=np.int32)
            for i in range(len(qb) - 1):
                qid[qb[i]:qb[i + 1]] = i
            self.query_id = qid

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def device_label(self):
        """Cached f32 device copy of the label (see _dev_cached for the
        cache key contract). Tunnel uploads cost seconds per 100 MB, so the
        copy must not be re-made per Booster."""
        return self._dev_cached("label")

    def device_weight(self):
        return self._dev_cached("weight")

    def bump_version(self) -> None:
        """Invalidate every cached device copy after an IN-PLACE host
        mutation (``meta.label[sel] = v`` style). Reassigning the attribute
        (``meta.label = new``) invalidates by identity and does not need
        this."""
        self._dev_version = getattr(self, "_dev_version", 0) + 1
        from .obs import telemetry
        telemetry.count("dataset/bump_version")

    def _dev_cached(self, name):
        # Keyed on (array identity, version token). Identity catches
        # attribute REASSIGNMENT; it cannot see in-place writes into the
        # same ndarray — callers that mutate in place must bump_version(),
        # otherwise the cached device copy is served stale. The arrays are
        # otherwise treated as immutable once a Booster holds the dataset
        # (the reference's set_label/set_weight APIs reassign).
        import jax.numpy as jnp
        from .obs import telemetry
        arr = getattr(self, name)
        if arr is None:
            return None
        ver = getattr(self, "_dev_version", 0)
        key = "_device_" + name + "_cache"
        cur = getattr(self, key, None)
        if cur is None or cur[0] is not arr or cur[1] != ver:
            telemetry.count("dataset/device_%s/miss" % name)
            telemetry.count("dataset/device_%s/upload_bytes" % name,
                            int(getattr(arr, "nbytes", 0)))
            setattr(self, key, (arr, ver, jnp.asarray(arr, jnp.float32)))
        else:
            telemetry.count("dataset/device_%s/hit" % name)
        return getattr(self, key)[2]


@dataclass
class FeatureGroupInfo:
    """One bundled column of the binned matrix (EFB bundle or single feature).

    Reference analog: FeatureGroup (include/LightGBM/feature_group.h:25) —
    features in a bundle are mutually exclusive; each sub-feature occupies a
    contiguous bin range [bin_offset, bin_offset + num_bins) in the column.
    """
    feature_indices: List[int]      # inner (used-feature) indices in this bundle
    bin_offsets: List[int]          # per sub-feature offset within the column
    num_bins: int                   # total bins in this column


class BinnedDataset:
    """The constructed training matrix (reference Dataset, dataset.h:282)."""

    def __init__(self) -> None:
        self.num_data: int = 0
        self.num_total_features: int = 0      # original input feature count
        self.used_feature_indices: List[int] = []   # original index per used feature
        self.bin_mappers: List[BinMapper] = []      # per used feature
        self.binned: Optional[np.ndarray] = None    # (num_data, num_groups) uint8/16
        self.groups: List[FeatureGroupInfo] = []
        self.feature_to_group: np.ndarray = np.array([], dtype=np.int32)   # used-feature -> group
        self.feature_group_offset: np.ndarray = np.array([], dtype=np.int32)  # bin offset in group
        self.metadata: Metadata = Metadata(0)
        self.max_bins_per_feature: int = 0
        self.feature_names: List[str] = []
        self.monotone_constraints: Optional[np.ndarray] = None
        self.feature_penalty: Optional[np.ndarray] = None
        # raw numerical feature values, kept only for linear_tree
        # (reference: Dataset::raw_data_, dataset.h numeric_feature_map_)
        self.raw_numeric: Optional[np.ndarray] = None   # (N, F) f32, NaN kept
        # distributed loading: (rank, world, global_rows) when this object
        # holds only one host's row shard (io.load_dataset_sharded)
        self.shard_info: Optional[tuple] = None

    # -- accessors used by the learners --
    def bump_version(self) -> None:
        """Invalidate the cached device matrix after an IN-PLACE host write
        into ``binned``. Rebinning (reassigning ``binned``) invalidates by
        identity and does not need this; ``binned`` is otherwise immutable
        once construction finishes."""
        self._dev_version = getattr(self, "_dev_version", 0) + 1
        from .obs import telemetry
        telemetry.count("dataset/bump_version")

    def device_bins(self):
        """Device copy of the binned matrix, cached on the dataset: the
        axon tunnel moves host arrays at ~10-30 MB/s, so re-uploading the
        matrix per Booster cost ~10-25 s at 10.5M x 28. Keyed on the host
        array's identity plus the user-bumpable version token
        (:meth:`bump_version`) — identity alone cannot see in-place writes
        into the same ndarray."""
        import jax.numpy as jnp
        from .obs import telemetry
        ver = getattr(self, "_dev_version", 0)
        cur = getattr(self, "_device_bins_cache", None)
        if cur is None or cur[0] is not self.binned or cur[1] != ver:
            telemetry.count("dataset/device_bins/miss")
            telemetry.count("dataset/device_bins/upload_bytes",
                            int(self.binned.nbytes))
            self._device_bins_cache = (self.binned, ver,
                                       jnp.asarray(self.binned))
        else:
            telemetry.count("dataset/device_bins/hit")
        return self._device_bins_cache[2]

    def device_resident_planes(self, guard: int, npad: int):
        """Resident (F, npad) bin planes for tpu_resident_state, cached on
        the dataset like :meth:`device_bins`: the planes live in ORIGINAL
        row order and never change during training, so serial Boosters
        reuse one device copy across trees instead of re-transposing the
        matrix per call. Keyed on the host array's identity, the version
        token AND the (guard, npad) geometry (part_chunk / part_kernel
        changes move the guard band)."""
        from .obs import telemetry
        from .ops.partition import resident_bin_planes
        ver = getattr(self, "_dev_version", 0)
        cur = getattr(self, "_device_resident_cache", None)
        if cur is None or cur[0] is not self.binned or cur[1] != ver \
                or cur[2] != (guard, npad):
            # no upload bytes counted: the planes derive ON DEVICE from the
            # (already counted) device_bins copy
            telemetry.count("dataset/resident_planes/miss")
            res = resident_bin_planes(self.device_bins(), guard, npad)
            self._device_resident_cache = (self.binned, ver, (guard, npad),
                                           res)
        else:
            telemetry.count("dataset/resident_planes/hit")
        return self._device_resident_cache[3]

    @property
    def num_features(self) -> int:
        return len(self.bin_mappers)

    def feature_num_bins(self) -> np.ndarray:
        return np.array([m.num_bins for m in self.bin_mappers], dtype=np.int32)

    def real_feature_index(self, inner: int) -> int:
        return self.used_feature_indices[inner]

    def inner_feature_index(self, real: int) -> int:
        try:
            return self.used_feature_indices.index(real)
        except ValueError:
            return -1

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def has_bundles(self) -> bool:
        return any(len(g.feature_indices) > 1 for g in self.groups)

    def group_num_bins(self) -> np.ndarray:
        return np.array([g.num_bins for g in self.groups], dtype=np.int32)

    def bundle_maps(self) -> Dict[str, np.ndarray]:
        """Static index maps between feature-bin space and bundle-bin space,
        used by the learner to reconstruct per-feature histogram views from
        bundled columns and to translate routing tables (reference analog:
        FeatureGroup bin offsets + Dataset::FixHistogram, dataset.h:503).

        - proj (F, B): flat index into (num_groups * Bm) for each feature bin
          (meaningless where ``valid`` is False)
        - valid (F, B): feature bin has its own bundle slot (False for the
          shared default bin of multi-bundles and past num_bins)
        - has_rest (F,): feature lives in a multi-feature bundle — its
          default bin must be recovered as parent_total - sum(own slots)
        - dpos (F,): the feature's default bin index
        - map_fb (F, Bm): bundle bin -> this feature's bin (its default bin
          for bundle bins belonging to other sub-features / shared zero)
        - group (F,), offset (F,), nbm1 (F,): routing arithmetic inputs
        """
        F = self.num_features
        B = int(self.feature_num_bins().max()) if F else 1
        Bm = int(self.group_num_bins().max()) if self.groups else 1
        proj = np.zeros((F, B), np.int32)
        valid = np.zeros((F, B), bool)
        has_rest = np.zeros(F, bool)
        dpos = np.zeros(F, np.int32)
        map_fb = np.zeros((F, Bm), np.int32)
        nbm1 = np.zeros(F, np.int32)
        for gid, grp in enumerate(self.groups):
            multi = len(grp.feature_indices) > 1
            for j, off in zip(grp.feature_indices, grp.bin_offsets):
                m = self.bin_mappers[j]
                nb = m.num_bins
                d = m.default_bin if multi else -1
                dpos[j] = m.default_bin
                has_rest[j] = multi
                nbm1[j] = nb - 1
                bb_ids = np.arange(nb)
                if multi:
                    adj = bb_ids - (bb_ids > d)
                    slots = np.where(bb_ids == d, 0, off + adj)
                    proj[j, :nb] = gid * Bm + slots
                    valid[j, :nb] = bb_ids != d
                    map_fb[j, :] = m.default_bin
                    own = np.arange(nb)[bb_ids != d]
                    map_fb[j, off:off + nb - 1] = own
                else:
                    proj[j, :nb] = gid * Bm + bb_ids
                    valid[j, :nb] = True
                    map_fb[j, :min(nb, Bm)] = np.arange(min(nb, Bm))
                    map_fb[j, nb:] = nb - 1
        return dict(proj=proj, valid=valid, has_rest=has_rest, dpos=dpos,
                    map_fb=map_fb, group=self.feature_to_group.astype(np.int32),
                    offset=self.feature_group_offset.astype(np.int32),
                    nbm1=nbm1)


def _resolve_categorical(
    categorical_feature: Union[str, Sequence[Union[int, str]], None],
    num_features: int,
    feature_names: List[str],
) -> List[int]:
    if categorical_feature is None or categorical_feature == "" or categorical_feature == "auto":
        return []
    if isinstance(categorical_feature, str):
        items: List[Any] = [s for s in categorical_feature.split(",") if s]
    else:
        items = list(categorical_feature)
    out: List[int] = []
    for it in items:
        if isinstance(it, str) and not it.lstrip("-").isdigit():
            if it.startswith("name:"):
                it = it[5:]
            if it in feature_names:
                out.append(feature_names.index(it))
            else:
                Log.warning("Unknown categorical feature name: %s", it)
        else:
            out.append(int(it))
    return sorted(set(i for i in out if 0 <= i < num_features))


def construct_dataset(
    X: np.ndarray,
    config: Config,
    *,
    label: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    init_score: Optional[np.ndarray] = None,
    feature_names: Optional[List[str]] = None,
    categorical_feature: Union[str, Sequence[Union[int, str]], None] = None,
    reference: Optional[BinnedDataset] = None,
) -> BinnedDataset:
    """Build a BinnedDataset from a raw feature matrix.

    Reference analog: DatasetLoader::LoadFromFile + Dataset::Construct
    (src/io/dataset_loader.cpp:182, src/io/dataset.cpp:318): sample rows for
    bin finding, fit BinMappers, drop trivial features, bundle (EFB), then
    extract (bin) all rows. When ``reference`` is given, reuse its bin mappers
    (validation sets must share the training set's binning —
    reference: LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:261).
    """
    sparse = _is_sparse(X)
    if not sparse:
        X = np.asarray(X)
    if X.ndim != 2:
        raise ValueError("X must be 2-dimensional, got shape %s" % (X.shape,))
    num_data, num_total = X.shape
    ds = BinnedDataset()
    ds.num_data = num_data
    ds.num_total_features = num_total
    ds.feature_names = feature_names or ["Column_%d" % i for i in range(num_total)]

    if reference is not None:
        ds.used_feature_indices = list(reference.used_feature_indices)
        ds.bin_mappers = reference.bin_mappers
        ds.groups = reference.groups
        ds.feature_to_group = reference.feature_to_group
        ds.feature_group_offset = reference.feature_group_offset
        ds.max_bins_per_feature = reference.max_bins_per_feature
        ds.feature_names = reference.feature_names
        ds.monotone_constraints = reference.monotone_constraints
        ds.feature_penalty = reference.feature_penalty
        ds.binned = _extract_binned(X, ds, nthreads=int(config.num_threads))
        ds.metadata = Metadata(num_data, label, weight, group, init_score)
        if config.linear_tree:
            ds.raw_numeric = _raw_numeric(X, ds)
        return ds

    cat_idx = set(_resolve_categorical(categorical_feature if categorical_feature is not None
                                       else config.categorical_feature,
                                       num_total, ds.feature_names))

    # ---- sampling for bin finding (reference: bin_construct_sample_cnt,
    # dataset_loader.cpp:903 SampleTextDataFromFile) ----
    sample_cnt = min(num_data, int(config.bin_construct_sample_cnt))
    rng = np.random.RandomState(config.data_random_seed)
    if sample_cnt < num_data:
        sample_idx = rng.choice(num_data, size=sample_cnt, replace=False)
        sample_idx.sort()
    else:
        sample_idx = np.arange(num_data)
    if sparse:
        import scipy.sparse as sp
        Xs_csc = sp.csc_matrix(sp.csr_matrix(X)[sample_idx])

        def sample_col(f: int) -> np.ndarray:
            # nonzeros only; find_bin counts the rest as implicit zeros
            return np.asarray(
                Xs_csc.data[Xs_csc.indptr[f]:Xs_csc.indptr[f + 1]], np.float64)

        def sample_nz_mask(f: int) -> np.ndarray:
            mask = np.zeros(sample_cnt, dtype=bool)
            mask[Xs_csc.indices[Xs_csc.indptr[f]:Xs_csc.indptr[f + 1]]] = True
            return mask
    else:
        X_sample = np.asarray(X[sample_idx], dtype=np.float64)

        def sample_col(f: int) -> np.ndarray:
            return X_sample[:, f]

        def sample_nz_mask(f: int) -> np.ndarray:
            col = X_sample[:, f]
            return np.abs(np.nan_to_num(col, nan=1.0)) > 1e-35

    # per-feature max_bin override (reference: max_bin_by_feature, config.h)
    max_bin_by_feature = config.max_bin_by_feature
    min_split_data = 0
    if config.feature_pre_filter:
        # features that cannot split given min_data_in_leaf are trivial
        min_split_data = int(config.min_data_in_leaf * sample_cnt / max(1, num_data))

    forced_bounds = _load_forced_bins(config.forcedbins_filename, num_total)

    mappers: List[BinMapper] = []
    used: List[int] = []
    for f in range(num_total):
        mb = (max_bin_by_feature[f] if f < len(max_bin_by_feature) else config.max_bin)
        m = find_bin(
            sample_col(f),
            sample_cnt,
            mb,
            config.min_data_in_bin,
            bin_type=BIN_CATEGORICAL if f in cat_idx else BIN_NUMERICAL,
            use_missing=config.use_missing,
            zero_as_missing=config.zero_as_missing,
            min_split_data=min_split_data,
            forced_bounds=forced_bounds.get(f),
        )
        if m.is_trivial:
            continue
        mappers.append(m)
        used.append(f)
    if not mappers:
        Log.warning("All features are trivial; training will produce constant predictions")
    ds.bin_mappers = mappers
    ds.used_feature_indices = used

    # ---- EFB bundling decision (reference: dataset.cpp:239 FastFeatureBundling) ----
    ds.groups, ds.feature_to_group, ds.feature_group_offset = _make_groups(
        sample_nz_mask, sample_cnt, used, mappers,
        # bundles are capped at 256 bins so the matrix stays uint8; with
        # max_bin > 256 single features already need uint16 — skip bundling
        enable_bundle=config.enable_bundle and config.max_bin <= 256,
        max_conflict_rate=float(getattr(config, "max_conflict_rate", 0.0)),
    )
    ds.max_bins_per_feature = max((g.num_bins for g in ds.groups), default=1)

    # monotone constraints / feature penalties mapped to used features
    if config.monotone_constraints:
        mc = np.zeros(len(used), dtype=np.int8)
        for i, f in enumerate(used):
            if f < len(config.monotone_constraints):
                mc[i] = np.sign(config.monotone_constraints[f])
        if np.any(mc != 0):
            ds.monotone_constraints = mc
    if config.feature_contri:
        fp = np.ones(len(used), dtype=np.float32)
        for i, f in enumerate(used):
            if f < len(config.feature_contri):
                fp[i] = config.feature_contri[f]
        ds.feature_penalty = fp

    ds.binned = _extract_binned(X, ds, nthreads=int(config.num_threads))
    ds.metadata = Metadata(num_data, label, weight, group, init_score)
    if config.linear_tree:
        ds.raw_numeric = _raw_numeric(X, ds)
    return ds


def _make_groups(
    sample_nz_mask,
    sample_cnt: int,
    used: List[int],
    mappers: List[BinMapper],
    *,
    enable_bundle: bool,
    max_conflict_rate: float = 0.0,
) -> tuple:
    """Greedy exclusive-feature bundling (reference: Dataset::FindGroups,
    src/io/dataset.cpp:100 — greedy graph coloring by conflict count).

    Only sufficiently sparse features are bundling candidates; dense features
    get their own group. Conflicts are counted on the sample: two features
    conflict on a row if both are away from their most-frequent (default) bin.
    A bundle's total bin count is capped at 256 so the training matrix stays
    uint8 (the partitioned learner's packed-row layout).
    """
    n = len(used)
    sparse_ok = [enable_bundle and m.sparse_rate >= 0.8 and m.bin_type == BIN_NUMERICAL
                 for m in mappers]
    if not any(sparse_ok):
        # dense data: every feature is its own group, skip the conflict scan
        groups = [FeatureGroupInfo([i], [0], mappers[i].num_bins) for i in range(n)]
        return (groups, np.arange(n, dtype=np.int32), np.zeros(n, dtype=np.int32))
    groups: List[FeatureGroupInfo] = []
    feature_to_group = np.zeros(n, dtype=np.int32)
    feature_offset = np.zeros(n, dtype=np.int32)

    # nonzero masks on the sample for bundling candidates
    bundles: List[List[int]] = []
    bundle_masks: List[np.ndarray] = []
    bundle_bins: List[int] = []
    max_conflicts = int(max_conflict_rate * sample_cnt)
    for i in range(n):
        if not sparse_ok[i]:
            continue
        nz = sample_nz_mask(used[i])
        nb = mappers[i].num_bins - 1    # bins it adds to a bundle
        placed = False
        for b, mask in enumerate(bundle_masks):
            if len(bundles[b]) >= 255 or bundle_bins[b] + nb > 256:
                continue
            conflicts = int(np.count_nonzero(mask & nz))
            if conflicts <= max_conflicts:
                bundles[b].append(i)
                bundle_masks[b] = mask | nz
                bundle_bins[b] += nb
                placed = True
                break
        if not placed:
            bundles.append([i])
            bundle_masks.append(nz)
            bundle_bins.append(1 + nb)

    # only multi-feature bundles count as bundles
    multi = [b for b in bundles if len(b) > 1]
    in_multi = set(i for b in multi for i in b)

    gid = 0
    for b in multi:
        offsets: List[int] = []
        # bin 0 of the bundle = "all defaults"; each sub-feature's non-default
        # bins occupy [off, off + (num_bins-1))
        off = 1
        for i in b:
            offsets.append(off)
            off += mappers[i].num_bins - 1
        groups.append(FeatureGroupInfo([int(i) for i in b], offsets, off))
        for i, o in zip(b, offsets):
            feature_to_group[i] = gid
            feature_offset[i] = o
        gid += 1
    for i in range(n):
        if i in in_multi:
            continue
        groups.append(FeatureGroupInfo([i], [0], mappers[i].num_bins))
        feature_to_group[i] = gid
        feature_offset[i] = 0
        gid += 1
    return groups, feature_to_group, feature_offset


def _bundle_bin(m: BinMapper, bins: np.ndarray, offset: int) -> np.ndarray:
    """Map a sub-feature's bins into its bundle range.

    Non-default bins keep their order in [offset, offset + num_bins - 1);
    the default (most-frequent/zero) bin maps to the bundle's shared bin 0
    (reference: FeatureGroup bin offsets, include/LightGBM/feature_group.h:25).
    """
    d = m.default_bin
    adj = bins - (bins > d).astype(bins.dtype)  # remove the default slot
    return np.where(bins == d, 0, offset + adj)


def _extract_binned(X, ds: BinnedDataset,
                    nthreads: int = 0) -> np.ndarray:
    """Bin every row into the (num_data, num_groups) bundled matrix.

    EFB (reference: Dataset::Construct + FeatureGroup::PushData,
    src/io/dataset.cpp:318): each group is one column; multi-feature
    bundles share the column with per-sub-feature bin offsets, so histogram
    and partition cost scale with the BUNDLED column count. Accepts dense
    numpy or scipy sparse input; sparse stays O(nnz).
    """
    num_data = X.shape[0]
    max_bins = max((g.num_bins for g in ds.groups), default=1)
    dtype = np.uint8 if max_bins <= 256 else np.uint16
    out = np.zeros((num_data, len(ds.groups)), dtype=dtype)
    sparse = _is_sparse(X)
    if sparse:
        import scipy.sparse as sp
        Xc = sp.csc_matrix(X)
    else:
        Xv = np.asarray(X, dtype=np.float64)

    def fill_group(gid: int) -> None:
        grp = ds.groups[gid]
        multi = len(grp.feature_indices) > 1
        for j, off in zip(grp.feature_indices, grp.bin_offsets):
            m = ds.bin_mappers[j]
            real = ds.used_feature_indices[j]
            if sparse:
                col = Xc.getcol(real)
                rows = col.indices
                vals = np.asarray(col.data, dtype=np.float64)
                zero_bin = int(m.value_to_bin(np.zeros(1))[0])
                b_nz = m.value_to_bin(vals)
                if multi:
                    bb = _bundle_bin(m, b_nz, off)
                    base = int(_bundle_bin(m, np.asarray([zero_bin]), off)[0])
                    if base != 0:
                        out[:, gid] = base
                    nz = bb != base
                    out[rows[nz], gid] = bb[nz].astype(dtype)
                else:
                    out[:, gid] = zero_bin
                    out[rows, gid] = b_nz.astype(dtype)
            else:
                b = m.value_to_bin(Xv[:, real])
                if multi:
                    bb = _bundle_bin(m, b, off)
                    nz = bb != 0
                    out[nz, gid] = bb[nz].astype(dtype)
                else:
                    out[:, gid] = b.astype(dtype)

    # Dense single-feature numerical groups bin through the native threaded
    # applier (native/binning.cpp — the reference's OpenMP PushData analog,
    # src/io/dataset.cpp:318); numpy's searchsorted holds the GIL, costing
    # ~4 s alone at 2M x 28. Bundled/categorical/u16 groups keep the exact
    # numpy path.
    done = set()
    if not sparse and dtype == np.uint8:
        from .io_native import apply_bins_native
        specs = []
        for gid, grp in enumerate(ds.groups):
            if len(grp.feature_indices) != 1:
                continue
            j = grp.feature_indices[0]
            m = ds.bin_mappers[j]
            if m.bin_type != BIN_NUMERICAL:
                continue
            specs.append((ds.used_feature_indices[j], m.upper_bounds,
                          m.missing_type, m.missing_bin, gid))
        if specs and apply_bins_native(Xv, specs, out, nthreads=nthreads):
            done = {s[4] for s in specs}
    for gid in range(len(ds.groups)):
        if gid not in done:
            fill_group(gid)
    return out


def _load_forced_bins(filename: str, num_features: int) -> Dict[int, list]:
    """Forced bin upper bounds per feature (reference:
    dataset_loader.cpp DatasetLoader::GetForcedBins; JSON list of
    {"feature": i, "bin_upper_bound": [...]})."""
    if not filename:
        return {}
    import json as _json
    import os as _os
    if not _os.path.exists(filename):
        Log.warning("forcedbins file %s not found", filename)
        return {}
    with open(filename) as f:
        spec = _json.load(f)
    out: Dict[int, list] = {}
    for item in spec:
        fi = int(item.get("feature", -1))
        if 0 <= fi < num_features:
            out[fi] = [float(v) for v in item.get("bin_upper_bound", [])]
    return out


def _raw_numeric(X, ds: BinnedDataset) -> np.ndarray:
    """Raw values of the used features for linear-leaf fitting (reference:
    dataset.cpp raw_data_ kept when linear_tree). Indexed by REAL feature."""
    n = X.shape[0]
    total = ds.num_total_features
    out = np.zeros((n, total), dtype=np.float32)
    if _is_sparse(X):
        import scipy.sparse as sp
        Xc = sp.csc_matrix(X)
        for f in ds.used_feature_indices:
            col = Xc.getcol(f)
            out[col.indices, f] = col.data
    else:
        Xv = np.asarray(X, dtype=np.float32)
        for f in ds.used_feature_indices:
            out[:, f] = Xv[:, f]
    return out


def _is_sparse(X) -> bool:
    return hasattr(X, "tocsc") and hasattr(X, "indptr") or \
        type(X).__module__.startswith("scipy.sparse")


# ---------------------------------------------------------------------------
# Binary dataset cache (reference: Dataset::SaveBinaryFile, dataset.h:441 +
# DatasetLoader::LoadFromBinFile, dataset_loader.cpp:314): the binned matrix,
# bin mappers, bundling structure and metadata round-trip through one npz so
# repeated runs skip text parsing and bin finding entirely.
# ---------------------------------------------------------------------------

def save_binned(ds: BinnedDataset, filename: str) -> None:
    import json as _json

    mappers = [dict(
        num_bins=m.num_bins, bin_type=m.bin_type, missing_type=m.missing_type,
        is_trivial=m.is_trivial, upper_bounds=list(map(float, m.upper_bounds)),
        categories=list(map(int, m.categories)), default_bin=m.default_bin,
        most_freq_bin=m.most_freq_bin, missing_bin=m.missing_bin,
        sparse_rate=m.sparse_rate, min_value=m.min_value, max_value=m.max_value,
    ) for m in ds.bin_mappers]
    groups = [dict(feature_indices=g.feature_indices,
                   bin_offsets=g.bin_offsets, num_bins=g.num_bins)
              for g in ds.groups]
    meta = dict(
        num_data=ds.num_data, num_total_features=ds.num_total_features,
        used_feature_indices=list(ds.used_feature_indices),
        feature_names=list(ds.feature_names), mappers=mappers, groups=groups,
    )
    md = ds.metadata
    empty = np.array([])
    np.savez_compressed(
        filename,
        header=np.frombuffer(_json.dumps(meta).encode(), dtype=np.uint8),
        binned=ds.binned,
        feature_to_group=ds.feature_to_group,
        feature_group_offset=ds.feature_group_offset,
        label=md.label if md.label is not None else empty,
        weight=md.weight if md.weight is not None else empty,
        init_score=md.init_score if md.init_score is not None else empty,
        query_boundaries=md.query_boundaries
        if md.query_boundaries is not None else empty,
        monotone=ds.monotone_constraints
        if ds.monotone_constraints is not None else empty,
        penalty=ds.feature_penalty if ds.feature_penalty is not None else empty,
    )


def load_binned(filename: str) -> BinnedDataset:
    import json as _json

    z = np.load(filename, allow_pickle=False)
    meta = _json.loads(bytes(z["header"]).decode())
    ds = BinnedDataset()
    ds.num_data = int(meta["num_data"])
    ds.num_total_features = int(meta["num_total_features"])
    ds.used_feature_indices = [int(i) for i in meta["used_feature_indices"]]
    ds.feature_names = list(meta["feature_names"])
    for md in meta["mappers"]:
        m = BinMapper()
        m.num_bins = int(md["num_bins"])
        m.bin_type = int(md["bin_type"])
        m.missing_type = int(md["missing_type"])
        m.is_trivial = bool(md["is_trivial"])
        m.upper_bounds = np.asarray(md["upper_bounds"], np.float64)
        m.categories = np.asarray(md["categories"], np.int64)
        m.default_bin = int(md["default_bin"])
        m.most_freq_bin = int(md["most_freq_bin"])
        m.missing_bin = int(md["missing_bin"])
        m.sparse_rate = float(md["sparse_rate"])
        m.min_value = float(md["min_value"])
        m.max_value = float(md["max_value"])
        ds.bin_mappers.append(m)
    ds.groups = [FeatureGroupInfo([int(i) for i in g["feature_indices"]],
                                  [int(o) for o in g["bin_offsets"]],
                                  int(g["num_bins"]))
                 for g in meta["groups"]]
    ds.binned = z["binned"]
    ds.feature_to_group = z["feature_to_group"]
    ds.feature_group_offset = z["feature_group_offset"]
    ds.max_bins_per_feature = max((g.num_bins for g in ds.groups), default=1)

    def opt(key):
        a = z[key]
        return a if a.size else None

    ds.metadata = Metadata(ds.num_data)
    ds.metadata.label = opt("label")
    ds.metadata.weight = opt("weight")
    ds.metadata.init_score = opt("init_score")
    qb = opt("query_boundaries")
    if qb is not None:
        ds.metadata.query_boundaries = qb.astype(np.int64)
        qid = np.zeros(ds.num_data, dtype=np.int32)
        for i in range(len(qb) - 1):
            qid[qb[i]:qb[i + 1]] = i
        ds.metadata.query_id = qid
    ds.monotone_constraints = opt("monotone")
    ds.feature_penalty = opt("penalty")
    return ds
