"""Single-source-of-truth parameter registry.

TPU-native equivalent of the reference's ``struct Config`` + generated alias
table (reference: include/LightGBM/config.h:34, src/io/config_auto.cpp,
helpers/parameter_generator.py). One dataclass holds every typed parameter;
``ALIASES`` maps every accepted alias to its canonical name
(reference: config.h:1087 ParameterAlias::KeyAliasTransform); ``Config.set``
applies a params dict with alias resolution and type coercion
(reference: src/io/config.cpp:196 Config::Set).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from .utils.log import Log

TaskType = str  # train | predict | convert_model | refit | save_binary | serve


def _parse_int_list(v: Any) -> List[int]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(x) for x in str(v).split(",") if x != ""]


def _parse_float_list(v: Any) -> List[float]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [float(x) for x in v]
    return [float(x) for x in str(v).split(",") if x != ""]


def _parse_str_list(v: Any) -> List[str]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    return [s for s in str(v).split(",") if s != ""]


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "y", "+"):
        return True
    if s in ("false", "0", "no", "n", "-"):
        return False
    raise ValueError("cannot parse bool from %r" % (v,))


@dataclass
class Config:
    # ---- core (reference: config.h "Core Parameters") ----
    task: TaskType = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data_sample_strategy: str = "bagging"  # bagging | goss
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"  # serial | feature | data | voting
    num_threads: int = 0
    device_type: str = "tpu"  # cpu | gpu | cuda | tpu — cpu/gpu/cuda accepted, all run the JAX backend
    seed: Optional[int] = None
    deterministic: bool = False

    # ---- learning control (reference: config.h "Learning Control Parameters") ----
    force_col_wise: bool = False
    force_row_wise: bool = False
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    extra_trees: bool = False
    extra_seed: int = 6
    early_stopping_round: int = 0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: List[int] = field(default_factory=list)
    monotone_constraints_method: str = "basic"
    monotone_penalty: float = 0.0
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = field(default_factory=list)
    path_smooth: float = 0.0
    interaction_constraints: str = ""
    verbosity: int = 1
    input_model: str = ""
    output_model: str = "LightGBM_model.txt"
    convert_model: str = "gbdt_prediction.cpp"
    convert_model_language: str = "cpp"   # cpp | json
    saved_feature_importance_type: int = 0
    snapshot_freq: int = -1
    # write the obs.Telemetry snapshot (JSON) here after the CLI task
    # finishes; empty = no dump (also settable as --dump-telemetry PATH).
    # The CLI additionally dumps to this path on SIGUSR1, and — while
    # task=serve runs — every telemetry_dump_interval_s seconds, so a
    # hung server can still be inspected from outside.
    dump_telemetry: str = ""
    telemetry_dump_interval_s: float = 0.0   # 0 = no periodic serve dump

    # ---- span tracing (obs_trace: host-side flight recorder) ----
    # off = no spans (zero-cost); on = train phases + serve chain;
    # serve_only = just the http/batcher/session request chain
    trace_spans: str = "off"
    trace_buffer_events: int = 65536  # flight recorder ring capacity
    # write the Chrome trace-event JSON (Perfetto-loadable) here after
    # the CLI task finishes; empty = no dump (also --dump-trace PATH,
    # and on SIGUSR2 while the task runs)
    dump_trace: str = ""

    # ---- device-cost observability (obs_device / obs_ledger) ----
    # capture Compiled.cost_analysis()/memory_analysis() per tracked-jit
    # compile into the telemetry device_cost section. Costs one extra AOT
    # backend compile per (entry point, signature) AT COMPILE TIME only;
    # steady-state training/serving pays nothing (the compile-budget
    # tests pin 0 new compiles on warm runs either way).
    obs_device_cost: bool = True
    # training health watchdog: per-block device-side isfinite reduction
    # over grads/scores. off (default) builds zero device ops; warn logs
    # and counts obs/nonfinite_*; raise aborts training on the block the
    # blow-up happened.
    obs_check_finite: str = "off"   # off | warn | raise
    # while task=serve runs, sample device.memory_stats() into the
    # hbm/* gauges every this many seconds (0 = boundary samples only;
    # CPU backends without memory stats degrade to a counted no-op)
    obs_hbm_sample_interval_s: float = 0.0
    # append one JSONL record per train/serve run (config fingerprint,
    # machine identity, resolved auto knobs, telemetry + device-cost
    # snapshot) and pre-resolve tpu_* auto knobs from the latest matching
    # (machine, dataset-shape, config) entry on the next run
    obs_ledger: bool = False
    obs_ledger_path: str = "lgbtpu_ledger.jsonl"

    # ---- linear tree ----
    linear_tree: bool = False
    linear_lambda: float = 0.0
    # leaf fit path: auto (device when a TPU backend is up, host otherwise)
    # | off (host NumPy oracle) | on (batched device solve, any backend)
    linear_device: str = "auto"

    # ---- dataset (reference: config.h "IO Parameters / Dataset") ----
    max_bin: int = 255
    max_bin_by_feature: List[int] = field(default_factory=list)
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    is_enable_sparse: bool = True
    enable_bundle: bool = True
    use_missing: bool = True
    zero_as_missing: bool = False
    feature_pre_filter: bool = True
    pre_partition: bool = False
    two_round: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: Union[str, List[int]] = ""
    forcedbins_filename: str = ""
    save_binary: bool = False

    # ---- predict ----
    start_iteration_predict: int = 0
    num_iteration_predict: int = -1
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    predict_disable_shape_check: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    output_result: str = "LightGBM_predict_result.txt"

    # ---- serving (task=serve: lightgbm_tpu/serve/ HTTP endpoint) ----
    serve_host: str = "127.0.0.1"
    serve_port: int = 8080            # 0 = bind an ephemeral port
    serve_max_batch_rows: int = 8192  # MicroBatcher coalescing cap (rows)
    serve_max_wait_ms: float = 2.0    # MicroBatcher first-request deadline
    serve_buckets: List[int] = field(default_factory=list)  # [] = default
    #   shape-bucket ladder (serve.session.DEFAULT_BUCKETS)
    serve_warmup: bool = True         # pre-compile the ladder on startup
    # admission control (serve.batcher.MicroBatcher backpressure):
    serve_max_queue_rows: int = 0     # cap on queued-but-undispatched rows
    #   (0 = unbounded). Overflow behavior is serve_overload.
    serve_overload: str = "shed"      # shed (reject at submit -> HTTP 429)
    #   | block (submitters wait for queue space; drains preserve order)
    serve_models: List[str] = field(default_factory=list)  # multi-tenant:
    #   extra "model_id=path" entries served next to input_model ("default")
    # per-tenant fairness (serve.batcher weighted-fair dequeue):
    serve_tenant_quota_rows: int = 0  # cap on any ONE tenant's queued rows
    #   (0 = no per-tenant cap; over-quota requests shed/block per
    #   serve_overload while other tenants keep being admitted)
    serve_tenant_weights: List[str] = field(default_factory=list)
    #   "tenant=weight" fair-share weights (unlisted tenants weigh 1.0)
    serve_dispatch: str = "continuous"  # continuous (standing dispatch loop,
    #   new requests join the next in-flight tile) | coalesce (wait up to
    #   serve_max_wait_ms for company, then launch — the pre-ISSUE-16 loop)

    # ---- online training (task=serve + online_train: lightgbm_tpu/online/) ----
    online_train: bool = False        # run an OnlineTrainer per served model
    online_mode: str = "refit"        # refit (frozen structure, leaf values
    #   re-estimated from ingested labels) | continue (init_model training)
    online_trigger_rows: int = 2048   # retrain once this many rows buffered
    online_trigger_interval_s: float = 0.0  # also retrain every N s (0 = off)
    online_buffer_rows: int = 65536   # bounded ingest buffer (drop-oldest)
    online_shadow_rows: int = 4096    # sliding window of recent labeled
    #   traffic the candidate is shadow-scored against before promotion
    online_promote_threshold: float = 1.0  # promote iff candidate_loss <=
    #   threshold * current_loss on the shadow window (1.0 = "not worse")
    online_min_rows: int = 64         # never train on fewer buffered rows
    online_continue_rounds: int = 10  # boosting rounds per continue-mode run
    online_shadow_decay: float = 1.0  # per-row exponential decay toward the
    #   oldest shadow row when scoring (1.0 = uniform window, current
    #   behavior; 0<d<1 weights recent traffic more)
    online_promote_patience: int = 1  # promotion hysteresis: candidate must
    #   win this many CONSECUTIVE shadow evaluations before the swap
    online_rollback_threshold: float = 0.0  # post-promotion live watch:
    #   auto-rollback when promoted live loss > threshold * displaced
    #   model's on traffic ingested AFTER the swap (0 = watch off)
    online_rollback_min_rows: int = 64  # fresh labeled rows required
    #   before the live watch renders its verdict

    # ---- fleet (task=serve --fleet: lightgbm_tpu/fleet/) ----
    fleet_dir: str = ""               # durable store root ("" = fleet off):
    #   <fleet_dir>/<model_id>/{events.jsonl, models/v*.txt}
    fleet_role: str = "trainer"       # trainer (ingest + train + publish)
    #   | replica (serve-only, watch the store and hot-swap publishes)
    fleet_poll_interval_s: float = 0.5  # replica publish-poll cadence
    fleet_replay: bool = True         # replay the event log on trainer boot
    #   (rows past the consumed watermark re-enter the training buffer,
    #   older rows only the shadow window)
    fleet_lease_ttl_s: float = 0.0    # trainer failover lease ttl (0 = one
    #   immortal trainer). >0: boot in standby, train only while holding
    #   the store lease; heartbeat every ttl/3; epoch-fenced publishes
    fleet_compact_bytes: int = 0      # compact events.jsonl once it exceeds
    #   this size (0 = never): snapshot watermark/streak + truncate the
    #   replayed prefix, replay stays bit-identical
    fleet_keep_artifacts: int = 0     # retention at compaction: keep only
    #   this many newest publish artifacts (0 = keep all)
    fleet_url: str = ""               # replica only: poll a remote trainer's
    #   /fleet endpoints instead of a shared-filesystem fleet_dir
    fleet_timeout_s: float = 5.0      # remote transport per-request timeout
    fleet_backoff_max_s: float = 10.0  # cap for replica poll backoff and
    #   remote transport retry backoff
    fleet_heartbeat_interval_s: float = 0.0  # federation cadence: every
    #   node (trainer/standby/replica) records a compact heartbeat to the
    #   store (remote replicas POST /fleet/heartbeat) for the
    #   /fleet/status + fleetctl rollup. 0 = heartbeats off
    fleet_urls: List[str] = field(default_factory=list)  # control plane:
    #   MULTIPLE fleet endpoints. replica: liveness-ranked failover
    #   (capped cooldown, switch on failure, exactly one version bump
    #   per publish regardless of endpoint); trainer: the first url is
    #   the store host the remote write surface (lease/publish/ingest/
    #   compact over HTTP) talks to — no shared filesystem needed
    fleet_forward_ingest: bool = False  # relay labeled traffic hitting
    #   this node (no online trainer here) to the current lease
    #   holder's advertised endpoint: leader_hint redirects, bounded
    #   X-Fleet-Hops chain, 503 when no leader is known
    fleet_snapshot_rows: int = 0      # compaction snapshot mode (0 = off):
    #   write at least this many retained ingest rows into one versioned
    #   snapshot blob instead of log lines, so a cold standby bootstraps
    #   from snapshot + tail instead of a full replay

    # ---- objective (reference: config.h "Objective Parameters") ----
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    lambdarank_truncation_level: int = 30
    lambdarank_norm: bool = True
    label_gain: List[float] = field(default_factory=list)
    objective_seed: int = 5

    # ---- metric ----
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1
    auc_mu_weights: List[float] = field(default_factory=list)

    # ---- network (reference: config.h "Network Parameters"; here: jax.distributed) ----
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # ---- device ----
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    num_gpu: int = 1
    # TPU-specific knobs (no reference analog):
    tpu_rows_per_chunk: int = 65536  # rows per device histogram chunk
    tpu_iter_block: int = 10         # boosting iterations fused per device launch
    tree_builder: str = "auto"       # auto|partition|dense: partitioned
    #   leaf-contiguous builder (O(child) histograms) vs round-1 dense
    #   (O(N) masked histograms; required when max_bin > 256)
    tpu_part_chunk: int = 0          # rows per partition compaction chunk
    #   (0 = auto: 1024 for the fused pallas kernel, 2048 for the XLA path)
    tpu_partition_kernel: str = "auto"  # auto|pallas|xla: fused Pallas DMA
    #   partition kernel (TPU only) vs the portable XLA op pipeline
    tpu_hist_chunk: int = 0          # rows per segment-histogram chunk
    #   (0 = auto: 4096 for narrow matrices, 1024 for wide ones)
    tpu_hist_kernel: str = "auto"    # auto|pallas|xla: in-VMEM Pallas
    #   segment-histogram kernel (TPU, F <= 64) vs the XLA einsum loop
    tpu_hist_lo: int = 0             # hi/lo split width of the histogram
    #   einsum factorization (0 = auto: 4 for narrow matrices, 8 for wide;
    #   all widths are bit-identical — this is a pure layout knob)
    tpu_hist_scatter: bool = True    # data-parallel: reduce-scatter
    #   histograms by feature-group block + owned-feature search + split
    #   argmax-sync (vs full psum + replicated search)
    tpu_hist_precision: str = "hilo"  # hilo (~2^-17 rel, bf16 pair) |
    #   bf16 (single bf16 grads) | int8 (quantized training)
    tpu_work_layout: str = "auto"    # auto|rows|planes: training work
    #   buffer layout. rows = (2, Npad, W) row-major; planes = transposed
    #   (2, W, Npad) feature-major planes — each 128-lane tile carries 128
    #   rows of ONE byte column (no dead lanes) and the root histogram is
    #   folded into the pack pass. auto: planes on TPU at row widths
    #   <= 256 B, rows elsewhere. Both layouts grow bit-identical trees.
    tpu_resident_state: str = "auto"  # auto|off|on: resident permuted
    #   training state (planes layout only). The bin planes live ONCE in a
    #   (F, Npad) resident buffer in original row order; the per-split
    #   partition moves only a slim 17-plane payload (route byte, i32
    #   row-index byte planes, g/h/c bytes) and segment histograms gather
    #   the bin planes through the permuted row-index plane. Cuts partition
    #   HBM traffic ~(F+12)/17-fold (~2.4x at F=28, ~8.8x at F=137) and
    #   grows bit-identical trees. auto: on when the resolved layout is
    #   planes on a TPU backend; on: force (requires a planes-capable
    #   config — errors with tpu_work_layout=rows or int8 histograms).
    tpu_split_kernel: str = "auto"   # auto|off|on: one-kernel split — ONE
    #   pallas_call per split running partition + smaller-child histogram
    #   + split scan as sequential phases (planes/resident layouts only),
    #   vs the three-launch chain. Bit-identical trees; the three-launch
    #   path stays as the parity oracle. auto: off everywhere until the
    #   fused kernel is validated on real Mosaic (scripts/split_bisect.py);
    #   on: force where structurally eligible (serial training, planes
    #   family, no feature bundling / CEGB / intermediate monotone).
    tpu_forest_kernel: str = "auto"  # auto|off|on: forest-at-once serving —
    #   ONE pallas_call per row tile holding the (tile, trees) traversal
    #   front in VMEM over BIN-space split-major node tables (ops/forest),
    #   vs the per-depth-gather predict. Bit-identical scores; the
    #   per-depth path stays the serving default and the parity oracle.
    #   auto: off everywhere until the kernel is validated on real Mosaic
    #   (scripts/forest_bisect.py); on: force where structurally eligible
    #   (booster trained in-process or with a constructed train_set, node
    #   tables within the VMEM budget).
    tpu_goss_compact: str = "auto"   # auto|off|on: GOSS row compaction —
    #   after the sampler emits the inbag mask, a device sort-by-inbag +
    #   static-shape slice packs the surviving rows into a compact work
    #   set sized ceil((top_rate+other_rate)*N) (+ a 4-sigma binomial
    #   margin), so planes pack / partition / histograms / split scan all
    #   run over the sample instead of N. The dense-mask path stays
    #   verbatim as the bit-parity oracle (and as the in-graph fallback
    #   for GOSS warmup iterations and margin overflow). auto: off
    #   everywhere until scripts/goss_bisect.py validates the win on
    #   hardware; on: force where eligible (GOSS sampling active, serial
    #   training, not int8 — the stochastic-rounding draws are
    #   row-position seeded).
    tpu_hist_mxu: str = "auto"       # auto|off|on: one-hot MXU histogram —
    #   a Pallas kernel (rows layout) that builds per-chunk one-hots in
    #   VMEM and feeds the MXU via matmul, serving both the f32 hi/lo-16
    #   path and the use_quantized_grad int8 path (int8 x int8 -> i32
    #   accumulation) from one kernel body. The segment-histogram einsum
    #   stays verbatim as the bit-parity oracle. auto: off everywhere
    #   until scripts/hist_mxu_bisect.py validates the MXU lowering on
    #   hardware; on: force where eligible (rows layout, pallas
    #   partition widths, hist chunk % 32 == 0).
    use_quantized_grad: bool = False  # int8 stochastic gradient quantization
    #   (LightGBM 4.x quantized training analog; rows per leaf <= ~16M)


    def __post_init__(self) -> None:
        # direct-constructor path must validate/normalize too (goss -> gbdt+goss)
        self._check()

    def set(self, params: Dict[str, Any]) -> "Config":
        """Apply a params dict (with aliases) onto this config in place.

        Mirrors reference Config::Set (src/io/config.cpp:196): alias
        resolution first, then typed assignment; unknown keys warn.
        """
        resolved = resolve_aliases(params)
        fields = {f.name: f for f in dataclasses.fields(self)}
        for key, value in resolved.items():
            if key not in fields:
                Log.warning("Unknown parameter: %s", key)
                continue
            f = fields[key]
            try:
                setattr(self, key, _coerce(f, value))
            except (TypeError, ValueError) as exc:
                Log.fatal('Parameter %s cannot be set to %r: %s', key, value, exc)
        self._check()
        return self

    def _check(self) -> None:
        """Constraint checks (reference: src/io/config.cpp Config::CheckParamConflict)."""
        if self.num_leaves < 2:
            Log.fatal("num_leaves must be >= 2, got %d", self.num_leaves)
        if self.max_bin < 2:
            Log.fatal("max_bin must be >= 2, got %d", self.max_bin)
        if not 0.0 < self.bagging_fraction <= 1.0:
            Log.fatal("bagging_fraction must be in (0, 1]")
        if not 0.0 < self.feature_fraction <= 1.0:
            Log.fatal("feature_fraction must be in (0, 1]")
        if self.boosting == "goss":
            # reference treats boosting=goss as gbdt + goss sampling
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        if self.boosting == "rf":
            if self.bagging_freq <= 0 or self.bagging_fraction >= 1.0 or self.bagging_fraction <= 0.0:
                Log.fatal("RF mode requires 0 < bagging_fraction < 1 and bagging_freq > 0")
        if self.data_sample_strategy == "goss" and self.top_rate + self.other_rate > 1.0:
            Log.fatal("GOSS requires top_rate + other_rate <= 1.0")
        if self.objective in ("multiclass", "multiclassova", "softmax", "ova") and self.num_class <= 1:
            Log.fatal("num_class must be > 1 for multiclass objectives")
        if self.tpu_rows_per_chunk < 1:
            Log.fatal("tpu_rows_per_chunk must be >= 1, got %d",
                      self.tpu_rows_per_chunk)
        if self.tpu_iter_block < 1:
            Log.fatal("tpu_iter_block must be >= 1, got %d",
                      self.tpu_iter_block)
        if self.tpu_part_chunk < 0:
            Log.fatal("tpu_part_chunk must be >= 0 (0 = auto), got %d",
                      self.tpu_part_chunk)
        if self.tpu_partition_kernel not in ("auto", "pallas", "xla"):
            Log.fatal("tpu_partition_kernel must be auto, pallas or xla; "
                      "got %s", self.tpu_partition_kernel)
        if self.tpu_hist_chunk < 0:
            Log.fatal("tpu_hist_chunk must be >= 0 (0 = auto), got %d",
                      self.tpu_hist_chunk)
        if self.tpu_hist_precision not in ("hilo", "bf16", "int8"):
            Log.fatal("tpu_hist_precision must be hilo, bf16 or int8; "
                      "got %s", self.tpu_hist_precision)
        if self.tpu_hist_lo not in (0, 2, 4, 8, 16):
            Log.fatal("tpu_hist_lo must be one of 0 (auto), 2, 4, 8, 16; "
                      "got %d", self.tpu_hist_lo)
        if self.tpu_hist_kernel not in ("auto", "pallas", "xla"):
            Log.fatal("tpu_hist_kernel must be auto, pallas or xla; got %s",
                      self.tpu_hist_kernel)
        if self.tpu_work_layout not in ("auto", "rows", "planes"):
            Log.fatal("tpu_work_layout must be auto, rows or planes; got %s",
                      self.tpu_work_layout)
        if self.tpu_resident_state not in ("auto", "off", "on"):
            Log.fatal("tpu_resident_state must be auto, off or on; got %s",
                      self.tpu_resident_state)
        if self.tpu_split_kernel not in ("auto", "off", "on"):
            Log.fatal("tpu_split_kernel must be auto, off or on; got %s",
                      self.tpu_split_kernel)
        if self.tpu_forest_kernel not in ("auto", "off", "on"):
            Log.fatal("tpu_forest_kernel must be auto, off or on; got %s",
                      self.tpu_forest_kernel)
        if self.tpu_goss_compact not in ("auto", "off", "on"):
            Log.fatal("tpu_goss_compact must be auto, off or on; got %s",
                      self.tpu_goss_compact)
        if self.tpu_hist_mxu not in ("auto", "off", "on"):
            Log.fatal("tpu_hist_mxu must be auto, off or on; got %s",
                      self.tpu_hist_mxu)
        if self.serve_dispatch not in ("continuous", "coalesce"):
            Log.fatal("serve_dispatch must be continuous or coalesce; "
                      "got %s", self.serve_dispatch)
        if not 0 <= self.serve_port <= 65535:
            Log.fatal("serve_port must be in [0, 65535], got %d",
                      self.serve_port)
        if self.serve_max_batch_rows < 1:
            Log.fatal("serve_max_batch_rows must be >= 1, got %d",
                      self.serve_max_batch_rows)
        if self.serve_max_wait_ms < 0:
            Log.fatal("serve_max_wait_ms must be >= 0, got %g",
                      self.serve_max_wait_ms)
        if any(b < 1 for b in self.serve_buckets):
            Log.fatal("serve_buckets must be positive row counts")
        if self.serve_max_queue_rows < 0:
            Log.fatal("serve_max_queue_rows must be >= 0 (0 = unbounded), "
                      "got %d", self.serve_max_queue_rows)
        if self.serve_overload not in ("shed", "block"):
            Log.fatal("serve_overload must be shed or block; got %s",
                      self.serve_overload)
        for spec in self.serve_models:
            if "=" not in spec or not spec.split("=", 1)[0].strip() \
                    or not spec.split("=", 1)[1].strip():
                Log.fatal("serve_models entries must be model_id=path, "
                          "got %r", spec)
        if self.online_mode not in ("refit", "continue"):
            Log.fatal("online_mode must be refit or continue; got %s",
                      self.online_mode)
        if self.online_trigger_rows < 1:
            Log.fatal("online_trigger_rows must be >= 1, got %d",
                      self.online_trigger_rows)
        if self.online_trigger_interval_s < 0:
            Log.fatal("online_trigger_interval_s must be >= 0, got %g",
                      self.online_trigger_interval_s)
        if self.online_buffer_rows < 1:
            Log.fatal("online_buffer_rows must be >= 1, got %d",
                      self.online_buffer_rows)
        if self.online_shadow_rows < 1:
            Log.fatal("online_shadow_rows must be >= 1, got %d",
                      self.online_shadow_rows)
        if self.online_promote_threshold < 0:
            Log.fatal("online_promote_threshold must be >= 0, got %g",
                      self.online_promote_threshold)
        if self.online_min_rows < 1:
            Log.fatal("online_min_rows must be >= 1, got %d",
                      self.online_min_rows)
        if self.online_continue_rounds < 1:
            Log.fatal("online_continue_rounds must be >= 1, got %d",
                      self.online_continue_rounds)
        if not 0.0 < self.online_shadow_decay <= 1.0:
            Log.fatal("online_shadow_decay must be in (0, 1], got %g",
                      self.online_shadow_decay)
        if self.online_promote_patience < 1:
            Log.fatal("online_promote_patience must be >= 1, got %d",
                      self.online_promote_patience)
        if self.online_rollback_threshold < 0:
            Log.fatal("online_rollback_threshold must be >= 0 (0 = live "
                      "watch off), got %g", self.online_rollback_threshold)
        if self.online_rollback_min_rows < 1:
            Log.fatal("online_rollback_min_rows must be >= 1, got %d",
                      self.online_rollback_min_rows)
        if self.serve_tenant_quota_rows < 0:
            Log.fatal("serve_tenant_quota_rows must be >= 0 (0 = no "
                      "per-tenant cap), got %d", self.serve_tenant_quota_rows)
        for spec in self.serve_tenant_weights:
            name, _, w = spec.partition("=")
            try:
                ok = bool(name.strip()) and float(w) > 0
            except ValueError:
                ok = False
            if not ok:
                Log.fatal("serve_tenant_weights entries must be "
                          "tenant=positive_weight, got %r", spec)
        if self.fleet_role not in ("trainer", "replica"):
            Log.fatal("fleet_role must be trainer or replica; got %s",
                      self.fleet_role)
        if self.fleet_poll_interval_s <= 0:
            Log.fatal("fleet_poll_interval_s must be > 0, got %g",
                      self.fleet_poll_interval_s)
        if self.fleet_dir == "" and self.fleet_url == "" \
                and not self.fleet_urls and self.fleet_role == "replica":
            Log.fatal("fleet_role=replica requires a fleet_dir (shared "
                      "filesystem), fleet_url or fleet_urls (remote "
                      "endpoints) to watch")
        if self.fleet_dir != "" and (self.fleet_url != ""
                                     or self.fleet_urls):
            Log.fatal("fleet_dir and fleet_url(s) are mutually exclusive "
                      "(one store per node)")
        if self.fleet_url != "" and self.fleet_urls:
            Log.fatal("pass fleet_url or fleet_urls, not both")
        if self.fleet_url != "" and self.fleet_role != "replica":
            Log.fatal("fleet_url is replica-only; a remote TRAINER "
                      "needs fleet_urls (the control-plane write "
                      "surface)")
        if self.fleet_urls and self.fleet_role == "trainer" \
                and len(self.fleet_urls) != 1:
            Log.fatal("fleet_role=trainer takes exactly one fleet url "
                      "(the store host), got %d", len(self.fleet_urls))
        if len(set(u.rstrip("/") for u in self.fleet_urls)) \
                != len(self.fleet_urls):
            Log.fatal("fleet_urls contains duplicates: %s",
                      ",".join(self.fleet_urls))
        if self.fleet_forward_ingest and self.fleet_dir == "" \
                and not self.fleet_urls and self.fleet_url == "":
            Log.fatal("fleet_forward_ingest needs a fleet store "
                      "(fleet_dir) or fleet url(s) to resolve the "
                      "lease holder from")
        if self.fleet_snapshot_rows < 0:
            Log.fatal("fleet_snapshot_rows must be >= 0 (0 disables "
                      "snapshot compaction), got %d",
                      self.fleet_snapshot_rows)
        if self.fleet_snapshot_rows > 0 and self.fleet_compact_bytes == 0:
            Log.fatal("fleet_snapshot_rows needs fleet_compact_bytes > 0 "
                      "(snapshots are written at compaction time)")
        if self.fleet_lease_ttl_s < 0:
            Log.fatal("fleet_lease_ttl_s must be >= 0, got %g",
                      self.fleet_lease_ttl_s)
        if self.fleet_compact_bytes < 0 or self.fleet_keep_artifacts < 0:
            Log.fatal("fleet_compact_bytes/fleet_keep_artifacts must be "
                      ">= 0")
        if self.fleet_timeout_s <= 0:
            Log.fatal("fleet_timeout_s must be > 0, got %g",
                      self.fleet_timeout_s)
        if self.fleet_heartbeat_interval_s < 0:
            Log.fatal("fleet_heartbeat_interval_s must be >= 0 "
                      "(0 disables heartbeats), got %g",
                      self.fleet_heartbeat_interval_s)
        if self.fleet_backoff_max_s < self.fleet_poll_interval_s:
            Log.fatal("fleet_backoff_max_s must be >= "
                      "fleet_poll_interval_s, got %g < %g",
                      self.fleet_backoff_max_s, self.fleet_poll_interval_s)
        if self.linear_device not in ("auto", "off", "on"):
            Log.fatal("linear_device must be auto, off or on; got %s",
                      self.linear_device)
        if self.trace_spans not in ("off", "on", "serve_only"):
            Log.fatal("trace_spans must be off, on or serve_only; got %s",
                      self.trace_spans)
        if self.trace_buffer_events < 1:
            Log.fatal("trace_buffer_events must be >= 1, got %d",
                      self.trace_buffer_events)
        if self.telemetry_dump_interval_s < 0:
            Log.fatal("telemetry_dump_interval_s must be >= 0, got %g",
                      self.telemetry_dump_interval_s)
        if self.obs_check_finite not in ("off", "warn", "raise"):
            Log.fatal("obs_check_finite must be off, warn or raise; got %s",
                      self.obs_check_finite)
        if self.obs_hbm_sample_interval_s < 0:
            Log.fatal("obs_hbm_sample_interval_s must be >= 0, got %g",
                      self.obs_hbm_sample_interval_s)
        if self.obs_ledger and not self.obs_ledger_path:
            Log.fatal("obs_ledger=true requires a non-empty obs_ledger_path")
        warned = getattr(self, "_noop_warned", None)
        if warned is None:
            warned = set()
            object.__setattr__(self, "_noop_warned", warned)
        for name, (default, reason) in NOOP_PARAMS.items():
            if name in warned:
                continue
            if getattr(self, name) != default:
                warned.add(name)
                Log.warning("%s is accepted but has no effect here: %s",
                            name, reason)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        cfg = cls()
        if params:
            cfg.set(params)
        return cfg

    def clone(self) -> "Config":
        return dataclasses.replace(self)


def _coerce(f: dataclasses.Field, value: Any) -> Any:
    t = str(f.type)
    if t == "int":
        return int(value)
    if t == "float":
        return float(value)
    if t == "bool":
        return _parse_bool(value)
    if t in ("str", "TaskType"):
        return str(value)
    if t == "Optional[int]":
        return None if value is None or value == "" else int(value)
    if t == "List[int]":
        return _parse_int_list(value)
    if t == "List[float]":
        return _parse_float_list(value)
    if t == "List[str]":
        return _parse_str_list(value)
    return value


# Alias -> canonical map. Mirrors the generated table in the reference
# (src/io/config_auto.cpp:6-180 "parameter2aliases").
ALIASES: Dict[str, str] = {}  # graftlint: disable=module-mutable-state -- filled once at import by _alias(), read-only after


def _alias(canonical: str, *names: str) -> None:
    for n in names:
        ALIASES[n] = canonical


_alias("config", "config_file")
_alias("task", "task_type")
_alias("objective", "objective_type", "app", "application", "loss")
_alias("boosting", "boosting_type", "boost")
_alias("data", "train", "train_data", "train_data_file", "data_filename")
_alias("valid", "test", "valid_data", "valid_data_file", "test_data", "test_data_file", "valid_filenames")
_alias("num_iterations", "num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
       "num_rounds", "nrounds", "num_boost_round", "n_estimators", "max_iter")
_alias("learning_rate", "shrinkage_rate", "eta")
_alias("num_leaves", "num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes")
_alias("tree_learner", "tree", "tree_type", "tree_learner_type")
_alias("num_threads", "num_thread", "nthread", "nthreads", "n_jobs")
_alias("device_type", "device")
_alias("seed", "random_seed", "random_state")
_alias("max_depth", "max_tree_depth")
_alias("min_data_in_leaf", "min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf")
_alias("min_sum_hessian_in_leaf", "min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian",
       "min_child_weight")
_alias("bagging_fraction", "sub_row", "subsample", "bagging")
_alias("pos_bagging_fraction", "pos_sub_row", "pos_subsample", "pos_bagging")
_alias("neg_bagging_fraction", "neg_sub_row", "neg_subsample", "neg_bagging")
_alias("bagging_freq", "subsample_freq")
_alias("bagging_seed", "bagging_fraction_seed")
_alias("feature_fraction", "sub_feature", "colsample_bytree")
_alias("feature_fraction_bynode", "sub_feature_bynode", "colsample_bynode")
_alias("extra_trees", "extra_tree")
_alias("early_stopping_round", "early_stopping_rounds", "early_stopping", "n_iter_no_change")
_alias("lambda_l1", "reg_alpha", "l1_regularization")
_alias("lambda_l2", "reg_lambda", "lambda", "l2_regularization")
_alias("min_gain_to_split", "min_split_gain")
_alias("drop_rate", "rate_drop")
_alias("top_k", "topk")
_alias("monotone_constraints", "mc", "monotone_constraint", "monotonic_cst")
_alias("monotone_constraints_method", "monotone_constraining_method", "mc_method")
_alias("monotone_penalty", "monotone_splits_penalty", "ms_penalty", "mc_penalty")
_alias("feature_contri", "feature_contrib", "fc", "fp", "feature_penalty")
_alias("forcedsplits_filename", "fs", "forced_splits_filename", "forced_splits_file", "forced_splits")
_alias("verbosity", "verbose")
_alias("input_model", "model_input", "model_in")
_alias("output_model", "model_output", "model_out")
_alias("snapshot_freq", "save_period")
_alias("max_bin", "max_bins")
_alias("bin_construct_sample_cnt", "subsample_for_bin")
_alias("data_random_seed", "data_seed")
_alias("is_enable_sparse", "is_sparse", "enable_sparse", "sparse")
_alias("enable_bundle", "is_enable_bundle", "bundle")
_alias("pre_partition", "is_pre_partition")
_alias("two_round", "two_round_loading", "use_two_round_loading")
_alias("header", "has_header")
_alias("label_column", "label")
_alias("weight_column", "weight")
_alias("group_column", "group", "group_id", "query_column", "query", "query_id")
_alias("ignore_column", "ignore_feature", "blacklist")
_alias("categorical_feature", "cat_feature", "categorical_column", "cat_column")
_alias("save_binary", "is_save_binary", "is_save_binary_file")
_alias("predict_raw_score", "is_predict_raw_score", "predict_rawscore", "raw_score")
_alias("predict_leaf_index", "is_predict_leaf_index", "leaf_index")
_alias("predict_contrib", "is_predict_contrib", "contrib")
_alias("output_result", "predict_result", "prediction_result", "predict_name",
       "prediction_name", "pred_name", "name_pred")
_alias("num_class", "num_classes")
_alias("is_unbalance", "unbalance", "unbalanced_sets")
_alias("scale_pos_weight", "scale_pos_weight")
_alias("sigmoid", "sigmoid")
_alias("metric", "metrics", "metric_types")
_alias("metric_freq", "output_freq")
_alias("is_provide_training_metric", "training_metric", "is_training_metric", "train_metric")
_alias("eval_at", "ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")
_alias("num_machines", "num_machine")
_alias("local_listen_port", "local_port", "port")
_alias("machine_list_filename", "machine_list_file", "machine_list", "mlist")
_alias("machines", "workers", "nodes")



# Parameters the reference implements but that have no effect in this
# framework's TPU design. Each maps to (default, reason). Setting one to a
# non-default value warns ONCE with the reason (same contract as the
# `machines` warning) — nothing is silently ignored; the audit test
# (tests/test_param_audit.py) enforces that every config field is either
# consumed by the code or listed here.
NOOP_PARAMS: Dict[str, tuple] = {
    "force_col_wise": (False, "the TPU histogram layout is fixed (dense "
                       "bundled columns on the MXU one-hot path)"),
    "force_row_wise": (False, "the TPU histogram layout is fixed"),
    "is_enable_sparse": (True, "sparse inputs are EFB-bundled into the "
                         "dense matrix at construction; storage is dense"),
    "histogram_pool_size": (-1.0, "the histogram pool is leaf-count sized "
                            "in HBM; there is no host-side pool to cap"),
    "deterministic": (False, "training is already deterministic for a "
                      "fixed config on a fixed topology"),
    "num_gpu": (1, "the JAX TPU backend is used; gpu_* options select the "
                "reference's OpenCL/CUDA code paths"),
    "gpu_platform_id": (-1, "the JAX TPU backend is used"),
    "gpu_device_id": (-1, "the JAX TPU backend is used"),
    "gpu_use_dp": (False, "the JAX TPU backend is used; histograms "
                   "accumulate in float32 (tpu_hist_precision)"),
    "device_type": ("tpu", "cpu/gpu/cuda select the reference's backends; "
                    "every value runs the JAX backend here"),
    "local_listen_port": (12400, "the reference's socket cluster port; "
                          "multi-host runs bootstrap via "
                          "parallel.distributed.init_distributed"),
    "time_out": (120, "the reference's socket timeout; jax.distributed "
                 "manages connection timeouts"),
    "machine_list_filename": ("", "the reference's socket cluster file; "
                              "use init_distributed(coordinator_address=...)"),
}

def resolve_aliases(params: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve aliases; canonical names win over aliases on conflict
    (mirrors python-package _ConfigAliases precedence, basic.py:258)."""
    out: Dict[str, Any] = {}
    canonical_present = set()
    for key in params:
        if key in ALIASES and ALIASES[key] != key:
            continue
        canonical_present.add(key)
    for key, value in params.items():
        canon = ALIASES.get(key, key)
        if canon != key and canon in canonical_present:
            continue  # explicit canonical setting wins
        if canon in out and key in ALIASES and ALIASES[key] != key:
            continue  # first alias wins among aliases
        out[canon] = value
    return out


# objective aliases (reference: src/objective/objective_function.cpp:15-53 name matching)
OBJECTIVE_ALIASES = {
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "cross_entropy": "cross_entropy",
    "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "none",
    "null": "none",
    "custom": "none",
    "na": "none",
}
