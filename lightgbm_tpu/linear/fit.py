"""Batched device fit of ridge linear leaf models.

Replaces the host oracle's per-leaf ``np.linalg.solve`` loop
(``boosting._fit_linear_tree``) with one device program per tree: every
leaf's normal equations ``-(Z^T H Z + lambda I') beta = Z^T g`` are
accumulated simultaneously by chunked one-hot contractions — per chunk,
the weighted outer products ``(C, k+1, k+1)`` flatten to
``(C, (k+1)^2)`` and a ``(L, C) x (C, (k+1)^2)`` matmul segment-sums them
into the stacked Gram matrices — then solved with a single batched
``jnp.linalg.solve``. Both contractions are MXU-shaped; nothing scales
with the leaf count on the host side.

Parity contract with the oracle (tests/test_linear_device.py):

- only branch-path NUMERICAL features enter a leaf's model;
- rows with NaN in any of the leaf's features are excluded from its
  normal equations (weight and z zeroed — identical contributions);
- ridge ``linear_lambda`` lands on feature diagonals only, never the
  intercept;
- a leaf is fit only when it has features and at least ``k+1`` total AND
  NaN-free rows; everything else keeps the plain constant output (the
  host path's ``continue``); a non-finite batched-solve row (the host
  path's ``LinAlgError``) falls back the same way.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import telemetry, track_jit
from ..obs_trace import tracer

#: rows per accumulation step: big enough to keep the (L, C) x (C, k^2)
#: contractions bandwidth-bound, small enough that the (C, (k+1)^2)
#: flattened outer products stay far from VMEM pressure
_CHUNK = 8192


def leaf_feature_table(tree, ds, num_leaves_cap: int
                       ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Per-leaf branch-path numerical features as padded index + mask
    tables (Lp, kp): the same feature filter as the host oracle
    (categorical and pre-filtered columns excluded). Feature axis pads to
    a power of two and the leaf axis to ``num_leaves_cap`` so the fit
    kernel compiles a handful of signatures per run instead of one per
    tree shape. None when no leaf has any usable feature."""
    from ..ops.binning import BIN_CATEGORICAL

    per_leaf = []
    kmax = 0
    for l in range(tree.num_leaves):
        feats = [int(f) for f in tree.branch_features(l)
                 if ds.inner_feature_index(int(f)) >= 0
                 and ds.bin_mappers[ds.inner_feature_index(int(f))]
                 .bin_type != BIN_CATEGORICAL]
        per_leaf.append(feats)
        kmax = max(kmax, len(feats))
    if kmax == 0:
        return None
    kp = 1
    while kp < kmax:
        kp *= 2
    Lp = max(int(num_leaves_cap), tree.num_leaves)
    feat_idx = np.zeros((Lp, kp), np.int32)
    feat_mask = np.zeros((Lp, kp), bool)
    for l, feats in enumerate(per_leaf):
        feat_idx[l, :len(feats)] = feats
        feat_mask[l, :len(feats)] = True
    return feat_idx, feat_mask


def fit_leaves_impl(X: jax.Array, row_leaf: jax.Array, g: jax.Array,
                    h: jax.Array, feat_idx: jax.Array, feat_mask: jax.Array,
                    lam: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """All leaves' ridge solves in one program.

    ``X`` (N, F) raw feature values (NaN kept), ``row_leaf`` (N,) i32 leaf
    assignment, ``g``/``h`` (N,) gradient/hessian channels (out-of-bag
    rows carry zeros and drop out of the sums), ``feat_idx``/``feat_mask``
    (L, k) per-leaf feature tables. Returns ``beta`` (L, k+1) with the
    intercept last and ``fit_ok`` (L,) — leaves whose solution is valid.
    """
    L, km = feat_idx.shape
    kp1 = km + 1
    n = row_leaf.shape[0]
    f32 = jnp.float32

    fi = jnp.take(feat_idx, row_leaf, axis=0)              # (N, km)
    fm = jnp.take(feat_mask, row_leaf, axis=0)             # (N, km)
    z = jnp.take_along_axis(X.astype(f32), fi, axis=1)     # (N, km)
    nan = jnp.isnan(z)
    valid = jnp.logical_not(jnp.any(nan & fm, axis=1)).astype(f32)
    z = jnp.where(fm & jnp.logical_not(nan), z, f32(0))
    wh = h.astype(f32) * valid
    wg = g.astype(f32) * valid

    pad = (-n) % _CHUNK
    if pad:
        # pad rows route to leaf slot L: their one-hot row is all-zero, so
        # they fall out of every sum including the row counts
        z = jnp.concatenate([z, jnp.zeros((pad, km), f32)])
        row_leaf = jnp.concatenate(
            [row_leaf, jnp.full((pad,), L, row_leaf.dtype)])
        wh = jnp.concatenate([wh, jnp.zeros((pad,), f32)])
        wg = jnp.concatenate([wg, jnp.zeros((pad,), f32)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), f32)])
    nc = (n + pad) // _CHUNK
    iota = jnp.arange(L, dtype=row_leaf.dtype)

    def dot(a, b):
        return jax.lax.dot(a, b, precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=f32)

    def one_chunk(carry, xs):
        A, B, cnt, vcnt = carry
        z_c, rl_c, wh_c, wg_c, v_c = xs
        zk = jnp.concatenate([z_c, jnp.ones((_CHUNK, 1), f32)], axis=1)
        oh = (rl_c[:, None] == iota[None, :]).astype(f32)  # (C, L)
        outer = (zk[:, :, None] * zk[:, None, :]) * wh_c[:, None, None]
        A = A + dot(oh.T, outer.reshape(_CHUNK, kp1 * kp1))
        B = B + dot(oh.T, zk * wg_c[:, None])
        cnt = cnt + jnp.sum(oh, axis=0)
        vcnt = vcnt + dot(oh.T, v_c[:, None])[:, 0]
        return (A, B, cnt, vcnt), None

    carry0 = (jnp.zeros((L, kp1 * kp1), f32), jnp.zeros((L, kp1), f32),
              jnp.zeros((L,), f32), jnp.zeros((L,), f32))
    xs = (z.reshape(nc, _CHUNK, km), row_leaf.reshape(nc, _CHUNK),
          wh.reshape(nc, _CHUNK), wg.reshape(nc, _CHUNK),
          valid.reshape(nc, _CHUNK))
    (A, B, cnt, vcnt), _ = jax.lax.scan(one_chunk, carry0, xs)

    A = A.reshape(L, kp1, kp1)
    # ridge on real feature dims only (never the intercept); padded dims
    # carry all-zero rows/columns, so a unit diagonal keeps the batched
    # solve nonsingular there while their zero RHS still yields beta == 0
    diag = jnp.concatenate(
        [jnp.where(feat_mask, lam.astype(f32), f32(1)),
         jnp.zeros((L, 1), f32)], axis=1)
    A = A + diag[:, :, None] * jnp.eye(kp1, dtype=f32)[None, :, :]
    beta = -jnp.linalg.solve(A, B[:, :, None])[:, :, 0]
    k_l = jnp.sum(feat_mask.astype(f32), axis=1)
    fit_ok = (k_l > f32(0)) & (cnt >= k_l + f32(1)) & (vcnt >= k_l + f32(1))
    fit_ok = fit_ok & jnp.all(jnp.isfinite(beta), axis=1)
    return beta, fit_ok


fit_leaves = track_jit("linear/fit_leaves", jax.jit(fit_leaves_impl))


def _device_raw(ds) -> jax.Array:
    """Device-resident raw numeric matrix, uploaded once per dataset (the
    resident-planes pattern applied to the linear fit input)."""
    arr = getattr(ds, "_device_raw_numeric", None)
    if arr is None:
        arr = ds._device_raw_numeric = jnp.asarray(ds.raw_numeric,
                                                   jnp.float32)
    return arr


def fit_linear_leaves(tree, ds, row_leaf, ghc, *, lam: float, rate: float,
                      num_leaves_cap: int) -> None:
    """Device counterpart of the ``_fit_linear_tree`` per-leaf loop:
    prepares the feature tables on host, runs the batched fit, and writes
    the surviving leaves' ``leaf_features``/``leaf_coeff``/``leaf_const``
    back onto the tree in ONE device->host transfer. Leaves the tree's
    constant outputs untouched wherever the fit declined — identical
    fallbacks to the oracle."""
    tables = leaf_feature_table(tree, ds, num_leaves_cap)
    if tables is None:
        return
    feat_idx, feat_mask = tables
    telemetry.count("linear/device_fits")
    with telemetry.timed_observe("linear/fit_ms"), \
            tracer.span("linear/fit", domain="train",
                        leaves=int(tree.num_leaves)):
        beta, fit_ok = fit_leaves(
            _device_raw(ds), row_leaf, ghc[:, 0], ghc[:, 1],
            jnp.asarray(feat_idx, jnp.int32),
            jnp.asarray(feat_mask, jnp.bool_),
            jnp.asarray(lam, jnp.float32))
        beta_h = np.asarray(beta, np.float64)
        ok_h = np.asarray(fit_ok)
    solved = 0
    for l in range(tree.num_leaves):
        if not ok_h[l]:
            continue
        m = feat_mask[l]
        coefs = beta_h[l, :-1][m]
        keep = np.abs(coefs) > 1e-35
        tree.leaf_features[l] = feat_idx[l, m].astype(np.int64)[keep]
        tree.leaf_coeff[l] = coefs[keep] * rate
        tree.leaf_const[l] = float(beta_h[l, -1]) * rate
        solved += 1
    telemetry.count("linear/leaves_solved", solved)
    attempted = int(feat_mask[:tree.num_leaves].any(axis=1).sum())
    telemetry.count("linear/solve_fallback", attempted - solved)
