"""Slot-ordered per-leaf coefficient tables for device linear predict.

``ops.predict`` routes rows to leaf SLOTS (``Tree.to_split_arrays``
order); linear prediction then needs the slot's constant term, feature
indices and coefficients. The tables here pad every tree of a pack to the
ensemble's max leaf-feature count so one program shape serves the whole
model; non-linear trees ride along with ``const == value`` and an
all-false mask, which evaluates to exactly the plain leaf output.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def linear_pack_arrays(trees: List, arrs: List[dict],
                       value_of_slot: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray, bool]:
    """(const_of_slot (T, L) f32, coeff (T, L, km) f32, coeff_feat
    (T, L, km) i32, coeff_mask (T, L, km) bool, has_linear) for the packed
    trees; ``arrs`` are their ``to_split_arrays`` dicts (for the
    slot -> leaf mapping) and ``value_of_slot`` the already-built constant
    table that non-linear slots inherit."""
    T, L = value_of_slot.shape
    has_linear = any(getattr(t, "is_linear", False) for t in trees)
    km = 1
    for t in trees:
        if getattr(t, "is_linear", False):
            for feats in t.leaf_features.values():
                km = max(km, len(feats))
    const_of_slot = value_of_slot.astype(np.float32).copy()
    coeff = np.zeros((T, L, km), np.float32)
    coeff_feat = np.zeros((T, L, km), np.int32)
    coeff_mask = np.zeros((T, L, km), bool)
    if not has_linear:
        return const_of_slot, coeff, coeff_feat, coeff_mask, False
    for ti, (t, a) in enumerate(zip(trees, arrs)):
        if not getattr(t, "is_linear", False):
            continue
        leaf_of_slot = a["leaf_of_slot"]
        n_slots = len(a["slot"]) + 1 if t.num_leaves > 1 else 1
        for s in range(n_slots):
            leaf = int(leaf_of_slot[s]) if t.num_leaves > 1 else 0
            const_of_slot[ti, s] = t.leaf_const[leaf]
            feats = t.leaf_features.get(leaf)
            if feats is None or len(feats) == 0:
                continue
            k = len(feats)
            coeff_feat[ti, s, :k] = feats
            coeff[ti, s, :k] = t.leaf_coeff[leaf]
            coeff_mask[ti, s, :k] = True
    return const_of_slot, coeff, coeff_feat, coeff_mask, True


def linear_values_by_row(X: jax.Array, slots: jax.Array, tp,
                         num_leaves: int, chunk: int = 65536) -> jax.Array:
    """Per-row linear-leaf outputs for one packed tree: slot one-hot
    contractions gather const/coeff/feature tables (the
    ``leaf_values_by_row`` pattern — no per-row element gathers on the
    small tables), then one feature gather + dot evaluates the models.
    Rows with NaN in any of their leaf's features fall back to the plain
    leaf value, exactly as ``Tree.linear_predict`` on host."""
    n = slots.shape[0]
    f32 = jnp.float32
    iota = jnp.arange(num_leaves, dtype=slots.dtype)
    value = tp.value_of_slot.astype(f32)[:, None]
    const = tp.const_of_slot.astype(f32)[:, None]
    # feature indices round-trip exactly through a 0/1 f32 contraction
    # (column indices are far below 2^24)
    featf = tp.coeff_feat.astype(f32)
    maskf = tp.coeff_mask.astype(f32)
    Xf = X.astype(f32)

    def dot(a, b):
        return jax.lax.dot(a, b, precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=f32)

    def one(xs):
        s_c, X_c = xs
        oh = (s_c[:, None] == iota[None, :]).astype(f32)   # (C, L)
        base = dot(oh, value)[:, 0]
        cst = dot(oh, const)[:, 0]
        cf = dot(oh, tp.coeff)                             # (C, km)
        fi = dot(oh, featf).astype(jnp.int32)
        cm = dot(oh, maskf) > f32(0.5)
        z = jnp.take_along_axis(X_c, fi, axis=1)
        nan = jnp.isnan(z)
        nanrow = jnp.any(nan & cm, axis=1)
        zz = jnp.where(cm & jnp.logical_not(nan), z, f32(0))
        contrib = jnp.sum(zz * cf, axis=1)
        return jnp.where(nanrow, base, cst + contrib)

    if n <= chunk:
        # serving buckets sit at or under one chunk — no padding there
        return one((slots, Xf))
    pad = (-n) % chunk
    if pad:
        slots = jnp.pad(slots, (0, pad))
        Xf = jnp.pad(Xf, ((0, pad), (0, 0)))
    out = jax.lax.map(one, (slots.reshape(-1, chunk),
                            Xf.reshape(-1, chunk, Xf.shape[1])))
    return out.reshape(-1)[:n]
