"""TPU-native piecewise-linear leaf trees (arXiv:1802.05640).

Per-leaf ridge normal equations are the one GBDT extension that is
matmul-shaped, so this package keeps the whole linear-leaf life cycle on
device:

- :mod:`fit` — after a tree's leaves are final, accumulate ALL leaves'
  Gram matrices/RHS at once with chunked one-hot contractions (MXU
  matmuls, no per-leaf host loop) and solve them as one batched
  ``jnp.linalg.solve``. The host NumPy loop in
  ``boosting._fit_linear_tree`` stays as the parity oracle behind
  ``linear_device=auto|off|on``.
- :mod:`pack` — slot-ordered per-leaf coefficient tables riding inside
  ``ops.predict.PackedSplits`` so device predict (and the serve/ bucket
  ladder) evaluates linear leaves as a leaf-indexed coefficient gather
  plus a feature dot.
"""
from .fit import fit_linear_leaves
from .pack import linear_pack_arrays, linear_values_by_row

__all__ = ["fit_linear_leaves", "linear_pack_arrays", "linear_values_by_row"]
