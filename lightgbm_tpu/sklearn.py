"""scikit-learn estimator wrappers.

Equivalent of the reference sklearn API (reference:
python-package/lightgbm/sklearn.py:343 LGBMModel, :809 LGBMRegressor,
:835 LGBMClassifier, :956 LGBMRanker).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .callback import early_stopping as early_stopping_cb, log_evaluation
from .engine import train as _train
from .utils.log import LightGBMError

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.preprocessing import LabelEncoder
    _SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover
    _SKLEARN_INSTALLED = False

    class BaseEstimator:  # type: ignore
        pass

    class ClassifierMixin:  # type: ignore
        pass

    class RegressorMixin:  # type: ignore
        pass

    class LabelEncoder:  # type: ignore
        def fit(self, y):
            self.classes_ = np.unique(y)
            return self

        def transform(self, y):
            return np.searchsorted(self.classes_, y)

        def fit_transform(self, y):
            return self.fit(y).transform(y)

        def inverse_transform(self, y):
            return self.classes_[np.asarray(y, dtype=np.int64)]


class LGBMModel(BaseEstimator):
    """Base estimator (reference: sklearn.py:343)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs: Any) -> None:
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep) if _SKLEARN_INSTALLED else {
            k: getattr(self, k) for k in (
                "boosting_type num_leaves max_depth learning_rate n_estimators "
                "subsample_for_bin objective class_weight min_split_gain "
                "min_child_weight min_child_samples subsample subsample_freq "
                "colsample_bytree reg_alpha reg_lambda random_state n_jobs "
                "importance_type").split()}
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _train_params(self) -> Dict[str, Any]:
        params = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self.objective or self._default_objective(),
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1,
        }
        if self.random_state is not None:
            params["seed"] = self.random_state
        params.update(self._other_params)
        return params

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None,
            callbacks: Optional[List[Callable]] = None) -> "LGBMModel":
        params = self._train_params()
        if eval_metric:
            params["metric"] = eval_metric if isinstance(eval_metric, list) \
                else [eval_metric]
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score)
        valid_sets, valid_names = [], []
        if eval_set:
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    vw = eval_sample_weight[i] if eval_sample_weight else None
                    vg = eval_group[i] if eval_group else None
                    valid_sets.append(train_set.create_valid(
                        vx, label=vy, weight=vw, group=vg))
                valid_names.append(eval_names[i] if eval_names and
                                   i < len(eval_names) else "valid_%d" % i)
        self._evals_result = {}
        from .callback import record_evaluation
        callbacks = list(callbacks or [])
        callbacks.append(record_evaluation(self._evals_result))
        self._Booster = _train(params, train_set,
                               num_boost_round=self.n_estimators,
                               valid_sets=valid_sets, valid_names=valid_names,
                               callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._n_features = np.asarray(X).shape[1] if hasattr(X, "shape") else \
            len(X[0])
        return self

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted")
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted")
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        return self._n_features


class LGBMRegressor(LGBMModel, RegressorMixin):
    """(reference: sklearn.py:809)"""

    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(LGBMModel, ClassifierMixin):
    """(reference: sklearn.py:835)"""

    def _default_objective(self) -> str:
        return "binary"

    def fit(self, X, y, **kwargs):
        self._le = LabelEncoder().fit(y)
        y_enc = self._le.transform(y)
        self._classes = self._le.classes_
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            if not self.objective or self.objective in ("binary",):
                self.objective = "multiclass"
            self._other_params["num_class"] = self._n_classes
        eval_set = kwargs.get("eval_set")
        if eval_set:
            kwargs["eval_set"] = [(vx, self._le.transform(vy))
                                  for vx, vy in eval_set]
        return super().fit(X, y_enc, **kwargs)

    def predict(self, X, raw_score=False, **kwargs):
        result = super().predict(X, raw_score=raw_score, **kwargs)
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return result
        if self._n_classes > 2:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result > 0.5).astype(np.int64)
        return self._le.inverse_transform(idx)

    def predict_proba(self, X, **kwargs) -> np.ndarray:
        result = super().predict(X, **kwargs)
        if self._n_classes > 2:
            return result
        return np.column_stack([1.0 - result, result])

    @property
    def classes_(self) -> np.ndarray:
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    """(reference: sklearn.py:956)"""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise LightGBMError("Ranker needs group information")
        return super().fit(X, y, group=group, **kwargs)
