"""SHAP feature contributions for tree ensembles.

Equivalent of the reference's TreeSHAP implementation
(reference: src/io/tree.cpp TreeSHAP / PredictContrib, based on Lundberg &
Lee's exact tree SHAP with EXPECTED-value path attribution). Host
implementation; per-row recursion over each tree's paths.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .tree import Tree


class _PathElem:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index, zero_fraction, one_fraction, pweight):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElem], zero_fraction, one_fraction, feature_index):
    path.append(_PathElem(feature_index, zero_fraction, one_fraction,
                          1.0 if len(path) == 0 else 0.0))
    n = len(path) - 1
    for i in range(n - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (n + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (n - i) / (n + 1)


def _unwind_path(path: List[_PathElem], path_index):
    n = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[n].pweight
    for i in range(n - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (n + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (n - i) / (n + 1)
        else:
            path[i].pweight = path[i].pweight * (n + 1) / (zero_fraction * (n - i))
    for i in range(path_index, n):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_sum(path: List[_PathElem], path_index):
    n = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[n].pweight
    total = 0.0
    for i in range(n - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (n + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * (n - i) / (n + 1)
        else:
            total += path[i].pweight / (zero_fraction * (n - i) / (n + 1))
    return total


def _tree_shap_row(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
                   path: List[_PathElem], parent_zero: float, parent_one: float,
                   parent_feature: int) -> None:
    path = [
        _PathElem(p.feature_index, p.zero_fraction, p.one_fraction, p.pweight)
        for p in path]
    _extend_path(path, parent_zero, parent_one, parent_feature)
    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, len(path)):
            w = _unwound_sum(path, i)
            phi[path[i].feature_index] += w * (path[i].one_fraction -
                                               path[i].zero_fraction) \
                * tree.leaf_value[leaf]
        return
    f = int(tree.split_feature[node])
    go_left = bool(tree._decide(node, np.asarray([x[f]]))[0])
    hot = tree.left_child[node] if go_left else tree.right_child[node]
    cold = tree.right_child[node] if go_left else tree.left_child[node]
    w_node = _node_weight(tree, node)
    w_hot = _child_weight(tree, hot)
    w_cold = _child_weight(tree, cold)
    incoming_zero, incoming_one = 1.0, 1.0
    path_index = -1
    for i in range(1, len(path)):
        if path[i].feature_index == f:
            path_index = i
            break
    if path_index >= 0:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind_path(path, path_index)
    _tree_shap_row(tree, x, phi, hot, path,
                   w_hot / w_node * incoming_zero, incoming_one, f)
    _tree_shap_row(tree, x, phi, cold, path,
                   w_cold / w_node * incoming_zero, 0.0, f)


def _node_weight(tree: Tree, node: int) -> float:
    if node < 0:
        return max(float(tree.leaf_count[~node]), 1e-10)
    return max(float(tree.internal_count[node]), 1e-10)


def _child_weight(tree: Tree, child: int) -> float:
    return _node_weight(tree, child)


def _expected_value(tree: Tree, node: int = 0) -> float:
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    if node < 0:
        return float(tree.leaf_value[~node])
    wl = _node_weight(tree, tree.left_child[node])
    wr = _node_weight(tree, tree.right_child[node])
    tot = wl + wr
    return (wl * _expected_value(tree, tree.left_child[node]) +
            wr * _expected_value(tree, tree.right_child[node])) / tot


def tree_shap_contribs(gbdt, X: np.ndarray, num_iteration=-1) -> np.ndarray:
    """(n, F+1) contributions per class, concatenated over classes like the
    reference's PredictContrib layout (c_api PredictForMat contrib)."""
    X = np.asarray(X, dtype=np.float64)
    n = X.shape[0]
    num_feat = gbdt.train_set.num_total_features if gbdt.train_set else X.shape[1]
    K = gbdt.num_tree_per_iteration
    total_iters = len(gbdt.models) // max(K, 1)
    if num_iteration is None or num_iteration <= 0:
        num_iteration = total_iters
    end = min(total_iters, num_iteration) * K
    out = np.zeros((n, K, num_feat + 1), dtype=np.float64)
    for i, tree in enumerate(gbdt.models[:end]):
        k = i % K
        base = _expected_value(tree)
        out[:, k, -1] += base
        if tree.num_leaves <= 1:
            continue
        for r in range(n):
            phi = np.zeros(num_feat + 1)
            _tree_shap_row(tree, X[r], phi, 0, [], 1.0, 1.0, -1)
            out[r, k, :num_feat] += phi[:num_feat]
    out[:, :, -1] += gbdt.init_scores[None, :K]
    if K == 1:
        return out[:, 0, :]
    return out.reshape(n, K * (num_feat + 1))
