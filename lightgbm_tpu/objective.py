"""Objective functions: gradients/hessians on device.

TPU-native equivalent of the reference objective zoo (reference:
src/objective/objective_function.cpp:15 factory; regression_objective.hpp,
binary_objective.hpp, multiclass_objective.hpp, rank_objective.hpp,
xentropy_objective.hpp). All gradient math is pure jnp — elementwise O(N)
fused by XLA; ranking objectives vectorize the reference's per-query pair
loops (rank_objective.hpp:54) into padded (query, doc) arrays with the
truncation-level cap expressed as a top-k slice instead of a loop bound.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config, OBJECTIVE_ALIASES
from .dataset import Metadata
from .utils.log import Log


def _weighted(grad, hess, weight):
    if weight is None:
        return grad, hess
    return grad * weight, hess * weight


def _percentile_weighted(values: np.ndarray, weights: Optional[np.ndarray],
                         alpha: float) -> float:
    """Weighted alpha-percentile with the reference's interpolation semantics
    (reference: regression_objective.hpp:18 PercentileFun, :50
    WeightedPercentileFun — including its boundary quirks)."""
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    if weights is None:
        v = np.sort(values)
        float_pos = (1.0 - alpha) * n
        pos = int(float_pos)
        if pos < 1:
            return float(v[-1])
        if pos >= n:
            return float(v[0])
        bias = float_pos - pos
        v1 = v[n - pos]          # pos-th largest
        v2 = v[n - 1 - pos]      # (pos+1)-th largest
        return float(v1 - (v1 - v2) * bias)
    order = np.argsort(values, kind="stable")
    v = values[order]
    cw = np.cumsum(weights[order].astype(np.float64))
    threshold = alpha * cw[-1]
    pos = int(np.searchsorted(cw, threshold, side="right"))
    pos = min(pos, n - 1)
    if pos == 0 or pos == n - 1:
        return float(v[pos])
    v1, v2 = float(v[pos - 1]), float(v[pos])
    if cw[pos + 1] - cw[pos] >= 1.0:
        return (threshold - cw[pos]) / (cw[pos + 1] - cw[pos]) * (v2 - v1) + v1
    return v2


class ObjectiveFunction:
    """Interface (reference: include/LightGBM/objective_function.h:19)."""

    name = "custom"
    num_model_per_iteration = 1
    is_constant_hessian = False
    need_renew = False
    is_ranking = False

    # attributes that only mirror device operands (or derive from
    # already-fingerprinted config/metadata): the fused-block fingerprint
    # skips hashing their N-sized contents
    fp_skip_attrs = frozenset({"_label_host", "_weight_host"})

    def __init__(self, config: Config) -> None:
        self.config = config
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None
        self.num_data = 0

    def init(self, metadata: Metadata) -> None:
        self.num_data = metadata.num_data
        self.label = metadata.device_label()
        self.weight = metadata.device_weight()
        # host mirrors: _label_np/_weight_np must not round-trip through
        # the device (a device_get through the tunnel costs seconds at 2M).
        # Defensive float32 COPIES: aliasing the user's buffer would let a
        # post-construction mutation change results, and float64 mirrors
        # would see different precision than the f32 device arrays
        self._label_host = None if metadata.label is None \
            else np.array(metadata.label, np.float32)
        self._weight_host = None if metadata.weight is None \
            else np.array(metadata.weight, np.float32)

    # objectives that draw per-iteration randomness take a traced iteration
    # index in get_gradients (see RankXENDCG)
    needs_iter = False

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        """Initial raw score (reference: BoostFromScore, used when
        boost_from_average=true, gbdt.cpp:333)."""
        return 0.0

    def convert_output(self, score):
        """Raw score -> prediction space (reference: ConvertOutput)."""
        return score

    def renew_leaf_values(self, leaf_assign: np.ndarray, num_leaves: int,
                          score_before: np.ndarray) -> Optional[np.ndarray]:
        return None

    # host mirrors for metric/renew paths
    def _label_np(self) -> np.ndarray:
        if getattr(self, "_label_host", None) is not None:
            return self._label_host
        return np.asarray(self.label)

    def _weight_np(self) -> Optional[np.ndarray]:
        if getattr(self, "_weight_host", None) is not None:
            return self._weight_host
        return None if self.weight is None else np.asarray(self.weight)


# ---------------------------------------------------------------- regression

class RegressionL2(ObjectiveFunction):
    """L2 loss (reference: regression_objective.hpp RegressionL2loss).
    Supports reg_sqrt: fit sqrt(|label|)·sign(label)."""
    name = "regression"
    is_constant_hessian = True

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if self.config.reg_sqrt:
            lab = self._label_np()
            trans = (np.sign(lab) * np.sqrt(np.abs(lab))).astype(np.float32)
            self.label = jnp.asarray(trans)
            self._label_host = trans  # keep the host mirror in sync

    def get_gradients(self, score):
        g = score - self.label
        h = jnp.ones_like(score)
        return _weighted(g, h, self.weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        lab, w = self._label_np(), self._weight_np()
        return float(np.average(lab, weights=w))

    def convert_output(self, score):
        if self.config.reg_sqrt:
            return jnp.sign(score) * score * score
        return score


class RegressionL1(RegressionL2):
    """L1 loss with leaf renewal by residual median
    (reference: RegressionL1loss::RenewTreeOutput)."""
    name = "regression_l1"
    need_renew = True

    def get_gradients(self, score):
        diff = score - self.label
        g = jnp.sign(diff)
        h = jnp.ones_like(score)
        return _weighted(g, h, self.weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        return _percentile_weighted(self._label_np(), self._weight_np(), 0.5)

    def renew_leaf_values(self, leaf_assign, num_leaves, score_before):
        lab, w = self._label_np(), self._weight_np()
        resid = lab - score_before
        out = np.zeros(num_leaves)
        for l in range(num_leaves):
            m = leaf_assign == l
            if np.any(m):
                out[l] = _percentile_weighted(resid[m], None if w is None else w[m], 0.5)
        return out


class RegressionHuber(RegressionL2):
    """Huber loss (reference: RegressionHuberLoss), alpha = transition point."""
    name = "huber"

    def get_gradients(self, score):
        a = self.config.alpha
        diff = score - self.label
        g = jnp.where(jnp.abs(diff) <= a, diff, a * jnp.sign(diff))
        h = jnp.ones_like(score)
        return _weighted(g, h, self.weight)


class RegressionFair(RegressionL2):
    """Fair loss (reference: RegressionFairLoss), c = fair_c."""
    name = "fair"
    is_constant_hessian = False

    def get_gradients(self, score):
        c = self.config.fair_c
        diff = score - self.label
        g = c * diff / (jnp.abs(diff) + c)
        h = c * c / ((jnp.abs(diff) + c) ** 2)
        return _weighted(g, h, self.weight)


class RegressionPoisson(RegressionL2):
    """Poisson with log link (reference: RegressionPoissonLoss)."""
    name = "poisson"
    is_constant_hessian = False

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if np.any(self._label_np() < 0):
            Log.fatal("[poisson]: labels must be non-negative")

    def get_gradients(self, score):
        g = jnp.exp(score) - self.label
        h = jnp.exp(score + self.config.poisson_max_delta_step)
        return _weighted(g, h, self.weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        lab, w = self._label_np(), self._weight_np()
        return float(np.log(max(np.average(lab, weights=w), 1e-20)))

    def convert_output(self, score):
        return jnp.exp(score)


class RegressionQuantile(RegressionL2):
    """Pinball/quantile loss with renewal (reference: RegressionQuantileloss)."""
    name = "quantile"
    need_renew = True

    def get_gradients(self, score):
        a = self.config.alpha
        g = jnp.where(score < self.label, -a, 1.0 - a)
        h = jnp.ones_like(score)
        return _weighted(g, h, self.weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        return _percentile_weighted(self._label_np(), self._weight_np(), self.config.alpha)

    def renew_leaf_values(self, leaf_assign, num_leaves, score_before):
        lab, w = self._label_np(), self._weight_np()
        resid = lab - score_before
        out = np.zeros(num_leaves)
        for l in range(num_leaves):
            m = leaf_assign == l
            if np.any(m):
                out[l] = _percentile_weighted(resid[m], None if w is None else w[m],
                                              self.config.alpha)
        return out


class RegressionMAPE(RegressionL2):
    """MAPE: L1 with 1/|label| weights and weighted-median renewal
    (reference: RegressionMAPELOSS)."""
    name = "mape"
    need_renew = True

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lab = self._label_np()
        lw = 1.0 / np.maximum(1.0, np.abs(lab))
        w = self._weight_np()
        self._label_weight = lw if w is None else lw * w
        self.weight = None  # folded into label_weight
        self._weight_host = None  # mirror must track self.weight

    def get_gradients(self, score):
        lw = jnp.asarray(self._label_weight, jnp.float32)
        diff = score - self.label
        g = jnp.sign(diff) * lw
        h = lw
        return g, h

    def boost_from_score(self, class_id: int = 0) -> float:
        return _percentile_weighted(self._label_np(), self._label_weight, 0.5)

    def renew_leaf_values(self, leaf_assign, num_leaves, score_before):
        lab = self._label_np()
        resid = lab - score_before
        out = np.zeros(num_leaves)
        for l in range(num_leaves):
            m = leaf_assign == l
            if np.any(m):
                out[l] = _percentile_weighted(resid[m], self._label_weight[m], 0.5)
        return out


class RegressionGamma(RegressionPoisson):
    """Gamma deviance with log link (reference: RegressionGammaLoss)."""
    name = "gamma"

    def get_gradients(self, score):
        g = 1.0 - self.label * jnp.exp(-score)
        h = self.label * jnp.exp(-score)
        return _weighted(g, h, self.weight)


class RegressionTweedie(RegressionPoisson):
    """Tweedie with log link (reference: RegressionTweedieLoss)."""
    name = "tweedie"

    def get_gradients(self, score):
        rho = self.config.tweedie_variance_power
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        g = -self.label * e1 + e2
        h = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return _weighted(g, h, self.weight)


# -------------------------------------------------------------------- binary

class BinaryLogloss(ObjectiveFunction):
    """Sigmoid binary cross-entropy (reference: binary_objective.hpp),
    with is_unbalance / scale_pos_weight label weighting."""
    name = "binary"

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lab = self._label_np()
        uniq = np.unique(lab)
        if not np.all(np.isin(uniq, [0, 1])):
            Log.fatal("[binary]: labels must be 0 or 1, got %s", uniq[:5])
        w = self._weight_np()
        cnt_pos = float(np.sum((lab > 0) * (w if w is not None else 1.0)))
        cnt_neg = float(np.sum((lab <= 0) * (w if w is not None else 1.0)))
        self._pavg = cnt_pos / max(cnt_pos + cnt_neg, 1e-10)
        pos_w, neg_w = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                neg_w = cnt_pos / cnt_neg
            else:
                pos_w = cnt_neg / cnt_pos
        pos_w *= self.config.scale_pos_weight
        self._label_sign = jnp.asarray(np.where(lab > 0, 1.0, -1.0), jnp.float32)
        self._label_w = jnp.asarray(np.where(lab > 0, pos_w, neg_w), jnp.float32)

    def get_gradients(self, score):
        sig = self.config.sigmoid
        y = self._label_sign
        response = -y * sig / (1.0 + jnp.exp(y * sig * score))
        absr = jnp.abs(response)
        g = response * self._label_w
        h = absr * (sig - absr) * self._label_w
        return _weighted(g, h, self.weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        p = np.clip(self._pavg, 1e-15, 1 - 1e-15)
        init = float(np.log(p / (1 - p)) / self.config.sigmoid)
        return init

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * score))


# ---------------------------------------------------------------- multiclass

class MulticlassSoftmax(ObjectiveFunction):
    """Softmax, K trees per iteration
    (reference: multiclass_objective.hpp MulticlassSoftmax)."""
    name = "multiclass"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_model_per_iteration = self.num_class

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lab = self._label_np().astype(np.int32)
        if lab.min() < 0 or lab.max() >= self.num_class:
            Log.fatal("[multiclass]: labels must be in [0, num_class)")
        self._onehot = jnp.asarray(np.eye(self.num_class, dtype=np.float32)[lab])
        self._class_p = np.bincount(lab, minlength=self.num_class) / len(lab)

    def get_gradients(self, score):
        p = jax.nn.softmax(score, axis=1)
        g = p - self._onehot
        # hessian upper-bound factor K/(K-1) (reference:
        # multiclass_objective.hpp:31 factor_)
        factor = self.num_class / max(self.num_class - 1, 1)
        h = factor * p * (1.0 - p)
        if self.weight is not None:
            g = g * self.weight[:, None]
            h = h * self.weight[:, None]
        return g, h

    def boost_from_score(self, class_id: int = 0) -> float:
        # reference inits multiclass scores at 0 (no average boost)
        return 0.0

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=1)


class MulticlassOVA(ObjectiveFunction):
    """K one-vs-all binary objectives (reference: MulticlassOVA)."""
    name = "multiclassova"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.num_model_per_iteration = self.num_class

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lab = self._label_np().astype(np.int32)
        self._sign = jnp.asarray(np.where(
            np.eye(self.num_class, dtype=np.float32)[lab] > 0, 1.0, -1.0), jnp.float32)

    def get_gradients(self, score):
        sig = self.config.sigmoid
        y = self._sign
        response = -y * sig / (1.0 + jnp.exp(y * sig * score))
        absr = jnp.abs(response)
        g, h = response, absr * (sig - absr)
        if self.weight is not None:
            g = g * self.weight[:, None]
            h = h * self.weight[:, None]
        return g, h

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * score))


# ------------------------------------------------------------- cross entropy

class CrossEntropy(ObjectiveFunction):
    """Cross-entropy with probabilistic labels in [0,1]
    (reference: xentropy_objective.hpp CrossEntropy), identity sigmoid=1 link."""
    name = "cross_entropy"

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        lab = self._label_np()
        if lab.min() < 0 or lab.max() > 1:
            Log.fatal("[cross_entropy]: labels must be in [0, 1]")

    def get_gradients(self, score):
        p = 1.0 / (1.0 + jnp.exp(-score))
        g = p - self.label
        h = p * (1.0 - p)
        return _weighted(g, h, self.weight)

    def boost_from_score(self, class_id: int = 0) -> float:
        lab, w = self._label_np(), self._weight_np()
        p = np.clip(np.average(lab, weights=w), 1e-15, 1 - 1e-15)
        return float(np.log(p / (1 - p)))

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))


class CrossEntropyLambda(ObjectiveFunction):
    """Alternative-parameterization cross-entropy
    (reference: CrossEntropyLambda — log1p(exp) link with weights folded into
    the link)."""
    name = "cross_entropy_lambda"

    def get_gradients(self, score):
        w = self.weight if self.weight is not None else 1.0
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - self.label + self.label * jnp.exp(w * hhat)
        enf = jnp.exp(-score)
        g = (1.0 - self.label / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - (1.0 - 1e-12) / z)
        h = w * epf / ((1.0 + epf) ** 2) * (1.0 + w * epf / (1.0 + epf) *
                                            (1.0 - 1.0 / jnp.maximum(c, 1e-12)))
        h = jnp.abs(h) + 1e-6
        return g, h

    def boost_from_score(self, class_id: int = 0) -> float:
        lab, w = self._label_np(), self._weight_np()
        p = np.clip(np.average(lab, weights=w), 1e-15, 1 - 1e-15)
        return float(np.log(np.expm1(p)) if p > 0 else 0.0)

    def convert_output(self, score):
        return jnp.log1p(jnp.exp(score))


# ------------------------------------------------------------------- ranking

def _pad_queries(qb: np.ndarray) -> Tuple[np.ndarray, int]:
    """(Q+1,) boundaries -> (Q, P) row-index matrix padded with -1."""
    sizes = np.diff(qb)
    P = int(sizes.max()) if len(sizes) else 1
    Q = len(sizes)
    idx = np.full((Q, P), -1, dtype=np.int32)
    for q in range(Q):
        idx[q, : sizes[q]] = np.arange(qb[q], qb[q + 1], dtype=np.int32)
    return idx, P


# padded query lengths quantize to this ladder: one compiled (Q, K, P)
# lambda kernel per distinct rung. Padding every query to the GLOBAL max
# (the round-3 design) wasted ~1.9x tensor volume at MSLR-like length
# spreads; the ladder caps waste at ~25% for a handful of compilations.
_BUCKET_LADDER = (8, 16, 24, 32, 48, 64, 96, 128, 160, 192, 224, 256,
                  320, 384, 448, 512, 640, 768, 1024)


def _bucket_queries(qb: np.ndarray):
    """(Q+1,) boundaries -> list of (P_b, query_index_array) buckets."""
    sizes = np.diff(qb)
    ladder = np.asarray(_BUCKET_LADDER)
    out = []
    for p_b in _BUCKET_LADDER:
        lo = 0 if p_b == _BUCKET_LADDER[0] else ladder[ladder < p_b].max()
        sel = np.where((sizes > lo) & (sizes <= p_b))[0]
        if len(sel):
            out.append((p_b, sel))
    big = np.where(sizes > _BUCKET_LADDER[-1])[0]
    if len(big):
        # beyond the ladder: one bucket per 256-multiple
        pmax = int(sizes[big].max())
        for p_b in range(_BUCKET_LADDER[-1] + 256, pmax + 256, 256):
            sel = big[(sizes[big] > p_b - 256) & (sizes[big] <= p_b)]
            if len(sel):
                out.append((p_b, sel))
    return out


class LambdarankNDCG(ObjectiveFunction):
    """LambdaRank with NDCG lambda gradients (reference: rank_objective.hpp:100
    LambdarankNDCG): per-query pairwise lambdas weighted by |ΔNDCG|,
    truncation_level caps the high-ranked side of each pair, optional
    lambdarank_norm. Vectorized as (query-chunk, trunc, P) tensors instead of
    the reference's per-query double loop."""
    name = "lambdarank"
    is_ranking = True
    # _gains_np derives from label + label_gain (both fingerprinted); the
    # bucket tables it feeds ride as jit operands
    fp_skip_attrs = ObjectiveFunction.fp_skip_attrs | {"_gains_np"}

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if metadata.query_boundaries is None:
            Log.fatal("[lambdarank]: query data (group) required")
        cfg = self.config
        label_gain = cfg.label_gain or [float(2 ** i - 1) for i in range(31)]
        lab = self._label_np().astype(np.int32)
        if lab.max() >= len(label_gain):
            Log.fatal("[lambdarank]: label %d exceeds label_gain size", lab.max())
        self._gains_np = np.asarray(label_gain, np.float64)[lab].astype(np.float32)
        qb = metadata.query_boundaries
        sizes = np.diff(qb)
        self.P = int(sizes.max()) if len(sizes) else 1
        self.trunc = min(int(cfg.lambdarank_truncation_level), self.P)
        # queries bucketed by padded length (_BUCKET_LADDER): the all-pairs
        # lambda tensors are (Q_b, K, P_b) per bucket instead of one
        # max-padded (Q, K, P) — at MSLR-like length spreads that is ~1.9x
        # less tensor volume (reference per-query loop:
        # rank_objective.hpp:54 GetGradients / :124 inverse_max_dcgs_)
        buckets = _bucket_queries(qb)
        p_max = max((p_b for p_b, _ in buckets), default=1)
        disc_np = 1.0 / np.log2(np.arange(p_max) + 2.0)
        self.bucket_shapes = []   # python-static (Q_b, P_b, K_b)
        self.bucket_arrays = []   # device tables, passed as jit operands
        for p_b, qsel in buckets:
            q_b = len(qsel)
            idx = np.full((q_b, p_b), -1, dtype=np.int32)
            for row, q in enumerate(qsel):
                idx[row, : sizes[q]] = np.arange(qb[q], qb[q + 1],
                                                 dtype=np.int32)
            valid = idx >= 0
            safe = np.maximum(idx, 0)
            gains = np.where(valid, self._gains_np[safe], 0.0)
            g_sorted = -np.sort(-gains, axis=1)
            max_dcg = (g_sorted * disc_np[None, :p_b]).sum(axis=1)
            inv = np.where(max_dcg > 0, 1.0 / np.maximum(max_dcg, 1e-20),
                           0.0)
            self.bucket_shapes.append((q_b, p_b, min(self.trunc, p_b)))
            self.bucket_arrays.append({
                "safe_idx": jnp.asarray(safe),
                "valid": jnp.asarray(valid),
                "gains": jnp.asarray(gains, jnp.float32),
                "inv_max_dcg": jnp.asarray(inv, jnp.float32),
            })
        self.discount = jnp.asarray(disc_np, jnp.float32)
        self.sigmoid_ = float(cfg.sigmoid)
        self.norm = bool(cfg.lambdarank_norm)

    def _bucket_lambdas(self, score, arrs, p_b: int, K: int):
        """Per-bucket (Q_b, P_b) grad/hess via padded pairwise lambdas."""
        valid = arrs["valid"]
        safe_idx = arrs["safe_idx"]
        s = jnp.where(valid, score[safe_idx], -jnp.inf)        # (Q, P)
        order = jnp.argsort(-s, axis=1)                        # rank -> slot
        s_sorted = jnp.take_along_axis(s, order, axis=1)
        g_sorted = jnp.take_along_axis(arrs["gains"], order, axis=1)
        valid_sorted = jnp.take_along_axis(valid, order, axis=1)
        # pairs: i in top-K ranks x j in all ranks; j > i counted once
        si = s_sorted[:, :K]                                   # (Q, K)
        gi = g_sorted[:, :K]
        vi = valid_sorted[:, :K]
        di = self.discount[:K]
        disc = self.discount[:p_b]
        delta_s = si[:, :, None] - s_sorted[:, None, :]        # (Q, K, P)
        worse = (gi[:, :, None] > g_sorted[:, None, :])
        better = (gi[:, :, None] < g_sorted[:, None, :])
        pair_mask = (worse | better) & vi[:, :, None] & valid_sorted[:, None, :]
        # |delta NDCG| of swapping ranks i<->j
        dd = jnp.abs(di[None, :, None] - disc[None, None, :])
        dgain = jnp.abs(gi[:, :, None] - g_sorted[:, None, :])
        delta_ndcg = dd * dgain * arrs["inv_max_dcg"][:, None, None]
        # orient each pair so "hi" is the better-labelled doc
        sgn = jnp.where(worse, 1.0, -1.0)
        d = sgn * delta_s                                      # s_hi - s_lo
        sig = self.sigmoid_
        p = 1.0 / (1.0 + jnp.exp(sig * d))                     # misorder prob
        lam = -sig * p * delta_ndcg
        hess = sig * sig * p * (1.0 - p) * delta_ndcg
        lam = jnp.where(pair_mask, lam, 0.0)
        hess = jnp.where(pair_mask, hess, 0.0)
        jr = jnp.arange(p_b)[None, None, :]
        ir = jnp.arange(K)[None, :, None]
        once = jr > ir
        lam = jnp.where(once, lam, 0.0)
        hess = jnp.where(once, hess, 0.0)
        lam_i = jnp.sum(lam * sgn, axis=2)                     # (Q, K)
        lam_j = -lam * sgn                                     # (Q, K, P)
        hess_i = jnp.sum(hess, axis=2)
        grad_sorted = jnp.zeros_like(s_sorted).at[:, :K].add(lam_i) \
            + jnp.sum(lam_j, axis=1)
        hess_sorted = jnp.zeros_like(s_sorted).at[:, :K].add(hess_i) \
            + jnp.sum(hess, axis=1)
        if self.norm:
            norm = jnp.sum(jnp.abs(grad_sorted), axis=1, keepdims=True)
            scale = jnp.where(norm > 0,
                              jnp.log2(1 + norm) / jnp.maximum(norm, 1e-20),
                              1.0)
            grad_sorted = grad_sorted * scale
            hess_sorted = hess_sorted * scale
        # unsort ranks back to slots
        inv = jnp.argsort(order, axis=1)
        grad_q = jnp.take_along_axis(grad_sorted, inv, axis=1)
        hess_q = jnp.take_along_axis(hess_sorted, inv, axis=1)
        return grad_q, hess_q

    def get_gradients(self, score):
        """(N,) score -> (N,) grad/hess; one padded pairwise-lambda kernel
        per length bucket, scattered back in a single disjoint update."""
        n = score.shape[0]
        idx_parts, g_parts, h_parts = [], [], []
        for (q_b, p_b, k_b), arrs in zip(self.bucket_shapes,
                                         self.bucket_arrays):
            grad_q, hess_q = self._bucket_lambdas(score, arrs, p_b, k_b)
            vm = arrs["valid"].reshape(-1)
            idx_parts.append(arrs["safe_idx"].reshape(-1))
            g_parts.append(jnp.where(vm, grad_q.reshape(-1), 0.0))
            h_parts.append(jnp.where(vm, hess_q.reshape(-1), 0.0))
        flat_idx = jnp.concatenate(idx_parts)
        grad = jnp.zeros((n,), jnp.float32).at[flat_idx].add(
            jnp.concatenate(g_parts))
        hess = jnp.zeros((n,), jnp.float32).at[flat_idx].add(
            jnp.concatenate(h_parts))
        hess = jnp.maximum(hess, 1e-20)
        if self.weight is not None:
            grad, hess = grad * self.weight, hess * self.weight
        return grad, hess


class RankXENDCG(ObjectiveFunction):
    """XE-NDCG listwise surrogate (reference: rank_objective.hpp RankXENDCG,
    per Bruch et al.): cross-entropy between a sampled Gumbel-perturbed label
    distribution and the score softmax, per query."""
    name = "rank_xendcg"
    is_ranking = True
    needs_iter = True
    # _doc_idx_np mirrors the doc_idx jit operand (derives from the
    # fingerprinted query boundaries)
    fp_skip_attrs = ObjectiveFunction.fp_skip_attrs | {"_doc_idx_np"}

    def init(self, metadata: Metadata) -> None:
        super().init(metadata)
        if metadata.query_boundaries is None:
            Log.fatal("[rank_xendcg]: query data (group) required")
        lab = self._label_np()
        self._doc_idx_np, self.P = _pad_queries(metadata.query_boundaries)
        self.doc_idx = jnp.asarray(self._doc_idx_np)
        self.doc_valid = self.doc_idx >= 0
        self.safe_idx = jnp.maximum(self.doc_idx, 0)
        phi = (2.0 ** lab - 1.0)
        self.q_phi = jnp.where(self.doc_valid,
                               jnp.asarray(phi, jnp.float32)[self.safe_idx], 0.0)
        self.key = jax.random.PRNGKey(int(self.config.objective_seed or 5))

    def get_gradients(self, score, it=0):
        # ``it`` is a traced iteration index threaded by the boosting loop so
        # each iteration draws a fresh Gumbel perturbation even under jit
        # (a host-side counter would be baked in at trace time)
        key = jax.random.fold_in(self.key, jnp.asarray(it, jnp.int32))
        s = jnp.where(self.doc_valid, score[self.safe_idx], -jnp.inf)
        # sampled relevance distribution: softmax(phi + gumbel)
        gumbel = jax.random.gumbel(key, s.shape)
        phi_pert = jnp.where(self.doc_valid, self.q_phi + gumbel, -jnp.inf)
        target = jax.nn.softmax(phi_pert, axis=1)
        rho = jax.nn.softmax(s, axis=1)
        grad_q = rho - target
        hess_q = rho * (1.0 - rho)
        n = score.shape[0]
        flat_idx = self.safe_idx.reshape(-1)
        vmask = self.doc_valid.reshape(-1)
        grad = jnp.zeros((n,), jnp.float32).at[flat_idx].add(
            jnp.where(vmask, grad_q.reshape(-1), 0.0))
        hess = jnp.zeros((n,), jnp.float32).at[flat_idx].add(
            jnp.where(vmask, hess_q.reshape(-1), 0.0))
        hess = jnp.maximum(hess, 1e-20)
        return grad, hess


class NoneObjective(ObjectiveFunction):
    """Custom objective placeholder: gradients supplied by the caller
    (reference: USE_CUSTOM_OBJECTIVE path, TrainOneIter(grad, hess))."""
    name = "none"

    def get_gradients(self, score):
        Log.fatal("custom objective: gradients must be passed to update()")


_REGISTRY = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
    "none": NoneObjective,
}


def create_objective(config: Config) -> ObjectiveFunction:
    """Factory (reference: src/objective/objective_function.cpp:15)."""
    name = OBJECTIVE_ALIASES.get(config.objective, config.objective)
    if name not in _REGISTRY:
        Log.fatal("Unknown objective: %s", config.objective)
    return _REGISTRY[name](config)
