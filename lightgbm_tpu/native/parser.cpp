// Native text parser for CSV / TSV / LibSVM training files.
//
// TPU-native analog of the reference's C++ data-loading path (reference:
// src/io/parser.cpp Parser::CreateParser + CSVParser/TSVParser/
// LibSVMParser, src/io/dataset_loader.cpp ExtractFeaturesFromFile): the
// device computes histograms, but turning gigabytes of text into the raw
// feature matrix is host runtime work and belongs in native code. Python
// binds via ctypes (no pybind11 in this image); lightgbm_tpu/io.py keeps a
// pure-Python fallback.
//
// Build: g++ -O3 -shared -fPIC -o libparser.so parser.cpp   (see io_native.py)
//
// Exported ABI:
//   parse_dense(path, sep, n_rows, n_cols, out)      CSV/TSV -> row-major
//   parse_libsvm(path, n_rows, n_cols, out)          index:value pairs
//   count_dims(path, sep_out, rows_out, cols_out)    format autodetection
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// fast strtod-ish for the common numeric case; falls back to strtod for
// exponents/specials (the reference vendors fast_double_parser for this)
inline const char* parse_double(const char* p, double* out) {
  while (*p == ' ') ++p;
  const char* start = p;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') ++p;
  if ((*p < '0' || *p > '9') && *p != '.') {
    // nan / inf / malformed
    char* end = nullptr;
    double v = std::strtod(start, &end);
    if (end == start) { *out = std::nan(""); return p; }
    *out = v;
    return end;
  }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  while (*p >= '0' && *p <= '9' && digits < 18) {
    mant = mant * 10 + (*p - '0');
    ++p; ++digits;
  }
  if (*p == '.') {
    ++p;
    while (*p >= '0' && *p <= '9' && digits < 18) {
      mant = mant * 10 + (*p - '0');
      ++p; ++digits; ++frac;
    }
  }
  if (*p == 'e' || *p == 'E' || (*p >= '0' && *p <= '9')) {
    char* end = nullptr;
    double v = std::strtod(start, &end);
    *out = v;
    return end;
  }
  static const double kPow10[19] = {
      1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11,
      1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18};
  double v = static_cast<double>(mant) / kPow10[frac];
  *out = neg ? -v : v;
  return p;
}

inline bool read_file(const char* path, std::string* buf) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  buf->resize(static_cast<size_t>(n));
  size_t got = n ? std::fread(&(*buf)[0], 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

}  // namespace

extern "C" {

// Detect separator (',' or '\t' or ' ' or libsvm=-1), rows, and max column
// count from the file. Returns 0 on success.
int count_dims(const char* path, int* sep_out, int64_t* rows_out,
               int64_t* cols_out) {
  std::string buf;
  if (!read_file(path, &buf)) return 1;
  int64_t rows = 0, cols = 0;
  char sep = 0;
  bool libsvm = false;
  const char* p = buf.c_str();
  const char* end = p + buf.size();
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* next = line_end ? line_end + 1 : end;
    if (!line_end) line_end = end;
    while (line_end > p && line_end[-1] == '\r') --line_end;
    if (line_end > p && *p != '#') {
      if (rows == 0) {
        // sniff the first line: libsvm has "idx:value" tokens
        for (const char* q = p; q < line_end; ++q) {
          if (*q == ':') { libsvm = true; break; }
          if (*q == ',') { sep = ','; break; }
          if (*q == '\t') { sep = '\t'; break; }
        }
        if (!sep && !libsvm) sep = ' ';
      }
      int64_t c = 0;
      if (libsvm) {
        for (const char* q = p; q < line_end; ++q) {
          if (*q == ':') {
            const char* b = q;
            while (b > p && b[-1] >= '0' && b[-1] <= '9') --b;
            int64_t idx = std::atoll(std::string(b, q).c_str());
            if (idx + 1 > c) c = idx + 1;
          }
        }
        c += 1;  // label column
      } else {
        c = 1;
        for (const char* q = p; q < line_end; ++q)
          if (*q == sep) ++c;
      }
      if (c > cols) cols = c;
      ++rows;
    }
    p = next;
  }
  *sep_out = libsvm ? -1 : sep;
  *rows_out = rows;
  *cols_out = cols;
  return 0;
}

// Parse a delimiter-separated file into a pre-allocated row-major
// (n_rows, n_cols) double array. Missing/short fields become NaN.
int parse_dense(const char* path, int sep_ci, int64_t n_rows, int64_t n_cols,
                double* out) {
  std::string buf;
  if (!read_file(path, &buf)) return 1;
  const char sep = static_cast<char>(sep_ci);
  const char* p = buf.c_str();
  const char* end = p + buf.size();
  int64_t r = 0;
  while (p < end && r < n_rows) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* next = line_end ? line_end + 1 : end;
    if (!line_end) line_end = end;
    while (line_end > p && line_end[-1] == '\r') --line_end;
    if (line_end > p && *p != '#') {
      double* row = out + r * n_cols;
      for (int64_t c = 0; c < n_cols; ++c) row[c] = std::nan("");
      int64_t c = 0;
      const char* q = p;
      while (q < line_end && c < n_cols) {
        if (*q == sep) { ++c; ++q; continue; }
        double v;
        const char* nq = parse_double(q, &v);
        if (nq == q || nq > line_end) { ++q; continue; }
        row[c] = v;
        q = nq;
      }
      ++r;
    }
    p = next;
  }
  return 0;
}

// Parse a LibSVM file: column 0 of `out` gets the label, feature j goes to
// column j+1. Absent features stay 0 (LibSVM sparse semantics).
int parse_libsvm(const char* path, int64_t n_rows, int64_t n_cols,
                 double* out) {
  std::string buf;
  if (!read_file(path, &buf)) return 1;
  const char* p = buf.c_str();
  const char* end = p + buf.size();
  int64_t r = 0;
  std::memset(out, 0, sizeof(double) * static_cast<size_t>(n_rows * n_cols));
  while (p < end && r < n_rows) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* next = line_end ? line_end + 1 : end;
    if (!line_end) line_end = end;
    while (line_end > p && line_end[-1] == '\r') --line_end;
    if (line_end > p && *p != '#') {
      double* row = out + r * n_cols;
      double label;
      const char* q = parse_double(p, &label);
      row[0] = label;
      while (q < line_end) {
        while (q < line_end && (*q == ' ' || *q == '\t')) ++q;
        if (q >= line_end) break;
        char* colon_end = nullptr;
        long idx = std::strtol(q, &colon_end, 10);
        if (!colon_end || *colon_end != ':') { ++q; continue; }
        q = colon_end + 1;
        double v;
        const char* nq = parse_double(q, &v);
        if (idx + 1 < n_cols && idx >= 0) row[idx + 1] = v;
        q = nq;
      }
      ++r;
    }
    p = next;
  }
  return 0;
}

}  // extern "C"
