// Threaded numerical bin application for Dataset construction.
//
// The device learner consumes a (rows, features) uint8 binned matrix; this
// builds it from raw doubles at memory bandwidth instead of one GIL-bound
// numpy searchsorted per feature (reference analog: the OpenMP loop around
// Dataset::PushData / BinMapper::ValueToBin, src/io/dataset.cpp:318,
// include/LightGBM/bin.h ValueToBin binary search — same contract, row-major
// blocks across std::thread workers here).
//
// Semantics mirror ops/binning.py BinMapper.value_to_bin (numerical):
//   bin = lower_bound(upper_bounds, v)        (first bound >= v)
//   NaN -> missing_bin when missing_type == NAN, else treated as 0.0
// Bounds end with +inf, so the result is always < n_bounds.

#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

inline int32_t lower_bound_idx(const double* b, int32_t n, double v) {
    int32_t lo = 0, hi = n;
    while (lo < hi) {
        int32_t mid = (lo + hi) >> 1;
        if (b[mid] < v) {
            lo = mid + 1;
        } else {
            hi = mid;
        }
    }
    return lo;
}

constexpr int32_t kMissingNan = 2;

}  // namespace

extern "C" {

// X: (n, x_cols) row-major doubles.
// For each of `f` output features: col_idx[f] selects the X column,
// bounds + bounds_off give that feature's upper bounds (last = +inf),
// out_col[f] selects the destination column of `out` ((n, out_cols) u8).
void lgbm_apply_bins_u8(const double* X, int64_t n, int64_t x_cols,
                        int32_t f, const int32_t* col_idx,
                        const double* bounds, const int64_t* bounds_off,
                        const int32_t* n_bounds, const int32_t* missing_type,
                        const int32_t* missing_bin, uint8_t* out,
                        int64_t out_cols, const int32_t* out_col,
                        int32_t nthreads) {
    if (nthreads < 1) nthreads = 1;
    int64_t block = (n + nthreads - 1) / nthreads;
    std::vector<std::thread> threads;
    for (int t = 0; t < nthreads; ++t) {
        int64_t r0 = t * block;
        int64_t r1 = r0 + block < n ? r0 + block : n;
        if (r0 >= r1) break;
        threads.emplace_back([=]() {
            for (int64_t r = r0; r < r1; ++r) {
                const double* xrow = X + r * x_cols;
                uint8_t* orow = out + r * out_cols;
                for (int32_t j = 0; j < f; ++j) {
                    double v = xrow[col_idx[j]];
                    const double* b = bounds + bounds_off[j];
                    int32_t bin;
                    if (std::isnan(v)) {
                        bin = missing_type[j] == kMissingNan
                                  ? missing_bin[j]
                                  : lower_bound_idx(b, n_bounds[j], 0.0);
                    } else {
                        bin = lower_bound_idx(b, n_bounds[j], v);
                    }
                    orow[out_col[j]] = static_cast<uint8_t>(bin);
                }
            }
        });
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"
