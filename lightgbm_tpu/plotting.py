"""Plotting utilities (reference: python-package/lightgbm/plotting.py:25
plot_importance / plot_metric / plot_tree / create_tree_digraph).

matplotlib and graphviz are optional: importing this module is always safe;
each function raises a clear error if its backend is missing.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import numpy as np

from .basic import Booster
from .utils.log import Log

__all__ = ["plot_importance", "plot_metric", "plot_tree",
           "create_tree_digraph"]


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("plot_* functions require matplotlib") from e


def _booster_of(model) -> Booster:
    if isinstance(model, Booster):
        return model
    if hasattr(model, "booster_"):
        return model.booster_
    raise TypeError("expected a Booster or fitted sklearn estimator")


def plot_importance(booster, ax=None, height: float = 0.2,
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features", grid: bool = True,
                    precision: int = 3, **kwargs):
    """Horizontal bar chart of feature importances
    (reference: plotting.py plot_importance)."""
    plt = _check_matplotlib()
    bst = _booster_of(booster)
    imp = bst.feature_importance(importance_type=importance_type)
    names = bst.feature_name()
    order = np.argsort(imp)
    order = order[imp[order] > 0]
    if max_num_features is not None and max_num_features > 0:
        order = order[-max_num_features:]
    if ax is None:
        _, ax = plt.subplots(1, 1)
    vals = imp[order]
    ylocs = np.arange(len(order))
    ax.barh(ylocs, vals, height=height, **kwargs)
    for v, y in zip(vals, ylocs):
        ax.text(v + 1e-9, y,
                ("%." + str(precision) + "g") % v, va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels([names[i] for i in order])
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(eval_result: Union[Dict, Booster], metric: Optional[str] = None,
                dataset_names=None, ax=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "auto",
                grid: bool = True):
    """Plot recorded evaluation metrics (reference: plotting.py plot_metric;
    pass the dict filled by ``record_evaluation``)."""
    plt = _check_matplotlib()
    if isinstance(eval_result, Booster):
        raise TypeError("pass the dict from lgb.record_evaluation(), "
                        "not the Booster")
    if not isinstance(eval_result, dict) or not eval_result:
        raise ValueError("eval_result is empty — use record_evaluation")
    if ax is None:
        _, ax = plt.subplots(1, 1)
    names = dataset_names or list(eval_result.keys())
    chosen = None
    for name in names:
        metrics = eval_result[name]
        m = metric or next(iter(metrics))
        chosen = m
        vals = metrics[m]
        ax.plot(np.arange(1, len(vals) + 1), vals, label="%s %s" % (name, m))
    ax.legend(loc="best")
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(chosen if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: int = 3, **kwargs):
    """Graphviz Digraph of one tree (reference: plotting.py
    create_tree_digraph)."""
    try:
        import graphviz
    except ImportError as e:  # pragma: no cover
        raise ImportError("create_tree_digraph requires the graphviz "
                          "package") from e
    bst = _booster_of(booster)
    tree = bst.inner.models[tree_index]
    names = bst.feature_name()
    g = graphviz.Digraph(**kwargs)

    def node_name(nd):
        return "split%d" % nd if nd >= 0 else "leaf%d" % (~nd)

    for nd in range(tree.num_internal):
        f = int(tree.split_feature[nd])
        label = "%s <= %.*g\ngain: %.*g" % (
            names[f] if f < len(names) else "f%d" % f,
            precision, tree.threshold[nd], precision, tree.split_gain[nd])
        g.node(node_name(nd), label=label, shape="box")
        for child in (tree.left_child[nd], tree.right_child[nd]):
            if child < 0:
                leaf = ~int(child)
                g.node(node_name(child),
                       label="leaf %d: %.*g" % (leaf, precision,
                                                tree.leaf_value[leaf]))
            g.edge(node_name(nd), node_name(int(child)))
    if tree.num_leaves <= 1:
        g.node("leaf0", label="leaf 0: %.3g" % tree.leaf_value[0])
    return g


def plot_tree(booster, tree_index: int = 0, figsize=None, ax=None, **kwargs):
    """Render one tree (matplotlib image of the graphviz digraph —
    reference: plotting.py plot_tree)."""
    plt = _check_matplotlib()
    g = create_tree_digraph(booster, tree_index=tree_index, **kwargs)
    import io as _io
    try:
        png = g.pipe(format="png")
    except Exception as e:  # pragma: no cover - graphviz binary missing
        raise RuntimeError("graphviz executable not available: %s" % e)
    img = plt.imread(_io.BytesIO(png))
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ax.imshow(img)
    ax.axis("off")
    return ax
