"""ctypes binding for the native text parser (native/parser.cpp).

The shared library builds on first use with the baked-in g++ (pybind11 is
not available in this image; the flat C ABI + ctypes mirrors how the
reference's python package binds its C API, basic.py ctypes). io.py falls
back to the pure-Python parser when no compiler is present.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional, Tuple

import numpy as np

from .utils.log import Log

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_lib() -> Optional[str]:
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "native", "parser.cpp")
    # per-user cache dir (a fixed world-writable /tmp path would allow
    # another local user to plant a library) + atomic rename so concurrent
    # builders never dlopen a half-written file
    out_dir = os.environ.get("LIGHTGBM_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "lightgbm_tpu")
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, "libparser.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++14", "-o", tmp, src]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        Log.debug("native parser build unavailable: %s", e)
        return None
    if r.returncode != 0:
        Log.warning("native parser build failed; using the Python parser:\n%s",
                    r.stderr[-500:])
        os.unlink(tmp)
        return None
    os.replace(tmp, out)
    return out


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _build_lib()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.count_dims.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
                               ctypes.POINTER(ctypes.c_int64),
                               ctypes.POINTER(ctypes.c_int64)]
    lib.count_dims.restype = ctypes.c_int
    dptr = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
    lib.parse_dense.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                ctypes.c_int64, ctypes.c_int64, dptr]
    lib.parse_dense.restype = ctypes.c_int
    lib.parse_libsvm.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                 ctypes.c_int64, dptr]
    lib.parse_libsvm.restype = ctypes.c_int
    _LIB = lib
    return lib


def parse_file(path: str,
               expect_fmt: Optional[str] = None
               ) -> Optional[Tuple[np.ndarray, str]]:
    """Parse a CSV/TSV/LibSVM file natively.

    Returns (matrix, fmt) where matrix column 0 is the raw first column
    (the caller applies label/ignore-column semantics), fmt in
    {"csv", "tsv", "space", "libsvm"} — or None when the native path is
    unavailable or the detected format differs from ``expect_fmt``
    (caller falls back to Python).
    """
    lib = get_lib()
    if lib is None:
        return None
    sep = ctypes.c_int(0)
    rows = ctypes.c_int64(0)
    cols = ctypes.c_int64(0)
    if lib.count_dims(path.encode(), ctypes.byref(sep), ctypes.byref(rows),
                      ctypes.byref(cols)) != 0:
        return None
    n, c = int(rows.value), int(cols.value)
    if n == 0 or c == 0:
        return None
    detected = "libsvm" if sep.value == -1 else \
        {",": "csv", "\t": "tsv"}.get(chr(sep.value), "space")
    if expect_fmt is not None and detected != expect_fmt:
        return None
    out = np.empty((n, c), dtype=np.float64)
    if sep.value == -1:
        rc = lib.parse_libsvm(path.encode(), n, c, out)
        fmt = "libsvm"
    else:
        rc = lib.parse_dense(path.encode(), sep.value, n, c, out)
        fmt = {",": "csv", "\t": "tsv"}.get(chr(sep.value), "space")
    if rc != 0:
        return None
    return out, fmt
