"""ctypes binding for the native text parser (native/parser.cpp).

The shared library builds on first use with the baked-in g++ (pybind11 is
not available in this image; the flat C ABI + ctypes mirrors how the
reference's python package binds its C API, basic.py ctypes). io.py falls
back to the pure-Python parser when no compiler is present.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional, Tuple

import numpy as np

from .utils.log import Log

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_lib(src_name: str = "parser.cpp",
               lib_name: str = "libparser.so",
               extra_flags: tuple = ()) -> Optional[str]:
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "native", src_name)
    # per-user cache dir (a fixed world-writable /tmp path would allow
    # another local user to plant a library) + atomic rename so concurrent
    # builders never dlopen a half-written file
    out_dir = os.environ.get("LIGHTGBM_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "lightgbm_tpu")
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, lib_name)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++14", "-o", tmp, src]
    cmd[1:1] = list(extra_flags)
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        Log.debug("native build unavailable (%s): %s", src_name, e)
        return None
    if r.returncode != 0:
        Log.warning("native build of %s failed; using the Python path:\n%s",
                    src_name, r.stderr[-500:])
        os.unlink(tmp)
        return None
    os.replace(tmp, out)
    return out


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _build_lib()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.count_dims.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
                               ctypes.POINTER(ctypes.c_int64),
                               ctypes.POINTER(ctypes.c_int64)]
    lib.count_dims.restype = ctypes.c_int
    dptr = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
    lib.parse_dense.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                ctypes.c_int64, ctypes.c_int64, dptr]
    lib.parse_dense.restype = ctypes.c_int
    lib.parse_libsvm.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                 ctypes.c_int64, dptr]
    lib.parse_libsvm.restype = ctypes.c_int
    _LIB = lib
    return lib


def parse_file(path: str,
               expect_fmt: Optional[str] = None
               ) -> Optional[Tuple[np.ndarray, str]]:
    """Parse a CSV/TSV/LibSVM file natively.

    Returns (matrix, fmt) where matrix column 0 is the raw first column
    (the caller applies label/ignore-column semantics), fmt in
    {"csv", "tsv", "space", "libsvm"} — or None when the native path is
    unavailable or the detected format differs from ``expect_fmt``
    (caller falls back to Python).
    """
    lib = get_lib()
    if lib is None:
        return None
    sep = ctypes.c_int(0)
    rows = ctypes.c_int64(0)
    cols = ctypes.c_int64(0)
    if lib.count_dims(path.encode(), ctypes.byref(sep), ctypes.byref(rows),
                      ctypes.byref(cols)) != 0:
        return None
    n, c = int(rows.value), int(cols.value)
    if n == 0 or c == 0:
        return None
    detected = "libsvm" if sep.value == -1 else \
        {",": "csv", "\t": "tsv"}.get(chr(sep.value), "space")
    if expect_fmt is not None and detected != expect_fmt:
        return None
    out = np.empty((n, c), dtype=np.float64)
    if sep.value == -1:
        rc = lib.parse_libsvm(path.encode(), n, c, out)
        fmt = "libsvm"
    else:
        rc = lib.parse_dense(path.encode(), sep.value, n, c, out)
        fmt = {",": "csv", "\t": "tsv"}.get(chr(sep.value), "space")
    if rc != 0:
        return None
    return out, fmt


# ---------------------------------------------------------------------------
# Native threaded bin application (native/binning.cpp)
# ---------------------------------------------------------------------------

_BIN_LIB: Optional[ctypes.CDLL] = None
_BIN_TRIED = False


def get_binning_lib() -> Optional[ctypes.CDLL]:
    global _BIN_LIB, _BIN_TRIED
    if _BIN_TRIED:
        return _BIN_LIB
    _BIN_TRIED = True
    path = _build_lib("binning.cpp", "libbinning.so", ("-pthread",))
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
        lib.lgbm_apply_bins_u8.argtypes = [
            f64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, i32p,
            f64p, i64p, i32p, i32p, i32p, u8p, ctypes.c_int64, i32p,
            ctypes.c_int32]
        lib.lgbm_apply_bins_u8.restype = None
    except (OSError, AttributeError) as e:
        # a corrupted/stale cached .so must degrade to the numpy path, the
        # same contract as compile failures in _build_lib
        Log.warning("native binning library unusable (%s); using numpy", e)
        return None
    _BIN_LIB = lib
    return lib


def apply_bins_native(Xv: np.ndarray, specs, out: np.ndarray,
                      nthreads: int = 0) -> bool:
    """Bin a batch of numerical features into `out` columns natively.

    specs: list of (x_col, upper_bounds f64 array, missing_type,
    missing_bin, out_col). Returns False when the native library is
    unavailable (caller falls back to numpy searchsorted).
    """
    lib = get_binning_lib()
    if lib is None or not specs:
        return False
    col_idx = np.asarray([s[0] for s in specs], np.int32)
    bounds_cat = np.concatenate([np.asarray(s[1], np.float64) for s in specs])
    off = np.zeros(len(specs), np.int64)
    nb = np.asarray([len(s[1]) for s in specs], np.int32)
    np.cumsum(nb[:-1], out=off[1:])
    mtype = np.asarray([s[2] for s in specs], np.int32)
    mbin = np.asarray([s[3] for s in specs], np.int32)
    ocol = np.asarray([s[4] for s in specs], np.int32)
    lib.lgbm_apply_bins_u8(
        np.ascontiguousarray(Xv), Xv.shape[0], Xv.shape[1],
        np.int32(len(specs)), col_idx, bounds_cat, off, nb, mtype, mbin,
        out, out.shape[1], ocol,
        np.int32(nthreads if nthreads > 0 else (os.cpu_count() or 1)))
    return True
