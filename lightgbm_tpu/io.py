"""Text dataset loading: CSV / TSV / LibSVM with auto-detection.

Equivalent of the reference's Parser + DatasetLoader text path (reference:
src/io/parser.cpp Parser::CreateParser format auto-detect,
src/io/dataset_loader.cpp:182 LoadFromFile) including label/weight/group
column designation, ignore columns, header handling, and the sidecar
``.query``/``.weight`` files the reference CLI reads
(src/io/metadata.cpp LoadQueryBoundaries/LoadWeights).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .config import Config
from .utils.log import Log


def detect_format(first_lines: List[str]) -> str:
    """'csv' | 'tsv' | 'libsvm' (reference: parser.cpp DetermineDataType)."""
    for line in first_lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.replace("\t", " ").split()
        if any(":" in t for t in tokens[1:]):
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
    return "tsv"


def _parse_column_spec(spec: str, header_names: Optional[List[str]]) -> int:
    """Column spec: int index or 'name:<col>' (reference: config docs
    label_column)."""
    if spec is None or spec == "":
        return -1
    if isinstance(spec, int):
        return spec
    s = str(spec)
    if s.startswith("name:"):
        name = s[5:]
        if header_names and name in header_names:
            return header_names.index(name)
        Log.fatal("Column name '%s' not found in header", name)
    return int(s)


def load_text_file(
    filename: str,
    config: Config,
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray],
           Optional[np.ndarray], Optional[List[str]]]:
    """Returns (X, label, weight, group_sizes, feature_names)."""
    if not os.path.exists(filename):
        Log.fatal("Data file %s does not exist", filename)
    with open(filename) as f:
        head = [f.readline() for _ in range(3)]
    has_header = bool(config.header)
    fmt = detect_format(head[1 if has_header else 0:])

    header_names: Optional[List[str]] = None
    skip = 0
    if has_header:
        sep = {"csv": ",", "tsv": "\t"}.get(fmt)
        header_names = [c.strip() for c in head[0].strip().split(sep)] if sep else None
        skip = 1

    if fmt == "libsvm":
        from .io_native import parse_file
        parsed = None if skip else parse_file(filename, expect_fmt="libsvm")
        if parsed is not None:
            M = parsed[0]
            label, X = M[:, 0], M[:, 1:]
        else:
            X, label = _load_libsvm(filename, skip)
        weight = None
        feature_names = None
        label_idx = -1
        used_cols = None
    else:
        sep = "," if fmt == "csv" else "\t"
        raw = None
        if not skip:
            # native parser (native/parser.cpp via ctypes) — the reference's
            # C++ Parser/fast_double_parser analog
            from .io_native import parse_file
            parsed = parse_file(filename, expect_fmt=fmt)
            if parsed is not None:
                raw = parsed[0]
        if raw is None:
            raw = np.genfromtxt(filename, delimiter=sep, skip_header=skip,
                                dtype=np.float64)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 1)
        ncol = raw.shape[1]
        label_idx = _parse_column_spec(config.label_column or "0", header_names)
        weight_idx = _parse_column_spec(config.weight_column, header_names)
        group_idx = _parse_column_spec(config.group_column, header_names)
        ignore: set = set()
        if config.ignore_column:
            for tok in str(config.ignore_column).split(","):
                if tok:
                    ignore.add(_parse_column_spec(tok, header_names))
        special = {label_idx} | ignore
        if weight_idx >= 0:
            special.add(weight_idx)
        if group_idx >= 0:
            special.add(group_idx)
        used_cols = [c for c in range(ncol) if c not in special]
        X = raw[:, used_cols]
        label = raw[:, label_idx] if 0 <= label_idx < ncol else None
        weight = raw[:, weight_idx] if weight_idx >= 0 else None
        feature_names = [header_names[c] for c in used_cols] if header_names else None
        group_col = raw[:, group_idx] if group_idx >= 0 else None
        if group_col is not None:
            # run lengths in order of appearance: query ids need not be
            # sorted, only contiguous (reference: metadata.cpp SetQuery)
            gc = group_col.astype(np.int64)
            change = np.flatnonzero(np.diff(gc)) + 1
            bounds = np.concatenate([[0], change, [len(gc)]])
            group = np.diff(bounds)
        else:
            group = None
    if fmt == "libsvm":
        group = None

    # sidecar files (reference: metadata.cpp — "<data>.query"/".weight")
    qfile = filename + ".query"
    if group is None and os.path.exists(qfile):
        group = np.loadtxt(qfile, dtype=np.int64).ravel()
    wfile = filename + ".weight"
    if weight is None and os.path.exists(wfile):
        weight = np.loadtxt(wfile, dtype=np.float64).ravel()
    return X, label, weight, group, feature_names


def _load_libsvm(filename: str, skip: int) -> Tuple[np.ndarray, np.ndarray]:
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = -1
    with open(filename) as f:
        for i, line in enumerate(f):
            if i < skip:
                continue
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            labels.append(float(toks[0]))
            row: Dict[int, float] = {}
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                idx = int(k)
                row[idx] = float(v)
                max_idx = max(max_idx, idx)
            rows.append(row)
    X = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for r, row in enumerate(rows):
        for k, v in row.items():
            X[r, k] = v
    return X, np.asarray(labels)


def load_config_file(path: str) -> Dict[str, str]:
    """Parse a LightGBM-style config file: ``key = value`` lines, ``#``
    comments (reference: application.cpp:52 LoadParameters)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# ---------------------------------------------------------------------------
# Two-round (low-memory) loading
# ---------------------------------------------------------------------------

def _dense_line_chunks(filename: str, skip: int, sep, chunk_rows: int):
    """Stream a dense text file as parsed float chunks (never the whole
    matrix). Uses pandas' C parser when available (~20x numpy's Python
    float loop — the native parser.cpp is whole-file, so the streaming
    low-memory paths chunk through pandas instead)."""
    try:
        import pandas as pd
        reader = pd.read_csv(filename, sep=sep if sep else r"\s+",
                             header=None, skiprows=skip, dtype=np.float64,
                             chunksize=chunk_rows, comment=None,
                             skip_blank_lines=True, engine="c")
        for chunk in reader:
            yield chunk.to_numpy(dtype=np.float64)
        return
    except ImportError:  # pragma: no cover - pandas is baked in
        pass
    buf: List[str] = []
    with open(filename) as f:
        for _ in range(skip):
            f.readline()
        for line in f:
            if line.strip():
                buf.append(line)
            if len(buf) >= chunk_rows:
                yield np.loadtxt(buf, delimiter=sep, ndmin=2)
                buf = []
    if buf:
        yield np.loadtxt(buf, delimiter=sep, ndmin=2)


def load_dataset_two_round(filename: str, config: Config,
                           chunk_rows: int = 200_000):
    """Two-pass low-memory dataset construction (reference:
    DatasetLoader two-round path, src/io/dataset_loader.cpp — sample on the
    first pass, bin row blocks on the second; the raw double matrix is
    never materialized).

    Pass 1 streams the file once: counts rows, collects label/weight/group
    columns and a uniform reservoir sample of feature rows. The sample
    drives bin finding / EFB / trivial-feature pruning exactly like the
    in-memory path (which also samples, bin_construct_sample_cnt). Pass 2
    streams again, binning each block straight into the final uint8 matrix.
    """
    from .dataset import Metadata, _extract_binned, construct_dataset

    if not os.path.exists(filename):
        Log.fatal("Data file %s does not exist", filename)
    if config.linear_tree:
        Log.fatal("two_round does not keep raw values; disable linear_tree "
                  "or two_round")
    with open(filename) as f:
        head = [f.readline() for _ in range(3)]
    has_header = bool(config.header)
    fmt = detect_format(head[1 if has_header else 0:])
    if fmt == "libsvm":
        Log.warning("two_round supports dense text; using the standard "
                    "libsvm loader")
        return None
    sep = "," if fmt == "csv" else ("\t" if fmt == "tsv" else None)
    header_names = None
    skip = 0
    if has_header:
        header_names = [c.strip() for c in head[0].strip().split(sep)] \
            if sep else None
        skip = 1

    data_line = next((l for l in head[skip:] if l and l.strip()), None)
    if data_line is None:
        Log.fatal("Data file %s has no data rows", filename)
    first = np.loadtxt([data_line], delimiter=sep, ndmin=2)
    ncol = first.shape[1]
    label_idx = _parse_column_spec(config.label_column or "0", header_names)
    weight_idx = _parse_column_spec(config.weight_column, header_names)
    group_idx = _parse_column_spec(config.group_column, header_names)
    ignore: set = set()
    if config.ignore_column:
        for tok in str(config.ignore_column).split(","):
            if tok:
                ignore.add(_parse_column_spec(tok, header_names))
    special = {label_idx} | ignore
    if weight_idx >= 0:
        special.add(weight_idx)
    if group_idx >= 0:
        special.add(group_idx)
    used_cols = [c for c in range(ncol) if c not in special]
    feature_names = [header_names[c] for c in used_cols] if header_names \
        else None

    # ---- pass 1: count + metadata columns + reservoir sample ----
    target = max(2, int(config.bin_construct_sample_cnt))
    rng = np.random.RandomState(config.data_random_seed)
    sample = None
    n_seen = 0
    labels, weights, gcols = [], [], []
    for chunk in _dense_line_chunks(filename, skip, sep, chunk_rows):
        if 0 <= label_idx < ncol:
            labels.append(chunk[:, label_idx].copy())
        if weight_idx >= 0:
            weights.append(chunk[:, weight_idx].copy())
        if group_idx >= 0:
            gcols.append(chunk[:, group_idx].copy())
        Xc = chunk[:, used_cols]
        m = len(Xc)
        if sample is None:
            sample = np.empty((target, len(used_cols)), np.float64)
        # vectorized reservoir update: row (n_seen + i) replaces a random
        # slot with probability target / (n_seen + i + 1)
        fill = min(max(target - n_seen, 0), m)
        if fill:
            sample[n_seen:n_seen + fill] = Xc[:fill]
        if m > fill:
            idx = np.arange(n_seen + fill, n_seen + m)
            r = (rng.random_sample(m - fill) * (idx + 1)).astype(np.int64)
            keep = r < target
            sample[r[keep]] = Xc[fill:][keep]
        n_seen += m
    if n_seen == 0:
        Log.fatal("Data file %s is empty", filename)
    X_sample = sample[:min(target, n_seen)]

    label = np.concatenate(labels) if labels else None
    weight = np.concatenate(weights) if weights else None
    group = None
    if gcols:
        gc = np.concatenate(gcols).astype(np.int64)
        change = np.flatnonzero(np.diff(gc)) + 1
        group = np.diff(np.concatenate([[0], change, [len(gc)]]))
    qfile = filename + ".query"
    if group is None and os.path.exists(qfile):
        group = np.loadtxt(qfile, dtype=np.int64).ravel()
    wfile = filename + ".weight"
    if weight is None and os.path.exists(wfile):
        weight = np.loadtxt(wfile, dtype=np.float64).ravel()

    # structure (bin mappers, EFB, pruning) from the sample
    ds = construct_dataset(X_sample, config, feature_names=feature_names,
                           categorical_feature=None)
    # ---- pass 2: bin row blocks into the final matrix ----
    ds.num_data = n_seen
    ds.metadata = Metadata(n_seen, label=label, weight=weight, group=group)
    out = np.zeros((n_seen, ds.num_groups), dtype=ds.binned.dtype)
    r0 = 0
    for chunk in _dense_line_chunks(filename, skip, sep, chunk_rows):
        Xc = chunk[:, used_cols]
        out[r0:r0 + len(Xc)] = _extract_binned(
            Xc, ds, nthreads=int(config.num_threads))
        r0 += len(Xc)
    ds.binned = out
    ds.raw_numeric = None
    return ds


def load_dataset_sharded(filename: str, config: Config, rank: Optional[int] = None,
                         world: Optional[int] = None, sample_gather=None,
                         count_gather=None):
    """Per-host sharded dataset loading (reference: the distributed loader,
    src/io/dataset_loader.cpp:182,951 — each rank reads its row partition,
    bin mappers are found from globally-gathered samples so every rank owns
    IDENTICAL binning without ever materializing the full matrix anywhere).

    - rank/world default to jax.process_index()/process_count().
    - Each rank streams the file and keeps only rows [rank*N/world, ...):
      the peak memory is one parse chunk plus the local shard.
    - Bin finding: every rank reservoir-samples its slice; samples are
      allgathered (``sample_gather``, defaulting to
      jax.experimental.multihost_utils.process_allgather on pods and
      identity single-process) and every rank derives the same BinMappers
      deterministically from the same global sample.
    - Returns a BinnedDataset holding ONLY the local row shard, with
      ``shard_info = (rank, world, n_total)``; the mesh learners assemble
      the global device array from per-process shards
      (jax.make_array_from_process_local_data).
    """
    import jax

    from .dataset import Metadata, _extract_binned, construct_dataset

    if rank is None:
        rank = jax.process_index()
    if world is None:
        world = jax.process_count()
    if not os.path.exists(filename):
        Log.fatal("Data file %s does not exist", filename)
    with open(filename) as f:
        head = [f.readline() for _ in range(3)]
    has_header = bool(config.header)
    fmt = detect_format(head[1 if has_header else 0:])
    if fmt == "libsvm":
        Log.fatal("sharded loading supports dense text formats")
    sep = "," if fmt == "csv" else ("\t" if fmt == "tsv" else None)
    header_names = None
    skip = 0
    if has_header:
        header_names = [c.strip() for c in head[0].strip().split(sep)] \
            if sep else None
        skip = 1
    data_line = next((l for l in head[skip:] if l and l.strip()), None)
    if data_line is None:
        Log.fatal("Data file %s has no data rows", filename)
    ncol = np.loadtxt([data_line], delimiter=sep, ndmin=2).shape[1]
    label_idx = _parse_column_spec(config.label_column or "0", header_names)
    weight_idx = _parse_column_spec(config.weight_column, header_names)
    group_idx = _parse_column_spec(config.group_column, header_names)
    ignore: set = set()
    if config.ignore_column:
        for tok in str(config.ignore_column).split(","):
            if tok:
                ignore.add(_parse_column_spec(tok, header_names))
    special = {label_idx} | ignore
    if weight_idx >= 0:
        special.add(weight_idx)
    if group_idx >= 0:
        special.add(group_idx)
    used_cols = [c for c in range(ncol) if c not in special]
    feature_names = [header_names[c] for c in used_cols] if header_names \
        else None

    if config.pre_partition:
        # the file IS this rank's partition already (reference:
        # config.h pre_partition; dataset_loader.cpp LoadFromFile skips
        # the row modulo-split when is_pre_partition) — keep every row;
        # no counting pass needed
        n_total = -1
        r0, r1 = 0, np.iinfo(np.int64).max
    else:
        # pass 1: count data rows (stream, no parsing)
        n_total = 0
        with open(filename) as f:
            for _ in range(skip):
                f.readline()
            for line in f:
                if line.strip():
                    n_total += 1
        r0 = rank * n_total // world
        r1 = (rank + 1) * n_total // world

    # pass 2: stream; keep only [r0, r1); reservoir-sample the local slice.
    # Every rank fills a uniform budget//world slot (identical allgather
    # shapes; pooled sample bounded by the configured budget); pad rows
    # inside a slot are dropped after the gather (see below)
    target = max(2, int(config.bin_construct_sample_cnt) // world)
    rng = np.random.RandomState(config.data_random_seed + rank)
    sample = np.empty((target, len(used_cols)), np.float64)
    n_samp = 0
    locals_X, locals_y, locals_w, locals_g = [], [], [], []
    seen = 0
    for chunk in _dense_line_chunks(filename, skip, sep, 100_000):
        c0, c1 = seen, seen + len(chunk)
        seen = c1
        lo, hi = max(r0, c0), min(r1, c1)
        if lo < hi:
            part = chunk[lo - c0:hi - c0]
            locals_X.append(part[:, used_cols])
            if 0 <= label_idx < ncol:
                locals_y.append(part[:, label_idx].copy())
            if weight_idx >= 0:
                locals_w.append(part[:, weight_idx].copy())
            if group_idx >= 0:
                locals_g.append(part[:, group_idx].copy())
            Xc = part[:, used_cols]
            m = len(Xc)
            fill = min(max(target - n_samp, 0), m)
            if fill:
                sample[n_samp:n_samp + fill] = Xc[:fill]
            if m > fill:
                idx = np.arange(n_samp + fill, n_samp + m)
                r = (rng.random_sample(m - fill) * (idx + 1)).astype(np.int64)
                keep = r < target
                sample[r[keep]] = Xc[fill:][keep]
            n_samp += m
    X_local = np.concatenate(locals_X) if locals_X else \
        np.zeros((0, len(used_cols)))
    if config.pre_partition:
        n_total = seen  # pass 2 counted the local file; world>1 gathers below
    local_sample = sample[:min(target, n_samp)]
    valid_rows = None
    shard_rows = None
    can_gather_stats = count_gather is not None \
        or jax.process_count() == world
    if world > 1 and config.pre_partition and not can_gather_stats:
        Log.fatal("pre_partition sharded loading needs per-rank stats: run "
                  "under jax.distributed with %d processes or supply "
                  "count_gather", world)
    if world > 1 and len(local_sample) == 0:
        Log.fatal("rank %d: no data rows in %s", rank, filename)
    default_gather = sample_gather is None
    if world > 1 and can_gather_stats:
        if count_gather is None:
            from jax.experimental import multihost_utils

            def count_gather(x):
                return multihost_utils.process_allgather(x)
        # per-rank (rows, samples held) — drives both the proportional
        # sample weighting and (for pre_partition) the shard capacity
        stats = np.asarray(count_gather(np.asarray(
            [float(seen if config.pre_partition else len(X_local)),
             float(len(local_sample))]))).reshape(world, 2)
        shard_rows = stats[:, 0]
        held = stats[:, 1].astype(np.int64)
        if config.pre_partition:
            # unequal shards: weight each rank's slot by its row share so
            # the pooled quantile sample tracks the true distribution.
            # Water-fill: ranks clipped at their held sample hand their
            # unused entitlement to the others, keeping relative shares
            share = shard_rows / max(shard_rows.sum(), 1.0)
            budget = target * world
            alloc = np.minimum(held, np.maximum(2, np.round(budget * share)))
            for _ in range(3):
                leftover = budget - alloc.sum()
                room = held - alloc
                open_share = share * (room > 0)
                if leftover <= 0 or open_share.sum() <= 0:
                    break
                alloc = np.minimum(held, alloc + np.round(
                    leftover * open_share / open_share.sum()))
            valid_rows = alloc.astype(np.int64)
        else:
            valid_rows = held
    if world > 1 and len(local_sample) < target:
        # identical allgather shapes on every rank: pad the slot by
        # cycling local rows; with stats available the pad rows are sliced
        # off after the gather
        if not default_gather:
            # a custom sample_gather receives the padded slot verbatim and
            # only the default path slices the duplicates back out —
            # duplicated rows bias quantile bin boundaries unless the
            # caller trims to the gathered per-rank counts itself
            Log.warning(
                "rank %d pads its quantile sample %d -> %d rows; the "
                "custom sample_gather sees duplicated rows (trim with the "
                "per-rank counts from count_gather)", rank,
                len(local_sample), target)
        reps = -(-target // len(local_sample))
        local_sample = np.tile(local_sample, (reps, 1))[:target]

    if sample_gather is None:
        if world > 1:
            from jax.experimental import multihost_utils

            def sample_gather(x):
                return multihost_utils.process_allgather(x).reshape(
                    -1, x.shape[1])
        else:
            def sample_gather(x):
                return x
    global_sample = np.asarray(sample_gather(local_sample))
    if valid_rows is not None and default_gather:
        # drop each rank's slot padding (every rank computes the identical
        # slice from the identical gathered stats). Only the DEFAULT
        # gather guarantees the (world, target) slot layout; custom
        # gathers own their sample weighting.
        if global_sample.shape[0] != world * target:
            Log.fatal("process_allgather returned %d sample rows, expected "
                      "%d", global_sample.shape[0], world * target)
        blocks = global_sample.reshape(world, target, -1)
        global_sample = np.concatenate(
            [blocks[r, :valid_rows[r]] for r in range(world)])

    # identical structure on every rank from the identical global sample
    ds = construct_dataset(global_sample, config,
                           feature_names=feature_names,
                           categorical_feature=None)
    group = None
    if locals_g:
        # per-row query ids: queries must not straddle shard boundaries —
        # the local slice must start/end on query edges for correct ranking
        gc = np.concatenate(locals_g).astype(np.int64)
        change = np.flatnonzero(np.diff(gc)) + 1
        group = np.diff(np.concatenate([[0], change, [len(gc)]]))
    elif os.path.exists(filename + ".query"):
        # pre-partitioned files own complete query sets, so their sidecars
        # apply verbatim; only the rank row-split cannot honor sidecars
        if world > 1 and not config.pre_partition:
            Log.fatal("sharded loading with a .query sidecar is not "
                      "supported (query sizes cannot be split per rank); "
                      "use a group_column instead")
        group = np.loadtxt(filename + ".query", dtype=np.int64).ravel()
    wfile = filename + ".weight"
    if not locals_w and os.path.exists(wfile):
        if world > 1 and not config.pre_partition:
            Log.fatal("sharded loading with a .weight sidecar is not "
                      "supported; use a weight_column instead")
        locals_w = [np.loadtxt(wfile, dtype=np.float64).ravel()]
    ds.num_data = len(X_local)
    ds.metadata = Metadata(
        len(X_local),
        label=np.concatenate(locals_y) if locals_y else None,
        weight=np.concatenate(locals_w) if locals_w else None,
        group=group)
    ds.binned = _extract_binned(X_local, ds,
                                nthreads=int(config.num_threads))
    ds.raw_numeric = None
    if config.pre_partition and world > 1:
        # pre-partitioned files may be unequal; the mesh assembles uniform
        # per-process blocks, so publish a capacity of world * max(local).
        # Padding rows carry zero gradients/hessians/counts and never
        # affect histograms or splits. shard_rows came from the stats
        # gather above.
        n_total = int(shard_rows.max()) * world
    ds.shard_info = (int(rank), int(world), int(n_total))
    return ds
