"""Text dataset loading: CSV / TSV / LibSVM with auto-detection.

Equivalent of the reference's Parser + DatasetLoader text path (reference:
src/io/parser.cpp Parser::CreateParser format auto-detect,
src/io/dataset_loader.cpp:182 LoadFromFile) including label/weight/group
column designation, ignore columns, header handling, and the sidecar
``.query``/``.weight`` files the reference CLI reads
(src/io/metadata.cpp LoadQueryBoundaries/LoadWeights).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .config import Config
from .utils.log import Log


def detect_format(first_lines: List[str]) -> str:
    """'csv' | 'tsv' | 'libsvm' (reference: parser.cpp DetermineDataType)."""
    for line in first_lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.replace("\t", " ").split()
        if any(":" in t for t in tokens[1:]):
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
    return "tsv"


def _parse_column_spec(spec: str, header_names: Optional[List[str]]) -> int:
    """Column spec: int index or 'name:<col>' (reference: config docs
    label_column)."""
    if spec is None or spec == "":
        return -1
    if isinstance(spec, int):
        return spec
    s = str(spec)
    if s.startswith("name:"):
        name = s[5:]
        if header_names and name in header_names:
            return header_names.index(name)
        Log.fatal("Column name '%s' not found in header", name)
    return int(s)


def load_text_file(
    filename: str,
    config: Config,
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray],
           Optional[np.ndarray], Optional[List[str]]]:
    """Returns (X, label, weight, group_sizes, feature_names)."""
    if not os.path.exists(filename):
        Log.fatal("Data file %s does not exist", filename)
    with open(filename) as f:
        head = [f.readline() for _ in range(3)]
    has_header = bool(config.header)
    fmt = detect_format(head[1 if has_header else 0:])

    header_names: Optional[List[str]] = None
    skip = 0
    if has_header:
        sep = {"csv": ",", "tsv": "\t"}.get(fmt)
        header_names = [c.strip() for c in head[0].strip().split(sep)] if sep else None
        skip = 1

    if fmt == "libsvm":
        from .io_native import parse_file
        parsed = None if skip else parse_file(filename, expect_fmt="libsvm")
        if parsed is not None:
            M = parsed[0]
            label, X = M[:, 0], M[:, 1:]
        else:
            X, label = _load_libsvm(filename, skip)
        weight = None
        feature_names = None
        label_idx = -1
        used_cols = None
    else:
        sep = "," if fmt == "csv" else "\t"
        raw = None
        if not skip:
            # native parser (native/parser.cpp via ctypes) — the reference's
            # C++ Parser/fast_double_parser analog
            from .io_native import parse_file
            parsed = parse_file(filename, expect_fmt=fmt)
            if parsed is not None:
                raw = parsed[0]
        if raw is None:
            raw = np.genfromtxt(filename, delimiter=sep, skip_header=skip,
                                dtype=np.float64)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 1)
        ncol = raw.shape[1]
        label_idx = _parse_column_spec(config.label_column or "0", header_names)
        weight_idx = _parse_column_spec(config.weight_column, header_names)
        group_idx = _parse_column_spec(config.group_column, header_names)
        ignore: set = set()
        if config.ignore_column:
            for tok in str(config.ignore_column).split(","):
                if tok:
                    ignore.add(_parse_column_spec(tok, header_names))
        special = {label_idx} | ignore
        if weight_idx >= 0:
            special.add(weight_idx)
        if group_idx >= 0:
            special.add(group_idx)
        used_cols = [c for c in range(ncol) if c not in special]
        X = raw[:, used_cols]
        label = raw[:, label_idx] if 0 <= label_idx < ncol else None
        weight = raw[:, weight_idx] if weight_idx >= 0 else None
        feature_names = [header_names[c] for c in used_cols] if header_names else None
        group_col = raw[:, group_idx] if group_idx >= 0 else None
        if group_col is not None:
            # run lengths in order of appearance: query ids need not be
            # sorted, only contiguous (reference: metadata.cpp SetQuery)
            gc = group_col.astype(np.int64)
            change = np.flatnonzero(np.diff(gc)) + 1
            bounds = np.concatenate([[0], change, [len(gc)]])
            group = np.diff(bounds)
        else:
            group = None
    if fmt == "libsvm":
        group = None

    # sidecar files (reference: metadata.cpp — "<data>.query"/".weight")
    qfile = filename + ".query"
    if group is None and os.path.exists(qfile):
        group = np.loadtxt(qfile, dtype=np.int64).ravel()
    wfile = filename + ".weight"
    if weight is None and os.path.exists(wfile):
        weight = np.loadtxt(wfile, dtype=np.float64).ravel()
    return X, label, weight, group, feature_names


def _load_libsvm(filename: str, skip: int) -> Tuple[np.ndarray, np.ndarray]:
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = -1
    with open(filename) as f:
        for i, line in enumerate(f):
            if i < skip:
                continue
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            labels.append(float(toks[0]))
            row: Dict[int, float] = {}
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                idx = int(k)
                row[idx] = float(v)
                max_idx = max(max_idx, idx)
            rows.append(row)
    X = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for r, row in enumerate(rows):
        for k, v in row.items():
            X[r, k] = v
    return X, np.asarray(labels)


def load_config_file(path: str) -> Dict[str, str]:
    """Parse a LightGBM-style config file: ``key = value`` lines, ``#``
    comments (reference: application.cpp:52 LoadParameters)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out
