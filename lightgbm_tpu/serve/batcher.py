"""Micro-batching request coalescer for the serving path.

A single background worker drains a submit queue, coalescing concurrent
``submit(X)`` calls into ONE bucketed device dispatch per batch — ensemble
inference throughput is won by amortizing launches over large coalesced
batches, so at batch size 1 the dominant cost is dispatch, not math. Two
knobs bound the trade: ``max_batch_rows`` caps how much a batch grows,
``max_wait_ms`` caps how long the first request in a batch waits for
company.

Results come back through ``concurrent.futures.Future``; a worker
exception fails every future of its batch (callers see the real error,
the worker keeps serving). ``close()`` drains and fails whatever is still
queued, then joins the thread.

Admission control: ``max_queue_rows`` bounds how many rows may sit queued
but undispatched. Overflow behavior is the ``overload`` policy — ``shed``
raises :class:`QueueFullError` at submit (the HTTP layer maps it to 429,
so overload degrades into fast rejections instead of unbounded latency),
``block`` parks submitters until the worker drains space (per-caller
backpressure; an upstream of bounded concurrency self-throttles).
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Optional

import numpy as np

from .. import obs
from ..obs import telemetry
from ..obs_trace import tracer

_STOP = object()

OVERLOAD_POLICIES = ("shed", "block")


class QueueFullError(RuntimeError):
    """submit() rejected because the queue holds ``max_queue_rows`` under
    the ``shed`` overload policy (HTTP maps this to 429)."""


class _Request:
    __slots__ = ("X", "rows", "future", "t0", "trace_id")

    def __init__(self, X: np.ndarray, trace_id: Optional[int] = None) -> None:
        self.X = X
        self.rows = X.shape[0]
        self.future: Future = Future()
        self.t0 = obs.monotonic()
        self.trace_id = trace_id


class MicroBatcher:
    """Coalesce concurrent predict requests into one device dispatch.

    ``raw_score`` applies to every request of the batcher (requests in one
    coalesced dispatch must share the output transform).
    """

    def __init__(self, session, *, max_batch_rows: int = 8192,
                 max_wait_ms: float = 2.0, raw_score: bool = False,
                 latency_window: int = 2048, max_queue_rows: int = 0,
                 overload: str = "shed") -> None:
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if max_queue_rows < 0:
            raise ValueError("max_queue_rows must be >= 0 (0 = unbounded)")
        if overload not in OVERLOAD_POLICIES:
            raise ValueError("overload must be one of %s, got %r"
                             % ("|".join(OVERLOAD_POLICIES), overload))
        self._session = session
        self._max_rows = int(max_batch_rows)
        self._max_wait = float(max_wait_ms) / 1000.0
        self._raw = bool(raw_score)
        self._max_queue_rows = int(max_queue_rows)
        self._shed = overload == "shed"
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        # one lock, three jobs: (a) makes submit's closed-check atomic
        # with the enqueue so no request can slip in behind close()'s
        # _STOP and hang its Future forever; (b) guards the latency
        # histogram, which the worker feeds while callers read
        # latency_stats(); (c) guards the queued-row accounting behind
        # admission control. It is a Condition so block-policy submitters
        # can park on it until the worker drains space.
        self._lock = threading.Condition()
        self._queued_rows = 0
        # log-bucketed histogram over submit->delivery latency in ms:
        # bounded memory at any request count, exact bucket counts for
        # /metrics; also mirrored into the global registry under
        # serve/latency_ms. latency_window is kept for signature compat
        # with the old deque-based stats and is ignored.
        del latency_window
        self._hist = obs.Histogram()
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="lgbtpu-serve-batcher", daemon=True)
        self._thread.start()

    # ---------------------------------------------------------------- submit
    def submit(self, X, trace_id: Optional[int] = None) -> Future:
        """Queue one request; returns a Future resolving to its predictions
        (same shapes as ``PredictSession.predict``). A 1-D row is treated
        as a single-row batch. ``trace_id`` (from the http handler) links
        this request's queue/coalesce/dispatch spans to its request span
        when span tracing is on. Raises ``RuntimeError`` once the batcher
        is closed — atomically with close(), so a submit either lands
        before the worker's stop marker (and gets an answer or a
        deterministic 'closed' failure from the drain) or raises here; it
        never hangs.

        With ``max_queue_rows`` set, an over-limit submit raises
        :class:`QueueFullError` (shed policy) or waits for queue space
        (block policy). A request alone bigger than the whole bound is
        admitted when the queue is empty — it can never fit better than
        that, so rejecting it forever would deadlock block-policy
        callers."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if trace_id is None and tracer.serve_on:
            trace_id = tracer.new_trace_id()
        req = _Request(X, trace_id)
        with self._lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if self._max_queue_rows > 0:
                while self._queued_rows > 0 and \
                        self._queued_rows + req.rows > self._max_queue_rows:
                    if self._shed:
                        telemetry.count("serve/shed")
                        telemetry.count("serve/shed_rows", req.rows)
                        raise QueueFullError(
                            "queue holds %d rows; admitting %d more would "
                            "exceed max_queue_rows=%d"
                            % (self._queued_rows, req.rows,
                               self._max_queue_rows))
                    self._lock.wait()
                    if self._closed:
                        raise RuntimeError("MicroBatcher is closed")
            self._queued_rows += req.rows
            depth = self._queued_rows
            self._q.put(req)
        telemetry.count("serve/requests")
        telemetry.count("serve/rows", req.rows)
        telemetry.gauge("serve/queue_depth", self._q.qsize())
        telemetry.observe("serve/queue_depth_rows", depth)
        return req.future

    def queue_rows(self) -> int:
        """Rows submitted but not yet picked up by the worker (the
        admission-control quantity; /healthz queue depth)."""
        with self._lock:
            return self._queued_rows

    def _dequeued(self, req) -> None:
        # a dequeued request frees its queue-space reservation; wake any
        # block-policy submitters parked in submit()
        with self._lock:
            self._queued_rows -= req.rows
            self._lock.notify_all()

    # ---------------------------------------------------------------- worker
    def _worker(self) -> None:
        stop = False
        while not stop:
            req = self._q.get()
            if req is _STOP:
                break
            self._dequeued(req)
            batch = [req]
            rows = req.rows
            t_first = obs.monotonic()    # lead request leaves the queue
            deadline = req.t0 + self._max_wait
            while rows < self._max_rows:
                # requests already queued join for free — draining them
                # never delays anyone. Only WAITING for company is bounded
                # by the deadline; otherwise a dispatch slower than
                # max_wait_ms degenerates every backlog into batches of 1.
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    remain = deadline - obs.monotonic()
                    if remain <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=remain)
                    except queue.Empty:
                        break
                if nxt is _STOP:
                    stop = True
                    break
                self._dequeued(nxt)
                batch.append(nxt)
                rows += nxt.rows
            telemetry.gauge("serve/queue_depth", self._q.qsize())
            if tracer.serve_on:
                # retroactive spans: each request's time-in-queue (submit
                # until its batch was sealed) plus one coalesce span for
                # the assembly window itself
                now = obs.monotonic()
                for r in batch:
                    tracer.record("serve/queue_wait", r.t0, now,
                                  trace_id=r.trace_id)
                tracer.record("serve/coalesce", t_first, now,
                              trace_id=batch[0].trace_id,
                              args={"requests": len(batch), "rows": rows})
            self._run_batch(batch)
        self._drain()

    def _run_batch(self, batch) -> None:
        n_rows = sum(r.rows for r in batch)
        telemetry.count("serve/batches")
        telemetry.count("serve/batch_rows", n_rows)
        telemetry.observe("serve/batch_rows", n_rows)
        try:
            with tracer.span("serve/batch", domain="serve",
                             trace_id=batch[0].trace_id,
                             requests=len(batch), rows=n_rows):
                X = batch[0].X if len(batch) == 1 else \
                    np.concatenate([r.X for r in batch], axis=0)
                with obs.wall("serve/batch"):
                    pieces = self._session.dispatch(X)
                    # the serve path's one sanctioned device->host sync:
                    # pull the coalesced scores for result delivery
                    with tracer.span("serve/slice_back", domain="serve"):
                        host = [np.asarray(s, np.float64)[:r]  # graftlint: disable=host-sync
                                for s, r in pieces]
                raw = host[0] if len(host) == 1 else np.concatenate(host)
                out = self._session.finalize(raw, raw_score=self._raw)
        except BaseException as exc:
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(exc)
            return
        off = 0
        now = obs.monotonic()
        for r in batch:
            r.future.set_result(np.array(out[off:off + r.rows]))
            off += r.rows
            dt = now - r.t0
            with self._lock:
                self._hist.observe(dt * 1000.0)
            telemetry.observe("serve/latency_ms", dt * 1000.0)
            telemetry.add_time("wall/serve/request", dt)
        self._update_latency_gauges()

    def _update_latency_gauges(self) -> None:
        with self._lock:
            if self._hist.count == 0:
                return
            p50 = self._hist.percentile(0.50)
            p99 = self._hist.percentile(0.99)
        telemetry.gauge("serve/latency_p50_ms", round(p50, 4))
        telemetry.gauge("serve/latency_p99_ms", round(p99, 4))

    def latency_stats(self) -> dict:
        """count + p50/p90/p99/p999 (seconds) derived from the latency
        histogram buckets (bucket-interpolated, not exact order stats)."""
        with self._lock:
            n = self._hist.count
            pcts = {label: self._hist.percentile(q) / 1000.0
                    for q, label in obs._PCTS}
        if n == 0:
            return {"count": 0, "p50_s": 0.0, "p90_s": 0.0,
                    "p99_s": 0.0, "p999_s": 0.0}
        return {"count": n,
                "p50_s": pcts["p50"], "p90_s": pcts["p90"],
                "p99_s": pcts["p99"], "p999_s": pcts["p999"]}

    # -------------------------------------------------------------- shutdown
    def _drain(self) -> None:
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if r is _STOP:
                continue
            self._dequeued(r)
            if not r.future.done():
                r.future.set_exception(RuntimeError("MicroBatcher closed"))

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting requests, finish the in-flight batch, fail any
        still-queued futures, join the worker. Idempotent. The flag flip
        and the stop marker go in under the submit lock, so every request
        that beat the flip sits ahead of _STOP and gets drained;
        block-policy submitters parked for queue space are woken and
        raise instead of hanging on a dead worker."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_STOP)
            self._lock.notify_all()
        self._thread.join(timeout)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
