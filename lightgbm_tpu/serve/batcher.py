"""Micro-batching request coalescer for the serving path.

A single background worker drains a submit queue, coalescing concurrent
``submit(X)`` calls into ONE bucketed device dispatch per batch — ensemble
inference throughput is won by amortizing launches over large coalesced
batches, so at batch size 1 the dominant cost is dispatch, not math. Two
knobs bound the trade: ``max_batch_rows`` caps how much a batch grows,
``max_wait_ms`` caps how long the first request in a batch waits for
company.

Results come back through ``concurrent.futures.Future``; a worker
exception fails every future of its batch (callers see the real error,
the worker keeps serving). ``close()`` drains and fails whatever is still
queued, then joins the thread.
"""
from __future__ import annotations

import queue
import threading
from collections import deque
from concurrent.futures import Future
from typing import Optional

import numpy as np

from .. import obs
from ..obs import telemetry

_STOP = object()


class _Request:
    __slots__ = ("X", "rows", "future", "t0")

    def __init__(self, X: np.ndarray) -> None:
        self.X = X
        self.rows = X.shape[0]
        self.future: Future = Future()
        self.t0 = obs.monotonic()


class MicroBatcher:
    """Coalesce concurrent predict requests into one device dispatch.

    ``raw_score`` applies to every request of the batcher (requests in one
    coalesced dispatch must share the output transform).
    """

    def __init__(self, session, *, max_batch_rows: int = 8192,
                 max_wait_ms: float = 2.0, raw_score: bool = False,
                 latency_window: int = 2048) -> None:
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        self._session = session
        self._max_rows = int(max_batch_rows)
        self._max_wait = float(max_wait_ms) / 1000.0
        self._raw = bool(raw_score)
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        # one lock, two jobs: (a) makes submit's closed-check atomic with
        # the enqueue so no request can slip in behind close()'s _STOP and
        # hang its Future forever; (b) guards the latency deque, which the
        # worker appends to while callers read latency_stats()
        self._lock = threading.Lock()
        self._lat: deque = deque(maxlen=int(latency_window))
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="lgbtpu-serve-batcher", daemon=True)
        self._thread.start()

    # ---------------------------------------------------------------- submit
    def submit(self, X) -> Future:
        """Queue one request; returns a Future resolving to its predictions
        (same shapes as ``PredictSession.predict``). A 1-D row is treated
        as a single-row batch. Raises ``RuntimeError`` once the batcher is
        closed — atomically with close(), so a submit either lands before
        the worker's stop marker (and gets an answer or a deterministic
        'closed' failure from the drain) or raises here; it never hangs."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        req = _Request(X)
        with self._lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._q.put(req)
        telemetry.count("serve/requests")
        telemetry.count("serve/rows", req.rows)
        telemetry.gauge("serve/queue_depth", self._q.qsize())
        return req.future

    # ---------------------------------------------------------------- worker
    def _worker(self) -> None:
        stop = False
        while not stop:
            req = self._q.get()
            if req is _STOP:
                break
            batch = [req]
            rows = req.rows
            deadline = req.t0 + self._max_wait
            while rows < self._max_rows:
                # requests already queued join for free — draining them
                # never delays anyone. Only WAITING for company is bounded
                # by the deadline; otherwise a dispatch slower than
                # max_wait_ms degenerates every backlog into batches of 1.
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    remain = deadline - obs.monotonic()
                    if remain <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=remain)
                    except queue.Empty:
                        break
                if nxt is _STOP:
                    stop = True
                    break
                batch.append(nxt)
                rows += nxt.rows
            telemetry.gauge("serve/queue_depth", self._q.qsize())
            self._run_batch(batch)
        self._drain()

    def _run_batch(self, batch) -> None:
        telemetry.count("serve/batches")
        telemetry.count("serve/batch_rows", sum(r.rows for r in batch))
        try:
            X = batch[0].X if len(batch) == 1 else \
                np.concatenate([r.X for r in batch], axis=0)
            with obs.wall("serve/batch"):
                pieces = self._session.dispatch(X)
                # the serve path's one sanctioned device->host sync: pull
                # the coalesced scores for result delivery
                host = [np.asarray(s, np.float64)[:r]  # graftlint: disable=host-sync
                        for s, r in pieces]
            raw = host[0] if len(host) == 1 else np.concatenate(host)
            out = self._session.finalize(raw, raw_score=self._raw)
        except BaseException as exc:
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(exc)
            return
        off = 0
        now = obs.monotonic()
        for r in batch:
            r.future.set_result(np.array(out[off:off + r.rows]))
            off += r.rows
            dt = now - r.t0
            with self._lock:
                self._lat.append(dt)
            telemetry.add_time("wall/serve/request", dt)
        self._update_latency_gauges()

    def _update_latency_gauges(self) -> None:
        with self._lock:
            if not self._lat:
                return
            ms = np.asarray(self._lat, np.float64) * 1000.0
        telemetry.gauge("serve/latency_p50_ms",
                        round(float(np.percentile(ms, 50)), 4))
        telemetry.gauge("serve/latency_p99_ms",
                        round(float(np.percentile(ms, 99)), 4))

    def latency_stats(self) -> dict:
        """p50/p99/count over the sliding latency window (seconds)."""
        with self._lock:
            lat = sorted(self._lat)
        if not lat:
            return {"count": 0, "p50_s": 0.0, "p99_s": 0.0}
        arr = np.asarray(lat, np.float64)
        return {"count": len(lat),
                "p50_s": float(np.percentile(arr, 50)),
                "p99_s": float(np.percentile(arr, 99))}

    # -------------------------------------------------------------- shutdown
    def _drain(self) -> None:
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if r is _STOP:
                continue
            if not r.future.done():
                r.future.set_exception(RuntimeError("MicroBatcher closed"))

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting requests, finish the in-flight batch, fail any
        still-queued futures, join the worker. Idempotent. The flag flip
        and the stop marker go in under the submit lock, so every request
        that beat the flip sits ahead of _STOP and gets drained."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_STOP)
        self._thread.join(timeout)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
