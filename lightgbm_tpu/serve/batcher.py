"""Micro-batching request dispatcher with per-tenant fair queuing.

A background worker drains the pending queues, batching concurrent
``submit(X)`` calls into bucketed device dispatches — ensemble
inference throughput is won by amortizing launches over large batches,
so at batch size 1 the dominant cost is dispatch, not math. Two
dispatch disciplines:

- ``continuous`` (default): a standing dispatch loop. The worker seals
  a tile from whatever is queued RIGHT NOW and launches it
  asynchronously; a separate deliver thread performs the one
  device->host sync and resolves futures. While one tile's sync is in
  flight, newly-submitted requests accumulate and are admitted into the
  next tile — batching emerges from device-side backpressure (a bounded
  in-flight window) instead of from a wall-clock company wait, so an
  idle server answers a lone request immediately instead of parking it
  for ``max_wait_ms``.
- ``coalesce``: the classic single-thread discipline — the first
  request of a batch waits up to ``max_wait_ms`` for company, then the
  batch is dispatched and delivered inline before the next is formed.

Two knobs bound batch growth in both modes: ``max_batch_rows`` caps how
much a tile grows; ``max_wait_ms`` caps the company wait (coalesce
only — continuous never waits for company).

Results come back through ``concurrent.futures.Future``; a worker
exception fails every future of its batch (callers see the real error,
the worker keeps serving). ``close()`` finishes the in-flight batch,
fails whatever is still queued, then joins the thread.

Multi-tenant fairness (the fleet layer): every request belongs to a
tenant (default ``"default"``), each tenant has its own pending deque,
and the worker picks the next request by **start-time fair queuing**:
the active tenant with the smallest virtual time goes first, and
dequeuing ``r`` rows advances that tenant's clock by ``r / weight`` —
so over any backlog window tenants drain rows proportionally to their
weights and a flooding tenant cannot starve the rest. An idle tenant's
clock is pulled up to the global virtual clock when it becomes active
again (no credit hoarding).

Admission control, two layers:

- ``max_queue_rows`` bounds TOTAL queued-but-undispatched rows (the
  memory/latency bound).
- ``tenant_quota_rows`` bounds each single tenant's queued rows (the
  noisy-neighbor bound): one tenant hitting its quota sheds/blocks only
  itself while others keep being admitted.

Overflow behavior is the ``overload`` policy — ``shed`` raises
:class:`QueueFullError` at submit (the HTTP layer maps it to 429, so
overload degrades into fast rejections instead of unbounded latency),
``block`` parks the submitter until the worker drains space. Per-tenant
shed counts and queue depths are exported as ``serve/tenant/<t>/*``
counters//gauges and via :meth:`MicroBatcher.tenant_stats` (/healthz).
"""
from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from .. import obs
from ..obs import telemetry
from ..obs_trace import tracer

OVERLOAD_POLICIES = ("shed", "block")

DISPATCH_MODES = ("continuous", "coalesce")

DEFAULT_TENANT = "default"

# continuous mode: how many dispatched-but-undelivered tiles may be in
# flight before the dispatch loop blocks. Depth 2 overlaps the next
# tile's launch with the current tile's host sync without letting an
# unbounded pipeline hide queue growth from admission control.
_INFLIGHT_DEPTH = 2


class QueueFullError(RuntimeError):
    """submit() rejected because the queue holds ``max_queue_rows`` (or
    the tenant holds ``tenant_quota_rows``) under the ``shed`` overload
    policy (HTTP maps this to 429)."""


class _Request:
    __slots__ = ("X", "rows", "future", "t0", "trace_id", "tenant")

    def __init__(self, X: np.ndarray, trace_id: Optional[int] = None,
                 tenant: str = DEFAULT_TENANT) -> None:
        self.X = X
        self.rows = X.shape[0]
        self.future: Future = Future()
        self.t0 = obs.monotonic()
        self.trace_id = trace_id
        self.tenant = tenant


class _TenantState:
    """Per-tenant accounting, all guarded by the batcher lock."""

    __slots__ = ("pending", "queued_rows", "vtime", "weight",
                 "shed", "shed_rows", "served_rows", "served_requests")

    def __init__(self, weight: float) -> None:
        self.pending: deque = deque()
        self.queued_rows = 0
        self.vtime = 0.0
        self.weight = weight
        self.shed = 0
        self.shed_rows = 0
        self.served_rows = 0
        self.served_requests = 0


class MicroBatcher:
    """Coalesce concurrent predict requests into one device dispatch.

    ``raw_score`` applies to every request of the batcher (requests in one
    coalesced dispatch must share the output transform).
    ``tenant_weights`` maps tenant id -> relative fair-share weight
    (unlisted tenants weigh 1.0); ``tenant_quota_rows`` caps any single
    tenant's queued rows (0 = no per-tenant cap). ``dispatch_mode``
    picks the discipline: ``continuous`` (standing dispatch loop +
    deliver thread, no company wait) or ``coalesce`` (single thread,
    first request waits up to ``max_wait_ms`` for company).
    """

    def __init__(self, session, *, max_batch_rows: int = 8192,
                 max_wait_ms: float = 2.0, raw_score: bool = False,
                 latency_window: int = 2048, max_queue_rows: int = 0,
                 overload: str = "shed", tenant_quota_rows: int = 0,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 dispatch_mode: str = "continuous") -> None:
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if max_queue_rows < 0:
            raise ValueError("max_queue_rows must be >= 0 (0 = unbounded)")
        if tenant_quota_rows < 0:
            raise ValueError("tenant_quota_rows must be >= 0 (0 = no "
                             "per-tenant cap)")
        if overload not in OVERLOAD_POLICIES:
            raise ValueError("overload must be one of %s, got %r"
                             % ("|".join(OVERLOAD_POLICIES), overload))
        if dispatch_mode not in DISPATCH_MODES:
            raise ValueError("dispatch_mode must be one of %s, got %r"
                             % ("|".join(DISPATCH_MODES), dispatch_mode))
        weights = dict(tenant_weights or {})
        for t, w in weights.items():
            if not w > 0:
                raise ValueError("tenant weight must be > 0, got %s=%r"
                                 % (t, w))
        self._session = session
        self._max_rows = int(max_batch_rows)
        self._max_wait = float(max_wait_ms) / 1000.0
        self._raw = bool(raw_score)
        self._max_queue_rows = int(max_queue_rows)
        self._tenant_quota = int(tenant_quota_rows)
        self._overload_shed = overload == "shed"
        self._weights = weights
        # one lock, all jobs: (a) makes submit's closed-check atomic with
        # the enqueue so no request can slip in after close() and hang its
        # Future forever; (b) guards the tenant queues + fair-queuing
        # clocks; (c) guards the latency histogram, which the worker
        # feeds while callers read latency_stats(); (d) guards the
        # queued-row accounting behind admission control. It is a
        # Condition so block-policy submitters can park on it until the
        # worker drains space, and so the worker can park on it while the
        # queues are empty.
        self._lock = threading.Condition()
        self._tenants: Dict[str, _TenantState] = {}
        self._queued_rows = 0      # total rows queued, all tenants
        self._queued_requests = 0
        self._vclock = 0.0         # global virtual time (last pick's start)
        # log-bucketed histogram over submit->delivery latency in ms:
        # bounded memory at any request count, exact bucket counts for
        # /metrics; also mirrored into the global registry under
        # serve/latency_ms. latency_window is kept for signature compat
        # with the old deque-based stats and is ignored.
        del latency_window
        self._hist = obs.Histogram()
        self._closed = False
        self.dispatch_mode = dispatch_mode
        self._continuous = dispatch_mode == "continuous"
        # continuous-mode in-flight window: the dispatch loop appends
        # (batch, pieces) after launching, the deliver thread pops and
        # performs the host sync. Its own Condition so delivery never
        # contends with submit/fair-queuing on the main lock.
        self._dcond = threading.Condition()
        self._inflight: deque = deque()   # graftlint: guarded-by=_dcond
        self._prod_done = False           # graftlint: guarded-by=_dcond
        self._thread = threading.Thread(
            target=self._worker, name="lgbtpu-serve-batcher", daemon=True)
        self._thread.start()
        self._deliver_thread: Optional[threading.Thread] = None
        if self._continuous:
            self._deliver_thread = threading.Thread(
                target=self._deliverer, name="lgbtpu-serve-deliver",
                daemon=True)
            self._deliver_thread.start()

    # ---------------------------------------------------------------- tenants
    def _tenant(self, tenant: str) -> _TenantState:
        # lock held. A tenant re-activating after idling starts at the
        # global virtual clock — fairness is about the backlog window,
        # not about banking credit while away.
        st = self._tenants.get(tenant)   # graftlint: guarded-by=_lock -- caller holds it
        if st is None:
            st = self._tenants[tenant] = _TenantState(  # graftlint: guarded-by=_lock
                self._weights.get(tenant, 1.0))
        return st

    @staticmethod
    def _metric_tenant(tenant: str) -> str:
        return obs.safe_metric_part(tenant)

    # ---------------------------------------------------------------- submit
    def submit(self, X, trace_id: Optional[int] = None,
               tenant: Optional[str] = None) -> Future:
        """Queue one request; returns a Future resolving to its predictions
        (same shapes as ``PredictSession.predict``). A 1-D row is treated
        as a single-row batch. ``trace_id`` (from the http handler) links
        this request's queue/coalesce/dispatch spans to its request span
        when span tracing is on; ``tenant`` buckets it for fair queuing
        and per-tenant admission control. Raises ``RuntimeError`` once
        the batcher is closed — atomically with close(), so a submit
        either lands before the close (and gets an answer or a
        deterministic 'closed' failure from the drain) or raises here; it
        never hangs.

        With ``max_queue_rows``/``tenant_quota_rows`` set, an over-limit
        submit raises :class:`QueueFullError` (shed policy) or waits for
        queue space (block policy). A request alone bigger than the whole
        bound is admitted when its scope (queue / tenant queue) is empty
        — it can never fit better than that, so rejecting it forever
        would deadlock block-policy callers."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if trace_id is None and tracer.serve_on:
            trace_id = tracer.new_trace_id()
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        req = _Request(X, trace_id, tenant)
        with self._lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            st = self._tenant(tenant)
            while self._over_limit(st, req.rows):
                if self._overload_shed:
                    st.shed += 1
                    st.shed_rows += req.rows
                    depth = self._queued_rows
                    t_queued = st.queued_rows
                    self._count_shed(tenant, req.rows)
                    raise QueueFullError(
                        "queue holds %d rows (%d for tenant %r); admitting "
                        "%d more would exceed max_queue_rows=%d / "
                        "tenant_quota_rows=%d"
                        % (depth, t_queued, tenant, req.rows,
                           self._max_queue_rows, self._tenant_quota))
                self._lock.wait()
                if self._closed:
                    raise RuntimeError("MicroBatcher is closed")
                st = self._tenant(tenant)
            if not st.pending:
                # (re-)activation: start at the global virtual clock so
                # an idle period does not bank dequeue credit
                st.vtime = max(st.vtime, self._vclock)
            st.pending.append(req)
            st.queued_rows += req.rows
            self._queued_rows += req.rows
            self._queued_requests += 1
            depth = self._queued_rows
            n_queued = self._queued_requests
            t_depth = st.queued_rows
            self._lock.notify_all()
        telemetry.count("serve/requests")
        telemetry.count("serve/rows", req.rows)
        telemetry.gauge("serve/queue_depth", n_queued)
        telemetry.observe("serve/queue_depth_rows", depth)
        telemetry.gauge("serve/tenant/%s/queue_rows"
                        % self._metric_tenant(tenant), t_depth)
        return req.future

    def _over_limit(self, st: _TenantState, rows: int) -> bool:
        # lock held. The oversize carve-out is per scope: a request alone
        # bigger than the global bound is admitted when the whole queue
        # is empty; one bigger than its tenant quota when that tenant's
        # queue is empty.
        total = self._queued_rows   # graftlint: guarded-by=_lock -- caller holds it
        mine = st.queued_rows       # graftlint: guarded-by=_lock -- caller holds it
        if self._max_queue_rows > 0 and total > 0 \
                and total + rows > self._max_queue_rows:
            return True
        if self._tenant_quota > 0 and mine > 0 \
                and mine + rows > self._tenant_quota:
            return True
        return False

    def _count_shed(self, tenant: str, rows: int) -> None:
        telemetry.count("serve/shed")
        telemetry.count("serve/shed_rows", rows)
        telemetry.count("serve/tenant/%s/shed" % self._metric_tenant(tenant))
        telemetry.count("serve/tenant/%s/shed_rows"
                        % self._metric_tenant(tenant), rows)

    def queue_rows(self) -> int:
        """Rows submitted but not yet picked up by the worker (the
        admission-control quantity; /healthz queue depth)."""
        with self._lock:
            return self._queued_rows

    def tenant_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant queue/shed/served snapshot (the /healthz
        ``tenants`` section)."""
        with self._lock:
            return {t: {"queue_rows": st.queued_rows,
                        "shed": st.shed,
                        "shed_rows": st.shed_rows,
                        "served_requests": st.served_requests,
                        "served_rows": st.served_rows,
                        "weight": st.weight}
                    for t, st in sorted(self._tenants.items())}

    # ---------------------------------------------------------------- worker
    def _pick_locked(self) -> Optional[_Request]:
        """Start-time-fair pick: the active tenant with the smallest
        virtual time goes first; dequeuing advances its clock by
        rows/weight. Lock held; returns None when nothing is queued."""
        best: Optional[str] = None
        best_v = 0.0
        for t, st in self._tenants.items():   # graftlint: guarded-by=_lock -- caller holds it
            if st.pending and (best is None or st.vtime < best_v
                               or (st.vtime == best_v and t < best)):
                best, best_v = t, st.vtime
        if best is None:
            return None
        st = self._tenants[best]   # graftlint: guarded-by=_lock -- caller holds it
        req = st.pending.popleft()
        self._vclock = st.vtime    # graftlint: guarded-by=_lock -- caller holds it
        st.vtime += req.rows / st.weight
        st.queued_rows -= req.rows
        self._queued_rows -= req.rows      # graftlint: guarded-by=_lock -- caller holds it
        self._queued_requests -= 1         # graftlint: guarded-by=_lock -- caller holds it
        st.served_requests += 1
        st.served_rows += req.rows
        # a dequeued request frees its queue-space reservation; wake any
        # block-policy submitters parked in submit()
        self._lock.notify_all()
        return req

    def _worker(self) -> None:
        try:
            if self._continuous:
                self._worker_continuous()
            else:
                self._worker_coalesce()
        finally:
            if self._continuous:
                # no more tiles will be launched; let the deliver thread
                # drain the in-flight window and exit
                with self._dcond:
                    self._prod_done = True   # graftlint: guarded-by=_dcond
                    self._dcond.notify_all()

    def _worker_coalesce(self) -> None:
        while True:
            with self._lock:
                while self._queued_requests == 0 and not self._closed:
                    self._lock.wait()
                if self._closed:
                    # requests admitted before the close flag flipped are
                    # failed deterministically — submit can no longer
                    # enqueue behind us, so this drains everything
                    self._drain_locked()
                    return
                req = self._pick_locked()
            batch = [req]
            rows = req.rows
            t_first = obs.monotonic()    # lead request leaves the queue
            deadline = req.t0 + self._max_wait
            while rows < self._max_rows:
                # requests already queued join for free — draining them
                # never delays anyone. Only WAITING for company is bounded
                # by the deadline; otherwise a dispatch slower than
                # max_wait_ms degenerates every backlog into batches of 1.
                with self._lock:
                    if self._queued_requests == 0 and not self._closed:
                        remain = deadline - obs.monotonic()
                        if remain > 0:
                            self._lock.wait(remain)
                    nxt = self._pick_locked()
                if nxt is None:
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._seal_batch(batch, t_first, rows)
            pieces = self._launch(batch)
            if pieces is not None:
                self._deliver(batch, pieces)

    def _worker_continuous(self) -> None:
        # the standing dispatch loop: seal a tile from whatever is
        # queued right now and launch it — never wait for company. While
        # the deliver thread syncs an in-flight tile, new submissions
        # accumulate and ride the NEXT tile; under load the bounded
        # in-flight window is what grows batches, not a wall-clock wait.
        while True:
            with self._lock:
                while self._queued_requests == 0 and not self._closed:
                    self._lock.wait()
                if self._closed:
                    self._drain_locked()
                    return
                req = self._pick_locked()
            batch = [req]
            rows = req.rows
            t_first = obs.monotonic()
            while rows < self._max_rows:
                with self._lock:
                    nxt = self._pick_locked()
                if nxt is None:
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._seal_batch(batch, t_first, rows)
            pieces = self._launch(batch)
            if pieces is None:
                continue
            with self._dcond:
                # bounded in-flight window: block the dispatch loop when
                # the deliver thread falls behind, so queue depth (the
                # admission-control quantity) reflects real backlog
                while len(self._inflight) >= _INFLIGHT_DEPTH:  # graftlint: guarded-by=_dcond
                    self._dcond.wait()
                self._inflight.append((batch, pieces))  # graftlint: guarded-by=_dcond
                self._dcond.notify_all()

    def _deliverer(self) -> None:
        # continuous mode's delivery side: pop in-flight tiles in launch
        # order, host-sync, finalize, resolve futures
        while True:
            with self._dcond:
                while not self._inflight and not self._prod_done:  # graftlint: guarded-by=_dcond
                    self._dcond.wait()
                if not self._inflight:   # graftlint: guarded-by=_dcond
                    return
                batch, pieces = self._inflight.popleft()  # graftlint: guarded-by=_dcond
                self._dcond.notify_all()
            self._deliver(batch, pieces)

    def _seal_batch(self, batch, t_first: float, rows: int) -> None:
        """Account for one sealed batch: queue-wait histogram (submit
        until its batch was sealed — the knob continuous batching exists
        to shrink), queue-depth gauge, and retroactive trace spans."""
        with self._lock:
            depth = self._queued_requests
        telemetry.gauge("serve/queue_depth", depth)
        now = obs.monotonic()
        for r in batch:
            telemetry.observe("serve/queue_wait_ms", (now - r.t0) * 1000.0)
        if tracer.serve_on:
            # retroactive spans: each request's time-in-queue plus one
            # coalesce span for the assembly window itself
            for r in batch:
                tracer.record("serve/queue_wait", r.t0, now,
                              trace_id=r.trace_id)
            tracer.record("serve/coalesce", t_first, now,
                          trace_id=batch[0].trace_id,
                          args={"requests": len(batch), "rows": rows})

    def _launch(self, batch):
        """Concatenate + dispatch one sealed batch on the device (async —
        no host sync here). Returns the dispatched pieces, or None after
        failing the batch's futures on a dispatch error."""
        n_rows = sum(r.rows for r in batch)
        telemetry.count("serve/batches")
        telemetry.count("serve/batch_rows", n_rows)
        telemetry.observe("serve/batch_rows", n_rows)
        try:
            with tracer.span("serve/batch", domain="serve",
                             trace_id=batch[0].trace_id,
                             requests=len(batch), rows=n_rows):
                X = batch[0].X if len(batch) == 1 else \
                    np.concatenate([r.X for r in batch], axis=0)
                with obs.wall("serve/batch"):
                    pieces = self._session.dispatch(X)
        except BaseException as exc:
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(exc)
            return None
        return pieces

    def _deliver(self, batch, pieces) -> None:
        """Host-sync one launched batch, finalize, resolve its futures.
        Runs on the deliver thread (continuous) or inline (coalesce)."""
        try:
            # the serve path's one sanctioned device->host sync: pull
            # the coalesced scores for result delivery
            with tracer.span("serve/slice_back", domain="serve",
                             trace_id=batch[0].trace_id):
                host = [np.asarray(s, np.float64)[:r]  # graftlint: disable=host-sync
                        for s, r in pieces]
            raw = host[0] if len(host) == 1 else np.concatenate(host)
            out = self._session.finalize(raw, raw_score=self._raw)
        except BaseException as exc:
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(exc)
            return
        off = 0
        now = obs.monotonic()
        for r in batch:
            r.future.set_result(np.array(out[off:off + r.rows]))
            off += r.rows
            dt = now - r.t0
            with self._lock:
                self._hist.observe(dt * 1000.0)
            telemetry.observe("serve/latency_ms", dt * 1000.0)
            telemetry.add_time("wall/serve/request", dt)
        self._update_latency_gauges()

    def _update_latency_gauges(self) -> None:
        with self._lock:
            if self._hist.count == 0:
                return
            p50 = self._hist.percentile(0.50)
            p99 = self._hist.percentile(0.99)
        telemetry.gauge("serve/latency_p50_ms", round(p50, 4))
        telemetry.gauge("serve/latency_p99_ms", round(p99, 4))

    def latency_stats(self) -> dict:
        """count + p50/p90/p99/p999 (seconds) derived from the latency
        histogram buckets (bucket-interpolated, not exact order stats)."""
        with self._lock:
            n = self._hist.count
            pcts = {label: self._hist.percentile(q) / 1000.0
                    for q, label in obs._PCTS}
        if n == 0:
            return {"count": 0, "p50_s": 0.0, "p90_s": 0.0,
                    "p99_s": 0.0, "p999_s": 0.0}
        return {"count": n,
                "p50_s": pcts["p50"], "p90_s": pcts["p90"],
                "p99_s": pcts["p99"], "p999_s": pcts["p999"]}

    # -------------------------------------------------------------- shutdown
    def _drain_locked(self) -> None:
        # lock held; fail every still-queued future so no caller hangs on
        # a stopped worker
        while True:
            req = self._pick_locked()
            if req is None:
                return
            if not req.future.done():
                req.future.set_exception(RuntimeError("MicroBatcher closed"))

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting requests, finish + deliver every in-flight
        batch, fail any still-queued futures, join the worker(s).
        Idempotent. The flag flips under the submit lock, so every
        request that beat the flip is either dispatched with an
        in-flight batch or failed deterministically by the worker's
        drain; block-policy submitters parked for queue space are woken
        and raise instead of hanging on a dead worker. In continuous
        mode the dispatch loop exits first (marking the in-flight window
        done), then the deliver thread drains launched tiles to their
        futures and exits — graceful drain, no dropped answers."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._lock.notify_all()
        self._thread.join(timeout)
        if self._deliver_thread is not None:
            self._deliver_thread.join(timeout)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
