"""Stdlib-HTTP JSON prediction endpoint (``task=serve`` in the CLI).

    POST /predict   {"rows": [[f0, f1, ...], ...]}
                    -> {"predictions": [...], "rows": n}
    GET  /healthz   liveness + model/bucket info
    GET  /telemetry full obs.Telemetry snapshot (serve/* counters, jit
                    compile counts, latency gauges + histograms)
    GET  /metrics   the registry in Prometheus text exposition format
                    (latency/batch-size histogram buckets included)

With span tracing on (``trace_spans=on|serve_only``), each POST opens a
``serve/http_request`` span carrying a fresh trace id that the batcher
threads through queue_wait -> coalesce -> batch -> session_dispatch ->
slice_back, so one request yields a full chain in the flight recorder.

``ThreadingHTTPServer`` gives one handler thread per connection, so
concurrent POSTs land in the MicroBatcher together and coalesce into one
device dispatch. No dependencies beyond the standard library.
"""
from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

import numpy as np

from .. import obs
from ..obs import telemetry
from ..obs_trace import tracer
from ..utils.log import Log
from .batcher import MicroBatcher
from .session import PredictSession


class PredictServer:
    """PredictSession + MicroBatcher behind a stdlib HTTP server.

    ``port=0`` binds an ephemeral port (tests); read it back from
    ``server.address``. ``serve_forever()`` blocks; call ``close()`` (any
    thread) to stop the server and the batcher worker.
    """

    def __init__(self, model, *, host: str = "127.0.0.1", port: int = 8080,
                 max_batch_rows: int = 8192, max_wait_ms: float = 2.0,
                 buckets: Optional[Sequence[int]] = None,
                 raw_score: bool = False, warmup: bool = True,
                 request_timeout_s: float = 30.0) -> None:
        self.session = PredictSession(model, buckets=buckets)
        if warmup:
            self.session.warmup()
        self.batcher = MicroBatcher(self.session,
                                    max_batch_rows=max_batch_rows,
                                    max_wait_ms=max_wait_ms,
                                    raw_score=raw_score)
        self.request_timeout_s = float(request_timeout_s)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # default writes to stderr
                Log.debug("serve: " + fmt % args)

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {
                        "status": "ok",
                        "model_version": server.session._gbdt.model_version,
                        "buckets": list(server.session.buckets),
                        "requests": telemetry.counter("serve/requests"),
                    })
                elif self.path == "/telemetry":
                    self._json(200, telemetry.snapshot())
                elif self.path == "/metrics":
                    body = obs.prometheus_text().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": "unknown path %s" % self.path})

            def do_POST(self):
                if self.path != "/predict":
                    self._json(404, {"error": "unknown path %s" % self.path})
                    return
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    rows = payload["rows"]
                    X = np.asarray(rows, np.float64)
                    if X.ndim == 1:
                        X = X[None, :]
                    tid = tracer.new_trace_id() if tracer.serve_on else None
                    with tracer.span("serve/http_request", domain="serve",
                                     trace_id=tid, rows=int(X.shape[0])):
                        fut = server.batcher.submit(X, trace_id=tid)
                        out = fut.result(timeout=server.request_timeout_s)
                    self._json(200, {"predictions": out.tolist(),
                                     "rows": int(X.shape[0])})
                except Exception as exc:
                    self._json(400, {"error": "%s: %s"
                                     % (type(exc).__name__, exc)})

        self.httpd = ThreadingHTTPServer((host, int(port)), Handler)

    @property
    def address(self):
        """(host, port) actually bound — resolves port=0 ephemeral binds."""
        return self.httpd.server_address[:2]

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        """Unblock serve_forever() (callable from any thread)."""
        self.httpd.shutdown()

    def close(self) -> None:
        try:
            self.httpd.server_close()
        finally:
            self.batcher.close()
