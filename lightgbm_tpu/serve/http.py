"""Stdlib-HTTP JSON prediction endpoint (``task=serve`` in the CLI).

    POST /predict              {"rows": [[f0, f1, ...], ...]}
                               -> {"predictions": [...], "rows": n,
                                   "model_version": v}
    POST /predict/<model_id>   same, routed to one registry entry
                               (also: {"model": "<id>"} in the body)
    POST /ingest[/<model_id>]  {"rows": [[...]], "labels": [...]}
                               feed labeled traffic to the model's
                               OnlineTrainer (409 if online training is
                               off for that model)
    GET  /healthz              liveness + per-model version/queue/online
                               state, registry size, uptime
    GET  /models               registered model ids
    GET  /telemetry            full obs.Telemetry snapshot
    GET  /metrics              Prometheus text exposition format
    GET  /fleet/latest         newest fleet publish event (trainer mode)
    GET  /fleet/publishes      all valid publish events oldest-first
    GET  /fleet/artifact/<v>   raw whole-model artifact bytes
    GET  /fleet/status         federated rollup: head version, lease,
                               every node's latest heartbeat with skew
    GET  /fleet/events         the whole event log (remote replay)
    GET  /fleet/snapshot/<id>  raw snapshot blob (remote cold bootstrap)
    POST /fleet/heartbeat      remote nodes report their heartbeat docs
    POST /fleet/lease          remote lease acquire/renew/release/state
    POST /fleet/publish        sha256-verified model upload, fenced by
                               (holder, lease_epoch); zombie epoch: 409
    POST /fleet/ingest         append one labeled chunk to the store log
    POST /fleet/gate           append one promotion-gate record
    POST /fleet/compact        run log compaction (snapshot mode incl.)

The /fleet routes exist when the CLI attaches a local ``FleetStore``
(``server.fleet_store``). The GETs are the network transport remote
replicas (:class:`~lightgbm_tpu.fleet.transport.RemoteStore`) converge
through; the POSTs are the control plane's write surface
(:class:`~lightgbm_tpu.fleet.control.RemoteWriteStore`) — fencing is
enforced server-side under the store lock, so a remote zombie's stale
epoch is rejected 409 (with a ``leader_hint``) exactly like a local
one. Both carry the ``transport/serve`` chaos point (slow/torn/dropped
responses for the failover tests). The write routes answer during a
drain: a draining store host must keep serving lease renewals or a
healthy remote trainer would demote for no reason.

Multi-tenant: the server fronts a
:class:`~lightgbm_tpu.online.registry.ModelRegistry`; the single-model
constructor registers its booster under id ``"default"``. Admission
control: an over-limit submit under the shed policy returns **429**;
during graceful shutdown (:meth:`PredictServer.begin_shutdown`, wired to
SIGTERM by the CLI) every new request gets **503** while already-queued
work drains to completion.

With span tracing on (``trace_spans=on|serve_only``), each POST opens a
``serve/http_request`` span carrying a fresh trace id that the batcher
threads through queue_wait -> coalesce -> batch -> session_dispatch ->
slice_back, so one request yields a full chain in the flight recorder.

``ThreadingHTTPServer`` gives one handler thread per connection, so
concurrent POSTs land in the MicroBatcher together and coalesce into one
device dispatch. No dependencies beyond the standard library.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

import numpy as np

from .. import obs
from ..obs import telemetry
from ..obs_trace import TRACE_HEADER, format_trace_id, parse_trace_id, tracer
from ..utils.log import LightGBMError, Log
from .batcher import QueueFullError


class PredictServer:
    """ModelRegistry (PredictSessions + MicroBatchers) behind a stdlib
    HTTP server.

    Single-model: ``PredictServer(booster, ...)`` (registered as
    ``"default"``; ``server.session``/``server.batcher`` keep pointing at
    it). Multi-tenant: build a
    :class:`~lightgbm_tpu.online.registry.ModelRegistry` yourself and
    pass ``registry=``. ``online`` (an OnlineTrainer or its kwargs dict)
    attaches continual training to the single-model constructor's entry.

    ``port=0`` binds an ephemeral port (tests); read it back from
    ``server.address``. ``serve_forever()`` blocks; call ``close()`` (any
    thread) to stop the server and the batcher workers, or
    ``begin_shutdown()`` for the draining path (refuse new work with 503,
    let queued requests finish, then unblock serve_forever).
    """

    def __init__(self, model=None, *, registry=None,
                 host: str = "127.0.0.1", port: int = 8080,
                 max_batch_rows: int = 8192, max_wait_ms: float = 2.0,
                 buckets: Optional[Sequence[int]] = None,
                 raw_score: bool = False, warmup: bool = True,
                 request_timeout_s: float = 30.0,
                 max_queue_rows: int = 0, overload: str = "shed",
                 tenant_quota_rows: int = 0, tenant_weights=None,
                 dispatch_mode: str = "continuous", forest=None,
                 online=None) -> None:
        from ..online.registry import ModelRegistry

        if registry is None:
            if model is None:
                raise LightGBMError(
                    "PredictServer needs a model or a registry")
            registry = ModelRegistry()
            registry.register("default", model, buckets=buckets,
                              max_batch_rows=max_batch_rows,
                              max_wait_ms=max_wait_ms,
                              max_queue_rows=max_queue_rows,
                              overload=overload,
                              tenant_quota_rows=tenant_quota_rows,
                              tenant_weights=tenant_weights,
                              raw_score=raw_score,
                              dispatch_mode=dispatch_mode, forest=forest,
                              warmup=warmup, online=online)
        elif model is not None or online is not None:
            raise LightGBMError(
                "pass either model/online or a pre-built registry, "
                "not both")
        self.registry = registry
        self.request_timeout_s = float(request_timeout_s)
        # fleet replica mode: the CLI attaches the ReplicaWatcher here so
        # /healthz reports applied version/swaps and close() stops it
        self.fleet_watcher = None
        # fleet trainer mode: a local FleetStore attached here turns on
        # the /fleet/* transport routes + the /healthz store section
        self.fleet_store = None
        # remote-replica mode: the RemoteStore, for /healthz retry stats
        self.fleet_transport = None
        # control plane: an IngestForwarder attached here relays labeled
        # traffic hitting this node to the current lease holder instead
        # of 409ing it on the floor
        self.ingest_forwarder = None
        self._started_at = obs.monotonic()
        # guards the draining flag: flipped by begin_shutdown (signal
        # helper thread) and read on every handler thread
        self._lock = threading.Lock()
        self._draining = False
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # default writes to stderr
                Log.debug("serve: " + fmt % args)

            def _json(self, code: int, obj, headers=None) -> None:
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for key, value in (headers or {}).items():
                    self.send_header(key, value)
                self.end_headers()
                self.wfile.write(body)

            def _raw(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, server.healthz())
                elif self.path == "/models":
                    self._json(200, {"models": server.registry.ids()})
                elif self.path == "/telemetry":
                    self._json(200, telemetry.snapshot())
                elif self.path == "/metrics":
                    self._raw(200, obs.prometheus_text().encode("utf-8"),
                              "text/plain; version=0.0.4")
                elif self.path.startswith("/fleet/"):
                    self._fleet()
                else:
                    self._json(404, {"error": "unknown path %s" % self.path})

            def _fleet(self) -> None:
                """The replica-facing transport routes, serving the
                attached local store's publish feed + artifacts. A torn
                chaos action truncates the response body (Content-Length
                included, so the client's checksum — not a short-read
                error — must catch it); a raise action answers 500.

                When serve tracing is on, an ``X-Trace-Id`` sent by the
                remote replica's transport joins this handler's span to
                the replica's poll trace — the trainer half of the
                cross-process adoption trace."""
                if not tracer.serve_on:
                    self._fleet_impl()
                    return
                tid = parse_trace_id(self.headers.get(TRACE_HEADER))
                with tracer.span("serve/fleet_request", domain="serve",
                                 trace_id=tid, path=self.path):
                    self._fleet_impl()

            def _fleet_impl(self) -> None:
                store = server.fleet_store
                if store is None:
                    self._json(404, {"error": "no fleet store attached"})
                    return
                from ..fleet import chaos
                try:
                    act = chaos.hit("transport/serve")
                except Exception as exc:
                    self._json(500, {"error": "%s: %s"
                                     % (type(exc).__name__, exc)})
                    return
                torn = float(act[1]) if act is not None \
                    and act[0] == "torn" else None

                def send(body: bytes, ctype: str) -> None:
                    if torn is not None:
                        body = body[:int(len(body) * torn)]
                    self._raw(200, body, ctype)

                seg = [s for s in self.path.split("/") if s]
                if seg == ["fleet", "status"]:
                    send(json.dumps(server.fleet_status())
                         .encode("utf-8"), "application/json")
                elif seg == ["fleet", "events"]:
                    # remote standby cold-boot replay: the whole event
                    # log in one response (with snapshot compaction on,
                    # this is a compact record + publishes + tail)
                    send(json.dumps({"events": list(store.events())})
                         .encode("utf-8"), "application/json")
                elif seg[:2] == ["fleet", "snapshot"] and len(seg) == 3:
                    try:
                        sid = int(seg[2])
                    except ValueError:
                        self._json(404, {"error": "bad snapshot id %r"
                                         % seg[2]})
                        return
                    try:
                        with open(store.snapshot_path(sid), "rb") as f:
                            data = f.read()
                    except OSError:
                        self._json(404, {"error": "no snapshot s%06d"
                                         % sid})
                        return
                    send(data, "application/json")
                elif seg == ["fleet", "latest"]:
                    latest = store.latest_publish()
                    if latest is None:
                        self._json(404, {"error": "nothing published yet"})
                        return
                    send(json.dumps(latest).encode("utf-8"),
                         "application/json")
                elif seg == ["fleet", "publishes"]:
                    send(json.dumps({"publishes": store.publishes()})
                         .encode("utf-8"), "application/json")
                elif seg[:2] == ["fleet", "artifact"] and len(seg) == 3:
                    try:
                        version = int(seg[2])
                    except ValueError:
                        self._json(404, {"error": "bad version %r" % seg[2]})
                        return
                    try:
                        with open(store.artifact_path(version), "rb") as f:
                            data = f.read()
                    except OSError:
                        self._json(404, {"error": "no artifact v%d"
                                         % version})
                        return
                    send(data, "text/plain; charset=utf-8")
                else:
                    self._json(404, {"error": "unknown path %s" % self.path})

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(length) or b"{}")
                except Exception as exc:
                    self._json(400, {"error": "bad request body: %s" % exc})
                    return
                if self.path == "/fleet/heartbeat":
                    # federation intake: remote nodes POST their
                    # heartbeats here; observability stays up while the
                    # serve plane drains, so this precedes the 503 gate
                    self._fleet_heartbeat(payload)
                    return
                if self.path.startswith("/fleet/"):
                    # the control plane's write surface (remote lease,
                    # fenced publish, ingest/gate appends, compaction).
                    # Like heartbeats it precedes the drain gate: a
                    # draining store host must keep answering lease
                    # renewals and fence checks or a healthy remote
                    # trainer demotes for no reason
                    self._fleet_post(payload)
                    return
                if server.draining():
                    telemetry.count("serve/drain_rejected")
                    self._json(503, {"error": "server is draining"})
                    return
                seg = [s for s in self.path.split("/") if s]
                route = seg[0] if seg else ""
                if route not in ("predict", "ingest") or len(seg) > 2:
                    self._json(404, {"error": "unknown path %s" % self.path})
                    return
                model_id = seg[1] if len(seg) == 2 \
                    else payload.get("model")
                try:
                    entry = server.registry.get(model_id)
                except KeyError as exc:
                    self._json(404, {"error": str(exc)})
                    return
                if route == "predict":
                    self._predict(entry, payload)
                else:
                    self._ingest(entry, payload)

            def _fleet_post(self, payload) -> None:
                """``POST /fleet/{lease,publish,ingest,gate,compact}`` —
                the store host's half of the remote write surface.
                Every route needs the attached local store; fencing is
                enforced HERE, under the store's own lock, so a remote
                zombie's stale epoch dies exactly like a local one
                (409, with a ``leader_hint`` naming who holds the lease
                now). Chaos ``transport/serve`` actions apply as on the
                GET side: raise answers 500, torn truncates the body
                under an intact Content-Length."""
                store = server.fleet_store
                if store is None:
                    self._json(404, {"error": "no fleet store attached"})
                    return
                if not isinstance(payload, dict):
                    self._json(400, {"error": "body must be a JSON "
                                     "object"})
                    return
                from ..fleet import chaos
                from ..fleet.store import StaleLeaseError
                try:
                    act = chaos.hit("transport/serve")
                except Exception as exc:
                    self._json(500, {"error": "%s: %s"
                                     % (type(exc).__name__, exc)})
                    return
                torn = float(act[1]) if act is not None \
                    and act[0] == "torn" else None

                def send(code: int, obj) -> None:
                    body = json.dumps(obj).encode("utf-8")
                    if torn is not None:
                        body = body[:int(len(body) * torn)]
                        self._raw(code, body, "application/json")
                        return
                    self._json(code, obj)

                seg = [s for s in self.path.split("/") if s]
                route = seg[1] if len(seg) == 2 else ""
                try:
                    if route == "lease":
                        self._fleet_lease(store, payload, send)
                    elif route == "publish":
                        self._fleet_publish(store, payload, send)
                    elif route == "ingest":
                        store.append_ingest(payload["rows"],
                                            payload["labels"])
                        rows = payload.get("labels") or []
                        send(200, {"ok": True, "rows": len(rows)})
                    elif route == "gate":
                        store.append_gate(
                            payload["result"], int(payload["wins"]),
                            int(payload["consumed_rows"]),
                            payload.get("losses"))
                        send(200, {"ok": True})
                    elif route == "compact":
                        send(200, store.compact(
                            watermark=int(payload["watermark"]),
                            wins=int(payload["wins"]),
                            keep_rows=int(payload["keep_rows"]),
                            keep_artifacts=int(
                                payload.get("keep_artifacts", 0)),
                            snapshot_rows=int(
                                payload.get("snapshot_rows", 0))))
                    else:
                        self._json(404, {"error": "unknown path %s"
                                         % self.path})
                except StaleLeaseError as exc:
                    doc = {"error": str(exc)}
                    hint = server._leader_hint()
                    if hint:
                        doc["leader_hint"] = hint
                    send(409, doc)
                except (KeyError, TypeError, ValueError,
                        LightGBMError) as exc:
                    send(400, {"error": "%s: %s"
                               % (type(exc).__name__, exc)})

            def _fleet_lease(self, store, payload, send) -> None:
                op = payload.get("op")
                holder = payload.get("holder")
                url = payload.get("url") or None
                if op == "acquire":
                    epoch = store.acquire_lease(
                        str(holder), float(payload["ttl_s"]), url=url)
                    send(200, {"epoch": epoch,
                               "lease": store.lease_state()})
                elif op == "renew":
                    ok = store.renew_lease(
                        str(holder), int(payload["epoch"]),
                        float(payload["ttl_s"]), url=url)
                    send(200, {"ok": ok})
                elif op == "release":
                    ok = store.release_lease(str(holder),
                                             int(payload["epoch"]))
                    send(200, {"ok": ok})
                elif op == "state":
                    send(200, {"lease": store.lease_state()})
                else:
                    send(400, {"error": "unknown lease op %r" % op})

            def _fleet_publish(self, store, payload, send) -> None:
                model = payload.get("model")
                if not isinstance(model, str) or not model:
                    send(400, {"error": "publish needs a non-empty "
                               "model string"})
                    return
                data = model.encode("utf-8")
                want_sha = payload.get("sha256")
                want_bytes = int(payload.get("bytes", -1))
                got_sha = hashlib.sha256(data).hexdigest()
                if (want_bytes >= 0 and want_bytes != len(data)) \
                        or (want_sha and want_sha != got_sha):
                    # verify the UPLOAD before the fence: a torn body
                    # must never become an artifact, fenced or not
                    telemetry.count("fleet/upload_checksum_failures")
                    send(400, {"error": "model upload failed its "
                               "checksum (%d bytes, sha %s...)"
                               % (len(data), got_sha[:12])})
                    return
                fence = (str(payload.get("holder")),
                         int(payload.get("lease_epoch", 0)))
                version = store.publish(
                    model, str(payload.get("event", "promotion")),
                    payload.get("meta"), fence=fence)
                send(200, {"version": version})

            def _fleet_heartbeat(self, payload) -> None:
                store = server.fleet_store
                if store is None:
                    self._json(404, {"error": "no fleet store attached"})
                    return
                try:
                    ok = store.record_heartbeat(
                        payload if isinstance(payload, dict) else {})
                except Exception as exc:
                    self._json(500, {"error": "%s: %s"
                                     % (type(exc).__name__, exc)})
                    return
                if not ok:
                    self._json(400, {"error": "heartbeat needs a node id"})
                    return
                self._json(200, {"ok": True})

            def _predict(self, entry, payload) -> None:
                # trace correlation: adopt the client's X-Trace-Id when
                # sent, mint one otherwise, and echo it back on EVERY
                # response so external clients can correlate against
                # flight-recorder dumps (echoed even with tracing off —
                # minting is one counter increment, no span records)
                tid = parse_trace_id(self.headers.get(TRACE_HEADER)) \
                    or tracer.new_trace_id()
                echo = {TRACE_HEADER: format_trace_id(tid)}
                span_tid = tid if tracer.serve_on else None
                try:
                    X = np.asarray(payload["rows"], np.float64)
                    if X.ndim == 1:
                        X = X[None, :]
                    # tenant for fair queuing + per-tenant admission:
                    # header wins (proxies inject it), body is the
                    # curl-friendly fallback, absent means "default"
                    tenant = self.headers.get("X-Tenant") \
                        or payload.get("tenant")
                    with tracer.span("serve/http_request", domain="serve",
                                     trace_id=span_tid, rows=int(X.shape[0]),
                                     model=entry.model_id):
                        fut = entry.batcher.submit(X, trace_id=span_tid,
                                                   tenant=tenant)
                        out = fut.result(timeout=server.request_timeout_s)
                    self._json(200, {"predictions": out.tolist(),
                                     "rows": int(X.shape[0]),
                                     "model_version":
                                         entry.booster.inner.model_version},
                               echo)
                except QueueFullError as exc:
                    # admission control shed: fast 429 beats unbounded
                    # queueing; clients back off or retry elsewhere
                    self._json(429, {"error": "overloaded: %s" % exc}, echo)
                except Exception as exc:
                    self._json(400, {"error": "%s: %s"
                                     % (type(exc).__name__, exc)}, echo)

            def _ingest(self, entry, payload) -> None:
                if entry.online is None:
                    fwd = server.ingest_forwarder
                    hops = int(self.headers.get("X-Fleet-Hops") or 0)
                    if fwd is not None:
                        # this node cannot train on the rows, but the
                        # control plane knows who can: relay to the
                        # lease holder instead of dropping the chunk
                        try:
                            doc = fwd.forward(entry.model_id,
                                              payload.get("rows"),
                                              payload.get("labels"),
                                              hops=hops)
                        except Exception as exc:
                            self._json(503, {"error": "ingest forward "
                                             "failed: %s" % exc})
                            return
                        self._json(200, doc)
                        return
                    doc = {"error": "online training is not enabled "
                           "for model %r" % entry.model_id}
                    hint = server._leader_hint()
                    if hint:
                        # no forwarder here, but tell the client who IS
                        # the leader so it can re-aim itself
                        doc["leader_hint"] = hint
                    self._json(409, doc)
                    return
                try:
                    rows = np.asarray(payload["rows"], np.float64)
                    labels = np.asarray(payload["labels"], np.float64)
                    buffered = entry.online.ingest(rows, labels)
                    self._json(200, {"buffered_rows": int(buffered),
                                     "rows": int(len(labels.ravel()))})
                except Exception as exc:
                    self._json(400, {"error": "%s: %s"
                                     % (type(exc).__name__, exc)})

        self.httpd = ThreadingHTTPServer((host, int(port)), Handler)

    # ---------------------------------------------------------- back-compat
    @property
    def session(self):
        """Default entry's PredictSession (single-model callers)."""
        return self.registry.get().session

    @property
    def batcher(self):
        """Default entry's MicroBatcher (single-model callers)."""
        return self.registry.get().batcher

    @property
    def online(self):
        """Default entry's OnlineTrainer (None when online is off)."""
        return self.registry.get().online

    # --------------------------------------------------------------- status
    @property
    def address(self):
        """(host, port) actually bound — resolves port=0 ephemeral binds."""
        return self.httpd.server_address[:2]

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def healthz(self) -> dict:
        """The /healthz document: substance, not a static OK — model
        versions, registry size, queue depth, per-tenant queue/shed
        counts, uptime, online-trainer state per model (including
        last-promotion/rollback timestamps) and — in fleet replica mode
        — the watcher's applied version."""
        models = self.registry.info()
        # fleet ops view: per-tenant depth/sheds merged across models,
        # and each model's promotion/rollback timestamps hoisted out of
        # the nested online state
        tenants: dict = {}
        for m in models.values():
            for t, st in (m.get("tenants") or {}).items():
                agg = tenants.setdefault(
                    t, {"queue_rows": 0, "shed": 0, "shed_rows": 0})
                agg["queue_rows"] += st.get("queue_rows", 0)
                agg["shed"] += st.get("shed", 0)
                agg["shed_rows"] += st.get("shed_rows", 0)
        promotions = {
            mid: {"last_promotion_ts": m["online"]["last_promotion_ts"],
                  "last_rollback_ts": m["online"]["last_rollback_ts"]}
            for mid, m in models.items()
            if m.get("online") and "last_promotion_ts" in m["online"]}
        doc = {
            "status": "draining" if self.draining() else "ok",
            "uptime_s": round(obs.monotonic() - self._started_at, 3),
            "model_count": len(self.registry),
            "models": models,
            "queue_rows": sum(m["queue_rows"] for m in models.values()),
            "tenants": tenants,
            "requests": telemetry.counter("serve/requests"),
        }
        if promotions:
            doc["promotions"] = promotions
        if self.fleet_watcher is not None:
            doc["fleet"] = self.fleet_watcher.state()
        if self.fleet_store is not None:
            # lease holder/epoch/expiry, log size, last compaction
            doc["fleet_store"] = self.fleet_store.state()
        if self.fleet_transport is not None:
            # remote replica: request/retry/checksum-failure counts
            doc["fleet_transport"] = self.fleet_transport.state()
        if self.ingest_forwarder is not None:
            # control plane: relayed-chunk counts + cached leader
            doc["ingest_forwarder"] = self.ingest_forwarder.state()
        try:
            from .. import obs_device
            # compact device-cost view: HBM watermark + capture totals
            # (full per-jit detail stays on /telemetry and /metrics)
            doc["device_cost"] = obs_device.summary()
        except Exception:  # pragma: no cover - health must never fail
            pass
        try:
            default = self.registry.get()
            # single-model back-compat: the old flat fields stay
            doc["model_version"] = default.booster.inner.model_version
            doc["buckets"] = list(default.session.buckets)
        except KeyError:
            pass
        return doc

    def _leader_hint(self) -> Optional[str]:
        """The current lease holder's advertised serving URL (from the
        attached local store's lease record), or None — stamped into
        409 bodies so a rejected writer learns where to go."""
        store = self.fleet_store
        if store is None:
            return None
        try:
            lease = store.lease_state()
        except Exception:
            return None
        if lease.get("held") and lease.get("url"):
            return str(lease["url"])
        return None

    def fleet_status(self) -> dict:
        """The ``GET /fleet/status`` rollup: one document describing the
        whole fleet from the trainer's vantage — store head version +
        lease + log size, and every node's latest heartbeat (local
        replicas and standbys write them straight to the store; remote
        replicas POST them to ``/fleet/heartbeat``), each stamped with
        server-side version skew and heartbeat age."""
        store = self.fleet_store
        if store is None:
            return {"nodes": []}
        st = store.state()
        head = int(st["last_published_version"])
        now = time.time()  # graftlint: disable=naked-timer -- epoch timestamp, not a duration
        nodes = []
        for hb in store.heartbeats():
            node = dict(hb)
            node["skew"] = max(0, head - int(node.get("version", 0) or 0))
            node["age_s"] = round(max(0.0, now - float(node.get("ts", now))),
                                  3)
            nodes.append(node)
        return {
            "model_id": st["model_id"],
            "head_version": head,
            "lease": st["lease"],
            "log_bytes": st["events_log_bytes"],
            "compactions": st["compactions"],
            "nodes": nodes,
        }

    # ------------------------------------------------------------ lifecycle
    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        """Unblock serve_forever() (callable from any thread)."""
        self.httpd.shutdown()

    def begin_shutdown(self, drain_timeout_s: float = 30.0) -> None:
        """Graceful drain (the SIGTERM path): flip /predict//ingest to
        503, keep the accept loop alive until the batcher queues are
        empty (new requests are answered 503 during the drain window,
        queued ones finish normally), then stop the accept loop. Call
        :meth:`close` afterwards to join the workers. Safe from any
        thread EXCEPT the one inside serve_forever (httpd.shutdown would
        deadlock there — the CLI's signal handler hops to a helper
        thread for exactly that reason)."""
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            return
        telemetry.count("serve/drain_begin")
        Log.info("serve: draining (refusing new requests)")
        deadline = obs.monotonic() + drain_timeout_s
        while obs.monotonic() < deadline:
            if all(e.batcher.queue_rows() == 0
                   for e in self.registry.entries()):
                break
            time.sleep(0.01)
        self.httpd.shutdown()

    def close(self) -> None:
        try:
            self.httpd.server_close()
        finally:
            if self.fleet_watcher is not None:
                self.fleet_watcher.close()
            self.registry.close()
