"""TPU-native inference subsystem.

Training keeps the model on host as a ``List[Tree]``; serving inverts
that: :class:`PredictSession` uploads the packed ensemble ONCE, keeps it
device-resident behind the booster's model-version token, and compiles the
batched predict against a fixed shape-bucket ladder (round N up, pad,
slice) so steady-state traffic pays zero host re-packs and zero retraces.
:class:`MicroBatcher` coalesces concurrent requests into one device
dispatch; :class:`PredictServer` exposes the pair as a stdlib-HTTP JSON
endpoint (``task=serve`` in the CLI).

    session = lgb.serve.PredictSession(booster)
    session.warmup()                       # pre-compile the bucket ladder
    preds = session.predict(X)             # padded to the covering bucket
    with lgb.serve.MicroBatcher(session) as mb:
        fut = mb.submit(x_row)             # coalesced device dispatch
        preds = fut.result()
"""
from .batcher import MicroBatcher
from .http import PredictServer
from .session import PredictSession

__all__ = ["PredictSession", "MicroBatcher", "PredictServer"]
