"""Serving throughput/latency benchmark (open + closed loop).

Compares three ways of answering the same request stream:

- **naive**: one ``Booster.predict`` call per request at batch size 1 —
  the pre-serve baseline (host per-tree walk; per-call overhead dominates);
- **open loop**: submit every request to a MicroBatcher at once, gather
  futures — measures coalesced throughput (requests/s, rows/s);
- **closed loop**: one request in flight at a time — measures per-request
  latency including the batcher's ``max_wait_ms`` deadline. Percentiles
  (p50/p90/p99/p999) come from an obs.Histogram's log buckets — the same
  representation ``/metrics`` exports — and the exact cumulative bucket
  counts ride along in the JSON.

Parity between naive and served predictions is asserted IN-RUN (the bench
refuses to report a speedup over wrong answers). Timing uses obs.wall;
warmup (bucket-ladder compilation) happens before any timed section, like
bench.py excludes one-time setup.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .. import obs
from ..obs import telemetry


def _trim_buckets(buckets):
    """Drop the all-zero prefix and the saturated suffix of cumulative
    [le, count] pairs so the JSON shows only the populated range (the
    +Inf terminator always stays)."""
    total = buckets[-1][1]
    out = [[le, c] for le, c in buckets[:-1] if 0 < c <= total]
    keep = []
    for le, c in out:
        keep.append([le, c])
        if c == total:
            break
    keep.append(list(buckets[-1]))
    return keep


def _make_data(n: int, f: int, seed: int):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] * np.sin(X[:, 2]) +
         0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def run_serve_bench(*, requests: int = 512, rows_per_request: int = 1,
                    trees: int = 120, num_leaves: int = 63,
                    n_features: int = 28, train_rows: int = 20000,
                    max_batch_rows: int = 8192, max_wait_ms: float = 2.0,
                    closed_loop_requests: int = 128,
                    assert_speedup: Optional[float] = None,
                    dispatch_mode: str = "continuous",
                    binned: bool = False,
                    seed: int = 3) -> Dict[str, Any]:
    """Train a small model, replay a request stream three ways, return a
    bench-style JSON-serializable dict. With ``assert_speedup``, raises
    AssertionError when open-loop throughput is below that multiple of the
    naive baseline."""
    import lightgbm_tpu as lgb

    X, y = _make_data(train_rows, n_features, seed)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": num_leaves,
                     "verbosity": -1, "tpu_iter_block": 20},
                    ds, num_boost_round=trees)

    rng = np.random.RandomState(seed + 1)
    pool = rng.randn(requests * rows_per_request, n_features)
    reqs = [pool[i * rows_per_request:(i + 1) * rows_per_request]
            for i in range(requests)]

    # -- naive: per-request Booster.predict at batch size 1 (host walk) --
    with obs.wall("serve_bench/naive") as w:
        naive = [bst.predict(r) for r in reqs]
    naive_s = max(w.seconds, 1e-9)

    # -- session + batcher (warmup excluded from every timed section) --
    session = lgb.serve.PredictSession(bst)
    session.warmup([rows_per_request, min(max_batch_rows, len(pool))])
    served = []
    with lgb.serve.MicroBatcher(session, max_batch_rows=max_batch_rows,
                                max_wait_ms=max_wait_ms,
                                dispatch_mode=dispatch_mode) as mb:
        with obs.wall("serve_bench/open_loop") as w:
            futs = [mb.submit(r) for r in reqs]
            served = [f.result(timeout=120) for f in futs]
        open_s = max(w.seconds, 1e-9)
        closed_hist = obs.Histogram()
        for r in reqs[:closed_loop_requests]:
            t0 = obs.monotonic()
            mb.submit(r).result(timeout=120)
            closed_hist.observe((obs.monotonic() - t0) * 1000.0)

    # -- parity asserted in-run: a fast wrong answer is not a result --
    flat_naive = np.concatenate([np.atleast_1d(p) for p in naive])
    flat_served = np.concatenate([np.atleast_1d(p) for p in served])
    np.testing.assert_allclose(flat_served, flat_naive, rtol=1e-4, atol=1e-5)
    parity = float(np.max(np.abs(flat_served - flat_naive))) \
        if len(flat_naive) else 0.0

    total_rows = requests * rows_per_request
    speedup = naive_s / open_s
    chist = closed_hist.snapshot()
    result = {
        "metric": "serve_open_loop_throughput",
        "value": round(total_rows / open_s, 2),
        "unit": "rows/s (%d requests x %d rows, %d trees x %d leaves, "
                "max_batch_rows=%d max_wait_ms=%g dispatch=%s)"
                % (requests, rows_per_request, trees, num_leaves,
                   max_batch_rows, max_wait_ms, dispatch_mode),
        "dispatch_mode": dispatch_mode,
        "vs_baseline": round(speedup, 3),
        "naive_rows_per_s": round(total_rows / naive_s, 2),
        "naive_s": round(naive_s, 4),
        "open_loop_s": round(open_s, 4),
        "open_loop_requests_per_s": round(requests / open_s, 2),
        "closed_loop_p50_ms": round(chist["p50"], 3),
        "closed_loop_p90_ms": round(chist["p90"], 3),
        "closed_loop_p99_ms": round(chist["p99"], 3),
        "closed_loop_p999_ms": round(chist["p999"], 3),
        # cumulative [le, count] pairs, trimmed to the populated range
        "closed_loop_hist_buckets": _trim_buckets(chist["buckets"]),
        # the batcher's own submit->delivery histogram (open + closed
        # loop requests), as served by /metrics
        "serve_latency_hist": telemetry.histogram("serve/latency_ms"),
        # time-in-queue until batch seal — the quantity continuous
        # dispatch exists to shrink
        "queue_wait_hist": telemetry.histogram("serve/queue_wait_ms"),
        "parity_max_abs_err": parity,
        "serve_counters": {
            k: v for k, v in telemetry.snapshot()["counters"].items()
            if k.startswith("serve/")},
    }
    if binned:
        # pre-binned fast path: the caller already holds a constructed
        # Dataset sharing the training bin mappers, so serving can route
        # in BIN space (no raw-threshold comparisons). Parity against
        # the naive per-request answers is asserted in-run.
        pool_ds = lgb.Dataset(pool, reference=ds,
                              free_raw_data=False).construct()
        binned_pred = session.predict_binned(pool_ds)  # warm bin-log cache
        with obs.wall("serve_bench/binned") as w:
            binned_pred = session.predict_binned(pool_ds)
        binned_s = max(w.seconds, 1e-9)
        np.testing.assert_allclose(np.atleast_1d(binned_pred), flat_naive,
                                   rtol=1e-4, atol=1e-5)
        result["binned_rows_per_s"] = round(total_rows / binned_s, 2)
        result["binned_s"] = round(binned_s, 4)
        result["binned_parity_max_abs_err"] = float(
            np.max(np.abs(np.atleast_1d(binned_pred) - flat_naive))) \
            if len(flat_naive) else 0.0
    if assert_speedup is not None and speedup < assert_speedup:
        raise AssertionError(
            "serve speedup %.2fx below the required %.1fx (naive %.3fs, "
            "open loop %.3fs)" % (speedup, assert_speedup, naive_s, open_s))
    return result
