"""Device-resident prediction session with a shape-bucket ladder.

The training-path device predict (boosting._raw_scores_range) used to
re-pack the ensemble on host per call and retrace ``predict_raw`` for
every distinct row count. A :class:`PredictSession` fixes both:

- the packed ensemble is fetched through the booster's version-keyed
  ``_packed_model`` cache (device-resident ``PackedSplits``; the
  ``device_resident_planes`` pattern applied to inference) and refreshed
  only when the model-version token moves;
- row counts are rounded UP to a fixed bucket ladder, the batch is padded
  to the bucket and the result sliced back, so the bucketed predict
  compiles once per rung instead of once per distinct N. Row routing is
  row-independent, so padding never changes real rows' scores.

A pre-binned fast path (:meth:`predict_binned`) routes in BIN space via
``tree_to_bin_log``/``assign_leaves`` when the caller holds a constructed
``Dataset`` — no raw-threshold comparisons, reusing the training router.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import telemetry, track_jit
from ..obs_trace import tracer
from ..ops.forest import forest_predict_impl
from ..ops.predict import predict_raw_impl
from ..utils.log import LightGBMError, Log

#: Default bucket ladder. Rungs are ~4x apart: at most ~25% of a dispatch
#: is padding in the worst case, and a full warmup compiles 5 programs.
DEFAULT_BUCKETS = (256, 1024, 4096, 16384, 65536)

# one process-wide jit shared by every session: packs come from the
# per-booster _packed_model cache, so two sessions over the same booster
# (or a session recreated after restart-free model reloads) hit the same
# compiled executables
_predict_bucket = track_jit("serve/predict_bucket", jax.jit(
    predict_raw_impl,
    static_argnames=("num_class", "has_cat", "has_linear", "tree_batch")))

# forest-at-once path (ops/forest.py): same process-wide sharing and the
# same bucket contract — one compile per (rung, model shape), zero on
# repeat dispatches. The per-depth-gather _predict_bucket above stays the
# default and the bit-parity oracle (tpu_forest_kernel discipline).
_forest_bucket = track_jit("serve/forest_bucket", jax.jit(
    forest_predict_impl,
    static_argnames=("num_class", "has_cat", "has_linear", "tree_batch",
                     "tile", "interpret")))


class PredictSession:
    """Serving handle over a trained booster (``lgb.Booster`` or inner
    ``GBDT``): device-resident pack + shape-bucketed compiled predict.

    Thread-safe for concurrent ``predict``/``raw_scores`` calls; pair with
    :class:`~lightgbm_tpu.serve.batcher.MicroBatcher` to coalesce many
    small requests into one dispatch.
    """

    def __init__(self, model, *, start_iteration: int = 0,
                 num_iteration: int = -1,
                 buckets: Optional[Sequence[int]] = None,
                 forest: Optional[str] = None) -> None:
        self._gbdt = getattr(model, "inner", model)
        if start_iteration < 0:
            raise LightGBMError("start_iteration must be >= 0")
        if forest not in (None, "on", "off"):
            raise LightGBMError(
                "forest must be None (follow tpu_forest_kernel), 'on' or "
                "'off', got %r" % (forest,))
        self._start = int(start_iteration)
        self._num = int(num_iteration)
        rungs = tuple(sorted({int(b) for b in (buckets or DEFAULT_BUCKETS)}))
        if not rungs or rungs[0] < 1:
            raise LightGBMError("serve buckets must be positive ints")
        self.buckets = rungs
        self._lock = threading.Lock()
        self._pack = None
        self._has_cat = False
        self._has_linear = False
        self._K = max(1, int(self._gbdt.num_tree_per_iteration))
        self._version = -1
        self._range = (0, 0)
        self._warm: set = set()
        # forest-at-once state: explicit override (None = follow the
        # booster's resolved tpu_forest_kernel knob), version-keyed entry,
        # inner->total column map for host binning, warn-once latch
        self._forest_cfg = forest
        self._fentry = None
        self._fver = -1
        self._frange = (0, 0)
        self._f_cols: Optional[np.ndarray] = None
        self._forest_warned = False

    # ------------------------------------------------------------ resolution
    def num_features(self) -> int:
        """Feature count for warmup batches (train_set, loaded feature
        names, or max split feature as a last resort)."""
        g = self._gbdt
        if g.train_set is not None:
            return int(g.train_set.num_total_features)
        names = getattr(g, "_feature_names", None)
        if names:
            return len(names)
        mx = -1
        for t in g.models:
            if t.num_leaves > 1:
                mx = max(mx, int(t.split_feature[:t.num_internal].max()))
        return mx + 1

    def bucket_for(self, rows: int) -> int:
        """Smallest ladder rung covering ``rows`` (the top rung for counts
        beyond the ladder — larger batches dispatch in top-rung chunks)."""
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def _resolve_range(self) -> Tuple[int, int]:
        g = self._gbdt
        total = len(g.models) // self._K
        end = total if self._num <= 0 else min(total, self._start + self._num)
        return self._start, max(self._start, end)

    def _ensure_pack(self):
        """Refresh the device-resident pack iff the model version (or the
        resolved iteration range) moved; returns (pack, has_cat,
        has_linear)."""
        g = self._gbdt
        # lock order is session -> booster (nothing takes them the other
        # way round). Holding the booster's model lock across the
        # version read, range resolution and pack build pins one
        # (models, version) pair — a concurrent training commit lands
        # wholly before or wholly after this snapshot, never inside it.
        with self._lock, g._cache_lock:
            ver = g.model_version
            rng = self._resolve_range()
            if self._pack is None or ver != self._version \
                    or rng != self._range:
                self._pack, self._has_cat, self._has_linear = \
                    g._packed_model(*rng)
                self._version, self._range = ver, rng
                # pack shapes may have changed -> compiled rungs are stale
                self._warm.clear()
            return self._pack, self._has_cat, self._has_linear

    def _forest_mode(self) -> str:
        """Effective forest-kernel mode for this session: the explicit
        constructor override when given, else the booster's resolved
        ``tpu_forest_kernel`` knob (ledger preresolution included)."""
        if self._forest_cfg is not None:
            return self._forest_cfg
        return self._gbdt._forest_knob()

    def _ensure_forest(self):
        """Version-keyed forest entry (``(ForestPack, has_cat,
        has_linear)``) via the booster's ``_forest_model`` cache, or
        ``None`` when the model is structurally ineligible (no train_set,
        unmapped splits, node tables past the VMEM budget). Same snapshot
        discipline as :meth:`_ensure_pack`."""
        g = self._gbdt
        with self._lock, g._cache_lock:
            ver = g.model_version
            rng = self._resolve_range()
            if self._fver != ver or self._frange != rng:
                self._fentry = g._forest_model(*rng)
                self._fver, self._frange = ver, rng
                self._f_cols = None
                if g.train_set is not None:
                    self._f_cols = np.asarray(
                        g.train_set.used_feature_indices, np.int64)
                # forest tables changed -> compiled rungs are stale
                self._warm.clear()
            return self._fentry

    def _bin_rows(self, X: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side binning for the forest path: (n, F_total) float32
        raw rows -> ((n, Fi) int32 BIN matrix, (n, Fi) float32 raw
        values), both in INNER feature order (the order the forest tables
        were packed in). Pure numpy — no device work, no sync."""
        ds = self._gbdt.train_set
        with self._lock:
            cols = self._f_cols
        if cols is not None and len(cols) and X.shape[1] <= int(cols.max()):
            raise LightGBMError(
                "predict rows have %d features but the model was trained "
                "on %d" % (X.shape[1], int(cols.max()) + 1))
        Xr = np.ascontiguousarray(X[:, cols]) if cols is not None else X
        bins = np.empty(Xr.shape, np.int32)
        for j in range(Xr.shape[1]):
            bins[:, j] = ds.bin_mappers[j].value_to_bin(Xr[:, j])
        return bins, Xr

    def version(self) -> int:
        """Model-version token of the currently-resident pack (-1 before
        the first dispatch). The online promotion gate's observable: a
        promoted candidate moves it, a rejected one must not."""
        with self._lock:
            return self._version

    def pack_fingerprint(self) -> str:
        """Content hash (sha256 hex) over every array of the resident
        pack. Test/debug hook for the online promotion contract: after a
        REJECTED candidate the serving pack must be byte-identical, after
        a promotion it must differ. Pulls the pack to host — never call
        on the hot path."""
        import hashlib

        pack, _, _ = self._ensure_pack()
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(pack):
            arr = np.asarray(leaf)  # graftlint: disable=host-sync
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        return h.hexdigest()

    # -------------------------------------------------------------- dispatch
    def dispatch(self, X) -> List[Tuple[jax.Array, int]]:
        """Bucketed device dispatch; returns [(device scores, real rows)].

        No device->host sync happens here — callers (raw_scores, the
        MicroBatcher) pull results when delivering them. N beyond the top
        rung is chunked; each chunk pads up to its covering bucket.
        """
        forest = None
        if self._forest_mode() == "on":
            forest = self._ensure_forest()
            if forest is None:
                with self._lock:
                    warn = not self._forest_warned
                    self._forest_warned = True
                if warn:
                    Log.warning(
                        "tpu_forest_kernel=on but this model is ineligible "
                        "for the forest path; serving stays on the "
                        "per-depth-gather oracle")
        if forest is None:
            pack, has_cat, has_linear = self._ensure_pack()
        X = np.ascontiguousarray(np.asarray(X), dtype=np.float32)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2:
            raise LightGBMError("predict expects a 2-D (rows, features) "
                                "array, got ndim=%d" % X.ndim)
        n = X.shape[0]
        pieces: List[Tuple[jax.Array, int]] = []
        if n == 0:
            return pieces
        if forest is not None:
            # bin the whole request once (host numpy); the kernel routes
            # in BIN space and gathers raw values only for linear leaves
            X, Xraw = self._bin_rows(X)
            telemetry.count("serve/forest_dispatches")
        nf = X.shape[1]
        top = self.buckets[-1]
        telemetry.count("serve/dispatches")
        # async dispatch only — the span ends when every chunk is queued,
        # not when the device finishes (that wait is serve/slice_back)
        with tracer.span("serve/session_dispatch", domain="serve", rows=n):
            for lo in range(0, n, top):
                chunk = X[lo:lo + top]
                rows = chunk.shape[0]
                b = self.bucket_for(rows)
                with self._lock:
                    warm = b in self._warm
                    self._warm.add(b)
                telemetry.count(
                    "serve/bucket_hit" if warm else "serve/bucket_miss")
                if b > rows:
                    telemetry.count("serve/pad_rows", b - rows)
                    chunk = np.concatenate(
                        [chunk, np.zeros((b - rows, nf), chunk.dtype)])
                if forest is not None:
                    fp, f_cat, f_lin = forest
                    xchunk = Xraw[lo:lo + top]
                    if b > rows:
                        xchunk = np.concatenate(
                            [xchunk,
                             np.zeros((b - rows, nf), np.float32)])
                    score = _forest_bucket(
                        jnp.asarray(chunk), jnp.asarray(xchunk), fp,
                        num_class=self._K, has_cat=f_cat,
                        has_linear=f_lin)
                else:
                    score = _predict_bucket(jnp.asarray(chunk), pack,
                                            num_class=self._K,
                                            has_cat=has_cat,
                                            has_linear=has_linear)
                pieces.append((score, rows))
        return pieces

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> "PredictSession":
        """Pre-compile the bucketed predict for the given row counts (the
        full ladder by default). Each count warms its covering rung, so a
        warmed rung costs at most one compile."""
        nf = max(1, self.num_features())
        for b in sorted({self.bucket_for(int(v))
                         for v in (buckets or self.buckets)}):
            self.dispatch(np.zeros((b, nf), np.float32))
            # warm the output transform at the rung shape too — finalize
            # evaluates convert_output at bucket shapes (see below), so a
            # warmed rung pays zero compiles end to end
            self.finalize(np.zeros((b, self._K), np.float64))
        return self

    # --------------------------------------------------------------- results
    def raw_scores(self, X) -> np.ndarray:
        """(n, F) raw rows -> (n, K) float64 raw ensemble sums (no init
        score, no output transform) — the boosting _raw_scores_range
        contract."""
        pieces = self.dispatch(X)
        if not pieces:
            return np.zeros((0, self._K), np.float64)
        outs = [np.asarray(s, np.float64)[:r] for s, r in pieces]
        raw = outs[0] if len(outs) == 1 else np.concatenate(outs)
        return raw.reshape(len(raw), -1) if raw.ndim == 1 else raw

    def finalize(self, raw: np.ndarray, *, raw_score: bool = False) -> np.ndarray:
        """Raw ensemble sums -> final predictions: RF averaging, init
        scores, objective output transform, (n,) squeeze for K == 1."""
        g = self._gbdt
        score = np.asarray(raw, np.float64)
        score = score.reshape(len(score), -1)
        if g.name == "rf":
            start, end = self._range if self._pack is not None \
                else self._resolve_range()
            score = score / max(1, end - start)
        score = score + g.init_scores[None, :self._K]
        if not raw_score and g.objective is not None:
            # evaluate the (row-independent) output transform at the
            # covering bucket shape: convert_output is eager jax, which
            # compiles per distinct shape — without padding every new
            # coalesced batch size would pay a compile at delivery time
            n = len(score)
            b = self.bucket_for(n)
            if 0 < n < b:
                score = np.concatenate(
                    [score, np.zeros((b - n, score.shape[1]), np.float64)])
            score = np.asarray(
                g.objective.convert_output(jnp.asarray(score)),
                np.float64)[:n]
        return score.ravel() if self._K == 1 else score

    def predict(self, X, *, raw_score: bool = False) -> np.ndarray:
        """Full prediction for raw feature rows (pads to the covering
        bucket, slices back; parity with ``Booster.predict``)."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        telemetry.count("serve/requests")
        telemetry.count("serve/rows", X.shape[0])
        return self.finalize(self.raw_scores(X), raw_score=raw_score)

    def predict_binned(self, dataset, *, raw_score: bool = False) -> np.ndarray:
        """Pre-binned fast path: route a constructed ``Dataset`` in BIN
        space via ``tree_to_bin_log`` + the training router — no raw
        thresholds, and the per-tree bin logs are cached per (tree,
        dataset) like DART score replay."""
        from ..boosting import ScoreTracker

        g = self._gbdt
        binned = dataset.construct() if hasattr(dataset, "construct") \
            else dataset
        start, end = self._resolve_range()
        K = self._K
        n = binned.num_data
        telemetry.count("serve/requests")
        telemetry.count("serve/rows", n)
        telemetry.count("serve/binned_requests")
        ts = ScoreTracker(n, K, np.zeros(K, np.float64))
        linear_extra = None
        for i, tree in enumerate(g.models[start * K:end * K]):
            vals, leaf = g._route_tree_device(tree, binned)
            if getattr(tree, "is_linear", False) \
                    and binned.raw_numeric is not None:
                # linear leaves need raw feature values; the router
                # returns to_split_arrays SLOTS — map to LEAF ids for the
                # coefficient lookup (boosting._linear_score_updates)
                leaf_of_slot = tree.to_split_arrays()["leaf_of_slot"]
                rv = tree.linear_predict(
                    binned.raw_numeric.astype(np.float64),
                    leaf_of_slot[np.asarray(leaf)])  # graftlint: disable=host-sync
                if linear_extra is None:
                    linear_extra = np.zeros((n, K), np.float64)
                linear_extra[:, i % K] += rv
                continue
            ts.add(vals, leaf, i % K, K)
        raw = np.asarray(ts.np(), np.float64).reshape(n, -1)
        if linear_extra is not None:
            raw = raw + linear_extra
        return self.finalize(raw, raw_score=raw_score)
