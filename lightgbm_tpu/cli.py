"""Command-line application: train / predict / convert_model / refit /
save_binary.

Equivalent of the reference CLI (reference: src/main.cpp:11,
src/application/application.h:29 Application, application.cpp:52
LoadParameters). Usage mirrors the reference:

    python -m lightgbm_tpu config=train.conf [key=value ...]
"""
from __future__ import annotations

import os
import sys
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config, resolve_aliases
from .engine import train as _train
from .io import load_config_file, load_text_file
from .utils.log import Log, verbosity_to_level


def parse_args(argv: List[str]) -> Dict[str, Any]:
    """``config=file`` + ``key=value`` overrides
    (reference: application.cpp:52-85 — config file first, CLI wins).
    Two flag-style extras on top of the reference grammar:
    ``--dump-telemetry PATH`` (or ``--dump-telemetry=PATH``) maps to the
    ``dump_telemetry`` parameter, ``--dump-trace PATH`` to ``dump_trace``
    (Chrome trace-event JSON from the span flight recorder)."""
    flags = {"--dump-telemetry": "dump_telemetry",
             "--dump-trace": "dump_trace"}
    cli: Dict[str, str] = {}
    argv = list(argv)
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in flags and i + 1 < len(argv):
            cli[flags[a]] = argv[i + 1].strip()
            i += 2
            continue
        if "=" in a and a.split("=", 1)[0] in flags:
            cli[flags[a.split("=", 1)[0]]] = a.split("=", 1)[1].strip()
            i += 1
            continue
        if "=" not in a:
            Log.warning("Unknown argument: %s", a)
            i += 1
            continue
        k, v = a.split("=", 1)
        cli[k.strip()] = v.strip()
        i += 1
    params: Dict[str, Any] = {}
    if "config" in cli or "config_file" in cli:
        params.update(load_config_file(cli.get("config") or cli["config_file"]))
    params.update(cli)
    params.pop("config", None)
    params.pop("config_file", None)
    return params


class Application:
    """(reference: application.h:29)"""

    def __init__(self, params: Dict[str, Any]) -> None:
        self.raw_params = resolve_aliases(params)
        self.config = Config.from_params(params)
        Log.reset_log_level(verbosity_to_level(self.config.verbosity))
        # the CLI process owns the span tracer: apply the (validated)
        # trace_spans mode up front so every task records consistently
        from .obs_trace import tracer
        tracer.configure(self.config.trace_spans,
                         self.config.trace_buffer_events)
        # same contract for the device-cost capture flag
        from . import obs_device
        obs_device.configure(cost_enabled=self.config.obs_device_cost)

    def run(self) -> None:
        task = self.config.task
        if task == "train":
            self.train()
        elif task in ("predict", "prediction", "test"):
            self.predict()
        elif task == "convert_model":
            self.convert_model()
        elif task == "refit":
            self.refit()
        elif task == "save_binary":
            self.save_binary()
        elif task == "serve":
            self.serve()
        else:
            Log.fatal("Unknown task: %s", task)

    def _load_train_data(self) -> Dataset:
        cfg = self.config
        if cfg.two_round:
            from .io import load_dataset_two_round
            binned = load_dataset_two_round(cfg.data, cfg)
            if binned is not None:
                ds = Dataset(None, params=dict(self.raw_params))
                ds._constructed = binned
                return ds
        X, label, weight, group, names = load_text_file(cfg.data, cfg)
        return Dataset(X, label=label, weight=weight, group=group,
                       feature_name=names or "auto",
                       params=dict(self.raw_params))

    def train(self) -> None:
        cfg = self.config
        train_set = self._load_train_data()
        valid_sets, valid_names = [], []
        for i, vf in enumerate(cfg.valid):
            Xv, lv, wv, gv, _ = load_text_file(vf, cfg)
            valid_sets.append(train_set.create_valid(Xv, label=lv, weight=wv,
                                                     group=gv))
            valid_names.append("valid_%d" % (i + 1) if len(cfg.valid) > 1
                               else "valid_1")
        params = dict(self.raw_params)
        params.setdefault("is_provide_training_metric",
                          cfg.is_provide_training_metric)
        if cfg.is_provide_training_metric:
            valid_sets.insert(0, train_set)
            valid_names.insert(0, "training")
        init_model = cfg.input_model or None
        bst = _train(params, train_set, num_boost_round=cfg.num_iterations,
                     valid_sets=valid_sets, valid_names=valid_names,
                     init_model=init_model)
        bst.save_model(cfg.output_model)
        Log.info("Finished training; model saved to %s", cfg.output_model)

    def predict(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            Log.fatal("task=predict requires input_model")
        bst = Booster(model_file=cfg.input_model)
        X, _, _, _, _ = load_text_file(cfg.data, cfg)
        pred = bst.predict(
            X, raw_score=cfg.predict_raw_score,
            start_iteration=cfg.start_iteration_predict,
            num_iteration=(cfg.num_iteration_predict
                           if cfg.num_iteration_predict > 0 else None),
            pred_leaf=cfg.predict_leaf_index, pred_contrib=cfg.predict_contrib)
        pred2d = pred if pred.ndim > 1 else pred.reshape(-1, 1)
        with open(cfg.output_result, "w") as f:
            for row in pred2d:
                f.write("\t".join("%g" % v for v in row) + "\n")
        Log.info("Finished prediction; results saved to %s", cfg.output_result)

    def convert_model(self) -> None:
        """reference: task=convert_model (gbdt_model_text.cpp ModelToIfElse
        for convert_model_language=cpp; JSON dump otherwise)."""
        cfg = self.config
        if not cfg.input_model:
            Log.fatal("task=convert_model requires input_model")
        bst = Booster(model_file=cfg.input_model)
        out = cfg.convert_model or "gbdt_prediction.cpp"
        if cfg.convert_model_language == "cpp":
            with open(out, "w") as f:
                f.write(bst.inner.to_if_else_cpp())
            Log.info("Model converted to C++ source at %s", out)
        else:
            with open(out, "w") as f:
                f.write(bst.inner.dump_json())
            Log.info("Model dumped to %s", out)

    def refit(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            Log.fatal("task=refit requires input_model")
        bst = Booster(model_file=cfg.input_model)
        X, label, _, _, _ = load_text_file(cfg.data, cfg)
        new_bst = bst.refit(X, label, decay_rate=cfg.refit_decay_rate)
        new_bst.save_model(cfg.output_model)
        Log.info("Refit model saved to %s", cfg.output_model)

    def save_binary(self) -> None:
        cfg = self.config
        ds = self._load_train_data()
        ds.save_binary(cfg.data + ".bin")
        Log.info("Saved binary dataset to %s.bin", cfg.data)

    def serve(self) -> None:
        """task=serve: stdlib-HTTP JSON prediction endpoint over loaded
        model(s) (POST /predict[/<id>], /ingest[/<id>]; GET /healthz,
        /models, /telemetry, /metrics). Device-resident pack +
        bucket-ladder compiled predict + request micro-batching with
        admission control — see lightgbm_tpu/serve/. ``serve_models=
        id=path,...`` hosts extra models next to input_model
        ("default"); ``online_train=true`` attaches an OnlineTrainer per
        model (POST /ingest feeds it) — see lightgbm_tpu/online/.
        SIGTERM drains gracefully: new requests get 503, queued work
        finishes, telemetry/trace dumps fire, exit 0.

        Fleet mode (``fleet_dir=...``): ``fleet_role=trainer`` persists
        ingest/gate/publish events in the durable store, replays them on
        boot, and publishes every promotion/rollback as a version-tokened
        artifact; ``fleet_role=replica`` serves without training, watching
        the store and hot-swapping each published version through the
        adopt path — see lightgbm_tpu/fleet/.

        Fleet hardening: ``fleet_lease_ttl_s>0`` makes the trainer
        lease-gated (boots in standby, trains only while holding the
        store lease, epoch-fenced publishes — run two trainer processes
        on one store and the survivor takes over);
        ``fleet_compact_bytes``/``fleet_keep_artifacts`` bound the store;
        ``fleet_url=http://trainer:port`` points a replica at a remote
        trainer's /fleet endpoints instead of a shared filesystem."""
        cfg = self.config
        entries = []
        if cfg.input_model:
            entries.append(("default", cfg.input_model))
        for spec in cfg.serve_models:
            mid, path = spec.split("=", 1)
            entries.append((mid.strip(), path.strip()))
        if not entries and not cfg.fleet_dir and not cfg.fleet_url \
                and not cfg.fleet_urls:
            Log.fatal("task=serve requires input_model or serve_models")
        fleet_on = bool(cfg.fleet_dir) or bool(cfg.fleet_url) \
            or bool(cfg.fleet_urls)
        fleet_trainer = fleet_on and cfg.fleet_role == "trainer"
        fleet_replica = fleet_on and cfg.fleet_role == "replica"
        import socket
        holder = "%s:%d" % (socket.gethostname(), os.getpid())
        if fleet_on:
            # stamp this process's fleet identity into the span tracer so
            # merged multi-process Perfetto loads keep nodes apart
            from .obs_trace import tracer
            tracer.set_identity(role=cfg.fleet_role, holder=holder)
        if fleet_trainer and not cfg.online_train:
            Log.fatal("fleet_role=trainer requires online_train=true (the "
                      "trainer is the process that publishes promotions)")
        if fleet_replica and cfg.online_train:
            Log.fatal("fleet_role=replica is serve-only (replicas apply "
                      "published models, they never train); drop "
                      "online_train or use fleet_role=trainer")
        if fleet_on and len(entries) > 1:
            Log.fatal("fleet mode serves one model per store; drop "
                      "serve_models or run one process per model")
        if fleet_replica and not entries:
            entries = [("default", "")]   # bootstrap purely from the store
        online_cfg = None
        if cfg.online_train:
            online_cfg = dict(
                mode=cfg.online_mode,
                trigger_rows=cfg.online_trigger_rows,
                trigger_interval_s=cfg.online_trigger_interval_s,
                buffer_rows=cfg.online_buffer_rows,
                shadow_rows=cfg.online_shadow_rows,
                promote_threshold=cfg.online_promote_threshold,
                min_rows=cfg.online_min_rows,
                continue_rounds=cfg.online_continue_rounds,
                decay_rate=cfg.refit_decay_rate,
                shadow_decay=cfg.online_shadow_decay,
                promote_patience=cfg.online_promote_patience,
                rollback_threshold=cfg.online_rollback_threshold,
                rollback_min_rows=cfg.online_rollback_min_rows)
        tenant_weights = {}
        for spec in cfg.serve_tenant_weights:
            name, _, w = spec.partition("=")
            tenant_weights[name.strip()] = float(w)
        from .online import ModelRegistry
        from .serve.http import PredictServer
        registry = ModelRegistry()
        watcher = None
        for mid, path in entries:
            booster, applied = None, 0
            store = None
            if cfg.fleet_dir:
                from .fleet import FleetStore, bootstrap_model
                # a replica over a shared filesystem is a pure reader:
                # it must not run the open-time torn-tail repair or
                # orphan reaping against a live trainer's files
                store = FleetStore(cfg.fleet_dir, mid,
                                   read_only=fleet_replica)
                booster, applied = bootstrap_model(store)
            elif cfg.fleet_url or cfg.fleet_urls:
                from .fleet import (MultiEndpointStore, RemoteStore,
                                    RemoteWriteStore, bootstrap_model)
                if fleet_trainer:
                    # remote trainer: the full write surface (lease,
                    # fenced publish, ingest/gate appends, compaction)
                    # over HTTP against the store host — no shared
                    # filesystem anywhere in the path
                    store = RemoteWriteStore(
                        cfg.fleet_urls[0],
                        timeout_s=cfg.fleet_timeout_s,
                        backoff_max_s=cfg.fleet_backoff_max_s)
                elif len(cfg.fleet_urls) > 1:
                    # multi-endpoint replica: liveness-ranked failover
                    store = MultiEndpointStore(
                        cfg.fleet_urls,
                        timeout_s=cfg.fleet_timeout_s,
                        backoff_max_s=cfg.fleet_backoff_max_s)
                    store.probe()
                else:
                    store = RemoteStore(
                        cfg.fleet_url or cfg.fleet_urls[0],
                        timeout_s=cfg.fleet_timeout_s,
                        backoff_max_s=cfg.fleet_backoff_max_s)
                try:
                    booster, applied = bootstrap_model(store)
                except Exception as exc:
                    # the remote trainer may simply not be up yet; the
                    # watcher keeps retrying with backoff
                    Log.warning("fleet: remote bootstrap failed (%s: "
                                "%s); watching %s for the first publish",
                                type(exc).__name__, exc,
                                cfg.fleet_url
                                or ",".join(cfg.fleet_urls))
            if booster is not None:
                Log.info("fleet: %s booted from published v%d",
                         mid, applied)
            if booster is None:
                if not path:
                    Log.fatal("fleet: store %s has no published model yet "
                              "and no input_model to seed from",
                              cfg.fleet_dir or cfg.fleet_url)
                booster = Booster(model_file=path)
                if fleet_trainer and store.latest_publish() is None:
                    # seed the store so replicas can boot before the
                    # first promotion
                    store.publish(booster.model_to_string(), event="boot")
            model_online = None
            if online_cfg is not None:
                model_online = dict(online_cfg)
                if fleet_trainer:
                    model_online.update(
                        store=store, replay=cfg.fleet_replay,
                        lease_ttl_s=cfg.fleet_lease_ttl_s,
                        holder_id=holder,
                        compact_bytes=cfg.fleet_compact_bytes,
                        keep_artifacts=cfg.fleet_keep_artifacts,
                        snapshot_rows=cfg.fleet_snapshot_rows,
                        heartbeat_interval_s=cfg.fleet_heartbeat_interval_s)
            entry = registry.register(
                mid, booster,
                buckets=cfg.serve_buckets or None,
                max_batch_rows=cfg.serve_max_batch_rows,
                max_wait_ms=cfg.serve_max_wait_ms,
                max_queue_rows=cfg.serve_max_queue_rows,
                overload=cfg.serve_overload,
                tenant_quota_rows=cfg.serve_tenant_quota_rows,
                tenant_weights=tenant_weights or None,
                raw_score=cfg.predict_raw_score,
                warmup=cfg.serve_warmup,
                dispatch_mode=cfg.serve_dispatch,
                forest=(None if cfg.tpu_forest_kernel == "auto"
                        else cfg.tpu_forest_kernel),
                online=model_online)
            if fleet_replica:
                from .fleet import ReplicaWatcher
                watcher = ReplicaWatcher(
                    entry.booster, store,
                    poll_interval_s=cfg.fleet_poll_interval_s,
                    applied_version=applied,
                    backoff_max_s=cfg.fleet_backoff_max_s,
                    heartbeat_interval_s=cfg.fleet_heartbeat_interval_s,
                    node_id=holder)
        server = PredictServer(registry=registry, host=cfg.serve_host,
                               port=cfg.serve_port)
        server.fleet_watcher = watcher
        if cfg.fleet_dir and store is not None:
            # local store: serve the /fleet transport routes (remote
            # replicas converge through them) + /healthz lease/log state
            server.fleet_store = store
        elif (cfg.fleet_url or cfg.fleet_urls) and store is not None:
            # remote store: surface transport retry/backoff on /healthz
            server.fleet_transport = store
        host, port = server.address
        if fleet_trainer:
            # advertise this trainer's serving endpoint in the lease
            # record (acquire/renew both write it): the leader_hint
            # ingest forwarding resolves. The bound port is only known
            # HERE, after the trainer exists — the next lease touch
            # carries it (set mutable advertise_url, per-call url= for
            # stores created before the bind)
            adv_host = host if host not in ("0.0.0.0", "::") \
                else __import__("socket").gethostname()
            advertise = "http://%s:%d" % (adv_host, port)
            try:
                ent = registry.get()
                if ent.online is not None:
                    ent.online.advertise_url = advertise
            except KeyError:
                pass
        if cfg.fleet_forward_ingest and store is not None:
            # relay labeled traffic hitting this node to the lease
            # holder instead of 409ing it (replicas and standbys have
            # no online trainer to buffer it)
            from .fleet import IngestForwarder
            server.ingest_forwarder = IngestForwarder(
                store=store if cfg.fleet_dir else None,
                urls=(cfg.fleet_urls or
                      ([cfg.fleet_url] if cfg.fleet_url else ())),
                timeout_s=cfg.fleet_timeout_s)
        Log.info("Serving %s on http://%s:%d (POST /predict, /ingest; GET "
                 "/healthz, /models, /telemetry, /metrics)%s",
                 ", ".join("%s=%s" % e for e in entries), host, port,
                 " [fleet %s @ %s]" % (cfg.fleet_role,
                                       cfg.fleet_dir or cfg.fleet_url
                                       or ",".join(cfg.fleet_urls))
                 if fleet_on else "")
        stop_dump = None
        if cfg.dump_telemetry and cfg.telemetry_dump_interval_s > 0:
            # a wedged server still leaves fresh counters on disk
            from .obs_trace import start_periodic_telemetry_dump
            stop_dump = start_periodic_telemetry_dump(
                cfg.dump_telemetry, cfg.telemetry_dump_interval_s)
        stop_hbm = None
        if cfg.obs_hbm_sample_interval_s > 0:
            # live-HBM watermark under load (hbm/* gauges on /metrics;
            # counted no-op on backends without memory stats)
            from . import obs_device
            stop_hbm = obs_device.start_hbm_sampler(
                cfg.obs_hbm_sample_interval_s)
        import signal
        import threading

        def _on_sigterm(signum, frame):
            # begin_shutdown calls httpd.shutdown(), which would deadlock
            # on the thread stuck inside serve_forever (this one) — hop
            # to a helper thread and let serve_forever return
            threading.Thread(target=server.begin_shutdown,
                             name="lgbtpu-serve-drain",
                             daemon=True).start()

        try:
            old_term = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:        # not the main thread (embedded use)
            old_term = None
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            # return normally so main() still honors --dump-telemetry —
            # serving counters must survive the process
            Log.info("serve: interrupted, shutting down")
        finally:
            if stop_dump is not None:
                stop_dump.set()
            if stop_hbm is not None:
                stop_hbm.set()
            # drains the batchers: requests admitted before the drain
            # flag flipped still get their answers
            server.close()
            if old_term is not None:
                signal.signal(signal.SIGTERM, old_term)
            if cfg.obs_ledger:
                # one serve entry per process lifetime: the serving
                # latency histograms + device-cost section at drain time
                from . import obs_ledger
                extra = None
                if fleet_on:
                    # record what this process actually WAS (a standby
                    # that never won the lease ledgers as standby, not
                    # trainer) so `ledger list` tells fleet runs apart
                    role, epoch = cfg.fleet_role, 0
                    try:
                        ent = registry.get()
                        if ent.online is not None:
                            st = ent.online.state()
                            role = st.get("role", role)
                            epoch = int(st.get("lease_epoch", 0))
                    except Exception:
                        pass
                    extra = {"fleet": {"role": role, "holder": holder,
                                       "lease_epoch": epoch}}
                obs_ledger.record_run(cfg, "serve", 0, 0, extra=extra)
        Log.info("serve: drained and closed")


def main(argv: Optional[List[str]] = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__)
        return
    app = Application(parse_args(argv))
    cfg = app.config
    if cfg.dump_telemetry or cfg.dump_trace:
        # SIGUSR1 -> telemetry snapshot, SIGUSR2 -> trace dump, live —
        # a hung run can be inspected without killing it
        from .obs_trace import install_signal_handlers
        try:
            install_signal_handlers(
                telemetry_path=cfg.dump_telemetry or None,
                trace_path=cfg.dump_trace or None)
        except ValueError:    # not the main thread (embedded use)
            pass
    app.run()
    if cfg.dump_telemetry:
        import json
        from .obs import telemetry
        with open(cfg.dump_telemetry, "w") as f:
            json.dump(telemetry.snapshot(), f, indent=2)
        Log.info("Dumped telemetry to %s", cfg.dump_telemetry)
    if cfg.dump_trace:
        from .obs_trace import tracer
        n = tracer.dump(cfg.dump_trace)
        Log.info("Dumped %d trace events to %s", n, cfg.dump_trace)


if __name__ == "__main__":
    main()
