"""User-facing Dataset and Booster.

Equivalent of the reference python package's ctypes layer
(reference: python-package/lightgbm/basic.py:1035 Dataset, :2142 Booster) —
except there is no C ABI to cross: the "native" side here is the jitted
JAX/XLA program, so Dataset wraps BinnedDataset construction lazily and
Booster wraps the boosting driver directly.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config, resolve_aliases
from .dataset import BinnedDataset, construct_dataset
from .boosting import GBDT, create_boosting
from .utils.log import Log, LightGBMError


def _to_2d(data) -> np.ndarray:
    if hasattr(data, "toarray"):  # scipy sparse
        data = data.toarray()
    data = _frame_values(data)
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return arr


def _frame_values(data):
    """pandas DataFrame -> float matrix; category columns become their codes
    (reference: python-package/lightgbm/basic.py _data_from_pandas)."""
    if hasattr(data, "dtypes") and hasattr(data, "columns") \
            and not isinstance(data, np.ndarray):
        import pandas as pd
        out = np.empty((len(data), data.shape[1]), dtype=np.float64)
        for j, col in enumerate(data.columns):
            c = data[col]
            if isinstance(c.dtype, pd.CategoricalDtype):
                codes = c.cat.codes.to_numpy().astype(np.float64)
                codes[codes < 0] = np.nan
                out[:, j] = codes
            else:
                out[:, j] = pd.to_numeric(c, errors="coerce").to_numpy(
                    dtype=np.float64)
        return out
    if hasattr(data, "values") and not isinstance(data, np.ndarray):
        return data.values
    return data


def _pandas_categorical_columns(data):
    """Indices of pandas category-dtype columns (categorical_feature='auto'
    semantics of the reference python package)."""
    if hasattr(data, "dtypes") and hasattr(data, "columns") \
            and not isinstance(data, np.ndarray):
        import pandas as pd
        return [j for j, col in enumerate(data.columns)
                if isinstance(data[col].dtype, pd.CategoricalDtype)]
    return []


def _to_1d(data) -> Optional[np.ndarray]:
    if data is None:
        return None
    if hasattr(data, "values") and not isinstance(data, np.ndarray):
        data = data.values
    return np.asarray(data).ravel()


class Dataset:
    """Lazily-constructed training dataset (reference: basic.py:1035).
    Binning happens at ``construct()`` (inside ``train``), so parameters set
    afterwards still apply — mirroring the reference's lazy ``_lazy_init``."""

    def __init__(self, data, label=None, *, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = False) -> None:
        self.data = data
        self.label = _to_1d(label)
        self.weight = _to_1d(weight)
        self.group = _to_1d(group)
        self.init_score = None if init_score is None else np.asarray(init_score)
        self.reference = reference
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self._constructed: Optional[BinnedDataset] = None
        self._used_params: Optional[Dict[str, Any]] = None

    # -- setters mirroring the reference API --
    def set_label(self, label) -> "Dataset":
        self.label = _to_1d(label)
        if self._constructed is not None:
            self._constructed.metadata.label = np.ascontiguousarray(
                self.label, dtype=np.float32)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = _to_1d(weight)
        if self._constructed is not None:
            self._constructed.metadata.weight = None if weight is None else \
                np.ascontiguousarray(self.weight, dtype=np.float32)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = _to_1d(group)
        self._constructed = None
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = None if init_score is None else np.asarray(init_score)
        self._constructed = None
        return self

    def get_label(self):
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        if self._constructed is not None and \
                self._constructed.metadata.query_boundaries is not None:
            return np.diff(self._constructed.metadata.query_boundaries)
        return self.group

    def get_init_score(self):
        return self.init_score

    def num_data(self) -> int:
        if self._constructed is not None:
            return self._constructed.num_data
        return _to_2d(self.data).shape[0]

    def num_feature(self) -> int:
        if self._constructed is not None:
            return self._constructed.num_total_features
        return _to_2d(self.data).shape[1]

    def construct(self, params: Optional[Dict[str, Any]] = None) -> BinnedDataset:
        if self._constructed is not None and self.data is None:
            # externally constructed (two-round loader): binning is fixed
            return self._constructed
        merged = dict(self.params)
        if params:
            merged.update(params)
        if self._constructed is not None and self._used_params == merged:
            return self._constructed
        cfg = Config.from_params(merged)
        if isinstance(self.data, str):
            # binary dataset cache (reference: LoadFromBinFile,
            # dataset_loader.cpp:314); explicitly-passed metadata overrides
            # the cached copy
            from .dataset import Metadata as _Meta
            from .dataset import load_binned
            ds = load_binned(self.data)
            if any(v is not None for v in
                   (self.label, self.weight, self.group, self.init_score)):
                md = _Meta(ds.num_data, _to_1d(self.label),
                           _to_1d(self.weight), _to_1d(self.group),
                           self.init_score)
                for f in ("label", "weight", "init_score",
                          "query_boundaries", "query_id"):
                    v = getattr(md, f)
                    if v is not None:
                        setattr(ds.metadata, f, v)
            if self.reference is not None:
                Log.warning("reference= is ignored for binary-cache "
                            "datasets (binning is already fixed)")
            self._constructed = ds
            self._used_params = merged
            return self._constructed
        if hasattr(self.data, "tocsc"):     # scipy sparse: stays O(nnz)
            X = self.data
        else:
            X = _to_2d(self.data)
        feature_names = None
        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)
        elif hasattr(self.data, "columns"):
            feature_names = [str(c) for c in self.data.columns]
        cat = self.categorical_feature
        if cat == "auto":
            auto_cats = _pandas_categorical_columns(self.data)
            cat = auto_cats if auto_cats else None
        ref_binned = self.reference.construct(params) if self.reference else None
        self._constructed = construct_dataset(
            X, cfg, label=self.label, weight=self.weight, group=self.group,
            init_score=self.init_score, feature_names=feature_names,
            categorical_feature=cat, reference=ref_binned)
        self._used_params = merged
        if self.free_raw_data:
            self.data = None
        return self._constructed

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, params=params)

    def save_binary(self, filename: str) -> "Dataset":
        """Cache the fully-constructed binned dataset (reference:
        Dataset::SaveBinaryFile, dataset.h:441); ``Dataset(path)`` loads it
        back without re-parsing or re-binning."""
        from .dataset import save_binned
        save_binned(self.construct(), filename)
        return self


class Booster:
    """Training-capable model handle (reference: basic.py:2142)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 comm_axis: Optional[str] = None) -> None:
        params = params or {}
        self.params = params
        self.train_dataset = train_set
        self._valid_names: List[str] = []
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("train_set must be a Dataset")
            binned = train_set.construct(params)
            self.config = Config.from_params(params)
            self.inner: GBDT = create_boosting(self.config, binned, comm_axis)
        elif model_file is not None:
            with open(model_file) as f:
                self.inner = GBDT.model_from_string(f.read())
            self.config = self.inner.config
        elif model_str is not None:
            self.inner = GBDT.model_from_string(model_str)
            self.config = self.inner.config
        else:
            raise LightGBMError("Need train_set, model_file or model_str")
        # span tracing is process-global: only an EXPLICIT trace_spans
        # param flips it, so a second Booster built with defaults cannot
        # silently turn off a tracer something else switched on
        if "trace_spans" in params:
            from .obs_trace import tracer
            tracer.configure(str(params["trace_spans"]),
                             int(params.get("trace_buffer_events", 0)) or None)
        # loaded models keep their stored best_iteration so predict()
        # defaults to the early-stopped tree count like the reference
        self.best_iteration = self.inner.best_iteration if train_set is None else -1
        self.best_score: Dict[str, Dict[str, float]] = {}

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if data.reference is None:
            data.reference = self.train_dataset
        binned = data.construct(self.params)
        self.inner.add_valid(name, binned)
        self._valid_names.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True if stopped
        (reference: basic.py:2565 update / __boost)."""
        if train_set is not None:
            raise LightGBMError("Resetting train_set is not supported yet")
        if fobj is not None:
            grad, hess = fobj(np.asarray(self.inner.train_score.score),
                              self.train_dataset)
            return self.inner.train_one_iter(np.asarray(grad), np.asarray(hess))
        return self.inner.train_one_iter()

    def rollback_one_iter(self) -> "Booster":
        self.inner.rollback_one_iter()
        return self

    @property
    def current_iteration(self) -> int:
        return self.inner.current_iteration

    def num_trees(self) -> int:
        return self.inner.num_trees()

    def num_model_per_iteration(self) -> int:
        return self.inner.num_tree_per_iteration

    def telemetry(self) -> Dict[str, Any]:
        """JSON-serializable snapshot of the process-global telemetry
        registry (phase timers, dataset device-cache hit/miss counts,
        fused-pipeline dispatch/flush counters, per-tree growth stats and
        ``auto`` knob resolutions). See :mod:`lightgbm_tpu.obs`."""
        from .obs import telemetry
        return telemetry.snapshot()

    def dump_trace(self, path: str) -> int:
        """Write the span flight recorder as Chrome trace-event JSON —
        load the file in Perfetto (ui.perfetto.dev) or chrome://tracing.
        Spans only record while ``trace_spans=on|serve_only``; returns
        the number of trace events written. See
        :mod:`lightgbm_tpu.obs_trace`."""
        from .obs_trace import tracer
        return tracer.dump(path)

    def eval_train(self, feval=None):
        return self.inner.eval_train(feval)

    def eval_valid(self, feval=None):
        return self.inner.eval_valid(feval)

    def predict(self, data, *, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        X = _to_2d(data)
        expected = self.num_feature()
        if expected > 0 and X.shape[1] != expected \
                and not self.config.predict_disable_shape_check:
            from .utils.log import Log
            Log.fatal(
                "The number of features in data (%d) is not the same as in "
                "the model (%d). Set predict_disable_shape_check=true to "
                "bypass (reference: LGBM_BoosterPredict shape check).",
                X.shape[1], expected)
        if num_iteration is None:
            # early stopping: default to the best iteration like the
            # reference python package (basic.py Booster.predict)
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        if pred_contrib:
            return self._predict_contrib(X, num_iteration)
        ni = num_iteration
        return self.inner.predict(X, raw_score=raw_score,
                                  start_iteration=start_iteration,
                                  num_iteration=ni, pred_leaf=pred_leaf)

    def _predict_contrib(self, X: np.ndarray, num_iteration) -> np.ndarray:
        """SHAP-style contributions via path-attribution on each tree
        (reference: TreeSHAP in src/io/tree.cpp). Round-1 implementation:
        exact SHAP for each tree computed on host."""
        from .shap import tree_shap_contribs
        return tree_shap_contribs(self.inner, X, num_iteration)

    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        ni = -1 if num_iteration is None else num_iteration
        with self.inner._cache_lock:
            self.inner.best_iteration = self.best_iteration
        self.inner.save_model(filename, ni)
        return self

    def model_to_string(self, num_iteration: Optional[int] = None) -> str:
        ni = -1 if num_iteration is None else num_iteration
        return self.inner.model_to_string(ni)

    def dump_model(self, num_iteration: Optional[int] = None) -> Dict[str, Any]:
        import json
        ni = -1 if num_iteration is None else num_iteration
        return json.loads(self.inner.dump_json(ni))

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        it = -1 if iteration is None else iteration
        return self.inner.feature_importance(importance_type, it)

    def feature_name(self) -> List[str]:
        if self.inner.train_set is not None:
            return self.inner.train_set.feature_names
        return getattr(self.inner, "_feature_names", [])

    def num_feature(self) -> int:
        """Number of features the model was trained on (reference:
        LGBM_BoosterGetNumFeature); -1 when unknown (featureless model)."""
        if self.inner.train_set is not None:
            return self.inner.train_set.num_total_features
        names = getattr(self.inner, "_feature_names", None)
        if names:
            return len(names)
        return -1

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """(reference: Booster::ResetConfig path, gbdt.cpp:684)"""
        self.params.update(params)
        self.config.set(params)
        inner = self.inner
        # refresh learner hyperparameters that affect future trees,
        # PRESERVING the learner class: a Data/Feature/Voting mesh learner
        # must not silently downgrade to SerialTreeLearner mid-training
        # under the model lock: serving threads read the learner (the
        # tpu_forest_kernel resolution rides on it) while we swap it
        with inner._cache_lock:
            if inner.learner is not None:
                from .parallel.mesh import _MeshTreeLearner, \
                    create_tree_learner
                mesh = inner.learner.mesh \
                    if isinstance(inner.learner, _MeshTreeLearner) else None
                inner.learner = create_tree_learner(
                    self.config, inner.train_set, mesh)
        # drop cached state derived from the old config (samplers, column
        # masks, fused block functions)
        for attr in ("_sampler_fn", "_fmask_fn"):
            if hasattr(inner, attr):
                delattr(inner, attr)
        inner._fused = None
        return self

    def refit(self, data, label, decay_rate: Optional[float] = None,
              weight=None, group=None, **kwargs):
        """Refit leaf values on new data (reference: GBDT::RefitTree,
        gbdt.cpp:285; python Booster.refit).

        ``weight``/``group`` carry the new data's metadata — ranking and
        weighted objectives need them to form correct gradients (a bare
        label stub would crash lambdarank or silently mis-weight)."""
        decay = self.config.refit_decay_rate if decay_rate is None else decay_rate
        X = _to_2d(data)
        y = _to_1d(label)
        new_booster = Booster(model_str=self.model_to_string())
        K = new_booster.inner.num_tree_per_iteration
        score = np.zeros((X.shape[0], K))
        score += new_booster.inner.init_scores[None, :K]
        from .dataset import Metadata
        meta = Metadata(num_data=len(y), label=np.asarray(y, np.float32),
                        weight=None if weight is None else _to_1d(weight),
                        group=None if group is None else _to_1d(group))
        obj = new_booster.inner.objective
        if obj.is_ranking and meta.query_boundaries is None:
            from .utils.log import Log
            Log.fatal("refit with a ranking objective requires group=")
        obj.init(meta)
        # the candidate is private to this call, but refit also runs on the
        # OnlineTrainer worker thread — rewrite its leaves under its model
        # lock so the leaf-value mutations and the final version bump land
        # as one committed model for any session handed the candidate
        with new_booster.inner._cache_lock:
            for i, tree in enumerate(new_booster.inner.models):
                leaf_idx = tree.predict_leaf_index(X)
                # grad at current score for this class
                import jax.numpy as jnp
                s = jnp.asarray(score if K > 1 else score.ravel(), jnp.float32)
                g, h = obj.get_gradients(s)
                g = np.asarray(g).reshape(len(y), -1)[:, i % K]
                h = np.asarray(h).reshape(len(y), -1)[:, i % K]
                lam = new_booster.config.lambda_l2
                for l in range(tree.num_leaves):
                    m = leaf_idx == l
                    if np.any(m):
                        new_val = -g[m].sum() / (h[m].sum() + lam)
                        tree.leaf_value[l] = decay * tree.leaf_value[l] + \
                            (1 - decay) * new_val * tree.shrinkage
                        if getattr(tree, "is_linear", False):
                            # linear leaves OUTPUT leaf_const (+ coeffs);
                            # decay it the same way or refit would only
                            # move the NaN-fallback value
                            tree.leaf_const[l] = \
                                decay * tree.leaf_const[l] + \
                                (1 - decay) * new_val * tree.shrinkage
                score[:, i % K] += tree.predict(X)
            # leaf values were rewritten in place on the fresh booster's trees
            new_booster.inner._bump_model_version()
        return new_booster

    def adopt(self, other: "Booster") -> tuple:
        """Atomically swap this booster's served model for ``other``'s
        (online promotion: single version bump under the model lock, so
        concurrent PredictSessions see old-or-new, never a mix). Returns
        a rollback token for :meth:`restore`."""
        token = self.inner.adopt(getattr(other, "inner", other))
        # keep the wrapper's predict-default cap in step with the swap
        with self.inner._cache_lock:
            self.best_iteration = self.inner.best_iteration
        return token

    def restore(self, snapshot: tuple) -> "Booster":
        """Roll back to a model captured by :meth:`adopt`."""
        self.inner.restore(snapshot)
        with self.inner._cache_lock:
            self.best_iteration = self.inner.best_iteration
        return self


def register_logger(logger) -> None:
    """Redirect framework logging to a python logging.Logger
    (reference: basic.py register_logger)."""
    Log.reset_callback(lambda msg: logger.info(msg.rstrip("\n")))
