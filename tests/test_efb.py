"""Exclusive Feature Bundling, wired end to end.

Reference: Dataset::FindGroups / FastFeatureBundling
(src/io/dataset.cpp:100,239) + FeatureGroup offsets
(include/LightGBM/feature_group.h:25) + FixHistogram (dataset.h:503).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import construct_dataset

sp = pytest.importorskip("scipy.sparse")


def _onehot_blocks(rng, n, n_vars=6, card=12):
    blocks, w = [], []
    for _ in range(n_vars):
        ids = rng.randint(0, card, n)
        blocks.append(sp.csr_matrix((np.ones(n), (np.arange(n), ids)),
                                    shape=(n, card)))
        w.append(rng.randn(card))
    X = sp.hstack(blocks).tocsr()
    y = (np.asarray(X @ np.concatenate(w)).ravel()
         + 0.2 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def test_bundles_shrink_columns(rng):
    X, y = _onehot_blocks(rng, 3000)
    cfg = Config.from_params({"objective": "binary", "verbosity": -1})
    ds = construct_dataset(X, cfg, label=y)
    assert ds.num_features == 72
    # mutually exclusive one-hot groups collapse to ~n_vars columns
    assert ds.num_groups <= 10
    assert ds.binned.shape == (3000, ds.num_groups)
    # every row of a one-hot block hits exactly one non-default slot
    maps = ds.bundle_maps()
    assert maps["proj"].shape[0] == ds.num_features


@pytest.mark.slow
def test_bundled_training_matches_unbundled(rng):
    X, y = _onehot_blocks(rng, 4000)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "metric": ["auc"], "min_data_in_leaf": 5}
    b1 = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=8)
    p2 = dict(params)
    p2["enable_bundle"] = False
    b2 = lgb.train(p2, lgb.Dataset(np.asarray(X.todense()), label=y),
                   num_boost_round=8)
    (_, _, auc1, _), = b1.eval_train()
    (_, _, auc2, _), = b2.eval_train()
    assert auc1 > 0.8
    # same splits are available either way; allow tiny numeric divergence
    assert abs(auc1 - auc2) < 0.02
    Xd = np.asarray(X.todense())
    pr1, pr2 = b1.predict(Xd[:300]), b2.predict(Xd[:300])
    assert np.corrcoef(pr1, pr2)[0, 1] > 0.98


def test_sparse_input_binning_matches_dense(rng):
    X, y = _onehot_blocks(rng, 2000)
    cfg = Config.from_params({"objective": "binary", "verbosity": -1})
    ds_sp = construct_dataset(X, cfg, label=y)
    ds_dn = construct_dataset(np.asarray(X.todense()), cfg, label=y)
    assert ds_sp.num_groups == ds_dn.num_groups
    np.testing.assert_array_equal(ds_sp.binned, ds_dn.binned)


@pytest.mark.slow
def test_valid_set_shares_bundling(rng):
    X, y = _onehot_blocks(rng, 3000)
    Xtr, ytr = X[:2000], y[:2000]
    Xva, yva = X[2000:], y[2000:]
    dtr = lgb.Dataset(Xtr, label=ytr)
    dva = lgb.Dataset(Xva, label=yva, reference=dtr)
    res = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "metric": ["binary_logloss"]},
                    dtr, num_boost_round=8, valid_sets=[dva],
                    valid_names=["va"],
                    callbacks=[lgb.record_evaluation(res)])
    # valid-set score tracking ran on the bundled matrix and is consistent
    # with raw-value prediction
    final_ll = res["va"]["binary_logloss"][-1]
    pred = bst.predict(np.asarray(Xva.todense()))
    eps = 1e-7
    ll = -np.mean(yva * np.log(pred + eps) + (1 - yva) * np.log(1 - pred + eps))
    assert abs(ll - final_ll) < 1e-3
