"""Device linear-leaf fit/predict vs the host oracle (ISSUE 9 tentpole).

``linear_device=on`` routes the per-leaf ridge solves through the batched
device kernel (lightgbm_tpu/linear/fit.py: one segment-sum of outer
products + one batched jnp.linalg.solve for ALL leaves); ``off`` keeps
the sequential host/numpy path. Both must produce the same model: these
tests pin coefficient AND prediction parity at atol=1e-6, the NaN
fallback, multiclass, categorical splits, and the serving path
(PredictSession used to refuse linear models outright).

Numerics note: the device path accumulates and solves in f32 (HIGHEST
precision matmuls), the host oracle in f64. The parity bar is met on
well-conditioned data; the fixtures keep coefficients O(0.3) so the f32
accumulation error stays under the absolute tolerance.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs

ATOL = 1e-6


def _params(**kw):
    p = {"objective": "regression", "num_leaves": 8, "verbosity": -1,
         "linear_tree": True, "linear_lambda": 0.01, "learning_rate": 0.2,
         "min_data_in_leaf": 20, "seed": 7}
    p.update(kw)
    return p


def _train_pair(X, y, rounds=3, **kw):
    """Same data/config trained with the host oracle and the device path."""
    out = []
    for dev in ("off", "on"):
        p = _params(linear_device=dev, **kw)
        out.append(lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                             num_boost_round=rounds))
    return out


def _assert_model_parity(host, device, atol=ATOL):
    assert len(host.inner.models) == len(device.inner.models)
    fitted = 0
    for i, (th, td) in enumerate(zip(host.inner.models, device.inner.models)):
        assert th.is_linear == td.is_linear, i
        assert sorted(th.leaf_coeff) == sorted(td.leaf_coeff), i
        for leaf in th.leaf_coeff:
            assert np.array_equal(th.leaf_features[leaf],
                                  td.leaf_features[leaf]), (i, leaf)
            np.testing.assert_allclose(
                np.asarray(td.leaf_coeff[leaf], np.float64),
                np.asarray(th.leaf_coeff[leaf], np.float64),
                rtol=0, atol=atol, err_msg="tree %d leaf %d coeff" % (i, leaf))
            np.testing.assert_allclose(
                td.leaf_const[leaf], th.leaf_const[leaf],
                rtol=0, atol=atol, err_msg="tree %d leaf %d const" % (i, leaf))
            fitted += len(th.leaf_coeff[leaf]) > 0
    return fitted


def test_device_fit_coefficient_and_prediction_parity(rng):
    n = 2000
    X = rng.randn(n, 6)
    y = 0.3 * X[:, 0] - 0.15 * X[:, 1] + 0.02 * rng.randn(n)
    host, device = _train_pair(X, y)
    assert _assert_model_parity(host, device) > 0
    np.testing.assert_allclose(device.predict(X), host.predict(X),
                               rtol=0, atol=ATOL)


def test_device_fit_nan_rows_parity(rng):
    """NaN rows drop out of the normal equations on both sides; leaves
    that lose too many rows fall back to the constant leaf value."""
    n = 2000
    X = rng.randn(n, 4)
    y = 0.3 * X[:, 0] + 0.1 * X[:, 2] + 0.02 * rng.randn(n)
    X[rng.rand(n) < 0.15, 0] = np.nan
    X[rng.rand(n) < 0.05, 2] = np.nan
    host, device = _train_pair(X, y)
    _assert_model_parity(host, device)
    ph, pd = host.predict(X), device.predict(X)
    assert np.isfinite(pd).all()
    np.testing.assert_allclose(pd, ph, rtol=0, atol=ATOL)


def test_device_fit_multiclass_parity(rng):
    n = 1500
    X = rng.randn(n, 5)
    y = ((X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
         + (X[:, 2] > 0.5).astype(int))
    host, device = _train_pair(
        X, y, rounds=2, objective="multiclass", num_class=3, num_leaves=6)
    assert _assert_model_parity(host, device) > 0
    np.testing.assert_allclose(device.predict(X), host.predict(X),
                               rtol=0, atol=ATOL)


def test_device_fit_categorical_parity(rng):
    """Categorical features split but never enter the per-leaf design
    matrix — the device feature tables must apply the same filter."""
    n = 1500
    X = rng.randn(n, 5)
    X[:, 4] = rng.randint(0, 8, size=n)
    y = (0.3 * X[:, 0] + 0.1 * (X[:, 4] % 3) + 0.02 * rng.randn(n))
    host, device = _train_pair(X, y, categorical_feature=[4])
    fitted = _assert_model_parity(host, device)
    assert fitted > 0
    for t in device.inner.models:
        for leaf, feats in t.leaf_features.items():
            assert 4 not in feats, (leaf, feats)
    np.testing.assert_allclose(device.predict(X), host.predict(X),
                               rtol=0, atol=ATOL)


def test_linear_device_auto_is_host_on_cpu(rng):
    """auto only takes the device path on a real TPU backend; on the CPU
    suite it must be bit-identical to the host oracle."""
    import jax
    if jax.default_backend() == "tpu":
        pytest.skip("auto resolves to the device path on TPU")
    n = 1200
    X = rng.randn(n, 4)
    y = 0.3 * X[:, 0] + 0.02 * rng.randn(n)
    p_auto = _params(linear_device="auto")
    p_off = _params(linear_device="off")
    b_auto = lgb.train(p_auto, lgb.Dataset(X, label=y, params=dict(p_auto)),
                       num_boost_round=3)
    b_off = lgb.train(p_off, lgb.Dataset(X, label=y, params=dict(p_off)),
                      num_boost_round=3)
    for ta, to in zip(b_auto.inner.models, b_off.inner.models):
        assert sorted(ta.leaf_coeff) == sorted(to.leaf_coeff)
        for leaf in ta.leaf_coeff:
            assert np.array_equal(ta.leaf_coeff[leaf], to.leaf_coeff[leaf])
            assert ta.leaf_const[leaf] == to.leaf_const[leaf]


def test_device_fit_telemetry_counters(rng):
    n = 1500
    X = rng.randn(n, 4)
    y = 0.3 * X[:, 0] + 0.02 * rng.randn(n)
    p = _params(linear_device="on")
    obs.telemetry.reset()
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=3)
    # first iteration never fits linear leaves -> 2 device fits
    assert obs.telemetry.counter("linear/device_fits") == 2
    solved = obs.telemetry.counter("linear/leaves_solved")
    assert solved > 0
    fitted = sum(len(c) > 0 for t in bst.inner.models
                 for c in t.leaf_coeff.values())
    assert solved == fitted
    assert obs.telemetry.counter("linear/solve_fallback") >= 0


def test_linear_device_param_validated():
    with pytest.raises(Exception):
        lgb.train(_params(linear_device="gpu"),
                  lgb.Dataset(np.zeros((50, 2)), label=np.zeros(50)),
                  num_boost_round=1)


# --------------------------------------------------------- device predict

def test_device_predict_matches_host_predict(rng):
    """The boosting ``has_linear`` host fallback is gone: large-n predict
    rides the packed device path for linear models and must agree with the
    small-n host path on the same model."""
    from lightgbm_tpu.ops.predict import pack_splits
    n = 2000
    X = rng.randn(n, 5)
    y = 0.3 * X[:, 0] - 0.1 * X[:, 1] + 0.02 * rng.randn(n)
    p = _params(linear_device="off")
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=4)
    assert any(t.is_linear for t in bst.inner.models)
    _, _, has_linear = pack_splits(bst.inner.models, num_class=1)
    assert has_linear
    small = bst.predict(X[:64])            # below DEVICE_PREDICT_MIN_ROWS
    large = bst.predict(X)                 # packed device predict
    np.testing.assert_allclose(large[:64], small, rtol=0, atol=ATOL)


def test_device_predict_nan_fallback_rows(rng):
    n = 2000
    X = rng.randn(n, 4)
    y = 0.3 * X[:, 0] + 0.02 * rng.randn(n)
    p = _params(linear_device="off")
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=3)
    Xn = X.copy()
    Xn[::5, 0] = np.nan                    # rows hit the constant fallback
    small = bst.predict(Xn[:64])
    large = bst.predict(Xn)
    assert np.isfinite(large).all()
    np.testing.assert_allclose(large[:64], small, rtol=0, atol=ATOL)


# ----------------------------------------------------------------- serving

def _session_data(rng, n=1500):
    X = rng.randn(n, 5)
    y = 0.3 * X[:, 0] - 0.1 * X[:, 1] + 0.02 * rng.randn(n)
    return X, y


def test_session_serves_linear_model(rng):
    """PredictSession used to refuse linear models; now they ride the
    bucket ladder with in-run parity against the host predict."""
    from lightgbm_tpu.serve import PredictSession
    X, y = _session_data(rng)
    p = _params(linear_device="off")
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=4)
    sess = PredictSession(bst, buckets=(256,))
    got = sess.predict(X[:200])
    want = bst.predict(X[:200])
    np.testing.assert_allclose(np.asarray(got).ravel(), want,
                               rtol=0, atol=ATOL)
    # version-token cache: continued training bumps the model version and
    # the SAME session must serve the new linear leaves (num_iteration=-1:
    # Booster.predict otherwise caps at the pre-update best_iteration)
    bst.update()
    got2 = sess.predict(X[:200])
    np.testing.assert_allclose(np.asarray(got2).ravel(),
                               bst.predict(X[:200], num_iteration=-1),
                               rtol=0, atol=ATOL)


def test_session_linear_nan_rows(rng):
    from lightgbm_tpu.serve import PredictSession
    X, y = _session_data(rng)
    p = _params(linear_device="off")
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=3)
    Xn = X[:128].copy()
    Xn[::4, 0] = np.nan
    sess = PredictSession(bst, buckets=(256,))
    np.testing.assert_allclose(np.asarray(sess.predict(Xn)).ravel(),
                               bst.predict(Xn), rtol=0, atol=ATOL)


def test_http_serves_linear_model(rng):
    import json
    import threading
    from urllib.request import Request, urlopen

    from lightgbm_tpu.serve.http import PredictServer
    X, y = _session_data(rng)
    p = _params(linear_device="off")
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=3)
    server = PredictServer(bst, port=0, buckets=(64,), max_wait_ms=1.0)
    host, port = server.address
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        body = json.dumps({"rows": X[:8].tolist()}).encode()
        req = Request("http://%s:%d/predict" % (host, port), data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        np.testing.assert_allclose(np.asarray(out["predictions"]).ravel(),
                                   bst.predict(X[:8]), rtol=0, atol=ATOL)
    finally:
        server.shutdown()
        thread.join(timeout=10)
        server.close()
