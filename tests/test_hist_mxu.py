"""int8 one-hot MXU histogram kernel (tpu_hist_mxu): parity with the
segment-einsum oracle.

hist_mxu_segment (ops/histogram.py, ISSUE 17) builds per-chunk one-hot
matrices in VMEM and contracts them on the MXU: one kernel body serves
BOTH gradient representations — the f32 path splits g/h into bf16
hi/lo-16 channels (same exact-decomposition as the rows pallas hist)
and accumulates in f32, the use_quantized_grad path decodes the int8
payload bytes and feeds an int8 x one-hot dot_general with i32
accumulation (integer adds are order-free, so parity with the host
quantized semantics is EXACT, stochastic-rounding seed contract
included). These tests pin both contracts bitwise under the pallas
interpreter, the wrapper validations, the auto-knob gates and the
zero-recompile discipline.
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs  # noqa: E402
from lightgbm_tpu.ops import partition as P  # noqa: E402
from lightgbm_tpu.ops.histogram import (hist16_segment,  # noqa: E402
                                        hist16_segment_q, hist_mxu_segment)

CH = 256

BASE = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
        "tpu_iter_block": 2, "tpu_work_layout": "rows",
        "tpu_partition_kernel": "pallas", "tpu_part_chunk": CH,
        "tpu_hist_chunk": CH}


def _pack(rng, n, f, nb, quantized, seed_key=7):
    guard, width = P.work_spec(f, quantized, "pallas", CH, CH, layout="rows")
    bins = rng.randint(0, nb, size=(n, f)).astype(np.uint8)
    ghc = rng.randn(n, 3).astype(np.float32)
    mask = rng.rand(n) < 0.8
    ghc[:, 2] = mask
    ghc[:, 0] *= mask
    ghc[:, 1] = np.abs(ghc[:, 1]) * mask
    pad = ((guard, guard), (0, 0))
    gscale = hscale = None
    if quantized:
        gscale = jnp.float32(127.0) / float(np.abs(ghc[:, 0]).max() + 1e-12)
        hscale = jnp.float32(127.0) / float(np.abs(ghc[:, 1]).max() + 1e-12)
        w0 = P.pack_rows_quantized(
            jnp.pad(jnp.asarray(bins), pad), jnp.pad(jnp.asarray(ghc), pad),
            jax.random.PRNGKey(seed_key), gscale, hscale)
    else:
        w0 = P.pack_rows(jnp.pad(jnp.asarray(bins), pad),
                         jnp.pad(jnp.asarray(ghc), pad))
    w0 = jnp.pad(w0, ((0, 0), (0, width - w0.shape[1])))
    return jnp.stack([w0, jnp.zeros_like(w0)]), guard, gscale, hscale


# --------------------------------------------------------------- op level

def test_op_parity_f32(rng, monkeypatch):
    """f32 hi/lo-16 mode vs hist16_segment: byte-identical, including
    unaligned starts and partial trailing chunks."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n, f, nb = 1500, 8, 64
    work, guard, _, _ = _pack(rng, n, f, nb, quantized=False)
    for start, cnt in [(guard, n), (guard + 37, 411), (guard + 1, 31)]:
        ho = hist16_segment(work, jnp.int32(0), jnp.int32(start),
                            jnp.int32(cnt), num_bins=nb, num_feat=f,
                            chunk=CH)
        hk, _ = hist_mxu_segment(work, jnp.int32(0), jnp.int32(start),
                                 jnp.int32(cnt), num_bins=nb, num_feat=f,
                                 chunk=CH)
        assert hk.dtype == ho.dtype and hk.shape == ho.shape
        assert np.array_equal(np.asarray(hk).view(np.uint8),
                              np.asarray(ho).view(np.uint8)), (start, cnt)


def test_op_parity_int8(rng, monkeypatch):
    """Quantized mode vs hist16_segment_q: identical down to the dequant
    bytes — the int8 matmul with i32 accumulation reproduces the host
    quantized semantics exactly (same packed dither bytes in, integer
    adds are order-free)."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n, f, nb = 1500, 8, 64
    work, guard, gscale, hscale = _pack(rng, n, f, nb, quantized=True)
    for start, cnt in [(guard, n), (guard + 37, 411), (guard + 3, 130)]:
        ho = hist16_segment_q(work, jnp.int32(0), jnp.int32(start),
                              jnp.int32(cnt), gscale, hscale, num_bins=nb,
                              num_feat=f, chunk=CH)
        hk, _ = hist_mxu_segment(work, jnp.int32(0), jnp.int32(start),
                                 jnp.int32(cnt), num_bins=nb, num_feat=f,
                                 quantized=True, gscale=gscale,
                                 hscale=hscale, chunk=CH)
        assert np.array_equal(np.asarray(hk).view(np.uint8),
                              np.asarray(ho).view(np.uint8)), (start, cnt)


def test_op_validations():
    work = jnp.zeros((2, 640, 100), jnp.uint8)    # width not 128-lane
    with pytest.raises(ValueError, match="128-lane"):
        hist_mxu_segment(work, jnp.int32(0), jnp.int32(64), jnp.int32(256),
                         num_bins=32, num_feat=4, chunk=256)
    work = jnp.zeros((2, 640, 128), jnp.uint8)
    with pytest.raises(ValueError, match="chunk"):
        hist_mxu_segment(work, jnp.int32(0), jnp.int32(64), jnp.int32(256),
                         num_bins=32, num_feat=4, chunk=100)
    with pytest.raises(ValueError, match="gscale"):
        hist_mxu_segment(work, jnp.int32(0), jnp.int32(64), jnp.int32(256),
                         num_bins=32, num_feat=4, quantized=True, chunk=256)


# ----------------------------------------------------- full-train parity

def _model(params, X, y, rounds=4):
    ds = lgb.Dataset(X, label=y, params=dict(params))
    bst = lgb.train(dict(params), ds, num_boost_round=rounds)
    return bst.model_to_string()


@pytest.mark.slow
def test_train_parity_f32(rng, monkeypatch):
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    on = _model(dict(BASE, tpu_hist_mxu="on"), X, y)
    off = _model(dict(BASE, tpu_hist_mxu="off"), X, y)
    assert on == off


@pytest.mark.slow
def test_train_parity_int8(rng, monkeypatch):
    """use_quantized_grad path: the one kernel body also serves the int8
    representation — byte parity including the stochastic-rounding seed
    contract (pack_rows_quantized draws ride the work buffer unchanged)."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    on = _model(dict(BASE, tpu_hist_mxu="on", use_quantized_grad=True),
                X, y)
    off = _model(dict(BASE, tpu_hist_mxu="off", use_quantized_grad=True),
                 X, y)
    assert on == off


@pytest.mark.slow
def test_train_parity_goss_compact_composition(rng, monkeypatch):
    """The two ISSUE 17 multipliers compose: compacted GOSS rows through
    the MXU kernel vs the dense einsum oracle, byte for byte."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    goss = {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2,
            "learning_rate": 0.5}
    on = _model(dict(BASE, tpu_hist_mxu="on", tpu_goss_compact="on",
                     **goss), X, y, rounds=6)
    off = _model(dict(BASE, tpu_hist_mxu="off", tpu_goss_compact="off",
                      **goss), X, y, rounds=6)
    assert on == off


# --------------------------------------------------- telemetry + retrace

@pytest.mark.slow
def test_second_identical_train_compiles_nothing(rng, monkeypatch):
    """test_retrace.py discipline on the MXU path: a second train at
    identical shapes/config hits every jit cache — zero new compiles."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 540                      # shape distinct from other test modules
    X = rng.randn(n, 7)
    y = (X @ rng.randn(7) > 0).astype(np.float64)
    params = dict(BASE, tpu_hist_mxu="on")
    ds = lgb.Dataset(X, label=y, params=dict(params))
    lgb.train(dict(params), ds, num_boost_round=2)   # warm every cache
    obs.telemetry.reset()
    bst = lgb.train(dict(params), ds, num_boost_round=2)
    jc = bst.telemetry()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc


# ------------------------------------------------------------ knob gates

def test_config_rejects_bad_hist_mxu():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError, match="tpu_hist_mxu"):
        Config.from_params({"tpu_hist_mxu": "maybe"})


def test_auto_resolves_off_with_record(rng):
    """auto stays off until scripts/hist_mxu_bisect.py validates the
    Mosaic lowering and a win on real hardware."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config.from_params({"objective": "binary", "num_leaves": 4,
                              "max_bin": 15, "verbosity": -1})
    ds = construct_dataset(X, cfg, label=y)
    obs.telemetry.reset()
    kw = SerialTreeLearner(cfg, ds).build_kwargs()
    assert kw["hist_mxu"] == "off"
    recs = obs.telemetry.snapshot()["records"]["auto_resolution"]
    mine = [r for r in recs if r["knob"] == "tpu_hist_mxu"]
    assert len(mine) == 1
    assert mine[0]["value"] == "off"
    assert "hist_mxu_bisect" in mine[0]["reason"]


def test_ineligible_on_downgrades_to_off(rng):
    """Forcing on where the structure can't support it warns and keeps the
    einsum path instead of failing the train."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    # planes layout: the kernel reads 128-lane work ROWS
    cfg = Config.from_params(dict(BASE, num_leaves=4, max_bin=15,
                                  tpu_work_layout="planes",
                                  tpu_hist_mxu="on"))
    ds = construct_dataset(X, cfg, label=y)
    assert SerialTreeLearner(cfg, ds).build_kwargs()["hist_mxu"] == "off"
    # xla partition: row width is not padded to whole 128-lane tiles
    cfg = Config.from_params({"objective": "binary", "num_leaves": 4,
                              "max_bin": 15, "verbosity": -1,
                              "tpu_work_layout": "rows",
                              "tpu_hist_mxu": "on"})
    ds = construct_dataset(X, cfg, label=y)
    assert SerialTreeLearner(cfg, ds).build_kwargs()["hist_mxu"] == "off"
    # hist chunk not a multiple of the 32-row DMA alignment
    cfg = Config.from_params(dict(BASE, num_leaves=4, max_bin=15,
                                  tpu_hist_chunk=100, tpu_hist_mxu="on"))
    ds = construct_dataset(X, cfg, label=y)
    assert SerialTreeLearner(cfg, ds).build_kwargs()["hist_mxu"] == "off"
