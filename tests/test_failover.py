"""Fleet-hardening tests (ISSUE 14): trainer failover leases with epoch
fencing, store compaction with bit-identical replay, the retrying HTTP
transport, and the deterministic fault-injection harness that drives all
of it.

The contracts under test: exactly one trainer holds the publish lease at
a time and EVERY acquisition bumps the fencing epoch, so a paused zombie
holder's late publishes are refused at the store (and rejected by
readers even when they raced the fence on another host); a standby
trainer taking over resumes the dead holder's watermark / win-streak /
shadow window from the log alone; compacting the log (snapshot +
truncate) changes replay in no observable way — same buffers, same
verdicts, same promoted model string; and a replica behind the HTTP
transport converges byte-identically to a filesystem replica through
injected drops, stalls and torn reads, every fault scheduled
deterministically by a seeded FaultPlan (no wall-clock races —
reproducible under ``pytest -p no:randomly``).
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import threading
import time
from urllib.request import urlopen

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.fleet import FleetStore, RemoteStore, ReplicaWatcher, \
    CorruptArtifactError, StaleLeaseError, TransportError, chaos  # noqa: E402
from lightgbm_tpu.fleet.chaos import FaultPlan, InjectedFault  # noqa: E402
from lightgbm_tpu.obs import telemetry  # noqa: E402
from lightgbm_tpu.online import OnlineTrainer  # noqa: E402
from lightgbm_tpu.serve import PredictServer  # noqa: E402
from lightgbm_tpu.utils.log import LightGBMError  # noqa: E402

from tests.conftest import clean_cpu_env  # noqa: E402

W = np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4])


def _data(n, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, len(W))
    y = (X @ W + 0.2 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _train(n=300, seed=0, rounds=6):
    X, y = _data(n, seed)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


def _get_text(url, timeout=30):
    with urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def _start_server(server):
    th = threading.Thread(target=server.serve_forever,
                          name="failover-test-http", daemon=True)
    th.start()
    return th


def _trainer(bst, store, **kw):
    """Trainer with the gate wide open (threshold 2.0) so a refit
    candidate always banks a win — the tests exercise durability and
    failover, not the gate's judgment."""
    args = dict(trigger_rows=10**9, min_rows=50, shadow_rows=120,
                promote_threshold=2.0, promote_patience=2,
                store=store, start=False)
    args.update(kw)
    return OnlineTrainer(bst, **args)


# ----------------------------------------------------------------- lease

def test_lease_acquire_renew_release_and_epoch_bump(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    assert store.lease_state()["held"] is False
    assert store.acquire_lease("a", ttl_s=30.0) == 1
    # held by a live holder: nobody else gets it
    assert store.acquire_lease("b", ttl_s=30.0) is None
    st = store.lease_state()
    assert st["held"] and st["holder"] == "a" and st["epoch"] == 1
    # heartbeat renews only at the exact (holder, epoch)
    assert store.renew_lease("a", 1, 30.0) is True
    assert store.renew_lease("a", 2, 30.0) is False
    assert store.renew_lease("b", 1, 30.0) is False
    # clean release expires immediately but keeps the epoch
    assert store.release_lease("b", 1) is False
    assert store.release_lease("a", 1) is True
    st = store.lease_state()
    assert st["held"] is False and st["epoch"] == 1
    # EVERY acquisition bumps the epoch — takeover and re-acquisition
    assert store.acquire_lease("b", ttl_s=30.0) == 2
    assert store.release_lease("b", 2) is True
    assert store.acquire_lease("b", ttl_s=30.0) == 3
    with pytest.raises(LightGBMError):
        store.acquire_lease("c", ttl_s=0.0)


def test_lease_expiry_allows_takeover(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    assert store.acquire_lease("a", ttl_s=0.15) == 1
    assert store.acquire_lease("b", ttl_s=30.0) is None
    time.sleep(0.3)
    # the dead holder's lease lapsed: takeover, at a HIGHER epoch
    assert store.acquire_lease("b", ttl_s=30.0) == 2
    st = store.lease_state()
    assert st["holder"] == "b" and st["epoch"] == 2
    # the late original holder can still heartbeat-fail cleanly
    assert store.renew_lease("a", 1, 30.0) is False


def test_lease_renew_release_guarded_against_concurrent_acquirer(tmp_path):
    """renew/release run the same cross-process O_EXCL guard as
    acquire_lease: an old holder's read-modify-write must never land
    around a standby's takeover and resurrect the dead epoch. While a
    live acquirer holds the guard, renew/release back off (and the
    caller demotes) instead of writing blind."""
    store = FleetStore(str(tmp_path), "m")
    assert store.acquire_lease("a", ttl_s=30.0) == 1
    guard = os.path.join(str(tmp_path), "m", "lease.json.lock")
    with open(guard, "w") as f:
        f.write("424242")   # a live (fresh-mtime) concurrent acquirer
    assert store.renew_lease("a", 1, 30.0) is False
    assert store.release_lease("a", 1) is False
    # the lease file itself was never touched through the held guard
    assert store.lease_state()["holder"] == "a"
    os.unlink(guard)
    assert store.renew_lease("a", 1, 30.0) is True
    assert store.release_lease("a", 1) is True
    assert store.lease_state()["held"] is False


def test_unfenced_publish_applied_after_fenced_history(tmp_path):
    """Leasing switched OFF after a fenced tenure: epoch-0 publishes are
    exempt from stale-epoch rejection (the fleet must keep converging),
    but each one is counted and the first is warned about."""
    store = FleetStore(str(tmp_path), "m")
    assert store.acquire_lease("a", ttl_s=30.0) == 1
    store.set_fence("a", 1)
    assert store.publish("model-one") == 1
    assert store.release_lease("a", 1) is True
    # operator restarts the trainer with fleet_lease_ttl_s=0: no fence
    store.clear_fence()
    unfenced0 = telemetry.counter("fleet/unfenced_publishes")
    rejected0 = telemetry.counter("fleet/stale_publishes_rejected")
    assert store.publish("model-two") == 2
    assert telemetry.counter("fleet/unfenced_publishes") == unfenced0 + 1
    # replicas and cold boots both apply the unfenced publish
    assert store.latest_publish()["version"] == 2
    fresh = FleetStore(str(tmp_path), "m", orphan_grace_s=3600.0)
    assert [e["version"] for e in fresh.publishes()] == [1, 2]
    event, model = fresh.latest_valid_publish(0)
    assert event["version"] == 2 and model == "model-two"
    assert telemetry.counter("fleet/stale_publishes_rejected") == rejected0


def test_publish_fencing_blocks_zombie(tmp_path):
    store_a = FleetStore(str(tmp_path), "m")
    assert store_a.acquire_lease("a", ttl_s=0.15) == 1
    store_a.set_fence("a", 1)
    assert store_a.publish("model-one") == 1
    assert next(store_a.events("publish"))["lease_epoch"] == 1
    time.sleep(0.3)
    # a second process takes over after the ttl lapses
    store_b = FleetStore(str(tmp_path), "m")
    assert store_b.acquire_lease("b", ttl_s=30.0) == 2
    store_b.set_fence("b", 2)
    assert store_b.publish("model-two") == 2
    # the zombie's publish is refused BEFORE anything lands
    blocked0 = telemetry.counter("fleet/stale_publishes_blocked")
    with pytest.raises(StaleLeaseError):
        store_a.publish("zombie-model")
    assert telemetry.counter("fleet/stale_publishes_blocked") == blocked0 + 1
    # no event, no artifact, and the version sequence is untouched
    assert [e["version"] for e in store_b.publishes()] == [1, 2]
    assert store_b.publish("model-three") == 3
    assert store_b.load_model(3) == "model-three"


def test_stale_epoch_publish_rejected_by_readers(tmp_path):
    """A zombie write that RACED the fence check on another host: its
    event is in the log, but readers reject any publish whose epoch is
    below one already seen — while its version still raises the
    allocation floor so tokens are never reused."""
    store = FleetStore(str(tmp_path), "m")
    assert store.acquire_lease("a", ttl_s=0.05) == 1
    store.set_fence("a", 1)
    assert store.publish("model-one") == 1
    time.sleep(0.1)
    assert store.acquire_lease("b", ttl_s=30.0) == 2
    store.set_fence("b", 2)
    assert store.publish("model-two") == 2
    # forge the raced zombie append: epoch 1 landing AFTER epoch 2
    import hashlib
    data = b"zombie-model"
    with open(store.artifact_path(3), "wb") as f:
        f.write(data)
    with open(store.events_path, "a", encoding="utf-8") as f:
        f.write(json.dumps({
            "v": 1, "kind": "publish", "ts": 0.0, "version": 3,
            "artifact": "v000003.txt", "event": "promotion",
            "sha256": hashlib.sha256(data).hexdigest(),
            "bytes": len(data), "lease_epoch": 1, "meta": None}) + "\n")
    rejected0 = telemetry.counter("fleet/stale_publishes_rejected")
    fresh = FleetStore(str(tmp_path), "m", orphan_grace_s=3600.0)
    assert [e["version"] for e in fresh.publishes()] == [1, 2]
    assert fresh.latest_publish()["version"] == 2
    event, model = fresh.latest_valid_publish(0)
    assert event["version"] == 2 and model == "model-two"
    assert telemetry.counter("fleet/stale_publishes_rejected") \
        == rejected0 + 1
    # repeat scans dedupe the counter per version
    fresh.publishes()
    assert telemetry.counter("fleet/stale_publishes_rejected") \
        == rejected0 + 1
    # the zombie's token is burned: the next publish allocates past it
    fresh.set_fence("b", 2)
    assert fresh.publish("model-four") == 4


# ------------------------------------------------------------- integrity

def test_corrupt_artifact_fallback_and_dedup(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    assert store.publish("model-one", event="boot") == 1
    assert store.publish("model-two") == 2
    # flip bytes in the newest artifact: same length, wrong sha256
    with open(store.artifact_path(2), "wb") as f:
        f.write(b"model-twX")
    corrupt0 = telemetry.counter("fleet/corrupt_artifacts")
    event, model = store.latest_valid_publish(0)
    assert event["version"] == 1 and model == "model-one"
    assert telemetry.counter("fleet/corrupt_artifacts") == corrupt0 + 1
    # counted once per version per instance, not per probe
    assert store.latest_valid_publish(0)[0]["version"] == 1
    assert telemetry.counter("fleet/corrupt_artifacts") == corrupt0 + 1
    # a truncated artifact fails the length check the same way
    with open(store.artifact_path(2), "wb") as f:
        f.write(b"model")
    with pytest.raises(CorruptArtifactError):
        store.load_publish(list(store.publishes())[-1])
    # a fresh, intact publish ends the fallback
    assert store.publish("model-three") == 3
    assert store.latest_valid_publish(0)[1] == "model-three"


def test_replica_skips_corrupt_artifact(tmp_path):
    bst_a, bst_b = _train(seed=0), _train(seed=3, rounds=8)
    store = FleetStore(str(tmp_path), "m")
    store.publish(bst_a.model_to_string(), event="boot")
    store.publish(bst_b.model_to_string())
    # corrupt the newest artifact on disk
    with open(store.artifact_path(2), "r+b") as f:
        f.write(b"corrupted beyond recognition")
    serving = lgb.Booster(model_str=bst_a.model_to_string())
    watcher = ReplicaWatcher(serving, store, start=False)
    # v2 is newer but corrupt: the poll falls back to v1 (the newest
    # publish that VERIFIES) instead of serving garbage or crashing
    assert watcher.poll_once() is True
    assert watcher.applied_version == 1
    Xq = _data(40, seed=9)[0]
    np.testing.assert_allclose(np.asarray(serving.predict(Xq)),
                               np.asarray(bst_a.predict(Xq)), rtol=1e-9)
    # the next good publish converges past the corruption
    store.publish(bst_b.model_to_string())
    assert watcher.poll_once() is True
    assert watcher.applied_version == 3
    np.testing.assert_allclose(np.asarray(serving.predict(Xq)),
                               np.asarray(bst_b.predict(Xq)), rtol=1e-9)


def test_orphan_artifacts_reaped_on_open(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    store.publish("model-one")
    models = os.path.dirname(store.artifact_path(1))
    # a publisher that died between artifact replace and event append
    # leaves an unreferenced artifact; a died publish also leaves tmps
    orphan = os.path.join(models, "v000009.txt")
    stray = os.path.join(models, "v000002.txt.tmp.12345")
    for p in (orphan, stray):
        with open(p, "w", encoding="utf-8") as f:
            f.write("never published")
    # within the grace window nothing is touched (could be a live
    # publish racing this open)
    fresh = FleetStore(str(tmp_path), "m")
    assert os.path.exists(orphan) and os.path.exists(stray)
    assert fresh.state()["orphan_artifacts_reaped"] == 0
    # past the grace both are reaped; the referenced artifact survives
    reaped0 = telemetry.counter("fleet/orphan_artifacts_reaped")
    fresh = FleetStore(str(tmp_path), "m", orphan_grace_s=0.0)
    assert not os.path.exists(orphan) and not os.path.exists(stray)
    assert os.path.exists(fresh.artifact_path(1))
    assert fresh.state()["orphan_artifacts_reaped"] == 2
    assert telemetry.counter("fleet/orphan_artifacts_reaped") == reaped0 + 2
    assert fresh.load_model(1) == "model-one"


def test_torn_append_repaired_on_open(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    X, y = _data(4, seed=1)
    store.append_ingest(X, y)
    store.append_gate("rejected", 0, 4, None)
    size_before = store.log_bytes()
    plan = FaultPlan({"store/append": [("torn", 0.4)]})
    with chaos.inject(plan):
        with pytest.raises(InjectedFault):
            store.append_gate("deferred", 1, 8, None)
    assert plan.injected() == {"store/append": 1}
    # the torn prefix is on disk, ending mid-line
    assert store.log_bytes() > size_before
    with open(store.events_path, "rb") as f:
        assert not f.read().endswith(b"\n")
    # a restarted store truncates the torn tail so its own appends can
    # never glue onto it and vanish
    repaired0 = telemetry.counter("fleet/torn_tail_repaired")
    fresh = FleetStore(str(tmp_path), "m")
    assert telemetry.counter("fleet/torn_tail_repaired") == repaired0 + 1
    assert fresh.log_bytes() == size_before
    fresh.append_gate("promoted", 0, 8, None)
    kinds = [(e["kind"], e.get("result")) for e in fresh.events()]
    assert kinds == [("ingest", None), ("gate", "rejected"),
                     ("gate", "promoted")]


def test_read_only_open_skips_destructive_maintenance(tmp_path):
    """A replica-role open over a shared filesystem is a pure reader: it
    must not truncate a tail it may be seeing mid-write, must not reap
    artifacts, and refuses to write outright."""
    store = FleetStore(str(tmp_path), "m")
    store.append_gate("rejected", 0, 4, None)
    assert store.publish("model-one", event="boot") == 1
    # a torn tail + an orphan artifact, as a reader might observe them
    # while a live writer is mid-publish
    with open(store.events_path, "a", encoding="utf-8") as f:
        f.write('{"v": 1, "kind": "ga')
    with open(store.artifact_path(9), "wb") as f:
        f.write(b"in-flight")
    size = store.log_bytes()
    repaired0 = telemetry.counter("fleet/torn_tail_repaired")
    replica = FleetStore(str(tmp_path), "m", read_only=True,
                         orphan_grace_s=0.0)
    assert replica.log_bytes() == size   # tail untouched
    assert os.path.exists(store.artifact_path(9))   # orphan untouched
    assert telemetry.counter("fleet/torn_tail_repaired") == repaired0
    assert replica.state()["read_only"] is True
    # reads work; every write surface is refused
    assert replica.latest_publish()["version"] == 1
    with pytest.raises(LightGBMError):
        replica.append_gate("promoted", 0, 8, None)
    with pytest.raises(LightGBMError):
        replica.publish("model-two")
    with pytest.raises(LightGBMError):
        replica.compact(watermark=0, wins=0, keep_rows=10)
    # a writer-role reopen still repairs the dead tail
    fresh = FleetStore(str(tmp_path), "m", orphan_grace_s=3600.0)
    assert fresh.log_bytes() < size
    assert telemetry.counter("fleet/torn_tail_repaired") == repaired0 + 1


# ------------------------------------------------------------ compaction

def test_compaction_replay_is_bit_identical(tmp_path):
    """The tentpole retention guarantee: compaction lands mid-shadow-
    window and a trainer replaying the compacted log is indistinguishable
    from one replaying the full log — same buffers, same streak, same
    next promotion, same promoted model string."""
    base = _train()
    base_str = base.model_to_string()
    orig = str(tmp_path / "orig")
    full = str(tmp_path / "full")
    store = FleetStore(orig, "m")
    tr = _trainer(lgb.Booster(model_str=base_str), store)
    for seed in (1, 2, 3):
        tr.ingest(*_data(30, seed=seed))
    assert tr.run_once() == "deferred"      # wins=1, watermark=90
    for seed in (4, 5):
        tr.ingest(*_data(25, seed=seed))    # 50 untrained rows on top
    st = tr.state()
    assert st["consumed_rows"] == 90 and st["win_streak"] == 1
    # shadow window (cap 120) spans the watermark: chunks 2..5 = 110 rows
    assert tr.buffer.shadow_rows == 110 and tr.buffer.rows == 50
    shutil.copytree(orig, full)
    summary = store.compact(watermark=90, wins=1,
                            keep_rows=tr.buffer.shadow_capacity)
    assert summary["dropped_rows"] == 30 and summary["dropped_events"] > 0
    full_store = FleetStore(full, "m")
    assert store.log_bytes() < full_store.log_bytes()
    kinds = [e["kind"] for e in store.events()]
    assert kinds[0] == "compact" and kinds.count("ingest") == 4
    # two cold boots: compacted vs untouched log
    bst_c = lgb.Booster(model_str=base_str)
    bst_f = lgb.Booster(model_str=base_str)
    tr_c = _trainer(bst_c, FleetStore(orig, "m"))
    tr_f = _trainer(bst_f, full_store)
    for a, b in ((tr_c, tr_f),):
        assert a.state()["consumed_rows"] == b.state()["consumed_rows"] == 90
        assert a.state()["win_streak"] == b.state()["win_streak"] == 1
        assert a.buffer.rows == b.buffer.rows == 50
        assert a.buffer.shadow_rows == b.buffer.shadow_rows == 110
    Xc, yc = tr_c.buffer.shadow()
    Xf, yf = tr_f.buffer.shadow()
    np.testing.assert_array_equal(Xc, Xf)
    np.testing.assert_array_equal(yc, yf)
    # the banked win completes identically: both promote, and the
    # refit on the replayed buffers yields the SAME model string
    assert tr_c.run_once() == "promoted"
    assert tr_f.run_once() == "promoted"
    assert bst_c.model_to_string() == bst_f.model_to_string()
    assert tr_c.state()["consumed_rows"] == tr_f.state()["consumed_rows"]


def test_trainer_compacts_and_bounds_log_and_artifacts(tmp_path):
    compactions0 = telemetry.counter("fleet/compactions")
    store = FleetStore(str(tmp_path), "m")
    bst = _train()
    tr = _trainer(bst, store, min_rows=40, shadow_rows=80,
                  promote_patience=1, compact_bytes=6000,
                  keep_artifacts=2)
    for i in range(6):
        tr.ingest(*_data(40, seed=10 + i))
        assert tr.run_once() == "promoted"
    st = store.state()
    assert st["compactions"] >= 2
    assert st["last_compaction_ts"] > 0
    assert telemetry.counter("fleet/compactions") >= compactions0 + 2
    # retention: ingest rows in the log are bounded by the shadow
    # capacity (+ at most the newest chunk), publishes by keep_artifacts
    assert sum(e["n"] for e in store.events("ingest")) <= 120
    pubs = store.publishes()
    assert len(pubs) <= 2
    assert pubs[-1]["version"] == 6
    models_dir = os.path.dirname(store.artifact_path(1))
    kept = [n for n in os.listdir(models_dir) if n.endswith(".txt")]
    assert len(kept) <= 2
    # dropped artifacts are really gone; kept ones still verify
    assert not os.path.exists(store.artifact_path(1))
    assert store.latest_valid_publish(0)[0]["version"] == 6
    # a cold boot over the compacted log still resumes cleanly and the
    # version sequence never rewinds
    tr2 = _trainer(lgb.Booster(model_str=bst.model_to_string()),
                   FleetStore(str(tmp_path), "m"),
                   min_rows=40, shadow_rows=80, promote_patience=1)
    assert tr2.state()["consumed_rows"] == 240
    assert tr2.buffer.shadow_rows == tr.buffer.shadow_rows
    tr2.ingest(*_data(40, seed=99))
    assert tr2.run_once() == "promoted"
    assert tr2.state()["store"]["last_published_version"] == 7


def test_compaction_retention_skips_stale_publishes(tmp_path):
    """keep_artifacts must count VALID publishes only: a zombie's
    stale-epoch events must neither fill the retention window (evicting
    the newest good artifacts) nor survive the rewrite — the compact
    record's version/epoch floors stand in for them."""
    import hashlib
    store = FleetStore(str(tmp_path), "m")
    assert store.acquire_lease("a", ttl_s=30.0) == 1
    store.set_fence("a", 1)
    assert store.publish("model-one") == 1
    assert store.release_lease("a", 1) is True
    assert store.acquire_lease("b", ttl_s=30.0) == 2
    store.set_fence("b", 2)
    assert store.publish("model-two") == 2
    assert store.publish("model-three") == 3
    # forge a raced zombie append at the OLD epoch, newest in the log
    data = b"zombie-model"
    with open(store.artifact_path(4), "wb") as f:
        f.write(data)
    with open(store.events_path, "a", encoding="utf-8") as f:
        f.write(json.dumps({
            "v": 1, "kind": "publish", "ts": 0.0, "version": 4,
            "artifact": "v000004.txt", "event": "promotion",
            "sha256": hashlib.sha256(data).hexdigest(),
            "bytes": len(data), "lease_epoch": 1, "meta": None}) + "\n")
    summary = store.compact(watermark=0, wins=0, keep_rows=10**9,
                            keep_artifacts=2)
    # the window kept v2+v3 (newest VALID), not v3+zombie-v4
    assert [e["version"] for e in store.publishes()] == [2, 3]
    assert store.latest_valid_publish(0)[0]["version"] == 3
    assert summary["dropped_artifacts"] == 2   # v1 and the zombie's v4
    assert not os.path.exists(store.artifact_path(1))
    assert not os.path.exists(store.artifact_path(4))
    # the zombie's token is still burned: allocation resumes past it
    assert store.publish("model-five") == 5


def test_compaction_never_loses_concurrent_appends(tmp_path):
    """The multi-writer hole the failover feature opens: a standby
    trainer (another process — here a second store instance, which holds
    its own flock fd) persists ingest chunks to the same events.jsonl
    while the active trainer compacts. Every acked append must survive
    every snapshot→rewrite, whatever the interleaving."""
    active = FleetStore(str(tmp_path), "m")
    standby = FleetStore(str(tmp_path), "m")
    n_chunks, errors = 40, []

    def standby_ingest():
        try:
            for i in range(n_chunks):
                X = np.full((1, len(W)), float(i))
                standby.append_ingest(X, [float(i)])
        except BaseException as exc:   # surfaced after join
            errors.append(exc)

    th = threading.Thread(target=standby_ingest, daemon=True)
    th.start()
    # compact repeatedly while the other writer streams appends;
    # watermark 0 + huge keep_rows => every ingest chunk is retained
    for _ in range(8):
        active.compact(watermark=0, wins=0, keep_rows=10**9)
    th.join(30.0)
    assert not th.is_alive() and not errors
    active.compact(watermark=0, wins=0, keep_rows=10**9)
    labels = sorted(int(e["labels"][0]) for e in active.events("ingest"))
    assert labels == list(range(n_chunks))


# -------------------------------------------------------------- failover

def test_standby_takeover_resumes_watermark_and_streak(tmp_path):
    base_str = _train().model_to_string()
    store_a = FleetStore(str(tmp_path), "m")
    tr_a = _trainer(lgb.Booster(model_str=base_str), store_a,
                    lease_ttl_s=1.0, holder_id="a")
    assert tr_a.state()["role"] == "standby"
    assert tr_a.wait_for_lease(5.0) is True
    st = tr_a.state()
    assert st["role"] == "active" and st["lease_epoch"] == 1
    # a second trainer on the same store stays standby while A is live
    store_b = FleetStore(str(tmp_path), "m")
    tr_b = _trainer(lgb.Booster(model_str=base_str), store_b,
                    lease_ttl_s=1.0, holder_id="b")
    assert tr_b.try_acquire() is False
    assert tr_b.run_once() == "standby"
    # A trains through a full promotion (deferred win, then promote)
    for seed in (21, 22, 23):
        tr_a.ingest(*_data(30, seed=seed))
    assert tr_a.run_once() == "deferred"
    tr_a.ingest(*_data(50, seed=24))
    assert tr_a.run_once() == "promoted"
    pubs = store_a.publishes()
    assert [p["version"] for p in pubs] == [1]
    assert pubs[0]["lease_epoch"] == 1
    # standby ingest persists to the log but never buffers locally —
    # takeover replays the log, so local state would double-count
    rows0 = sum(e["n"] for e in store_b.events("ingest"))
    assert rows0 == 140
    assert tr_b.ingest(*_data(5, seed=20)) == 0
    assert tr_b.buffer.rows == 0 and tr_b.buffer.shadow_rows == 0
    assert sum(e["n"] for e in store_a.events("ingest")) == rows0 + 5
    # crash A: worker gone, lease NOT released, fence still armed
    tr_a.close(release_lease=False)
    takeovers0 = telemetry.counter("fleet/lease_takeovers")
    assert tr_b.wait_for_lease(10.0) is True
    st = tr_b.state()
    assert st["role"] == "active" and st["lease_epoch"] == 2
    assert telemetry.counter("fleet/lease_takeovers") >= takeovers0 + 1
    # B resumed the dead holder's durable state from the log alone:
    # watermark and streak from A's last gate, and the 5 rows it
    # standby-persisted after that gate land as the trainable tail —
    # nothing lost, nothing double-counted
    assert st["consumed_rows"] == 140
    assert st["win_streak"] == 0               # the promotion reset it
    assert tr_b.buffer.rows == 5
    assert tr_b.buffer.shadow_rows == 115      # 30+30+50 kept + 5 fresh
    # the zombie's store is fenced off at its dead epoch
    blocked0 = telemetry.counter("fleet/stale_publishes_blocked")
    with pytest.raises(StaleLeaseError):
        store_a.publish("zombie-model")
    assert telemetry.counter("fleet/stale_publishes_blocked") == blocked0 + 1
    # B publishes under epoch 2 with a fresh, unique version token
    tr_b.ingest(*_data(60, seed=25))
    assert tr_b.run_once() == "deferred"
    tr_b.ingest(*_data(60, seed=26))
    assert tr_b.run_once() == "promoted"
    pubs = store_b.publishes()
    assert [p["version"] for p in pubs] == [1, 2]
    assert [p["lease_epoch"] for p in pubs] == [1, 2]
    assert len({p["version"] for p in pubs}) == len(pubs)
    tr_b.close()
    assert store_b.lease_state()["held"] is False


def test_worker_thread_heartbeats_and_acquires(tmp_path):
    """The worker's lease tick end-to-end: a STARTED standby trainer
    acquires on its own, heartbeats past several ttls, and a started
    second trainer stays standby the whole time."""
    base_str = _train().model_to_string()
    tr_a = _trainer(lgb.Booster(model_str=base_str),
                    FleetStore(str(tmp_path), "m"),
                    lease_ttl_s=0.3, holder_id="a", start=True)
    tr_b = None
    try:
        # A must hold the lease before B's worker exists, or the two
        # workers would race for the first acquisition
        assert tr_a.wait_for_lease(5.0) is True
        tr_b = _trainer(lgb.Booster(model_str=base_str),
                        FleetStore(str(tmp_path), "m"),
                        lease_ttl_s=0.3, holder_id="b", start=True)
        # several ttls of heartbeats: A keeps the lease, B stays standby
        time.sleep(1.0)
        assert tr_a.state()["role"] == "active"
        assert tr_b.state()["role"] == "standby"
        st = FleetStore(str(tmp_path), "m").lease_state()
        assert st["held"] and st["holder"] == "a" and st["epoch"] == 1
        # A dies without releasing; B's worker takes over by itself
        tr_a.close(release_lease=False)
        assert tr_b.wait_for_lease(10.0) is True
        assert tr_b.state()["lease_epoch"] == 2
    finally:
        tr_a.close()
        if tr_b is not None:
            tr_b.close()


# ----------------------------------------------------------------- chaos

def test_chaos_seeded_plan_is_deterministic():
    def schedule(plan):
        out = []
        for point in chaos.FAILURE_POINTS:
            while True:
                act = plan.next_action(point)
                if act is None:
                    break
                kind = act[0]
                val = str(act[1]) if kind == "raise" else float(act[1])
                out.append((point, kind, val))
        return out
    counts = {"transport/request": 5, "store/append": 3, "store/lease": 2}
    s1 = schedule(FaultPlan.seeded(7, counts))
    s2 = schedule(FaultPlan.seeded(7, counts))
    assert s1 == s2 and len(s1) == 10
    assert s1 != schedule(FaultPlan.seeded(8, counts))
    kinds = {k for _, k, _ in s1}
    assert kinds <= {"raise", "torn", "sleep"}
    with pytest.raises(ValueError):
        FaultPlan().add("store/definitely_not_a_point", ("raise", None))


def test_chaos_install_uninstall_and_bookkeeping(tmp_path):
    assert chaos.active() is None
    assert chaos.hit("store/append") is None    # no plan: free no-op
    store = FleetStore(str(tmp_path), "m")
    store.publish("model-one")
    plan = FaultPlan({
        "store/artifact_read": [("raise", InjectedFault("boom")),
                                ("sleep", 0.0), ("torn", 0.5)]})
    injected0 = telemetry.counter("chaos/injected/store/artifact_read")
    with chaos.inject(plan) as p:
        assert chaos.active() is p
        with pytest.raises(InjectedFault):
            store.load_model(1)
        assert store.load_model(1) == "model-one"   # sleep: delayed, intact
        with pytest.raises(CorruptArtifactError):   # torn: checksum catches
            store.load_publish(store.publishes()[0])
        assert p.pending() == {}
        assert p.injected() == {"store/artifact_read": 3}
    assert chaos.active() is None
    assert telemetry.counter("chaos/injected/store/artifact_read") \
        == injected0 + 3
    # a plan never leaks past its block, even when the test body raised
    with pytest.raises(RuntimeError):
        with chaos.inject(FaultPlan({"store/append": [("raise",
                                                       InjectedFault())]})):
            raise RuntimeError("test body blew up")
    assert chaos.active() is None
    assert store.load_model(1) == "model-one"


# ------------------------------------------------------------- transport

def test_remote_store_serves_feed_and_artifacts(tmp_path):
    bst = _train(seed=1)
    store = FleetStore(str(tmp_path), "default")
    server = PredictServer(bst, port=0, warmup=False)
    server.fleet_store = store
    _start_server(server)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    try:
        remote = RemoteStore(base, timeout_s=5.0, retries=1,
                             backoff_base_s=0.01, backoff_max_s=0.05)
        # empty store: 404 is an answer, not a retry storm
        assert remote.latest_publish() is None
        assert remote.latest_valid_publish(0) is None
        store.publish("model-one", event="boot")
        store.publish(bst.model_to_string())
        latest = remote.latest_publish()
        assert latest["version"] == 2 and latest["lease_epoch"] == 0
        assert remote.load_model(1) == "model-one"
        event, model = remote.latest_valid_publish(0)
        assert event["version"] == 2
        assert model == bst.model_to_string()
        # already-applied floor: nothing newer than v2
        assert remote.latest_valid_publish(2) is None
        st = remote.state()
        assert st["requests"] >= 5 and st["errors"] == 0
        with pytest.raises(LightGBMError):
            RemoteStore("ftp://nope")
        with pytest.raises(LightGBMError):
            RemoteStore(base, timeout_s=0.0)
    finally:
        server.close()


def test_remote_store_resumes_after_partition(tmp_path):
    store = FleetStore(str(tmp_path), "default")
    store.publish("model-one")
    server = PredictServer(_train(), port=0, warmup=False)
    server.fleet_store = store
    _start_server(server)
    host, port = server.address
    remote = RemoteStore("http://%s:%d" % (host, port), retries=2,
                         backoff_base_s=0.001, backoff_max_s=0.005,
                         jitter_seed=42)
    errors0 = telemetry.counter("fleet/transport_errors")
    retries0 = telemetry.counter("fleet/transport_retries")
    try:
        # 6 consecutive drops vs 3 attempts/call: two calls fail whole,
        # the third sails through — resume needs no extra state
        plan = FaultPlan({"transport/request":
                          [("raise", InjectedFault("partition"))] * 6})
        with chaos.inject(plan):
            with pytest.raises(TransportError):
                remote.latest_publish()
            with pytest.raises(TransportError):
                remote.latest_publish()
            assert remote.latest_publish()["version"] == 1
        st = remote.state()
        assert st["errors"] == 2 and st["retries"] >= 4
        assert "InjectedFault" in st["last_error"]
        assert telemetry.counter("fleet/transport_errors") == errors0 + 2
        assert telemetry.counter("fleet/transport_retries") >= retries0 + 4
    finally:
        server.close()


def test_remote_replica_converges_through_faults(tmp_path):
    """Satellite e2e: a replica behind the HTTP transport ends
    byte-identical to a filesystem replica despite injected drops,
    stalls and torn responses on BOTH sides of the wire — and the
    faults show up on the serving process's /metrics."""
    bst_v1, bst_v2 = _train(seed=0), _train(seed=3, rounds=8)
    store = FleetStore(str(tmp_path), "default")
    store.publish(bst_v1.model_to_string(), event="boot")
    server = PredictServer(_train(), port=0, warmup=False)
    server.fleet_store = store
    _start_server(server)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    base_str = _train(seed=5).model_to_string()
    bst_remote = lgb.Booster(model_str=base_str)
    bst_fs = lgb.Booster(model_str=base_str)
    remote = RemoteStore(base, retries=4, backoff_base_s=0.002,
                         backoff_max_s=0.01, jitter_seed=3)
    w_remote = ReplicaWatcher(bst_remote, remote, start=False)
    w_fs = ReplicaWatcher(bst_fs, FleetStore(str(tmp_path), "default"),
                          start=False)
    checksum0 = telemetry.counter("fleet/transport_checksum_failures")
    try:
        plan = FaultPlan.seeded(1234, {"transport/request": 4,
                                       "transport/serve": 4})
        with chaos.inject(plan):
            store.publish(bst_v2.model_to_string())
            # drive both replicas through the fault schedule; a poll may
            # fail whole (the watcher thread would back off and retry —
            # here the loop is the retry)
            for _ in range(12):
                try:
                    w_remote.poll_once()
                except Exception:
                    pass
                w_fs.poll_once()
                if not plan.pending() \
                        and w_remote.applied_version == 2:
                    break
        # out of the storm: one clean poll settles any leftover gap
        w_remote.poll_once()
        w_fs.poll_once()
        assert w_remote.applied_version == w_fs.applied_version == 2
        # byte-identical convergence, remote vs filesystem — and both
        # serve exactly the published model
        assert bst_remote.model_to_string() == bst_fs.model_to_string()
        Xq = _data(40, seed=11)[0]
        np.testing.assert_allclose(np.asarray(bst_remote.predict(Xq)),
                                   np.asarray(bst_v2.predict(Xq)),
                                   rtol=1e-9)
        # the storm left fingerprints: retries/backoff and (if a torn
        # body got through) checksum rejections, all on /metrics
        st = remote.state()
        assert st["requests"] > 0
        metrics = _get_text(base + "/metrics")
        assert "lgbtpu_fleet_transport_requests_total" in metrics
        injected = plan.injected()
        assert sum(injected.values()) > 0
        assert telemetry.counter("fleet/transport_checksum_failures") \
            >= checksum0
    finally:
        server.close()


def test_replica_poll_backoff_grows_and_resets(tmp_path):
    class FlakyStore:
        """Duck-typed store that fails until told otherwise."""
        def __init__(self):
            self.broken = True
            self.polls = 0

        def latest_publish(self):
            self.polls += 1
            if self.broken:
                raise OSError("store unreachable")
            return None

    flaky = FlakyStore()
    bst = _train()
    errors0 = telemetry.counter("fleet/replica_poll_errors")
    watcher = ReplicaWatcher(bst, flaky, poll_interval_s=0.02,
                             backoff_max_s=0.08, start=True)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            st = watcher.state()
            if st["poll_errors"] >= 3 and st["poll_backoff_s"] >= 0.08:
                break
            time.sleep(0.01)
        st = watcher.state()
        assert st["poll_errors"] >= 3
        assert st["poll_backoff_s"] == 0.08       # capped, not unbounded
        assert "OSError" in st["last_error"]
        assert telemetry.counter("fleet/replica_poll_errors") >= errors0 + 3
        # first success resets the backoff to the plain poll interval
        flaky.broken = False
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if watcher.state()["poll_backoff_s"] == 0.0:
                break
            time.sleep(0.01)
        assert watcher.state()["poll_backoff_s"] == 0.0
    finally:
        watcher.close()
    with pytest.raises(LightGBMError):
        ReplicaWatcher(bst, flaky, poll_interval_s=0.5, backoff_max_s=0.1,
                       start=False)


# ---------------------------------------------------------- observability

def test_healthz_and_metrics_expose_fleet_hardening(tmp_path):
    bst = _train(seed=2)
    store = FleetStore(str(tmp_path), "default")
    assert store.acquire_lease("trainer-1", ttl_s=30.0) == 1
    store.set_fence("trainer-1", 1)
    store.publish(bst.model_to_string(), event="boot")
    server = PredictServer(bst, port=0, warmup=False)
    server.fleet_store = store
    _start_server(server)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    try:
        server.fleet_transport = RemoteStore(base, timeout_s=2.0,
                                             retries=0)
        with urlopen(base + "/healthz", timeout=30) as resp:
            doc = json.loads(resp.read())
        fs = doc["fleet_store"]
        assert fs["lease"]["holder"] == "trainer-1"
        assert fs["lease"]["epoch"] == 1 and fs["lease"]["held"] is True
        assert fs["events_log_bytes"] > 0
        assert fs["compactions"] == 0
        assert doc["fleet_transport"]["base_url"] == base
        metrics = _get_text(base + "/metrics")
        assert "lgbtpu_fleet_lease_epoch" in metrics
        assert "lgbtpu_fleet_events_log_bytes" in metrics
        assert "lgbtpu_fleet_lease_acquired_total" in metrics
    finally:
        server.close()


# -------------------------------------------------------- SIGKILL e2e

_CRASH_HOLDER = textwrap.dedent("""
    import os, signal, sys
    sys.path.insert(0, %(repo)r)
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.fleet import FleetStore
    from lightgbm_tpu.online import OnlineTrainer

    W = np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4])

    def data(n, seed):
        rng = np.random.RandomState(seed)
        X = rng.randn(n, len(W))
        y = (X @ W + 0.2 * rng.randn(n) > 0).astype(np.float64)
        return X, y

    store = FleetStore(sys.argv[1], "m")
    bst = lgb.Booster(model_file=sys.argv[2])
    tr = OnlineTrainer(bst, trigger_rows=10**9, min_rows=64,
                       shadow_rows=10**6, promote_threshold=2.0,
                       promote_patience=2, store=store,
                       lease_ttl_s=1.0, holder_id="holder-a",
                       start=False)
    assert tr.wait_for_lease(10.0), "holder-a could not take the lease"
    assert tr.state()["lease_epoch"] == 1
    tr.ingest(*data(150, seed=5))
    result = tr.run_once()          # banks one win: "deferred" on disk
    assert result == "deferred", result
    tr.ingest(*data(60, seed=6))    # mid-shadow-window, never trained
    print("READY", flush=True)
    os.kill(os.getpid(), signal.SIGKILL)
""")


@pytest.mark.slow
def test_sigkill_failover_standby_takes_over(tmp_path):
    """Tentpole e2e: SIGKILL the lease-holding trainer mid-shadow-window.
    A standby on the same store must wait out the ttl, take the lease at
    a HIGHER epoch, resume the dead holder's exact watermark and
    win-streak, complete the pending promotion under its own epoch —
    while the dead holder's fenced store can never publish again and no
    version token is ever issued twice."""
    model_path = str(tmp_path / "seed.txt")
    store_dir = str(tmp_path / "fleet")
    _train().save_model(model_path)
    script = tmp_path / "crash_holder.py"
    script.write_text(_CRASH_HOLDER % {"repo": REPO})
    proc = subprocess.run(
        [sys.executable, str(script), store_dir, model_path],
        env=clean_cpu_env(4), capture_output=True, text=True, timeout=600)
    assert "READY" in proc.stdout, (proc.stdout, proc.stderr)
    assert proc.returncode == -signal.SIGKILL
    # the dead holder's lease survives it, at epoch 1
    store = FleetStore(store_dir, "m")
    st = store.lease_state()
    assert st["holder"] == "holder-a" and st["epoch"] == 1
    # standby boots over the same store: blocked until the ttl lapses
    bst = lgb.Booster(model_file=model_path)
    v0 = bst.inner.model_version
    tr = OnlineTrainer(bst, trigger_rows=10**9, min_rows=64,
                       shadow_rows=10**6, promote_threshold=2.0,
                       promote_patience=2, store=store,
                       lease_ttl_s=1.0, holder_id="holder-b",
                       start=False)
    assert tr.state()["role"] == "standby"
    assert tr.run_once() == "standby"
    assert tr.wait_for_lease(30.0) is True
    st = tr.state()
    assert st["role"] == "active" and st["lease_epoch"] == 2
    # takeover replay resumed the dead holder's exact durable state
    assert tr.buffer.rows == 60                 # only the untrained tail
    assert tr.buffer.shadow_rows == 210         # full window resumed
    assert st["consumed_rows"] == 150
    assert st["win_streak"] == 1                # pending promotion resumed
    # the zombie's fenced store is locked out forever
    zombie = FleetStore(store_dir, "m")
    zombie.set_fence("holder-a", 1)
    with pytest.raises(StaleLeaseError):
        zombie.publish("zombie-model")
    # the resumed streak completes under epoch 2: exactly one version
    # bump on the serving booster, exactly one (unique) version token
    X, y = _data(100, seed=7)
    tr.ingest(X, y)
    assert tr.run_once() == "promoted"
    assert bst.inner.model_version == v0 + 1
    pubs = store.publishes()
    assert [p["version"] for p in pubs] == [1]
    assert pubs[0]["lease_epoch"] == 2
    assert len({p["version"] for p in pubs}) == len(pubs)
    # a replica adopts the failover-published model, whole
    replica = lgb.Booster(model_file=model_path)
    watcher = ReplicaWatcher(replica, FleetStore(store_dir, "m"),
                             start=False)
    assert watcher.poll_once() is True
    assert watcher.applied_version == 1
    Xq = _data(50, seed=8)[0]
    np.testing.assert_allclose(np.asarray(replica.predict(Xq)),
                               np.asarray(bst.predict(Xq)), rtol=1e-9)
    tr.close()
