"""Resident permuted training state: parity with the planes/rows paths.

tpu_resident_state keeps the bin planes ONCE in original row order and
partitions only the slim route/ridx/g/h/c payload; segment histograms
gather the resident planes through the permuted row-index plane. The
contract is BIT-IDENTICAL trees to tpu_work_layout=planes (same chunking,
same f32 accumulation order, same compaction dest arithmetic). These tests
pin that contract on the CPU backend, validate the fused Pallas partition
on the slim payload and the plane-major Pallas histogram kernel under the
pallas interpreter, and cover the config gates.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops import partition as P
from lightgbm_tpu.ops.histogram import (
    hist16_segment_planes, hist16_segment_resident,
    hist_pallas_segment_planes)

CH = 256
G = P.guard_rows(CH)


def _mk(rng, n, f=6, num_bin=32):
    bins = rng.randint(0, num_bin, (n, f)).astype(np.uint8)
    ghc = rng.randn(n, 3).astype(np.float32)
    ghc[:, 2] = 1.0
    return jnp.asarray(bins), jnp.asarray(ghc)


def _pack_pair(bins, ghc, num_bin, guard=G, part_kernel="xla"):
    """(resident work + planes, planes work) packed from the same rows."""
    n, f = bins.shape
    npad = P.planes_npad(n, guard, part_kernel)
    res = P.resident_bin_planes(bins, guard, npad)
    _, w_rs = P.work_spec(f, False, part_kernel, CH, CH, layout="resident")
    _, w_pl = P.work_spec(f, False, part_kernel, CH, CH, layout="planes")
    work_r = jnp.zeros((2, w_rs, npad), jnp.uint8)
    work_r, root_r = P.pack_resident_fold_root(
        work_r, bins, ghc, guard, num_bins=num_bin, exact=True, chunk=CH)
    work_p = jnp.zeros((2, w_pl, npad), jnp.uint8)
    work_p, root_p = P.pack_planes_fold_root(
        work_p, bins, ghc, guard, num_bins=num_bin, exact=True, chunk=CH)
    return res, work_r, root_r, work_p, root_p, npad


def test_pack_resident_fold_root_matches_planes(rng):
    """Same root histogram bits as the planes fold, ridx planes encoding
    absolute positions, and the g/h/c byte planes equal to the planes
    pack's payload planes."""
    n, f, num_bin = 1000, 6, 32
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin)
    res, work_r, root_r, work_p, root_p, npad = _pack_pair(bins, ghc, num_bin)
    assert np.array_equal(np.asarray(root_r).view(np.uint8),
                          np.asarray(root_p).view(np.uint8))
    s = slice(G, G + n)
    ridx = np.asarray(P._decode_ridx(work_r[0, P.RST_ROUTE:P.RST_GH_OFF, s],
                                     npad))
    assert np.array_equal(ridx, np.arange(G, G + n))
    assert np.array_equal(np.asarray(work_r)[0, P.RST_GH_OFF:P.RST_WIDTH, s],
                          np.asarray(work_p)[0, f:f + P.GH_BYTES, s])
    # resident planes carry the transposed bins at the guard offset
    assert np.array_equal(np.asarray(res)[:, G:G + n], np.asarray(bins).T)


def test_hist16_segment_resident_bit_identical(rng):
    n, f, num_bin = 900, 5, 32
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin)
    res, work_r, _, work_p, _, _ = _pack_pair(bins, ghc, num_bin)
    hr = np.asarray(hist16_segment_resident(
        work_r, res, jnp.int32(0), jnp.int32(G + 57), jnp.int32(700),
        num_bins=num_bin, num_feat=f, chunk=CH))
    hp = np.asarray(hist16_segment_planes(
        work_p, jnp.int32(0), jnp.int32(G + 57), jnp.int32(700),
        num_bins=num_bin, num_feat=f, chunk=CH))
    assert np.array_equal(hr.view(np.uint8), hp.view(np.uint8))


def test_write_route_plane_gathers_split_feature(rng):
    n, f, num_bin = 777, 6, 32
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin)
    res, work_r, _, _, _, _ = _pack_pair(bins, ghc, num_bin)
    wk = P.write_route_plane(work_r, res, jnp.int32(0), jnp.int32(G),
                             jnp.int32(n), jnp.int32(4), ch=CH)
    assert np.array_equal(np.asarray(wk)[0, 0, G:G + n],
                          np.asarray(bins)[:, 4])
    # planes 1.. and the sibling plane are untouched
    assert np.array_equal(np.asarray(wk)[0, 1:], np.asarray(work_r)[0, 1:])
    assert np.array_equal(np.asarray(wk)[1], np.asarray(work_r)[1])


@pytest.mark.parametrize("start,cnt", [(0, 1000), (137, 700), (513, 100)])
def test_partition_resident_matches_planes(rng, start, cnt):
    """The slim partition (route pre-pass + planes partition on plane 0)
    applies the SAME permutation as the planes partition on the full
    payload: gathering the bins through the moved ridx plane reproduces the
    moved bin planes, and the moved g/h/c planes match bit-for-bit."""
    n, f, num_bin = 1000, 6, 32
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin)
    res, work_r, _, work_p, _, npad = _pack_pair(bins, ghc, num_bin)
    table = jnp.asarray(rng.rand(num_bin) < 0.45)
    feat = jnp.int32(3)
    a = (jnp.int32(0), jnp.int32(G + start), jnp.int32(cnt))
    wk = P.write_route_plane(work_r, res, *a, feat, ch=CH)
    out_r, lt_r = P.partition_segment_planes(wk, *a, jnp.int32(0), table,
                                             ch=CH)
    out_p, lt_p = P.partition_segment_planes(work_p, *a, feat, table, ch=CH)
    assert int(lt_r) == int(lt_p)
    s = slice(G + start, G + start + cnt)
    ridx = np.asarray(P._decode_ridx(out_r[1, P.RST_ROUTE:P.RST_GH_OFF, s],
                                     npad))
    got_bins = np.asarray(bins)[ridx - G].T
    assert np.array_equal(got_bins, np.asarray(out_p)[1, :f, s])
    assert np.array_equal(np.asarray(out_r)[1, P.RST_GH_OFF:P.RST_WIDTH, s],
                          np.asarray(out_p)[1, f:f + P.GH_BYTES, s])


@pytest.mark.parametrize("start,cnt,ch", [(137, 700, 256), (0, 1500, 256),
                                          (333, 1400, 512)])
def test_resident_fused_kernel_interpret(rng, start, cnt, ch, monkeypatch):
    """The fused Pallas partition streaming the slim resident payload, run
    under the pallas interpreter, must match the XLA resident path: left
    child bit-exact in order, right child the same row set, neighbors
    outside the segment untouched (same contract as the planes kernel)."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n, f, num_bin = 1500, 20, 32
    guard = ch + 2 * P.PLANE_ALIGN
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin)
    npad = P.planes_npad(n, guard, "pallas")
    res = P.resident_bin_planes(bins, guard, npad)
    _, w_rs = P.work_spec(f, False, "pallas", ch, ch, layout="resident")
    assert w_rs % 32 == 0
    work = jnp.zeros((2, w_rs, npad), jnp.uint8)
    work, _ = P.pack_resident_fold_root(
        work, bins, ghc, guard, num_bins=num_bin, exact=True, chunk=ch)
    sib = rng.randint(0, 256, (w_rs, npad)).astype(np.uint8)  # junk dst
    work = work.at[1].set(jnp.asarray(sib))
    table = jnp.asarray(rng.rand(num_bin) < 0.45)
    a = (jnp.int32(0), jnp.int32(guard + start), jnp.int32(cnt))
    wk = P.write_route_plane(work, res, *a, jnp.int32(7), ch=ch)
    out_x, lt_x = P.partition_segment_planes(wk, *a, jnp.int32(0), table,
                                             ch=ch)
    out_p, lt_p = P.partition_segment_planes_fused(wk, *a, jnp.int32(0),
                                                   table, ch=ch)
    out_x, out_p = np.asarray(out_x), np.asarray(out_p)
    lt = int(lt_p)
    assert lt == int(lt_x)
    s0, s1 = guard + start, guard + start + cnt
    assert np.array_equal(out_p[1, :, s0:s0 + lt], out_x[1, :, s0:s0 + lt])
    assert sorted(map(bytes, out_p[1, :, s0 + lt:s1].T)) == \
        sorted(map(bytes, out_x[1, :, s0 + lt:s1].T))
    assert np.array_equal(out_p[1, :, :s0], sib[:, :s0])
    assert np.array_equal(out_p[1, :, s1:], sib[:, s1:])


@pytest.mark.parametrize("start,cnt", [(0, 1500), (57, 700), (513, 100)])
def test_hist_pallas_planes_kernel_interpret(rng, start, cnt, monkeypatch):
    """The plane-major Pallas histogram kernel under the interpreter is
    bit-identical to the XLA planes einsum: per-bucket accumulation stays
    in ascending row order whatever the 128-aligned chunk grid."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n, f, num_bin = 1500, 28, 16
    guard = CH + 2 * P.PLANE_ALIGN
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin)
    npad = P.planes_npad(n, guard, "pallas")
    _, w_pl = P.work_spec(f, False, "pallas", CH, CH, layout="planes")
    work = jnp.zeros((2, w_pl, npad), jnp.uint8)
    work, _ = P.pack_planes_fold_root(
        work, bins, ghc, guard, num_bins=num_bin, exact=True, chunk=CH)
    a = (jnp.int32(0), jnp.int32(guard + start), jnp.int32(cnt))
    ref = np.asarray(hist16_segment_planes(
        work, *a, num_bins=num_bin, num_feat=f, chunk=CH))
    got, work_out = hist_pallas_segment_planes(
        work, *a, num_bins=num_bin, num_feat=f, chunk=256)
    assert np.array_equal(np.asarray(got).view(np.uint8),
                          ref.view(np.uint8))
    assert np.array_equal(np.asarray(work_out), np.asarray(work))


def test_hist_pallas_planes_raises_on_bad_shapes():
    work = jnp.zeros((2, 40, 1280), jnp.uint8)     # 40 planes: not 32-mult
    with pytest.raises(ValueError, match="32-sublane"):
        hist_pallas_segment_planes(work, jnp.int32(0), jnp.int32(0),
                                   jnp.int32(64), num_bins=16, num_feat=6,
                                   chunk=256)
    work = jnp.zeros((2, 64, 1280), jnp.uint8)
    with pytest.raises(ValueError, match="multiple of 128"):
        hist_pallas_segment_planes(work, jnp.int32(0), jnp.int32(0),
                                   jnp.int32(64), num_bins=16, num_feat=6,
                                   chunk=100)


def _train_tree(layout, resident, n, f, leaves, seed=0):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    cfg = Config.from_params({
        "objective": "binary", "num_leaves": leaves, "max_bin": 31,
        "tree_builder": "partition", "tpu_part_chunk": CH,
        "tpu_hist_chunk": CH, "min_data_in_leaf": 2, "verbosity": -1,
        "tpu_work_layout": layout,
        "tpu_resident_state": "on" if resident else "off"})
    ds = construct_dataset(X, cfg, label=y)
    lrn = SerialTreeLearner(cfg, ds)
    want = "resident" if resident else layout
    assert lrn.build_kwargs()["work_layout"] == want
    ghc = jnp.stack([jnp.asarray(g), jnp.asarray(h),
                     jnp.ones(n, jnp.float32)], axis=1)
    return jax.device_get(
        lrn.train(ghc, jnp.ones(ds.num_features, bool),
                  jax.random.PRNGKey(0)))


_FIELDS = ("split_leaf", "feature", "bin", "kind", "default_left", "gain",
           "left_sum", "right_sum", "go_left", "leaf_value", "leaf_sum",
           "row_leaf")


# F=28 / F=137 cross leaves=255 / leaves=2; N deliberately NOT a multiple
# of the 256-row chunks
@pytest.mark.parametrize("n,f,leaves", [(2999, 28, 255), (1237, 137, 2),
                                        (1237, 28, 2), (1501, 137, 255)])
def test_tree_parity_resident_vs_planes(n, f, leaves):
    a = _train_tree("planes", False, n, f, leaves)
    b = _train_tree("planes", True, n, f, leaves)
    assert int(a.num_splits) == int(b.num_splits)
    for fld in _FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, fld)), np.asarray(getattr(b, fld)),
            err_msg=fld)


@pytest.mark.parametrize("n,f,leaves", [(2999, 28, 255), (1237, 28, 2)])
def test_tree_parity_resident_vs_rows(n, f, leaves):
    a = _train_tree("rows", False, n, f, leaves)
    b = _train_tree("planes", True, n, f, leaves)
    assert int(a.num_splits) == int(b.num_splits)
    for fld in _FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, fld)), np.asarray(getattr(b, fld)),
            err_msg=fld)


def test_resident_carried_work_buf_parity(rng):
    """A resident work buffer carried from a previous tree (fused-block
    contract) must grow the same tree as a fresh zero buffer, with the
    resident planes hoisted once outside the build like fused.py does."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    n, f = 1201, 6
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    cfg = Config.from_params({
        "objective": "binary", "num_leaves": 8, "max_bin": 31,
        "tree_builder": "partition", "tpu_part_chunk": CH,
        "tpu_hist_chunk": CH, "min_data_in_leaf": 5, "verbosity": -1,
        "tpu_work_layout": "planes", "tpu_resident_state": "on"})
    ds = construct_dataset(X, cfg, label=y)
    lrn = SerialTreeLearner(cfg, ds)
    rspec = lrn.resident_spec()
    assert rspec is not None
    bins_res = ds.device_resident_planes(*rspec)

    def mk_ghc():
        return jnp.stack(
            [jnp.asarray(rng.randn(n).astype(np.float32)),
             jnp.asarray(np.abs(rng.randn(n)).astype(np.float32) + 0.1),
             jnp.ones(n, jnp.float32)], axis=1)

    build = lrn.make_build_fn()
    key = jax.random.PRNGKey(0)
    used = jnp.zeros((ds.num_features,), bool)
    fmask = jnp.ones(ds.num_features, bool)
    ghc1, ghc2 = mk_ghc(), mk_ghc()
    _, carried = build(lrn.bins, ghc1, lrn.meta, fmask, key, used,
                       return_work=True, bins_res=bins_res)
    log_a = build(lrn.bins, ghc2, lrn.meta, fmask, key, used,
                  bins_res=bins_res)
    log_b, _ = build(lrn.bins, ghc2, lrn.meta, fmask, key, used,
                     work_buf=carried, return_work=True, bins_res=bins_res)
    # and the in-graph derivation (bins_res=None) matches the hoisted copy
    log_c = build(lrn.bins, ghc2, lrn.meta, fmask, key, used)
    for fld in ("num_splits", "feature", "bin", "gain", "leaf_value",
                "row_leaf"):
        np.testing.assert_array_equal(
            np.asarray(getattr(log_a, fld)), np.asarray(getattr(log_b, fld)),
            err_msg=fld)
        np.testing.assert_array_equal(
            np.asarray(getattr(log_a, fld)), np.asarray(getattr(log_c, fld)),
            err_msg=fld)


def test_config_rejects_bad_resident_state():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError, match="tpu_resident_state"):
        Config.from_params({"tpu_resident_state": "maybe"})


def _mini_ds(rng, params):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 4, "max_bin": 15,
            "tree_builder": "partition", "verbosity": -1,
            "min_data_in_leaf": 2}
    base.update(params)
    cfg = Config.from_params(base)
    return cfg, construct_dataset(X, cfg, label=y)


def test_resident_on_rejects_rows_layout(rng):
    from lightgbm_tpu.learner import SerialTreeLearner
    from lightgbm_tpu.utils.log import LightGBMError

    cfg, ds = _mini_ds(rng, {"tpu_resident_state": "on",
                             "tpu_work_layout": "rows"})
    with pytest.raises(LightGBMError, match="planes work layout"):
        SerialTreeLearner(cfg, ds)


def test_resident_on_rejects_int8(rng):
    from lightgbm_tpu.learner import SerialTreeLearner
    from lightgbm_tpu.utils.log import LightGBMError

    cfg, ds = _mini_ds(rng, {"tpu_resident_state": "on",
                             "use_quantized_grad": True})
    with pytest.raises(LightGBMError, match="int8"):
        SerialTreeLearner(cfg, ds)


def test_resident_auto_stays_planes_on_cpu(rng):
    """auto only turns resident on for TPU backends: the gather has no
    payoff without HBM bandwidth pressure, and CPU meshes keep the plain
    planes path (resident+CPU mesh fallback)."""
    from lightgbm_tpu.learner import SerialTreeLearner

    cfg, ds = _mini_ds(rng, {"tpu_resident_state": "auto",
                             "tpu_work_layout": "planes"})
    kw = SerialTreeLearner(cfg, ds).build_kwargs()
    assert kw["work_layout"] == "planes"
    cfg, ds = _mini_ds(rng, {"tpu_resident_state": "on",
                             "tpu_work_layout": "planes"})
    lrn = SerialTreeLearner(cfg, ds)
    assert lrn.build_kwargs()["work_layout"] == "resident"
    # forcing resident with the pallas hist kernel falls back to the XLA
    # gather (no resident gather path in the kernel)
    cfg, ds = _mini_ds(rng, {"tpu_resident_state": "on",
                             "tpu_work_layout": "planes",
                             "tpu_partition_kernel": "pallas",
                             "tpu_hist_kernel": "pallas",
                             "tpu_part_chunk": 256, "tpu_hist_chunk": 256})
    kw = SerialTreeLearner(cfg, ds).build_kwargs()
    assert kw["work_layout"] == "resident"
    assert kw["hist_kernel"] == "xla"


def test_device_resident_planes_version_token(rng):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset

    X = rng.randn(64, 3)
    cfg = Config.from_params({"max_bin": 15, "verbosity": -1,
                              "min_data_in_leaf": 1, "min_data_in_bin": 1})
    ds = construct_dataset(X, cfg, label=(X[:, 0] > 0).astype(np.float64))
    cached = ds.device_resident_planes(256, 576)
    assert ds.device_resident_planes(256, 576) is cached   # cache hit
    other = ds.device_resident_planes(128, 576)            # new geometry
    assert other is not cached
    assert cached.shape == (3, 576) and cached.dtype == jnp.uint8
    old = int(ds.binned[0, 0])
    ds.binned[0, 0] = old ^ 1                 # in-place host write
    ds.bump_version()
    fresh = ds.device_resident_planes(128, 576)
    assert fresh is not other                 # token invalidated the entry
    assert int(np.asarray(fresh)[0, 128]) == old ^ 1


def test_traffic_spec_resident_halves_partition_bytes(rng):
    """Acceptance: the resident partition moves >= 2x less data per split
    than the planes path at the HIGGS shape (F=28)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(400, 28)
    y = (X[:, 0] > 0).astype(np.float64)

    def spec(rs):
        cfg = Config.from_params({
            "objective": "binary", "num_leaves": 4, "max_bin": 15,
            "tree_builder": "partition", "verbosity": -1,
            "min_data_in_leaf": 2, "tpu_work_layout": "planes",
            "tpu_resident_state": rs})
        ds = construct_dataset(X, cfg, label=y)
        return SerialTreeLearner(cfg, ds).traffic_spec()

    planes, res = spec("off"), spec("on")
    assert planes["work_layout"] == "planes"
    assert res["work_layout"] == "resident"
    assert planes["partition_bytes_per_row"] >= \
        2 * res["partition_bytes_per_row"]


def test_bench_phases_traffic_merge():
    """The optional traffic dict merges into the breakdown without touching
    the wall-accounting fields (accounted_pct stays a pure self-check)."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from bench import _phases

    class _T:
        times = {"fused/block_fn": 0.5, "fused/dispatch": 0.3,
                 "fused/logs_transfer": 0.15, "fused/host_trees": 0.05}

    base = _phases(_T, 1.0)
    traffic = {"work_layout": "resident", "partition_bytes_per_row": 40,
               "hist_bytes_per_row": 23}
    got = _phases(_T, 1.0, traffic)
    assert got["accounted_pct"] == base["accounted_pct"]
    assert got["other"] == base["other"]
    assert got["work_layout"] == "resident"
    assert got["partition_bytes_per_row_split"] == 40
    assert got["hist_gather_bytes_per_row"] == 23
    assert _phases(_T, 1.0, None) == base
