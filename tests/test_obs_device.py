"""Device-cost observability (ISSUE 10 tentpole).

Pins the obs_device contracts: every tracked-jit compile yields a
cost/memory capture (FLOPs, bytes accessed, HBM footprint) visible in
``Booster.telemetry()["device_cost"]`` and as Prometheus families; the
live-HBM sampler degrades to a counted no-op on CPU; the
``obs_check_finite`` watchdog catches injected NaN gradients in warn and
raise modes; and the off modes add ZERO tracked compiles and ZERO device
ops (the compile-budget harness from tests/test_retrace.py).
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs, obs_device  # noqa: E402
from lightgbm_tpu.utils.log import LightGBMError  # noqa: E402

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "tpu_iter_block": 5}


# NOT test_retrace.py's (600, 8): these suites share the cross-Booster
# block cache, and retrace's "first train" must stay genuinely cold
def _data(n=560, f=7, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.1 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _fresh():
    obs.telemetry.reset()
    obs_device.reset()
    obs_device.configure(cost_enabled=True)


# ------------------------------------------------------------- cost capture

def test_device_cost_section_after_train():
    """Any backend: a train must land per-jit FLOPs/bytes/HBM aggregates
    in the telemetry device_cost section, including the fused block."""
    _fresh()
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    snap = bst.telemetry()
    sec = snap["device_cost"]
    assert sec["enabled"] is True
    assert sec["jits"], "no captures despite fresh compiles"
    assert "fused/run_block" in sec["jits"], sorted(sec["jits"])
    entry = sec["jits"]["fused/run_block"]
    assert entry["compiles"] >= 1
    assert entry["flops"] > 0
    assert entry["bytes_accessed"] > 0
    # memory_analysis fields present (values may be 0 on some backends)
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "generated_code_bytes"):
        assert key in entry
    # the watermark section is always present
    assert "peak_bytes" in sec["hbm"]


def test_device_cost_prometheus_families():
    _fresh()
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    lgb.train(dict(PARAMS), ds, num_boost_round=3)
    text = obs.prometheus_text()
    assert "lgbtpu_device_cost_flops_" in text
    assert "lgbtpu_device_cost_bytes_accessed_" in text
    assert "lgbtpu_device_cost_temp_hbm_bytes_" in text


def test_capture_does_not_inflate_backend_compiles():
    """The AOT re-compile inside on_compile runs under the suppression
    context: jit/backend_compiles keeps counting only the program's own
    compiles (one here), not the capture's."""
    _fresh()

    @jax.jit
    def f(x):
        return (x * 2.0).sum()

    g = obs.track_jit("test/suppress", f)
    x = jnp.ones((16,))     # array creation may itself backend-compile
    before = obs.telemetry.counter("jit/backend_compiles")
    g(x)
    assert obs.telemetry.counter("device_cost/captures") == 1
    # one program compile; the capture's AOT re-compile is suppressed
    assert obs.telemetry.counter("jit/backend_compiles") - before == 1


def test_capture_off_is_zero_overhead():
    """obs_device_cost=False: no captures, no capture timers, and the
    tracked-jit path stays identical (compile counts unchanged)."""
    _fresh()
    obs_device.configure(cost_enabled=False)
    try:
        @jax.jit
        def f(x):
            return x + 1

        g = obs.track_jit("test/capoff", f)
        g(jnp.ones((8,)))
        assert obs.jit_compiles().get("test/capoff") == 1
        assert obs.telemetry.counter("device_cost/captures") == 0
        snap = obs.telemetry.snapshot()
        assert snap["device_cost"]["jits"] == {}
        assert "device_cost/capture_s" not in snap["timers"]
    finally:
        obs_device.configure(cost_enabled=True)


# ---------------------------------------------------------------- HBM stats

def test_cpu_memory_stats_graceful_noop():
    """CPU jax has no device.memory_stats(): the sampler returns None,
    counts the no-op, and section() reports supported=False — never an
    exception."""
    if jax.default_backend() != "cpu":
        pytest.skip("backend has real memory stats")
    _fresh()
    assert obs_device.sample_hbm() is None
    assert obs.telemetry.counter("obs_device/hbm_sample_noop") == 1
    sec = obs_device.section()
    assert sec["hbm"]["supported"] is False
    assert sec["hbm"]["peak_bytes"] == 0
    # the boundary sampler stops re-probing once unsupported
    assert obs_device.maybe_sample_hbm() is None
    assert obs.telemetry.counter("obs_device/hbm_sample_noop") == 1


def test_hbm_summary_shape():
    _fresh()
    s = obs_device.summary()
    for key in ("hbm_supported", "hbm_peak_bytes", "captured_jits",
                "total_flops"):
        assert key in s


# ----------------------------------------------------------------- watchdog

def _nan_fobj(preds, dataset):
    g = np.full(len(preds), np.nan)
    h = np.ones(len(preds))
    return g, h


def test_watchdog_warn_counts_nan_grads():
    _fresh()
    X, y = _data(300, 6)
    p = dict(PARAMS, obs_check_finite="warn")
    ds = lgb.Dataset(X, label=y, params=dict(p))
    lgb.train(p, ds, num_boost_round=1, fobj=_nan_fobj)
    assert obs.telemetry.counter("obs/nonfinite_grads") > 0
    assert obs.telemetry.counter("obs/finite_checks") >= 1


def test_watchdog_raise_aborts_on_nan_grads():
    _fresh()
    X, y = _data(300, 6)
    p = dict(PARAMS, obs_check_finite="raise")
    ds = lgb.Dataset(X, label=y, params=dict(p))
    with pytest.raises(LightGBMError, match="non-finite"):
        lgb.train(p, ds, num_boost_round=1, fobj=_nan_fobj)


def test_watchdog_clean_training_raises_nothing():
    """raise mode on healthy data: checks run, nothing trips — including
    the fused-path per-block score check."""
    _fresh()
    X, y = _data(400, 6)
    p = dict(PARAMS, obs_check_finite="raise")
    ds = lgb.Dataset(X, label=y, params=dict(p))
    bst = lgb.train(p, ds, num_boost_round=3)
    assert bst.inner.iter_ == 3
    assert obs.telemetry.counter("obs/finite_checks") >= 1
    assert obs.telemetry.counter("obs/nonfinite_scores") == 0


def test_watchdog_off_zero_device_ops():
    """The acceptance pin: obs_check_finite=off (the default) must add
    ZERO tracked compiles and ZERO device ops — asserted with the
    compile-budget harness: a warm second train still compiles nothing,
    and the watchdog's own jit never appears."""
    _fresh()
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    lgb.train(dict(PARAMS), ds, num_boost_round=5)      # warm every cache
    obs.telemetry.reset()
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    jc = bst.telemetry()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc
    assert "obs/check_finite" not in jc["per_function"]
    assert obs.telemetry.counter("obs/finite_checks") == 0
    assert obs.telemetry.counter("obs/nonfinite_grads") == 0
