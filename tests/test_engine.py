"""End-to-end behavioral tests (model: reference
tests/python_package_test/test_engine.py — train/eval on synthetic data,
every objective family, model IO round-trips, early stopping)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _reg_data(rng, n=1500, f=8):
    X = rng.randn(n, f)
    y = 2 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.5 * X[:, 2] ** 2 + 0.05 * rng.randn(n)
    return X, y


def _bin_data(rng, n=2000, f=8):
    X = rng.randn(n, f)
    y = (2 * X[:, 0] - X[:, 1] + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y


BASE = {"verbosity": -1, "num_leaves": 15, "min_data_in_leaf": 5,
        "learning_rate": 0.15}


def test_regression_improves(rng):
    X, y = _reg_data(rng)
    bst = lgb.train({**BASE, "objective": "regression"},
                    lgb.Dataset(X, label=y), num_boost_round=40)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.1 * float(np.var(y))


def test_binary_auc(rng):
    X, y = _bin_data(rng)
    bst = lgb.train({**BASE, "objective": "binary", "metric": ["auc"]},
                    lgb.Dataset(X, label=y), num_boost_round=40)
    (_, _, auc, _), = bst.eval_train()
    assert auc > 0.97
    p = bst.predict(X)
    assert 0 <= p.min() and p.max() <= 1


@pytest.mark.parametrize("objective", [
    "regression_l1", "huber", "fair", "quantile", "mape"])
def test_robust_regression_objectives(rng, objective):
    X, y = _reg_data(rng)
    # alpha=0.5 makes quantile an L1 fit so the MAE check below applies
    bst = lgb.train({**BASE, "objective": objective, "alpha": 0.5},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    mae = float(np.mean(np.abs(bst.predict(X) - y)))
    base_mae = float(np.mean(np.abs(y - np.median(y))))
    assert mae < 0.5 * base_mae


@pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
def test_positive_regression_objectives(rng, objective):
    X, _ = _reg_data(rng)
    y = np.exp(0.5 * X[:, 0] + 0.2 * X[:, 1]) + 0.01
    bst = lgb.train({**BASE, "objective": objective},
                    lgb.Dataset(X, label=y), num_boost_round=40)
    p = bst.predict(X)
    assert p.min() > 0
    corr = np.corrcoef(np.log(p), np.log(y))[0, 1]
    assert corr > 0.8


def test_multiclass(rng):
    X = rng.randn(2000, 6)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0.3).astype(int))
    bst = lgb.train({**BASE, "objective": "multiclass", "num_class": 3},
                    lgb.Dataset(X, label=y), num_boost_round=25)
    p = bst.predict(X)
    assert p.shape == (2000, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-4)
    assert float(np.mean(np.argmax(p, 1) == y)) > 0.92


@pytest.mark.slow
def test_multiclassova(rng):
    X = rng.randn(1500, 6)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0.3).astype(int))
    bst = lgb.train({**BASE, "objective": "multiclassova", "num_class": 3},
                    lgb.Dataset(X, label=y), num_boost_round=25)
    p = bst.predict(X)
    assert float(np.mean(np.argmax(p, 1) == y)) > 0.9


def test_cross_entropy(rng):
    X = rng.randn(1500, 6)
    y = 1.0 / (1.0 + np.exp(-(X[:, 0] - 0.5 * X[:, 1])))  # soft labels
    bst = lgb.train({**BASE, "objective": "cross_entropy"},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    p = bst.predict(X)
    assert float(np.mean((p - y) ** 2)) < 0.01


def test_lambdarank(rng):
    n_q, per_q = 60, 20
    n = n_q * per_q
    X = rng.randn(n, 6)
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(n)) * 1.2 + 1.5,
                  0, 4).astype(int)
    group = np.full(n_q, per_q)
    ds = lgb.Dataset(X, label=rel, group=group)
    bst = lgb.train({**BASE, "objective": "lambdarank", "metric": ["ndcg"],
                     "eval_at": [5]}, ds, num_boost_round=30)
    res = {m: v for _, m, v, _ in bst.eval_train()}
    assert res["ndcg@5"] > 0.85


def test_rank_xendcg(rng):
    n_q, per_q = 60, 20
    n = n_q * per_q
    X = rng.randn(n, 6)
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1]) * 1.2 + 1.5, 0, 4).astype(int)
    ds = lgb.Dataset(X, label=rel, group=np.full(n_q, per_q))
    bst = lgb.train({**BASE, "objective": "rank_xendcg", "metric": ["ndcg"],
                     "eval_at": [5]}, ds, num_boost_round=30)
    res = {m: v for _, m, v, _ in bst.eval_train()}
    assert res["ndcg@5"] > 0.85


def test_model_io_roundtrip(tmp_path, rng):
    X, y = _reg_data(rng)
    bst = lgb.train({**BASE, "objective": "regression"},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    p1 = bst.predict(X)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    p2 = bst2.predict(X, raw_score=True)
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_early_stopping(rng):
    X, y = _bin_data(rng, 3000)
    Xtr, ytr, Xv, yv = X[:2000], y[:2000], X[2000:], y[2000:]
    tr = lgb.Dataset(Xtr, label=ytr)
    ev = tr.create_valid(Xv, label=yv)
    bst = lgb.train({**BASE, "objective": "binary", "metric": ["binary_logloss"],
                     "early_stopping_round": 5},
                    tr, num_boost_round=500, valid_sets=[ev])
    assert bst.best_iteration < 500
    assert bst.inner.iter_ <= bst.best_iteration + 5 + 1


def test_bagging_and_feature_fraction(rng):
    X, y = _reg_data(rng)
    bst = lgb.train({**BASE, "objective": "regression", "bagging_fraction": 0.6,
                     "bagging_freq": 1, "feature_fraction": 0.7},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.2 * float(np.var(y))


def test_goss(rng):
    X, y = _reg_data(rng, n=3000)
    bst = lgb.train({**BASE, "objective": "regression",
                     "data_sample_strategy": "goss", "learning_rate": 0.1},
                    lgb.Dataset(X, label=y), num_boost_round=40)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.15 * float(np.var(y))


def test_dart(rng):
    X, y = _reg_data(rng)
    bst = lgb.train({**BASE, "objective": "regression", "boosting": "dart",
                     "drop_rate": 0.2},
                    lgb.Dataset(X, label=y), num_boost_round=25)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.35 * float(np.var(y))


@pytest.mark.slow
def test_rf(rng):
    X, y = _bin_data(rng)
    bst = lgb.train({**BASE, "objective": "binary", "boosting": "rf",
                     "bagging_fraction": 0.7, "bagging_freq": 1},
                    lgb.Dataset(X, label=y), num_boost_round=20)
    p = bst.predict(X)
    assert float(np.mean((p > 0.5) == y)) > 0.9


def test_categorical_feature(rng):
    n = 2000
    cat = rng.randint(0, 8, n)
    effect = np.asarray([3.0, -2.0, 1.0, -1.0, 2.5, 0.0, -3.0, 0.5])[cat]
    X = np.column_stack([cat.astype(float), rng.randn(n, 3)])
    y = effect + X[:, 1] + 0.05 * rng.randn(n)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0],
                     params={"min_data_per_group": 5})
    bst = lgb.train({**BASE, "objective": "regression", "min_data_per_group": 5,
                     "cat_smooth": 1.0, "cat_l2": 1.0},
                    ds, num_boost_round=40)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.1 * float(np.var(y))


def test_monotone_constraints(rng):
    n = 2000
    X = rng.rand(n, 2)
    y = 2 * X[:, 0] + 0.3 * np.sin(8 * X[:, 1]) + 0.05 * rng.randn(n)
    bst = lgb.train({**BASE, "objective": "regression",
                     "monotone_constraints": [1, 0]},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    # predictions must be non-decreasing along feature 0
    grid = np.linspace(0.01, 0.99, 50)
    for x1 in (0.2, 0.8):
        pts = np.column_stack([grid, np.full(50, x1)])
        p = bst.predict(pts)
        assert np.all(np.diff(p) >= -1e-6)


def test_weights(rng):
    X, y = _reg_data(rng)
    w = np.where(X[:, 0] > 0, 10.0, 0.1)
    bst = lgb.train({**BASE, "objective": "regression"},
                    lgb.Dataset(X, label=y, weight=w), num_boost_round=30)
    err = (bst.predict(X) - y) ** 2
    assert err[X[:, 0] > 0].mean() < err[X[:, 0] <= 0].mean()


@pytest.mark.slow
def test_cv(rng):
    X, y = _bin_data(rng)
    res = lgb.cv({**BASE, "objective": "binary", "metric": ["auc"]},
                 lgb.Dataset(X, label=y), num_boost_round=10, nfold=3)
    assert "valid auc-mean" in res
    assert res["valid auc-mean"][0] > 0.9


def test_feature_importance(rng):
    X, y = _reg_data(rng)
    bst = lgb.train({**BASE, "objective": "regression"},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    imp = bst.feature_importance()
    assert imp.shape == (X.shape[1],)
    assert imp[0] == imp.max()  # feature 0 dominates the target


def test_continued_training(rng):
    X, y = _reg_data(rng)
    ds = lgb.Dataset(X, label=y)
    bst1 = lgb.train({**BASE, "objective": "regression"}, ds, num_boost_round=10)
    mse1 = float(np.mean((bst1.predict(X) - y) ** 2))
    bst2 = lgb.train({**BASE, "objective": "regression"}, ds,
                     num_boost_round=10, init_model=bst1)
    assert bst2.num_trees() == 20
    mse2 = float(np.mean((bst2.predict(X) - y) ** 2))
    assert mse2 < mse1


def test_custom_objective(rng):
    X, y = _reg_data(rng)
    ds = lgb.Dataset(X, label=y)

    def fobj(score, _ds):
        return score - y, np.ones_like(y)

    bst = lgb.train({**BASE}, ds, num_boost_round=30, fobj=fobj)
    pred = bst.predict(X, raw_score=True)
    assert float(np.mean((pred - y) ** 2)) < 0.15 * float(np.var(y))


def test_predict_leaf_index(rng):
    X, y = _reg_data(rng)
    bst = lgb.train({**BASE, "objective": "regression"},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    leaves = bst.predict(X, pred_leaf=True)
    assert leaves.shape == (len(X), 5)
    assert leaves.max() < 15


def test_pred_contrib_sums_to_prediction(rng):
    X, y = _reg_data(rng, n=300)
    bst = lgb.train({**BASE, "objective": "regression"},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    contrib = bst.predict(X[:20], pred_contrib=True)
    raw = bst.predict(X[:20], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-4, atol=1e-4)


def test_categorical_fused_matches_eager(rng):
    """The fused block path carries the (R, B) go_left tables only for
    categorical datasets (numerical trees rebuild routing arithmetically);
    fused and eager training must produce identical categorical models."""
    n = 1500
    cat = rng.randint(0, 10, n)
    effect = rng.randn(10)[cat]
    X = np.column_stack([cat.astype(float), rng.randn(n, 3)])
    y = (effect + X[:, 1] + 0.2 * rng.randn(n) > 0).astype(np.float64)
    params = {**BASE, "objective": "binary", "min_data_per_group": 5}

    def run(fused_path):
        ds = lgb.Dataset(X, label=y, categorical_feature=[0],
                         params={"min_data_per_group": 5})
        # a user callback forces the per-iteration eager loop
        cbs = None if fused_path else [lambda env: None]
        return lgb.train(dict(params, tpu_iter_block=4), ds,
                         num_boost_round=8, callbacks=cbs)

    fused = run(True)
    eager = run(False)
    assert fused.model_to_string() == eager.model_to_string()
    # the fused model's categorical tables survive a text round-trip
    clone = lgb.Booster(model_str=fused.model_to_string())
    np.testing.assert_allclose(clone.predict(X), fused.predict(X),
                               rtol=0, atol=1e-12)
