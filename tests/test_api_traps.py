"""Regression tests for the round-2 API traps (VERDICT r2 weak #6/#7/#8):
reset_parameter must preserve the learner class, refit must carry real
metadata, init_distributed must fail loudly."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import LightGBMError


def _data(n=1200, f=8, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + rng.normal(scale=0.3, size=n) > 0).astype(float)
    return X, y


def test_reset_parameter_preserves_mesh_learner():
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from lightgbm_tpu.parallel.mesh import DataParallelTreeLearner
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "tree_learner": "data", "verbose": -1},
                      train_set=ds)
    assert isinstance(bst.inner.learner, DataParallelTreeLearner)
    bst.update()
    bst.reset_parameter({"learning_rate": 0.02})
    assert isinstance(bst.inner.learner, DataParallelTreeLearner), \
        "reset_parameter downgraded the mesh learner to serial"
    bst.update()  # must keep training without crashing
    assert bst.current_iteration == 2


def test_reset_parameter_refreshes_samplers():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "bagging_fraction": 0.8, "bagging_freq": 1,
                              "verbose": -1}, train_set=ds)
    bst.update()
    bst.reset_parameter({"bagging_fraction": 0.5})
    bst.update()
    assert bst.inner._sampler_fn is not None
    assert bst.current_iteration == 2


def test_refit_weighted():
    X, y = _data()
    w = np.linspace(0.5, 2.0, len(y))
    ds = lgb.Dataset(X, label=y, weight=w)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                    ds, num_boost_round=3)
    out = bst.refit(X, 1.0 - y, weight=w, decay_rate=0.1)
    assert out.current_iteration == 3
    # refitting on flipped labels must move the leaf values
    assert not np.allclose(out.predict(X), bst.predict(X))


def test_refit_ranking_requires_group():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(300, 6))
    y = rng.randint(0, 3, 300).astype(float)
    group = np.full(10, 30)
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 7,
                     "verbose": -1, "min_data_in_leaf": 5},
                    ds, num_boost_round=2)
    with pytest.raises(LightGBMError):
        bst.refit(X, y)   # no group -> loud failure, not a crash/mis-fit
    out = bst.refit(X, y, group=group)
    assert out.current_iteration == 2


def test_init_distributed_fails_loudly(monkeypatch):
    import jax
    from lightgbm_tpu.parallel import distributed

    def boom(**kw):
        raise RuntimeError("bootstrap broken")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    monkeypatch.setattr(distributed.init_distributed, "_done", False,
                        raising=False)
    with pytest.raises(LightGBMError):
        distributed.init_distributed(coordinator_address="127.0.0.1:9999",
                                     num_processes=2, process_id=0)
