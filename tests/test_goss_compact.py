"""GOSS row compaction (tpu_goss_compact): parity with the dense-mask oracle.

After `make_sampler` zeroes out-of-bag gradients, the compact path
(ISSUE 17) sorts the in-bag survivors to the front of the row set
(ops/partition.py compact_rows_by_inbag) and rebuilds the tree over a
STATIC ceil((top_rate+other_rate)*N)-row slice — same shapes every
iteration, zero recompiles — while the dense-mask path is retained
verbatim as the bit-parity oracle. The contract is byte-identical
model_to_string() output: the compact branch feeds the dense row sums
to the root (f32 row-reduction grouping is the one compaction-visible
reassociation) and routes leaf assignment over the FULL bin matrix, so
leaf counts and values match the oracle exactly.

Also pins satellite 1: the GOSS threshold in fused.make_sampler moved
from a full jnp.sort to jax.lax.top_k — bit-compatible by test.
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs  # noqa: E402
from lightgbm_tpu.ops import partition as P  # noqa: E402

# lr=0.5 keeps the 1/lr GOSS warmup at 2 rounds, so rounds 2+ exercise
# the compacted branch of the in-graph cond
BASE = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
        "boosting": "goss", "top_rate": 0.3, "other_rate": 0.2,
        "learning_rate": 0.5, "tpu_iter_block": 2}


# --------------------------------------------------------------- op level

def test_topk_threshold_matches_sort(rng):
    """Satellite 1 pin: lax.top_k's k-th value is bit-identical to the
    full-sort threshold make_sampler used before, ties included."""
    for n, k in ((700, 210), (1024, 1), (333, 333), (64, 17)):
        s = jnp.asarray(rng.randn(n).astype(np.float32))
        s = jnp.where(jnp.asarray(rng.rand(n) < 0.3), s[0], s)  # duplicates
        thr_topk = jax.lax.top_k(s, k)[0][k - 1]
        thr_sort = jnp.sort(s)[n - k]
        assert thr_topk.dtype == thr_sort.dtype
        assert np.asarray(thr_topk).tobytes() == np.asarray(thr_sort).tobytes()


def test_goss_compact_rows_margin():
    """The static slice must cover top_k + binomial(rest, p) draws with
    slack, never exceed n, and stay well under n at production rates."""
    for n in (1000, 10_500_000):
        m = P.goss_compact_rows(n, 0.2, 0.1)
        assert int(n * 0.3) < m <= n
    assert P.goss_compact_rows(10_500_000, 0.2, 0.1) < 0.35 * 10_500_000
    assert P.goss_compact_rows(100, 0.9, 0.5) == 100       # clamps at n
    # slack covers 4 sigma of the binomial other_rate draw
    n, top, other = 50_000, 0.2, 0.1
    m = P.goss_compact_rows(n, top, other)
    top_k = int(n * top)
    rest = n - top_k
    p = other / (1 - top)
    assert m >= top_k + rest * p + 4 * np.sqrt(rest * p * (1 - p))


def test_compact_rows_by_inbag_stable_order(rng):
    """In-bag rows move to the front in their original relative order
    (bucket-stable integer argsort), and the in-bag count rides along."""
    n, f, m = 500, 6, 320
    bins = jnp.asarray(rng.randint(0, 32, (n, f)).astype(np.uint8))
    ghc = rng.randn(n, 3).astype(np.float32)
    mask = rng.rand(n) < 0.5
    ghc[:, 2] = mask
    ghc = jnp.asarray(ghc)
    bc, gc, c_in = P.compact_rows_by_inbag(bins, ghc, m)
    assert bc.shape == (m, f) and gc.shape == (m, 3)
    assert int(c_in) == int(mask.sum())
    idx = np.nonzero(mask)[0]
    np.testing.assert_array_equal(np.asarray(bc)[:len(idx)],
                                  np.asarray(bins)[idx])
    np.testing.assert_array_equal(np.asarray(gc)[:len(idx)],
                                  np.asarray(ghc)[idx])
    # tail is the out-of-bag filler, also in stable order
    out_idx = np.nonzero(~mask)[0][:m - len(idx)]
    np.testing.assert_array_equal(np.asarray(bc)[len(idx):],
                                  np.asarray(bins)[out_idx])


# ----------------------------------------------------- full-train parity

def _model(params, X, y, rounds=6, **dskw):
    ds = lgb.Dataset(X, label=y, params=dict(params), **dskw)
    bst = lgb.train(dict(params), ds, num_boost_round=rounds)
    return bst.model_to_string()


def _ab_models(extra, X, y, rounds=6, **dskw):
    on = dict(BASE, tpu_goss_compact="on", **extra)
    off = dict(BASE, tpu_goss_compact="off", **extra)
    return (_model(on, X, y, rounds, **dskw),
            _model(off, X, y, rounds, **dskw))


def test_train_parity_binary(rng):
    n = 700
    X = rng.randn(n, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    on, off = _ab_models({}, X, y)
    assert on == off


@pytest.mark.slow
def test_train_parity_multiclass(rng):
    n = 700
    X = rng.randn(n, 6)
    y = (np.abs(X[:, 0]) + X[:, 1] > 0.5).astype(np.float64) \
        + (X[:, 2] > 0.3)
    on, off = _ab_models({"objective": "multiclass", "num_class": 3}, X, y,
                         rounds=4)
    assert on == off


@pytest.mark.slow
def test_train_parity_nan_missing(rng):
    n = 700
    X = rng.randn(n, 6)
    X[rng.rand(n, 6) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.2 * rng.randn(n) > 0).astype(np.float64)
    on, off = _ab_models({"use_missing": True}, X, y)
    assert on == off


@pytest.mark.slow
def test_train_parity_categorical(rng):
    n = 700
    X = rng.randn(n, 5)
    X[:, 0] = rng.randint(0, 12, n)
    y = ((X[:, 0] % 3 == 0) ^ (X[:, 1] > 0)).astype(np.float64)
    on, off = _ab_models({"min_data_per_group": 5}, X, y,
                         categorical_feature=[0])
    assert on == off


@pytest.mark.slow
def test_train_parity_planes_split_kernel(rng, monkeypatch):
    """Satellite 2: compaction composes with the planes pallas partition
    stream AND the one-kernel split — GOSS rides tpu_split_kernel through
    the compacted recursion, byte for byte."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    extra = {"tpu_work_layout": "planes", "tpu_partition_kernel": "pallas",
             "tpu_part_chunk": 256, "tpu_hist_chunk": 256,
             "tpu_split_kernel": "on", "max_bin": 31}
    on, off = _ab_models(extra, X, y, rounds=4)
    assert on == off


# --------------------------------------------------- telemetry + retrace

def test_second_identical_train_compiles_nothing(rng):
    """test_retrace.py discipline: the in-graph sort/slice/cond keeps one
    static shape across iterations — a second identical train recompiles
    nothing."""
    n = 530                      # shape distinct from other test modules
    X = rng.randn(n, 9)
    y = (X @ rng.randn(9) > 0).astype(np.float64)
    params = dict(BASE, tpu_goss_compact="on")
    ds = lgb.Dataset(X, label=y, params=dict(params))
    lgb.train(dict(params), ds, num_boost_round=4)   # warm every cache
    obs.telemetry.reset()
    bst = lgb.train(dict(params), ds, num_boost_round=4)
    jc = bst.telemetry()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc


def test_traffic_spec_effective_rows(rng):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)

    def spec(gc):
        cfg = Config.from_params(dict(BASE, num_leaves=4, max_bin=15,
                                      tpu_goss_compact=gc))
        ds = construct_dataset(X, cfg, label=y)
        lrn = SerialTreeLearner(cfg, ds)
        return lrn.build_kwargs(), lrn.traffic_spec()

    kw, tr = spec("on")
    m = P.goss_compact_rows(300, 0.3, 0.2)
    assert kw["goss_compact_rows"] == m
    assert tr["goss_compact"] == "on"
    assert tr["effective_rows"] == m
    # work buffers shrink to the compact row count
    lrn_spec = None
    kw_off, tr_off = spec("off")
    assert kw_off["goss_compact_rows"] == 0
    assert tr_off["goss_compact"] == "off"
    assert tr_off["effective_rows"] == 300


# ------------------------------------------------------------ knob gates

def test_config_rejects_bad_goss_compact():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError, match="tpu_goss_compact"):
        Config.from_params({"tpu_goss_compact": "maybe"})


def test_auto_resolves_off_with_record(rng):
    """auto stays off until scripts/goss_bisect.py validates a win on real
    hardware; the honest reason names the bisect script on GOSS configs
    and the structural miss elsewhere."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)

    def resolve(params):
        cfg = Config.from_params(params)
        ds = construct_dataset(X, cfg, label=y)
        obs.telemetry.reset()
        kw = SerialTreeLearner(cfg, ds).build_kwargs()
        recs = obs.telemetry.snapshot()["records"]["auto_resolution"]
        mine = [r for r in recs if r["knob"] == "tpu_goss_compact"]
        assert len(mine) == 1
        assert kw["goss_compact_rows"] == 0
        return mine[0]

    rec = resolve(dict(BASE, num_leaves=4, max_bin=15))
    assert rec["value"] == "off"
    assert "goss_bisect" in rec["reason"]
    rec = resolve({"objective": "binary", "num_leaves": 4, "max_bin": 15,
                   "verbosity": -1})
    assert rec["value"] == "off"
    assert "no GOSS sampling" in rec["reason"]


def test_ineligible_on_downgrades_to_off(rng):
    """Forcing on where the structure can't support it warns and keeps the
    dense-mask path instead of failing the train."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    # no GOSS sampling: nothing to compact
    cfg = Config.from_params({"objective": "binary", "num_leaves": 4,
                              "max_bin": 15, "verbosity": -1,
                              "tpu_goss_compact": "on"})
    ds = construct_dataset(X, cfg, label=y)
    assert SerialTreeLearner(cfg, ds).build_kwargs()["goss_compact_rows"] == 0
    # int8 quantized gradients: stochastic-rounding draws are row-position
    # seeded, so moving rows changes the dither stream
    cfg = Config.from_params(dict(BASE, num_leaves=4, max_bin=15,
                                  tpu_goss_compact="on",
                                  use_quantized_grad=True))
    ds = construct_dataset(X, cfg, label=y)
    assert SerialTreeLearner(cfg, ds).build_kwargs()["goss_compact_rows"] == 0
