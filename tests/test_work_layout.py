"""Transposed (W, N) work-plane layout: parity with the row-major path.

The planes layout (ops/partition.py pack_planes, tpu_work_layout=planes)
must grow BIT-IDENTICAL trees to the rows layout: identical chunk
boundaries, identical compaction dest arithmetic (stable row order) and
identical f32 accumulation order in the histogram einsums. These tests pin
that contract on the CPU backend, and validate the fused planes Pallas
kernel under the pallas interpreter (the kernel reads dst-plane state
through the aliased output ref, which makes interpret runs byte-faithful
to device runs).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops import partition as P
from lightgbm_tpu.ops.histogram import (
    _hist16_chunk, _hist16_chunk_planes, hist16_segment,
    hist16_segment_planes, hist_pallas_segment)

CH = 256
G = P.guard_rows(CH)


def _mk(rng, n, f=6, num_bin=32, guard=G):
    npad = n + 2 * guard
    bins = np.zeros((npad, f), np.uint8)
    bins[guard:guard + n] = rng.randint(0, num_bin, (n, f))
    ghc = np.zeros((npad, 3), np.float32)
    ghc[guard:guard + n] = rng.randn(n, 3)
    ghc[guard:guard + n, 2] = 1.0
    return bins, ghc


def _pair(bins, ghc):
    """(rows work pair, planes work pair) from the same padded source."""
    w_r = np.asarray(P.pack_rows(jnp.asarray(bins), jnp.asarray(ghc)))
    w_p = np.asarray(P.pack_planes(jnp.asarray(bins), jnp.asarray(ghc)))
    work_r = jnp.stack([jnp.asarray(w_r), jnp.zeros_like(jnp.asarray(w_r))])
    work_p = jnp.stack([jnp.asarray(w_p), jnp.zeros_like(jnp.asarray(w_p))])
    return w_r, work_r, work_p


def test_pack_planes_is_transposed_pack_rows(rng):
    bins, ghc = _mk(rng, 777)
    w_r = np.asarray(P.pack_rows(jnp.asarray(bins), jnp.asarray(ghc)))
    w_p = np.asarray(P.pack_planes(jnp.asarray(bins), jnp.asarray(ghc)))
    assert np.array_equal(w_p, w_r.T)
    cg_r = np.asarray(P.unpack_ghc(jnp.asarray(w_r[G:G + 256]), 6))
    cg_p = np.asarray(P.unpack_ghc_planes(jnp.asarray(w_p[:, G:G + 256]), 6))
    assert np.array_equal(cg_p, cg_r.T)


@pytest.mark.parametrize("n,start,cnt", [(1000, 0, 1000), (1000, 137, 700),
                                         (300, 10, 100), (700, 100, 550)])
def test_partition_segment_planes_matches_rows(rng, n, start, cnt):
    num_bin = 32
    bins, ghc = _mk(rng, n, num_bin=num_bin)
    _, work_r, work_p = _pair(bins, ghc)
    table = rng.rand(num_bin) < 0.45
    args = (jnp.int32(0), jnp.int32(G + start), jnp.int32(cnt), jnp.int32(3),
            jnp.asarray(table))
    out_r, lt_r = P.partition_segment(work_r, *args, ch=CH)
    out_p, lt_p = P.partition_segment_planes(work_p, *args, ch=CH)
    assert int(lt_r) == int(lt_p)
    # the planes compaction uses the same dest arithmetic transposed:
    # the whole destination plane is the rows result bit-for-bit
    assert np.array_equal(np.asarray(out_p)[1], np.asarray(out_r)[1].T)


@pytest.mark.parametrize("num_bin,exact,lo_w", [(32, True, 4), (32, True, 8),
                                                (256, True, 8),
                                                (17, False, 4)])
def test_hist_chunk_planes_bit_identical(rng, num_bin, exact, lo_w):
    bins, ghc = _mk(rng, 600, num_bin=num_bin)
    cb = jnp.asarray(bins[G:G + CH])
    cg = jnp.asarray(ghc[G:G + CH])
    hr = np.asarray(_hist16_chunk(cb, cg, num_bin, exact, lo_w))
    hp = np.asarray(_hist16_chunk_planes(cb.T, cg.T, num_bin, exact, lo_w))
    assert np.array_equal(hr.view(np.uint8), hp.view(np.uint8))


def test_hist16_segment_planes_bit_identical(rng):
    n, f, num_bin = 900, 5, 32
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin)
    _, work_r, work_p = _pair(bins, ghc)
    hr = np.asarray(hist16_segment(
        work_r, jnp.int32(0), jnp.int32(G + 57), jnp.int32(700),
        num_bins=num_bin, num_feat=f, chunk=CH))
    hp = np.asarray(hist16_segment_planes(
        work_p, jnp.int32(0), jnp.int32(G + 57), jnp.int32(700),
        num_bins=num_bin, num_feat=f, chunk=CH))
    assert np.array_equal(hr.view(np.uint8), hp.view(np.uint8))


def test_pack_planes_fold_root_matches_segment_hist(rng):
    """The folded root histogram must be bit-identical to hist16_segment
    over the packed root segment (same chunking and accumulation order)."""
    n, f, num_bin = 1000, 6, 32
    guard, width = P.work_spec(f, False, "xla", CH, CH, layout="planes")
    bins, ghc = _mk(rng, n, f=f, num_bin=num_bin, guard=guard)
    npad = P.planes_npad(n, guard, "xla")
    work = jnp.zeros((2, width, npad), jnp.uint8)
    work, root = P.pack_planes_fold_root(
        work, jnp.asarray(bins[guard:guard + n]),
        jnp.asarray(ghc[guard:guard + n]), guard,
        num_bins=num_bin, exact=True, chunk=CH)
    w_r = np.asarray(P.pack_rows(jnp.asarray(bins), jnp.asarray(ghc)))
    work_r = jnp.stack([jnp.asarray(w_r), jnp.zeros_like(jnp.asarray(w_r))])
    ref = np.asarray(hist16_segment(
        work_r, jnp.int32(0), jnp.int32(guard), jnp.int32(n),
        num_bins=num_bin, num_feat=f, chunk=CH))
    assert np.array_equal(np.asarray(root).view(np.uint8),
                          ref.view(np.uint8))
    # and the packed planes equal the transposed packed rows
    got = np.asarray(work)[0, :w_r.shape[1], :w_r.shape[0]]
    assert np.array_equal(got, w_r.T)


@pytest.mark.parametrize("start,cnt,ch", [(137, 700, 256), (0, 1500, 256),
                                          (513, 100, 256), (333, 1400, 512)])
def test_planes_pallas_kernel_interpret(rng, start, cnt, ch, monkeypatch):
    """The fused planes kernel, run under the pallas interpreter, must match
    the XLA planes path: left child bit-exact in order, right child the same
    row set, neighbors outside the segment untouched."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n, f, num_bin = 1500, 20, 32
    guard = ch + 2 * P.PLANE_ALIGN
    npad = ((n + 2 * guard + 127) // 128) * 128
    bins = np.zeros((npad, 20), np.uint8)
    bins[guard:guard + n, :f] = rng.randint(0, num_bin, (n, f))
    ghc = np.zeros((npad, 3), np.float32)
    ghc[guard:guard + n] = rng.randn(n, 3)
    ghc[guard:guard + n, 2] = 1.0
    w0 = np.asarray(P.pack_planes(jnp.asarray(bins), jnp.asarray(ghc)))
    sib = rng.randint(0, 256, w0.shape).astype(np.uint8)  # junk dst plane
    work = jnp.stack([jnp.asarray(w0), jnp.asarray(sib)])
    table = rng.rand(num_bin) < 0.45
    args = (jnp.int32(0), jnp.int32(guard + start), jnp.int32(cnt),
            jnp.int32(3), jnp.asarray(table))
    out_x, lt_x = P.partition_segment_planes(work, *args, ch=ch)
    out_p, lt_p = P.partition_segment_planes_fused(work, *args, ch=ch)
    out_x, out_p = np.asarray(out_x), np.asarray(out_p)
    lt = int(lt_p)
    assert lt == int(lt_x)
    s0, s1 = guard + start, guard + start + cnt
    assert np.array_equal(out_p[1, :, s0:s0 + lt], out_x[1, :, s0:s0 + lt])
    assert sorted(map(bytes, out_p[1, :, s0 + lt:s1].T)) == \
        sorted(map(bytes, out_x[1, :, s0 + lt:s1].T))
    assert np.array_equal(out_p[1, :, :s0], sib[:, :s0])
    assert np.array_equal(out_p[1, :, s1:], sib[:, s1:])


def _train_tree(layout, n, f, leaves, seed=0, part_chunk=CH, hist_chunk=CH):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    cfg = Config.from_params({
        "objective": "binary", "num_leaves": leaves, "max_bin": 31,
        "tree_builder": "partition", "tpu_part_chunk": part_chunk,
        "tpu_hist_chunk": hist_chunk, "min_data_in_leaf": 2,
        "verbosity": -1, "tpu_work_layout": layout})
    ds = construct_dataset(X, cfg, label=y)
    lrn = SerialTreeLearner(cfg, ds)
    assert lrn.build_kwargs()["work_layout"] == layout
    ghc = jnp.stack([jnp.asarray(g), jnp.asarray(h),
                     jnp.ones(n, jnp.float32)], axis=1)
    return jax.device_get(
        lrn.train(ghc, jnp.ones(ds.num_features, bool),
                  jax.random.PRNGKey(0)))


# F=28 / F=137 cross leaves=255 / leaves=2; N deliberately NOT a multiple
# of the 256-row chunks
@pytest.mark.parametrize("n,f,leaves", [(2999, 28, 255), (1237, 137, 2),
                                        (1237, 28, 2), (1501, 137, 255)])
def test_tree_parity_layouts(n, f, leaves):
    a = _train_tree("rows", n, f, leaves)
    b = _train_tree("planes", n, f, leaves)
    assert int(a.num_splits) == int(b.num_splits)
    for fld in ("split_leaf", "feature", "bin", "kind", "default_left",
                "gain", "left_sum", "right_sum", "go_left", "leaf_value",
                "leaf_sum", "row_leaf"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, fld)), np.asarray(getattr(b, fld)),
            err_msg=fld)


def test_planes_carried_work_buf_parity(rng):
    """A planes buffer carried from a PREVIOUS tree (the fused-block
    contract) must grow the same tree as a fresh zero buffer: the pack fold
    rewrites every consumed lane, so last tree's leftovers are never read."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    n, f = 1201, 6
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    cfg = Config.from_params({
        "objective": "binary", "num_leaves": 8, "max_bin": 31,
        "tree_builder": "partition", "tpu_part_chunk": CH,
        "tpu_hist_chunk": CH, "min_data_in_leaf": 5, "verbosity": -1,
        "tpu_work_layout": "planes"})
    ds = construct_dataset(X, cfg, label=y)
    lrn = SerialTreeLearner(cfg, ds)

    def mk_ghc():
        return jnp.stack(
            [jnp.asarray(rng.randn(n).astype(np.float32)),
             jnp.asarray(np.abs(rng.randn(n)).astype(np.float32) + 0.1),
             jnp.ones(n, jnp.float32)], axis=1)

    build = lrn.make_build_fn()
    key = jax.random.PRNGKey(0)
    used = jnp.zeros((ds.num_features,), bool)
    fmask = jnp.ones(ds.num_features, bool)
    ghc1, ghc2 = mk_ghc(), mk_ghc()
    _, carried = build(lrn.bins, ghc1, lrn.meta, fmask, key, used,
                       return_work=True)
    log_a = build(lrn.bins, ghc2, lrn.meta, fmask, key, used)
    log_b, _ = build(lrn.bins, ghc2, lrn.meta, fmask, key, used,
                     work_buf=carried, return_work=True)
    for fld in ("num_splits", "feature", "bin", "gain", "leaf_value",
                "row_leaf"):
        np.testing.assert_array_equal(
            np.asarray(getattr(log_a, fld)), np.asarray(getattr(log_b, fld)),
            err_msg=fld)


def test_hist_pallas_chunk_not_32_raises():
    work = jnp.zeros((2, 256, 128), jnp.uint8)
    with pytest.raises(ValueError, match="multiple of 32"):
        hist_pallas_segment(work, jnp.int32(0), jnp.int32(0), jnp.int32(64),
                            num_bins=32, num_feat=6, chunk=100)


def test_learner_gate_hist_chunk_32(rng):
    """The learner gate refuses a misaligned tpu_hist_chunk with the pallas
    histogram kernel instead of silently corrupting histograms."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner
    from lightgbm_tpu.utils.log import LightGBMError

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config.from_params({
        "objective": "binary", "num_leaves": 4, "max_bin": 15,
        "tree_builder": "partition", "verbosity": -1,
        "tpu_partition_kernel": "pallas", "tpu_hist_kernel": "pallas",
        "tpu_hist_chunk": 100, "tpu_part_chunk": 256})
    ds = construct_dataset(X, cfg, label=y)
    with pytest.raises(LightGBMError, match="multiple of 32"):
        SerialTreeLearner(cfg, ds).build_kwargs()


def test_config_rejects_bad_layout():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError, match="tpu_work_layout"):
        Config.from_params({"tpu_work_layout": "diagonal"})


def test_device_cache_version_token(rng):
    """In-place host mutation + bump_version() must refresh the cached
    device copies (identity alone cannot see in-place writes)."""
    from lightgbm_tpu.dataset import Metadata

    meta = Metadata(8)
    meta.label = np.arange(8, dtype=np.float32)
    cached = meta.device_label()
    assert meta.device_label() is cached      # identity-keyed cache hit
    meta.label[0] = 99.0          # in-place: identity key unchanged
    meta.bump_version()
    fresh = meta.device_label()
    assert fresh is not cached                # token invalidated the entry
    assert float(np.asarray(fresh)[0]) == 99.0


def test_device_bins_version_token(rng):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset

    X = rng.randn(64, 3)
    cfg = Config.from_params({"max_bin": 15, "verbosity": -1,
                              "min_data_in_leaf": 1, "min_data_in_bin": 1})
    ds = construct_dataset(X, cfg, label=(X[:, 0] > 0).astype(np.float64))
    cached = ds.device_bins()
    assert ds.device_bins() is cached         # identity-keyed cache hit
    old = int(ds.binned[0, 0])
    ds.binned[0, 0] = old ^ 1                 # in-place host write
    ds.bump_version()
    fresh = ds.device_bins()
    assert fresh is not cached                # token invalidated the entry
    assert int(np.asarray(fresh)[0, 0]) == old ^ 1


def test_bench_breakdown_accounting():
    """bench.py's phase attribution must account for >= 95% of a fused
    train's wall (the PERF.md tables rely on this attribution)."""
    import sys
    import time
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from bench import _phases
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.timer import global_timer

    rng = np.random.RandomState(3)
    n = 3000
    X = rng.randn(n, 8)
    y = (X @ rng.randn(8) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 31,
              "verbosity": -1, "tpu_iter_block": 5}
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    lgb.train(dict(params), ds, num_boost_round=5)   # warmup/compile
    global_timer.reset()
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=10)
    wall = time.time() - t0
    ph = _phases(global_timer, wall)
    assert ph["accounted_pct"] >= 95.0, ph
