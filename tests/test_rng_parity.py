"""Eager and fused training must produce IDENTICAL models (VERDICT r2 #7).

The round-2 paths diverged under bagging: the host loop drew numpy masks
from bagging_seed while fused blocks used jax fold_in streams of the
boosting key — same params, different models depending on whether the run
qualified for fusing. Both now share fused.make_sampler /
make_feature_mask_fn streams derived from the seeds alone.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=1500, f=10, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n) > 0).astype(float)
    return X, y


@pytest.mark.parametrize("extra", [
    {"bagging_fraction": 0.7, "bagging_freq": 2},
    {"feature_fraction": 0.6},
    {"bagging_fraction": 0.8, "bagging_freq": 1, "feature_fraction": 0.7},
    {"data_sample_strategy": "goss", "top_rate": 0.3, "other_rate": 0.2,
     "learning_rate": 0.5},
])
def test_eager_fused_identical(extra):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5, **extra}

    ds1 = lgb.Dataset(X, label=y)
    fused = lgb.train(dict(params, tpu_iter_block=4), ds1, num_boost_round=8)

    # a user callback disqualifies fusing -> eager per-iteration loop
    ds2 = lgb.Dataset(X, label=y)
    eager = lgb.train(dict(params, tpu_iter_block=1), ds2, num_boost_round=8,
                      callbacks=[lambda env: None])

    sf = fused.model_to_string()
    se = eager.model_to_string()
    assert sf == se, "fused and eager models differ under %r" % (extra,)


@pytest.mark.slow
def test_balanced_bagging_parity():
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "pos_bagging_fraction": 0.6, "neg_bagging_fraction": 0.9,
              "bagging_freq": 1, "min_data_in_leaf": 5}
    fused = lgb.train(dict(params, tpu_iter_block=4),
                      lgb.Dataset(X, label=y), num_boost_round=6)
    eager = lgb.train(dict(params, tpu_iter_block=1),
                      lgb.Dataset(X, label=y), num_boost_round=6,
                      callbacks=[lambda env: None])
    assert fused.model_to_string() == eager.model_to_string()
