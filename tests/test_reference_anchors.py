"""Absolute quality pins anchored to the reference's own published bars.

Each test reproduces a quality assertion from the reference's test suite —
same data, same params, same budget, same threshold — so the framework's
accuracy is checked against reference-documented numbers rather than
self-recorded fixtures (VERDICT r3 missing #2):

- binary:     test_engine.py test_binary — breast_cancer split 42,
              50 rounds, test log_loss < 0.14
- multiclass: test_engine.py test_multiclass — digits split 42,
              50 rounds, test multi_logloss < 0.16
- lambdarank: test_sklearn.py test_lambdarank — examples/lambdarank
              rank.{train,test}, test NDCG@1 > 0.5674, NDCG@3 > 0.578
              (the reference reaches these by iteration <= 24 with a
              decaying learning rate; same budget here)

The f32-histogram accuracy precedent is the reference's own GPU mode
(docs/GPU-Performance.rst:133-158: f32 histograms match CPU doubles to the
third decimal on Higgs/Yahoo/MS-LTR at 255 bins).
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

EX = "/root/reference/examples"


def _logloss(y, p):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


@pytest.mark.slow  # real-dataset accuracy anchor (~4 min train), not a parity pin
def test_binary_breast_cancer_anchor():
    sklearn = pytest.importorskip("sklearn")
    from sklearn.datasets import load_breast_cancer
    from sklearn.model_selection import train_test_split

    X, y = load_breast_cancer(return_X_y=True)
    X_tr, X_te, y_tr, y_te = train_test_split(X, y, test_size=0.1,
                                              random_state=42)
    params = {"objective": "binary", "metric": "binary_logloss",
              "verbose": -1}
    ds = lgb.Dataset(X_tr, label=y_tr)
    bst = lgb.train(params, ds, num_boost_round=50)
    ret = _logloss(y_te, bst.predict(X_te))
    assert ret < 0.14, ret  # reference bar (test_engine.py test_binary)


@pytest.mark.slow
def test_multiclass_digits_anchor():
    sklearn = pytest.importorskip("sklearn")
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    X, y = load_digits(n_class=10, return_X_y=True)
    X_tr, X_te, y_tr, y_te = train_test_split(X, y, test_size=0.1,
                                              random_state=42)
    params = {"objective": "multiclass", "metric": "multi_logloss",
              "num_class": 10, "verbose": -1}
    ds = lgb.Dataset(X_tr, label=y_tr)
    bst = lgb.train(params, ds, num_boost_round=50)
    p = np.clip(bst.predict(X_te), 1e-15, None)
    ret = float(-np.mean(np.log(p[np.arange(len(y_te)),
                                  y_te.astype(int)])))
    assert ret < 0.16, ret  # reference bar (test_engine.py test_multiclass)


@pytest.mark.skipif(not os.path.isdir(EX),
                    reason="reference examples not mounted")
def test_lambdarank_ndcg_anchor():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io import load_text_file

    cfg = Config.from_params({"verbosity": -1})
    X, y, _, grp, _ = load_text_file(os.path.join(EX, "lambdarank",
                                                  "rank.train"), cfg)
    Xt, yt, _, grpt, _ = load_text_file(os.path.join(EX, "lambdarank",
                                                     "rank.test"), cfg)
    ds = lgb.Dataset(X, label=y, group=grp)
    dt = lgb.Dataset(Xt, label=yt, group=grpt, reference=ds)
    rec = {}
    lgb.train({"objective": "lambdarank", "metric": ["ndcg"],
               "eval_at": [1, 3], "verbose": -1}, ds, num_boost_round=24,
              valid_sets=[dt], valid_names=["valid_0"],
              callbacks=[lgb.record_evaluation(rec)])
    best1 = max(rec["valid_0"]["ndcg@1"])
    best3 = max(rec["valid_0"]["ndcg@3"])
    # reference bars (test_sklearn.py test_lambdarank, best_iteration <= 24)
    assert best1 > 0.5674, best1
    assert best3 > 0.578, best3
