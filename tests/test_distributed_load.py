"""Distributed (per-host sharded) data loading — VERDICT r2 missing #2.

Each rank streams only its row slice and bin mappers derive from a
globally-gathered sample, so NO host ever materializes the full matrix.
Driven single-process here by calling the loader once per rank with an
explicit gather function (the pod path uses
jax.experimental.multihost_utils.process_allgather for the same step).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io import load_dataset_sharded


@pytest.fixture()
def csv_file(tmp_path):
    rng = np.random.RandomState(7)
    n = 4003   # deliberately not divisible by the shard count
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    w = rng.uniform(0.5, 1.5, size=n)
    f = tmp_path / "train.csv"
    np.savetxt(f, np.column_stack([y, X, w]), delimiter=",", fmt="%.10g")
    return str(f), X, y, w, n


def test_shards_reassemble_to_full_dataset(csv_file):
    path, X, y, w, n = csv_file
    world = 4
    params = {"weight_column": "7", "bin_construct_sample_cnt": 4 * n,
              "verbosity": -1}
    cfg = Config.from_params(params)

    # the global sample every rank would see after the pod allgather
    per_rank = []
    for rank in range(world):
        r0, r1 = rank * n // world, (rank + 1) * n // world
        per_rank.append(X[r0:r1])

    def gather(local):
        # stand-in for multihost_utils.process_allgather: with the sample
        # budget >= slice sizes, each rank's reservoir IS its full slice
        return np.concatenate(per_rank)

    shards = [load_dataset_sharded(path, Config.from_params(params),
                                   rank=rank, world=world,
                                   sample_gather=gather)
              for rank in range(world)]

    # no shard ever held the full matrix
    for rank, ds in enumerate(shards):
        r0, r1 = rank * n // world, (rank + 1) * n // world
        assert ds.num_data == r1 - r0
        assert ds.binned.shape[0] == r1 - r0
        assert ds.shard_info == (rank, world, n)
        np.testing.assert_allclose(ds.metadata.label,
                                   y[r0:r1].astype(np.float32))
        np.testing.assert_allclose(ds.metadata.weight,
                                   w[r0:r1].astype(np.float32), rtol=1e-6)

    # identical binning structure on every rank (same global sample)
    b0 = shards[0]
    for ds in shards[1:]:
        assert len(ds.bin_mappers) == len(b0.bin_mappers)
        for ma, mb in zip(ds.bin_mappers, b0.bin_mappers):
            np.testing.assert_array_equal(ma.upper_bounds, mb.upper_bounds)

    # shard rows concatenate to the full in-memory construction with the
    # same sample
    from lightgbm_tpu.dataset import construct_dataset
    full = construct_dataset(np.concatenate(per_rank), cfg)
    got = np.concatenate([ds.binned for ds in shards])
    want = full.binned  # same mappers -> same codes
    np.testing.assert_array_equal(got, want)


def test_sharded_training_quality(csv_file):
    path, X, y, w, n = csv_file
    # world=1 shard == full dataset; train end-to-end through the normal API
    ds = load_dataset_sharded(path, Config.from_params(
        {"weight_column": "7", "verbosity": -1}), rank=0, world=1)
    assert ds.shard_info == (0, 1, n)
    wrap = lgb.Dataset(None)
    wrap._constructed = ds
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, wrap, num_boost_round=10)
    assert ((bst.predict(X) > 0.5) == y).mean() > 0.95


def test_sharded_group_column(tmp_path):
    rng = np.random.RandomState(9)
    n, qsize = 1200, 20
    X = rng.normal(size=(n, 4))
    y = rng.randint(0, 3, n).astype(float)
    qid = np.repeat(np.arange(n // qsize), qsize).astype(float)
    f = tmp_path / "rank.csv"
    np.savetxt(f, np.column_stack([y, qid, X]), delimiter=",", fmt="%.10g")
    # query ids in column 1; shards must exclude it from features and
    # rebuild query boundaries from the local slice
    cfg_params = {"group_column": "1", "verbosity": -1}
    world = 3  # 1200/3 = 400 rows/shard = 20 whole queries each
    shards = [load_dataset_sharded(str(f), Config.from_params(cfg_params),
                                   rank=r, world=world,
                                   sample_gather=lambda s: X)
              for r in range(world)]
    for ds in shards:
        assert ds.num_features == 4          # qid column not a feature
        assert ds.metadata.query_boundaries is not None
        assert ds.metadata.num_queries == 20


def test_pre_partitioned_files(tmp_path):
    """pre_partition=true: each rank's file IS its partition (reference:
    config.h pre_partition; the loader skips the rank row-split). Unequal
    shards publish a world*max capacity so the mesh's uniform per-process
    blocks can hold every rank."""
    rng = np.random.RandomState(5)
    sizes = [600, 400]
    world = 2
    Xs, paths = [], []
    for r, sz in enumerate(sizes):
        X = rng.normal(size=(sz, 5))
        y = (X[:, 0] > 0).astype(np.float64)
        f = tmp_path / f"part{r}.csv"
        np.savetxt(f, np.column_stack([y, X]), delimiter=",", fmt="%.8g")
        Xs.append(X)
        paths.append(str(f))
    params = {"pre_partition": True, "verbosity": -1,
              "bin_construct_sample_cnt": 4000}

    def gather(local):
        return np.concatenate(Xs)  # global reservoir sample

    def counts(local):
        # (rows, samples-held) stats per rank; budget exceeds both shards,
        # so each rank holds its whole file as its sample
        return np.asarray([[float(s), float(s)] for s in sizes])

    shards = [load_dataset_sharded(paths[r], Config.from_params(params),
                                   rank=r, world=world, sample_gather=gather,
                                   count_gather=counts)
              for r in range(world)]
    for r, ds in enumerate(shards):
        assert ds.num_data == sizes[r]
        assert ds.binned.shape[0] == sizes[r]
        # capacity = world * max local rows
        assert ds.shard_info == (r, world, world * max(sizes))
    # identical mappers on both ranks (same global sample)
    b0 = [m.upper_bounds for m in shards[0].bin_mappers]
    b1 = [m.upper_bounds for m in shards[1].bin_mappers]
    for a, b in zip(b0, b1):
        np.testing.assert_allclose(a, b)
