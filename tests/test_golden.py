"""Cross-round golden quality fixtures (VERDICT r2 #9).

Pins per-iteration metric curves on the reference's real example datasets
so performance work between rounds cannot silently trade model quality.
The golden values were recorded from the round-3 code (deterministic: the
builders and seed-derived samplers produce identical models per config on
a fixed dataset) and carry a small tolerance for cross-backend float
reassociation. Regenerate ONLY after an intentional algorithm change:
    python tests/test_golden.py --regen
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io import load_text_file

EX = "/root/reference/examples"
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden_curves.json")
TOL = 2e-3   # absolute per-point metric tolerance

pytestmark = pytest.mark.skipif(not os.path.isdir(EX),
                                reason="reference examples not mounted")


def _data(subdir, fname):
    cfg = Config.from_params({"verbosity": -1})
    X, y, w, grp, _ = load_text_file(os.path.join(EX, subdir, fname), cfg)
    return X, y, w, grp


def _both(rec, tag):
    """train + held-out test curves (a generalization regression — e.g. an
    overfit shift — is invisible to train-only pins; VERDICT r3 #7)."""
    out = {}
    for split in ("training", "test"):
        for k, v in rec[split].items():
            out["%s:%s:%s" % (tag, split, k)] = v
    return out


def _run_binary(rounds=20):
    X, y, _, _ = _data("binary_classification", "binary.train")
    Xt, yt, _, _ = _data("binary_classification", "binary.test")
    ds = lgb.Dataset(X, label=y)
    dt = lgb.Dataset(Xt, label=yt, reference=ds)
    rec = {}
    lgb.train({"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
               "metric": ["auc", "binary_logloss"], "verbose": -1}, ds,
              num_boost_round=rounds, valid_sets=[ds, dt],
              valid_names=["training", "test"],
              callbacks=[lgb.record_evaluation(rec)])
    return _both(rec, "binary")


def _run_multiclass(rounds=15):
    X, y, _, _ = _data("multiclass_classification", "multiclass.train")
    Xt, yt, _, _ = _data("multiclass_classification", "multiclass.test")
    ds = lgb.Dataset(X, label=y)
    dt = lgb.Dataset(Xt, label=yt, reference=ds)
    rec = {}
    lgb.train({"objective": "multiclass", "num_class": 5, "num_leaves": 31,
               "learning_rate": 0.05, "metric": ["multi_logloss"],
               "verbose": -1}, ds, num_boost_round=rounds, valid_sets=[ds, dt],
              valid_names=["training", "test"],
              callbacks=[lgb.record_evaluation(rec)])
    return _both(rec, "multiclass")


def _run_lambdarank(rounds=15):
    X, y, _, grp = _data("lambdarank", "rank.train")
    Xt, yt, _, grpt = _data("lambdarank", "rank.test")
    ds = lgb.Dataset(X, label=y, group=grp)
    dt = lgb.Dataset(Xt, label=yt, group=grpt, reference=ds)
    rec = {}
    lgb.train({"objective": "lambdarank", "num_leaves": 31,
               "learning_rate": 0.1, "metric": ["ndcg"], "eval_at": [10],
               "verbose": -1}, ds, num_boost_round=rounds, valid_sets=[ds, dt],
              valid_names=["training", "test"],
              callbacks=[lgb.record_evaluation(rec)])
    return _both(rec, "lambdarank")


def _collect(scale=1.0):
    out = {}
    out.update(_run_binary(rounds=max(2, int(20 * scale))))
    out.update(_run_multiclass(rounds=max(2, int(15 * scale))))
    out.update(_run_lambdarank(rounds=max(2, int(15 * scale))))
    return out


def _check(got, full_length):
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert set(got) == set(golden), (sorted(got), sorted(golden))
    for key, want in golden.items():
        have = got[key]
        if full_length:
            assert len(have) == len(want), key
        want = want[:len(have)]
        diffs = np.abs(np.asarray(have) - np.asarray(want))
        assert float(diffs.max()) <= TOL, \
            "%s drifted: max |delta|=%.2e (tol %.0e)\nwant %s\ngot  %s" % (
                key, diffs.max(), TOL, want[:5], have[:5])


@pytest.mark.skipif(not os.path.exists(GOLDEN),
                    reason="golden_curves.json not recorded yet")
def test_metric_curve_prefixes_match_golden():
    """Fast gate: half-length trainings against the recorded prefixes."""
    _check(_collect(scale=0.5), full_length=False)


@pytest.mark.slow
@pytest.mark.skipif(not os.path.exists(GOLDEN),
                    reason="golden_curves.json not recorded yet")
def test_metric_curves_match_golden():
    _check(_collect(), full_length=True)


if __name__ == "__main__":
    import sys
    if "--regen" in sys.argv:
        curves = _collect()
        with open(GOLDEN, "w") as f:
            json.dump(curves, f, indent=1)
        print("wrote", GOLDEN, "with", len(curves), "curves")
