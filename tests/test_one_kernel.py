"""One-kernel split (tpu_split_kernel): parity with the three-launch oracle.

The fused one-kernel split (ops/partition.py one_kernel_split_planes,
ISSUE 13) runs partition + smaller-child histogram + split scan as three
sequential phases of ONE pallas_call. The contract is BIT-IDENTICAL trees
to the retained three-launch chain (partition kernel, segment histogram,
node_best_pair scan) — same routed bytes, same f32 chunk accumulation
order, same find_best_split arithmetic. These tests pin that contract
under the pallas interpreter on CPU (incl. NaN/missing-direction,
categorical, multiclass and GOSS-masked gradients), pin the telemetry
launch accounting (exactly one launch per split) and extend the
test_retrace.py zero-recompile discipline to the fused path.
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs  # noqa: E402
from lightgbm_tpu.ops import partition as P  # noqa: E402
from lightgbm_tpu.ops.histogram import hist16_segment_planes  # noqa: E402
from lightgbm_tpu.ops.split import (FeatureMeta, SplitHyper,  # noqa: E402
                                    find_best_split)

CH = 256

BASE = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
        "tree_builder": "partition", "verbosity": -1, "min_data_in_leaf": 2,
        "tpu_work_layout": "planes", "tpu_partition_kernel": "pallas",
        "tpu_part_chunk": CH, "tpu_hist_chunk": CH, "tpu_iter_block": 2}


# --------------------------------------------------------------- op level

def test_op_parity_interpret(rng, monkeypatch):
    """Jitted one_kernel_split_planes vs the jitted three-launch chain on
    the same packed planes buffer: identical routed work bytes, lt, child
    histograms and every SplitInfo field, bit for bit."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n, f, num_bin = 1500, 20, 32
    guard = CH + 2 * P.PLANE_ALIGN
    bins = jnp.asarray(rng.randint(0, num_bin, (n, f)).astype(np.uint8))
    ghc = rng.randn(n, 3).astype(np.float32)
    ghc[:, 2] = 1.0
    ghc = jnp.asarray(ghc)
    npad = P.planes_npad(n, guard, "pallas")
    _, w_pl = P.work_spec(f, False, "pallas", CH, CH, layout="planes")
    work = jnp.zeros((2, w_pl, npad), jnp.uint8)
    work, root = P.pack_planes_fold_root(work, bins, ghc, guard,
                                         num_bins=num_bin, exact=True,
                                         chunk=CH)
    meta = FeatureMeta(
        num_bins=jnp.full((f,), num_bin, jnp.int32),
        movable_missing=jnp.zeros((f,), bool),
        missing_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        monotone=jnp.zeros((f,), jnp.int8),
        penalty=jnp.ones((f,), jnp.float32),
        cegb_coupled=jnp.zeros((f,), jnp.float32))
    hp = SplitHyper(min_data_in_leaf=2.0)
    fmask = jnp.ones((f,), bool)
    root_sum = jnp.sum(ghc, axis=0)
    info0 = find_best_split(root, root_sum, meta, fmask, hp)
    ls = info0.left_sum[2] <= info0.right_sum[2]
    sums2 = jnp.stack([info0.left_sum, info0.right_sum])
    outs2 = jnp.zeros((2,), jnp.float32)
    lows2 = jnp.full((2,), -jnp.inf, jnp.float32)
    ups2 = jnp.full((2,), jnp.inf, jnp.float32)
    depth = jnp.int32(1)
    scan = jax.vmap(lambda hg, tg, po, lo, up: find_best_split(
        hg, tg, meta, fmask, hp, parent_output=po, leaf_lower=lo,
        leaf_upper=up, node_depth=depth))

    @jax.jit
    def oracle(work):
        w, lt = P.partition_segment_planes_fused(
            work, jnp.int32(0), jnp.int32(guard), jnp.int32(n),
            info0.feature, info0.go_left, ch=CH)
        ss = jnp.where(ls, jnp.int32(guard), jnp.int32(guard) + lt)
        sc = jnp.where(ls, lt, jnp.int32(n) - lt)
        hs = hist16_segment_planes(w, jnp.int32(1), ss, sc,
                                   num_bins=num_bin, num_feat=f, chunk=CH)
        hlg = root - hs
        hl = jnp.where(ls, hs, hlg)
        hr = jnp.where(ls, hlg, hs)
        return w, lt, hl, hr, scan(jnp.stack([hl, hr]), sums2, outs2,
                                   lows2, ups2)

    @jax.jit
    def fused(work):
        return P.one_kernel_split_planes(
            work, jnp.int32(0), jnp.int32(guard), jnp.int32(n),
            info0.feature, info0.go_left, ls, depth, root, meta, fmask,
            sums2, outs2, lows2, ups2, hp, num_bins=num_bin, num_feat=f,
            ch=CH, hist_chunk=CH)

    w_o, lt_o, hl_o, hr_o, infos_o = oracle(work)
    w_k, lt_k, hl_k, hr_k, infos_k = fused(work)
    assert int(lt_k) == int(lt_o)
    assert np.array_equal(np.asarray(w_k), np.asarray(w_o))
    assert np.array_equal(np.asarray(hl_k).view(np.uint8),
                          np.asarray(hl_o).view(np.uint8))
    assert np.array_equal(np.asarray(hr_k).view(np.uint8),
                          np.asarray(hr_o).view(np.uint8))
    for fld in infos_o._fields:
        a, b = np.asarray(getattr(infos_o, fld)), \
            np.asarray(getattr(infos_k, fld))
        assert np.array_equal(a.view(np.uint8) if a.dtype.kind == "f"
                              else a,
                              b.view(np.uint8) if b.dtype.kind == "f"
                              else b), fld


def test_op_validations():
    work = jnp.zeros((2, 40, 1280), jnp.uint8)   # 40 planes: not 32-mult
    args = (jnp.int32(0), jnp.int32(0), jnp.int32(64), jnp.int32(0),
            jnp.zeros((16,), bool), jnp.bool_(True), jnp.int32(1),
            jnp.zeros((6, 16, 3), jnp.float32))
    meta = FeatureMeta(
        num_bins=jnp.full((6,), 16, jnp.int32),
        movable_missing=jnp.zeros((6,), bool),
        missing_bin=jnp.zeros((6,), jnp.int32),
        is_categorical=jnp.zeros((6,), bool),
        monotone=jnp.zeros((6,), jnp.int8),
        penalty=jnp.ones((6,), jnp.float32),
        cegb_coupled=jnp.zeros((6,), jnp.float32))
    tail = (meta, jnp.ones((6,), bool), jnp.zeros((2, 3), jnp.float32),
            jnp.zeros((2,), jnp.float32),
            jnp.full((2,), -jnp.inf, jnp.float32),
            jnp.full((2,), jnp.inf, jnp.float32), SplitHyper())
    with pytest.raises(ValueError, match="32-sublane"):
        P.one_kernel_split_planes(work, *args, *tail, num_bins=16,
                                  num_feat=6, ch=256, hist_chunk=256)
    work = jnp.zeros((2, 64, 1280), jnp.uint8)
    with pytest.raises(ValueError, match="hist_chunk"):
        P.one_kernel_split_planes(work, *args, *tail, num_bins=16,
                                  num_feat=6, ch=256, hist_chunk=100)


# ------------------------------------------------------------ tree parity

def _train_tree(split_kernel, n, f, leaves, resident=False, seed=0):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    params = dict(BASE, num_leaves=leaves, tpu_split_kernel=split_kernel,
                  tpu_resident_state="on" if resident else "off")
    cfg = Config.from_params(params)
    ds = construct_dataset(X, cfg, label=y)
    lrn = SerialTreeLearner(cfg, ds)
    kw = lrn.build_kwargs()
    assert kw["split_kernel"] == split_kernel
    assert kw["work_layout"] == ("resident" if resident else "planes")
    ghc = jnp.stack([jnp.asarray(g), jnp.asarray(h),
                     jnp.ones(n, jnp.float32)], axis=1)
    return jax.device_get(
        lrn.train(ghc, jnp.ones(ds.num_features, bool),
                  jax.random.PRNGKey(0)))


_FIELDS = ("split_leaf", "feature", "bin", "kind", "default_left", "gain",
           "left_sum", "right_sum", "go_left", "leaf_value", "leaf_sum",
           "row_leaf")


# N deliberately NOT a multiple of the 256-row chunks; leaves=2 covers the
# single-split tree, 15 a deep leaf-wise one; interpret mode is slow so the
# grid stays small (the full-train suite below covers more structure)
@pytest.mark.parametrize("n,f,leaves,resident", [
    (1501, 20, 15, False), (1101, 16, 7, True)])
def test_tree_parity_one_kernel(n, f, leaves, resident, monkeypatch):
    monkeypatch.setattr(P, "_INTERPRET", True)
    a = _train_tree("off", n, f, leaves, resident=resident)
    b = _train_tree("on", n, f, leaves, resident=resident)
    assert int(a.num_splits) == int(b.num_splits)
    for fld in _FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, fld)), np.asarray(getattr(b, fld)),
            err_msg=fld)


# ----------------------------------------------------- full-train parity

def _model(params, X, y, rounds=2, **dskw):
    ds = lgb.Dataset(X, label=y, params=dict(params), **dskw)
    bst = lgb.train(dict(params), ds, num_boost_round=rounds)
    return bst.model_to_string()


def _ab_models(extra, X, y, rounds=2, **dskw):
    on = dict(BASE, tpu_split_kernel="on", **extra)
    off = dict(BASE, tpu_split_kernel="off", **extra)
    return (_model(on, X, y, rounds, **dskw),
            _model(off, X, y, rounds, **dskw))


def test_train_parity_nan_missing(rng, monkeypatch):
    """NaN features exercise the missing-direction (default_left) scan
    logic; model strings must match byte for byte."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 6)
    X[rng.rand(n, 6) < 0.2] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.2 * rng.randn(n) > 0).astype(np.float64)
    on, off = _ab_models({"use_missing": True}, X, y)
    assert on == off


def test_train_parity_categorical(rng, monkeypatch):
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 5)
    X[:, 0] = rng.randint(0, 12, n)
    y = ((X[:, 0] % 3 == 0) ^ (X[:, 1] > 0)).astype(np.float64)
    on, off = _ab_models({"min_data_per_group": 5}, X, y,
                         categorical_feature=[0])
    assert on == off


def test_train_parity_multiclass(rng, monkeypatch):
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 6)
    y = (np.abs(X[:, 0]) + X[:, 1] > 0.5).astype(np.float64) \
        + (X[:, 2] > 0.3)
    on, off = _ab_models({"objective": "multiclass", "num_class": 3}, X, y,
                         rounds=1)
    assert on == off


def test_train_parity_goss(rng, monkeypatch):
    """GOSS masks gradients but still streams all rows — the fused kernel
    must reproduce the masked-gradient histograms bit for bit."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 700
    X = rng.randn(n, 6)
    y = (X @ rng.randn(6) > 0).astype(np.float64)
    on, off = _ab_models({"data_sample_strategy": "goss", "top_rate": 0.3,
                          "other_rate": 0.2}, X, y)
    assert on == off


# --------------------------------------------------- telemetry + retrace

def test_telemetry_one_launch_per_split(rng, monkeypatch):
    """Acceptance pin: the one-kernel path reports exactly ONE kernel
    launch per split — partition_launches == splits, hist_launches == 0
    (the root folds into the planes pack), scan_launches == 0."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 600
    X = rng.randn(n, 6)
    y = (X @ rng.randn(6) > 0).astype(np.float64)
    params = dict(BASE, tpu_split_kernel="on")
    ds = lgb.Dataset(X, label=y, params=dict(params))
    obs.telemetry.reset()
    bst = lgb.train(dict(params), ds, num_boost_round=1)
    snap = bst.telemetry()
    c = snap["counters"]
    assert c["tree/splits"] > 0
    assert c["learner/partition_launches"] == c["tree/splits"]
    assert c.get("learner/hist_launches", 0) == 0
    assert c.get("learner/scan_launches", 0) == 0
    assert snap["gauges"]["learner/launches_per_split"] == 1
    # and the oracle path still reports 3 per split
    params = dict(BASE, tpu_split_kernel="off")
    ds = lgb.Dataset(X, label=y, params=dict(params))
    obs.telemetry.reset()
    bst = lgb.train(dict(params), ds, num_boost_round=1)
    snap = bst.telemetry()
    c = snap["counters"]
    assert c["learner/hist_launches"] == c["tree/splits"]
    assert c["learner/scan_launches"] == c["tree/splits"]
    assert snap["gauges"]["learner/launches_per_split"] == 3


def test_second_identical_train_compiles_nothing(rng, monkeypatch):
    """test_retrace.py discipline on the one-kernel path: a second train at
    identical shapes/config hits every jit cache — zero new compiles."""
    monkeypatch.setattr(P, "_INTERPRET", True)
    n = 520                      # shape distinct from other test modules
    X = rng.randn(n, 7)
    y = (X @ rng.randn(7) > 0).astype(np.float64)
    params = dict(BASE, tpu_split_kernel="on")
    ds = lgb.Dataset(X, label=y, params=dict(params))
    lgb.train(dict(params), ds, num_boost_round=2)   # warm every cache
    obs.telemetry.reset()
    bst = lgb.train(dict(params), ds, num_boost_round=2)
    jc = bst.telemetry()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc


# ------------------------------------------------------------ knob gates

def test_config_rejects_bad_split_kernel():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError, match="tpu_split_kernel"):
        Config.from_params({"tpu_split_kernel": "maybe"})


def test_auto_resolves_off_with_record(rng):
    """auto stays off everywhere until the kernel is validated on real
    Mosaic; the resolution is recorded like the other six auto knobs."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    cfg = Config.from_params({"objective": "binary", "num_leaves": 4,
                              "max_bin": 15, "tree_builder": "partition",
                              "verbosity": -1})
    ds = construct_dataset(X, cfg, label=y)
    obs.telemetry.reset()
    kw = SerialTreeLearner(cfg, ds).build_kwargs()
    assert kw["split_kernel"] == "off"
    recs = obs.telemetry.snapshot()["records"]["auto_resolution"]
    mine = [r for r in recs if r["knob"] == "tpu_split_kernel"]
    assert len(mine) == 1
    assert mine[0]["value"] == "off"
    assert "split_bisect" in mine[0]["reason"]


def test_ineligible_on_downgrades_to_off(rng):
    """Forcing on where the structure can't support it warns and falls
    back to the three-launch path instead of failing the train."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    # rows layout: no planes partition stream to fuse into
    cfg = Config.from_params({"objective": "binary", "num_leaves": 4,
                              "max_bin": 15, "tree_builder": "partition",
                              "verbosity": -1, "tpu_work_layout": "rows",
                              "tpu_split_kernel": "on"})
    ds = construct_dataset(X, cfg, label=y)
    kw = SerialTreeLearner(cfg, ds).build_kwargs()
    assert kw["split_kernel"] == "off"
    # CEGB is a scan-input the kernel does not carry
    cfg = Config.from_params(dict(BASE, num_leaves=4, max_bin=15,
                                  tpu_split_kernel="on",
                                  cegb_penalty_split=0.1))
    ds = construct_dataset(X, cfg, label=y)
    kw = SerialTreeLearner(cfg, ds).build_kwargs()
    assert kw["split_kernel"] == "off"


def test_builder_rejects_ineligible_on():
    """build_tree_partitioned itself re-validates (defense in depth for
    direct callers bypassing the learner gate)."""
    from lightgbm_tpu.learner import Comm, build_tree_partitioned
    from lightgbm_tpu.ops.split import SplitHyper

    f = 4
    meta = FeatureMeta(
        num_bins=jnp.full((f,), 8, jnp.int32),
        movable_missing=jnp.zeros((f,), bool),
        missing_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        monotone=jnp.zeros((f,), jnp.int8),
        penalty=jnp.ones((f,), jnp.float32),
        cegb_coupled=jnp.zeros((f,), jnp.float32))
    with pytest.raises(ValueError, match="not eligible"):
        build_tree_partitioned(
            jnp.zeros((64, f), jnp.uint8), jnp.zeros((64, 3), jnp.float32),
            meta, jnp.ones((f,), bool), jax.random.PRNGKey(0),
            jnp.zeros((f,), bool), SplitHyper(), num_leaves=4, num_bin=8,
            comm=Comm(), split_kernel="on", work_layout="rows")


def test_traffic_spec_launches(rng):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)

    def spec(sk):
        cfg = Config.from_params(dict(BASE, num_leaves=4, max_bin=15,
                                      tpu_split_kernel=sk))
        ds = construct_dataset(X, cfg, label=y)
        return SerialTreeLearner(cfg, ds).traffic_spec()

    assert spec("off")["launches_per_split"] == 3
    on = spec("on")
    assert on["split_kernel"] == "on"
    assert on["launches_per_split"] == 1
