"""Partitioned learner building blocks: ops/partition.py + hist16_segment.

Mirrors the reference's implicit DataPartition contract (reference:
src/treelearner/data_partition.hpp Split): after a split, the parent's rows
are exactly the union of the two children's contiguous segments, left rows
in stable order.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.partition import (
    DEFAULT_CH, guard_rows, pack_rows, partition_segment, unpack_ghc)
from lightgbm_tpu.ops.histogram import hist16_segment

CH = 256  # small chunk so multi-chunk paths are exercised at test sizes
G = guard_rows(CH)


def _mk(rng, n, f=6, num_bin=32):
    npad = n + 2 * G
    bins = np.zeros((npad, f), np.uint8)
    bins[G:G + n] = rng.randint(0, num_bin, (n, f))
    ghc = np.zeros((npad, 3), np.float32)
    ghc[G:G + n] = rng.randn(n, 3)
    ghc[G:G + n, 2] = 1.0
    work0 = np.asarray(pack_rows(jnp.asarray(bins), jnp.asarray(ghc)))
    work = jnp.stack([jnp.asarray(work0), jnp.zeros_like(jnp.asarray(work0))])
    return bins, ghc, work0, work


@pytest.mark.parametrize("n,start,cnt", [(1000, 0, 1000), (1000, 137, 700),
                                         (300, 10, 100), (700, 100, 550)])
def test_partition_segment(rng, n, start, cnt):
    num_bin = 32
    bins, ghc, work0, work = _mk(rng, n, num_bin=num_bin)
    table = rng.rand(num_bin) < 0.45
    feat = 3
    out, lt = partition_segment(work, jnp.int32(0), jnp.int32(G + start),
                                jnp.int32(cnt), jnp.int32(feat),
                                jnp.asarray(table), ch=CH)
    out, lt = np.asarray(out), int(lt)
    seg = work0[G + start:G + start + cnt]
    go = table[seg[:, feat]]
    assert lt == int(go.sum())
    got = out[1, G + start:G + start + cnt]          # children land in plane 1
    # left child: stable order; right child: same rows, any order
    assert np.array_equal(got[:lt], seg[go])
    assert sorted(map(bytes, got[lt:])) == sorted(map(bytes, seg[~go]))
    # everything outside the segment in the target plane is untouched (zeros)
    assert not np.any(out[1, :G + start - CH])


def test_partition_preserves_channels(rng):
    n = 500
    bins, ghc, work0, work = _mk(rng, n)
    table = rng.rand(32) < 0.5
    out, lt = partition_segment(work, jnp.int32(0), jnp.int32(G),
                                jnp.int32(n), jnp.int32(0),
                                jnp.asarray(table), ch=CH)
    got = np.asarray(unpack_ghc(jnp.asarray(np.asarray(out)[1, G:G + n]), 6))
    seg_g = ghc[G:G + n]
    go = table[bins[G:G + n, 0]]
    exp = np.concatenate([seg_g[go], seg_g[~go]])
    # rows are bit-exact through the compaction matmul (byte payloads)
    assert np.array_equal(np.sort(got, axis=0), np.sort(exp, axis=0))
    assert np.allclose(got[:lt], seg_g[go])


@pytest.mark.parametrize("num_bin,exact", [(32, True), (256, True), (17, False)])
def test_hist16_segment(rng, num_bin, exact):
    n, f = 900, 5
    bins, ghc, work0, work = _mk(rng, n, f=f, num_bin=num_bin)
    start, cnt = 57, 700
    out = np.asarray(hist16_segment(
        work, jnp.int32(0), jnp.int32(G + start), jnp.int32(cnt),
        num_bins=num_bin, num_feat=f, exact=exact, chunk=CH))
    seg_b = bins[G + start:G + start + cnt]
    seg_g = ghc[G + start:G + start + cnt]
    ref = np.zeros((f, num_bin, 3), np.float64)
    for ff in range(f):
        for ch in range(3):
            ref[ff, :, ch] = np.bincount(seg_b[:, ff],
                                         weights=seg_g[:, ch].astype(np.float64),
                                         minlength=num_bin)
    tol = 1e-4 if exact else 2e-2
    scale = max(1.0, np.abs(ref).max())
    assert np.abs(out - ref).max() / scale < tol


def test_builders_agree_first_tree(rng):
    """Dense (O(N) masked) and partitioned builders grow the same tree."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import construct_dataset
    from lightgbm_tpu.learner import SerialTreeLearner

    n, f = 1200, 6
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    logs = {}
    for builder in ("dense", "partition"):
        cfg = Config.from_params({
            "objective": "binary", "num_leaves": 8, "max_bin": 31,
            "tree_builder": builder, "tpu_part_chunk": CH,
            "tpu_hist_chunk": CH, "min_data_in_leaf": 5, "verbosity": -1})
        ds = construct_dataset(X, cfg, label=y)
        lrn = SerialTreeLearner(cfg, ds)
        ghc = jnp.stack([jnp.asarray(g), jnp.asarray(h),
                         jnp.ones(n, jnp.float32)], axis=1)
        log = lrn.train(ghc, jnp.ones(ds.num_features, bool),
                        jax.random.PRNGKey(0))
        logs[builder] = jax.device_get(log)
    a, b = logs["dense"], logs["partition"]
    assert a.num_splits == b.num_splits
    np.testing.assert_array_equal(a.split_leaf, b.split_leaf)
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.bin, b.bin)
    np.testing.assert_array_equal(a.row_leaf, b.row_leaf)
    np.testing.assert_allclose(a.leaf_value, b.leaf_value, rtol=2e-3,
                               atol=1e-5)


def test_zero_as_missing_predict_parity(rng):
    """Training-time routing and all prediction paths must agree on
    zero_as_missing models (reference: tree.h NumericalDecision
    MissingType::Zero -> default direction for zeros)."""
    import lightgbm_tpu as lgb

    n, f = 1500, 3
    X = rng.randn(n, f)
    X[rng.rand(n) < 0.4, 0] = 0.0
    y = ((X[:, 0] != 0) * 1.0 + X[:, 1] > 0.5).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "zero_as_missing": True, "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    train_raw = np.asarray(bst.inner.train_score.score)
    pred_raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(train_raw, pred_raw, atol=1e-4)
    # text round-trip keeps routing identical
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst2.predict(X, raw_score=True), pred_raw,
                               atol=1e-4)
