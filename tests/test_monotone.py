"""Monotone constraint methods: soundness and quality ordering.

Reference semantics: all three methods GUARANTEE monotone predictions;
basic is the most constraining (split midpoint bounds), intermediate and
advanced are progressively less constraining and so fit no worse
(reference: monotone_constraints.hpp:327 Basic, :463 Intermediate,
:856 AdvancedLeafConstraints; docs/Parameters.rst monotone_constraints_method).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Booster


def _data(seed=7, n=5000):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 4)
    y = 1.6 * X[:, 0] - 1.1 * X[:, 1] + np.sin(X[:, 2] * 6) * X[:, 3] \
        + 0.12 * rng.randn(n)
    return X, y


def _train(X, y, method, rounds=15):
    return lgb.train({"objective": "regression", "num_leaves": 63,
                      "verbosity": -1,
                      "monotone_constraints": [1, -1, 0, 0],
                      "monotone_constraints_method": method,
                      "tpu_iter_block": 5},
                     lgb.Dataset(X, label=y), num_boost_round=rounds)


def _worst_slope(bst, feature, sign, reps=25, seed=3):
    rng = np.random.RandomState(seed)
    grid = np.linspace(0.01, 0.99, 50)
    worst = 0.0
    for _ in range(reps):
        pts = np.tile(rng.rand(4), (50, 1))
        pts[:, feature] = grid
        p = bst.predict(pts) * sign
        worst = min(worst, float(np.diff(p).min()))
    return worst


# tier-1 keeps one soundness train (basic); the heavier methods ride the
# full run — each is a ~2 min multi-tree training on this one-core host
@pytest.mark.parametrize("method", [
    "basic",
    pytest.param("intermediate", marks=pytest.mark.slow),
    pytest.param("advanced", marks=pytest.mark.slow),
])
def test_monotone_soundness(method):
    X, y = _data()
    bst = _train(X, y, method)
    # feature 0 increasing, feature 1 decreasing — no violated slope anywhere
    assert _worst_slope(bst, 0, +1) >= -1e-7
    assert _worst_slope(bst, 1, -1) >= -1e-7


@pytest.mark.slow  # three full trainings; quality comparison, not a parity pin
def test_method_quality_ordering():
    X, y = _data()
    l2 = {}
    for m in ("basic", "intermediate", "advanced"):
        bst = _train(X, y, m)
        l2[m] = float(np.mean((bst.predict(X) - y) ** 2))
    # less-constraining methods fit at least as well (small slack for f32)
    assert l2["intermediate"] <= l2["basic"] * 1.02
    assert l2["advanced"] <= l2["basic"] * 1.02


def test_advanced_enabled_no_downgrade():
    X, y = _data(n=1200)
    b = Booster(params={"objective": "regression", "num_leaves": 15,
                        "verbosity": -1,
                        "monotone_constraints": [1, 0, 0, 0],
                        "monotone_constraints_method": "advanced"},
                train_set=lgb.Dataset(X, label=y))
    hp = b.inner.learner.hp
    assert hp.mono_advanced and hp.has_monotone


@pytest.mark.slow  # two full trainings; quality comparison, not a parity pin
def test_advanced_beats_intermediate_on_restricted_neighbor():
    """The reference's motivating case for advanced constraints
    (monotone_constraints.hpp:856): a neighbor's bound applies only to part
    of a leaf's range along a FREE feature (the neighbor is itself split on
    it). intermediate collapses the bound to a whole-leaf scalar and
    over-clamps; advanced keeps it per-threshold and fits strictly better.

    Construction: x0 monotone increasing, x2 free, four cells
    (a=5, b=2 | c=9, d=4.5) with P(x2 < 0.5) = 0.1 so the x0 root split
    wins the gain race while the bite margin a - d = 0.5 > 0 makes
    intermediate clamp the (x0 < 0.6, x2 < 0.5) cell from 5 to 4.5."""
    rng = np.random.RandomState(5)
    n = 2000
    x0 = rng.rand(n)
    x2 = np.where(rng.rand(n) > 0.1, 0.6 + rng.rand(n) * 0.35,
                  rng.rand(n) * 0.35)
    y = np.where(x0 >= 0.6, np.where(x2 < 0.5, 9.0, 4.5),
                 np.where(x2 < 0.5, 5.0, 2.0)) + 0.01 * rng.randn(n)
    X = np.stack([x0, x2], axis=1)
    mse = {}
    for m in ("intermediate", "advanced"):
        bst = lgb.train({"objective": "regression", "num_leaves": 4,
                         "max_bin": 63, "learning_rate": 1.0,
                         "verbosity": -1, "monotone_constraints": [1, 0],
                         "monotone_constraints_method": m,
                         "min_data_in_leaf": 5,
                         "tree_builder": "partition"},
                        lgb.Dataset(X, label=y), num_boost_round=1)
        pred = bst.predict(X)
        mse[m] = float(np.mean((pred - y) ** 2))
        # monotonicity in x0 must hold for both methods
        grid = np.linspace(0.01, 0.99, 50)
        for x2v in (0.2, 0.8):
            pts = np.stack([grid, np.full(50, x2v)], axis=1)
            assert float(np.diff(bst.predict(pts)).min()) >= -1e-7, m
    assert mse["advanced"] < mse["intermediate"] * 0.95, mse
