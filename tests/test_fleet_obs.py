"""Fleet observatory tests (ISSUE 15): cross-process trace correlation,
convergence-lag metrics and the federated fleet status plane.

The contracts under test: a replica poll running under serve tracing
carries its trace id over the HTTP transport as ``X-Trace-Id``, so the
trainer-side handler spans and the replica-side poll/swap spans share
ONE trace id across two processes (one merged Perfetto load, two
distinct process identities); every node — trainer, standby, replica,
local or remote — heartbeats a compact latest-wins summary into the
store, and one ``fleetctl status`` call against the trainer renders the
whole fleet (role, version, skew, publish->adopt lag) from a single
``GET /fleet/status``; and heartbeats are pure observability — they
never grow the event log, never perturb replay/compaction, and work on
read-only replica store opens.
"""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time
from urllib.error import HTTPError
from urllib.request import Request, urlopen

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.fleet import FleetStore, ReplicaWatcher  # noqa: E402
from lightgbm_tpu.fleet.transport import RemoteStore  # noqa: E402
from lightgbm_tpu.obs import telemetry  # noqa: E402
from lightgbm_tpu.obs_trace import TRACE_HEADER, tracer  # noqa: E402
from lightgbm_tpu.online import OnlineTrainer  # noqa: E402
from lightgbm_tpu.serve import PredictServer  # noqa: E402

from tests.conftest import clean_cpu_env  # noqa: E402

W = np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4])


@pytest.fixture(autouse=True)
def _tracer_reset():
    """Tests here flip the process-global tracer mode and identity; both
    must not leak into the rest of the suite."""
    yield
    tracer.configure("off")
    tracer.clear()
    tracer.set_identity(None, None)


def _data(n, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, len(W))
    y = (X @ W + 0.2 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _train(n=300, seed=0, rounds=6):
    X, y = _data(n, seed)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


def _request(url, obj=None, headers=None, timeout=30):
    """(status, response headers, parsed body) — non-2xx included."""
    data = json.dumps(obj).encode() if obj is not None else None
    hdrs = {"Content-Type": "application/json"} if obj is not None else {}
    hdrs.update(headers or {})
    req = Request(url, data=data, headers=hdrs)
    try:
        with urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except HTTPError as exc:
        return exc.code, dict(exc.headers), json.loads(exc.read())


def _start_server(server):
    th = threading.Thread(target=server.serve_forever,
                          name="fleet-obs-test-http", daemon=True)
    th.start()
    return th


def _fleetctl():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import fleetctl
    finally:
        sys.path.pop(0)
    return fleetctl


# ----------------------------------------------------- federated status plane

@pytest.mark.slow
def test_fleetctl_status_federates_trainer_and_replicas(tmp_path, capsys):
    """Acceptance e2e: trainer + 2 replicas (one over RemoteStore), one
    ``fleetctl status`` call reports per-node role, model version,
    version skew and publish->adopt lag."""
    fleetctl = _fleetctl()
    bst = _train()
    store = FleetStore(str(tmp_path), "default")
    store.publish(bst.model_to_string(), event="boot")

    trainer = OnlineTrainer(bst, trigger_rows=10**9, min_rows=64,
                            shadow_rows=10**6, promote_threshold=2.0,
                            promote_patience=2, store=store,
                            holder_id="trainer-1", start=False)
    server = PredictServer(_train(seed=1), port=0, warmup=False)
    server.fleet_store = store
    _start_server(server)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    try:
        # replica A: shared-filesystem store, replica-role read_only open
        bst_fs = lgb.Booster(model_str=_train(seed=2).model_to_string())
        w_fs = ReplicaWatcher(
            bst_fs, FleetStore(str(tmp_path), "default", read_only=True),
            node_id="replica-fs", start=False)
        # replica B: behind the HTTP transport
        bst_remote = lgb.Booster(model_str=_train(seed=3).model_to_string())
        w_remote = ReplicaWatcher(
            bst_remote, RemoteStore(base, timeout_s=10.0),
            node_id="replica-remote", start=False)
        assert w_fs.poll_once() and w_remote.poll_once()

        # every node beats once: trainer straight into the store, the
        # fs replica likewise, the remote replica POSTs over the wire
        assert trainer.maybe_heartbeat(force=True)
        assert w_fs.maybe_heartbeat(force=True)
        assert w_remote.maybe_heartbeat(force=True)

        doc = fleetctl.fetch_status(base)
        assert doc["head_version"] == 1
        assert doc["model_id"] == "default"
        nodes = {n["node"]: n for n in doc["nodes"]}
        assert set(nodes) == {"trainer-1", "replica-fs", "replica-remote"}
        assert nodes["trainer-1"]["role"] == "solo"   # no lease configured
        for name in ("replica-fs", "replica-remote"):
            n = nodes[name]
            assert n["role"] == "replica"
            assert n["version"] == 1 and n["skew"] == 0
            # publish->adopt lag measured off the publish event's ts
            assert n["lag_ms"]["last"] is not None
            assert 0.0 <= n["lag_ms"]["last"] < 60_000.0
            assert n["lag_ms"]["p50"] is not None
            assert n["consec_poll_errors"] == 0
            assert n["age_s"] >= 0.0
        # the rollup carries the store vitals fleetctl's header line shows
        assert doc["log_bytes"] > 0 and doc["compactions"] >= 0
        assert "lease" in doc

        # the rendered table names every node with its role and version
        lines = fleetctl.render_status(doc)
        text = "\n".join(lines)
        for fragment in ("trainer-1", "replica-fs", "replica-remote",
                         "solo", "replica"):
            assert fragment in text
        assert fleetctl.main(["status", base]) == 0
        assert fleetctl.main(["lag", base]) == 0
        assert fleetctl.main(["tail", base, "-n", "5"]) == 0
        out = capsys.readouterr().out
        assert "replica-remote" in out and "v" in out
    finally:
        server.close()
        trainer.close()


def test_fleet_status_and_heartbeat_routes(tmp_path):
    server = PredictServer(_train(), port=0, warmup=False)
    _start_server(server)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    try:
        # no store attached: both surfaces answer 404, not a crash
        code, _, body = _request(base + "/fleet/status")
        assert code == 404 and "error" in body
        code, _, _ = _request(base + "/fleet/heartbeat", {"node": "n1"})
        assert code == 404

        store = FleetStore(str(tmp_path), "default")
        server.fleet_store = store
        code, _, body = _request(base + "/fleet/status")
        assert code == 200 and body["nodes"] == []

        # federation intake: a remote node's POST lands in the store
        code, _, body = _request(base + "/fleet/heartbeat",
                                 {"node": "edge-1", "role": "replica",
                                  "version": 0})
        assert code == 200 and body == {"ok": True}
        assert [h["node"] for h in store.heartbeats()] == ["edge-1"]
        # and the rollup serves it back, skew computed server-side
        code, _, body = _request(base + "/fleet/status")
        assert code == 200
        assert body["nodes"][0]["node"] == "edge-1"
        assert body["nodes"][0]["skew"] == 0

        # a heartbeat without a node id is a client error
        code, _, _ = _request(base + "/fleet/heartbeat", {"role": "x"})
        assert code == 400
    finally:
        server.close()


def test_fleetctl_unreachable_exits_nonzero():
    fleetctl = _fleetctl()
    # nothing listens on a fresh ephemeral port 1: connection refused
    assert fleetctl.main(["status", "http://127.0.0.1:9",
                          "--timeout", "0.5"]) == 1


# --------------------------------------------------- cross-process tracing

_REPLICA_CHILD = textwrap.dedent("""
    import json, sys
    sys.path.insert(0, %(repo)r)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.fleet import ReplicaWatcher
    from lightgbm_tpu.fleet.transport import RemoteStore
    from lightgbm_tpu.obs_trace import tracer

    base, model_path, out_path = sys.argv[1:4]
    tracer.configure("serve_only")
    tracer.set_identity(role="replica", holder="replica-child")
    bst = lgb.Booster(model_file=model_path)
    w = ReplicaWatcher(bst, RemoteStore(base, timeout_s=30.0),
                       node_id="replica-child", start=False)
    assert w.poll_once(), "expected the child to adopt v1"
    assert w.maybe_heartbeat(force=True)
    with open(out_path, "w") as f:
        json.dump(tracer.chrome_trace(), f)
    print("ADOPTED", w.applied_version, flush=True)
""")


def _span_trace_ids(doc, name):
    return {ev["args"]["trace_id"] for ev in doc["traceEvents"]
            if ev.get("ph") == "X" and ev["name"] == name
            and "trace_id" in ev.get("args", {})}


def _process_meta(doc):
    names = [ev["args"]["name"] for ev in doc["traceEvents"]
             if ev.get("ph") == "M" and ev["name"] == "process_name"]
    assert len(names) == 1
    return names[0]


@pytest.mark.slow
def test_remote_adoption_is_one_trace_across_two_processes(tmp_path):
    """Acceptance: a Chrome/Perfetto export from a remote-replica
    adoption contains trainer-side and replica-side spans sharing one
    trace id, under two distinct process identities."""
    bst = _train()
    store = FleetStore(str(tmp_path), "default")
    store.publish(bst.model_to_string(), event="boot")
    base_model = str(tmp_path / "base.txt")
    _train(seed=4).save_model(base_model)

    tracer.configure("serve_only")
    tracer.clear()
    tracer.set_identity(role="trainer", holder="trainer-parent")
    server = PredictServer(_train(seed=1), port=0, warmup=False)
    server.fleet_store = store
    _start_server(server)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    out_path = str(tmp_path / "replica_trace.json")
    script = tmp_path / "replica_child.py"
    script.write_text(_REPLICA_CHILD % {"repo": REPO})
    try:
        proc = subprocess.run(
            [sys.executable, str(script), base, base_model, out_path],
            env=clean_cpu_env(4), capture_output=True, text=True,
            timeout=600)
        assert "ADOPTED 1" in proc.stdout, (proc.stdout, proc.stderr)
        doc_trainer = tracer.chrome_trace()
        with open(out_path, encoding="utf-8") as f:
            doc_replica = json.load(f)

        # the replica's poll id crossed the wire: the trainer handler
        # spans for /fleet/* carry the SAME trace id
        poll_ids = _span_trace_ids(doc_replica, "fleet/replica_poll")
        serve_ids = _span_trace_ids(doc_trainer, "serve/fleet_request")
        assert len(poll_ids) == 1
        shared = poll_ids & serve_ids
        assert shared, (poll_ids, serve_ids)
        # the swap span nested under the poll inherits the id too
        assert _span_trace_ids(doc_replica, "fleet/replica_swap") == poll_ids
        # a poll drives several transport requests (latest + artifact
        # fetch at minimum) — all joined under the one trace
        trainer_spans = [ev for ev in doc_trainer["traceEvents"]
                         if ev.get("ph") == "X"
                         and ev["name"] == "serve/fleet_request"
                         and ev.get("args", {}).get("trace_id")
                         in shared]
        assert len(trainer_spans) >= 2

        # two processes, two identities: distinct pids, distinct
        # process_name metas a merged Perfetto load keeps apart
        pids = {ev["pid"] for ev in trainer_spans}
        pids |= {ev["pid"] for ev in doc_replica["traceEvents"]
                 if ev.get("ph") == "X"}
        assert len(pids) == 2
        assert _process_meta(doc_trainer) == \
            "lightgbm-tpu [trainer trainer-parent]"
        assert _process_meta(doc_replica) == \
            "lightgbm-tpu [replica replica-child]"
        # pid-salted ids: the shared id encodes the CHILD's pid
        child_pid = (set(pids) - {os.getpid()}).pop()
        assert (next(iter(shared)) >> 40) == (child_pid & 0x3FFFFF)

        # federation rode along: the child's heartbeat POST landed
        assert [h["node"] for h in store.heartbeats()] == ["replica-child"]
    finally:
        server.close()


def test_predict_echoes_trace_id_header(tmp_path):
    server = PredictServer(_train(), port=0, warmup=False)
    _start_server(server)
    host, port = server.address
    url = "http://%s:%d/predict" % (host, port)
    X, _ = _data(4, seed=9)
    try:
        # tracing OFF: the echo still works (header-only correlation for
        # external clients) and records zero spans on the hot path
        assert not tracer.serve_on
        started0 = tracer.spans_started
        code, headers, body = _request(
            url, {"rows": X.tolist()}, headers={TRACE_HEADER: "424242"})
        assert code == 200 and len(body["predictions"]) == 4
        assert headers[TRACE_HEADER] == "424242"
        # no header sent: the server mints one and still echoes it
        code, headers, _ = _request(url, {"rows": X.tolist()})
        assert code == 200
        minted = int(headers[TRACE_HEADER])
        assert (minted >> 40) == (os.getpid() & 0x3FFFFF)
        assert tracer.spans_started == started0

        # tracing ON: the client's id is adopted by the request spans
        tracer.configure("serve_only")
        tracer.clear()
        code, headers, _ = _request(
            url, {"rows": X.tolist()}, headers={TRACE_HEADER: "7777"})
        assert code == 200 and headers[TRACE_HEADER] == "7777"
        assert any(sp.trace_id == 7777 for sp in tracer.events()), \
            [(sp.name, sp.trace_id) for sp in tracer.events()]
        # bad rows: the error response carries the echo too
        code, headers, body = _request(
            url, {"rows": [["oops"]]}, headers={TRACE_HEADER: "31337"})
        assert code == 400 and headers[TRACE_HEADER] == "31337"
    finally:
        server.close()


# -------------------------------------------------- /healthz adoption state

def test_healthz_surfaces_replica_adoption_state(tmp_path):
    bst_serving = _train(seed=1)
    store = FleetStore(str(tmp_path), "default")
    store.publish(_train().model_to_string(), event="boot")
    server = PredictServer(bst_serving, port=0, warmup=False)
    server.fleet_watcher = ReplicaWatcher(bst_serving, store,
                                          node_id="hz-replica", start=False)
    _start_server(server)
    host, port = server.address
    try:
        assert server.fleet_watcher.poll_once()
        code, _, doc = _request("http://%s:%d/healthz" % (host, port))
        assert code == 200
        fl = doc["fleet"]
        assert fl["node"] == "hz-replica" and fl["role"] == "replica"
        assert fl["applied_version"] == 1 and fl["head_version"] == 1
        assert fl["version_skew"] == 0
        assert fl["last_adopt_lag_ms"] is not None
        assert fl["last_adopt_lag_ms"] >= 0.0
        assert fl["consec_poll_errors"] == 0
        assert fl["poll_backoff_s"] == 0.0
        assert fl["heartbeats"] == {"interval_s": 0.0, "sent": 0,
                                    "errors": 0}
    finally:
        server.close()


@pytest.mark.slow
def test_watcher_convergence_metrics(tmp_path):
    """The lag histogram and skew gauge feed off real publish
    timestamps; consecutive-error tracking resets on success."""
    store = FleetStore(str(tmp_path), "default")
    bst = lgb.Booster(model_str=_train(seed=2).model_to_string())
    w = ReplicaWatcher(bst, store, node_id="m-replica", start=False)
    polls0 = telemetry.counter("fleet/replica_polls")
    store.publish(_train().model_to_string(), event="boot")
    store.publish(_train(seed=3, rounds=8).model_to_string())
    assert w.poll_once()                       # jumps straight to head v2
    assert telemetry.counter("fleet/replica_polls") == polls0 + 1
    snap = telemetry.snapshot(include_global_timer=False)
    assert snap["gauges"]["fleet/version_skew"] == 0
    hist = telemetry.histogram("fleet/publish_adopt_lag_ms")
    assert hist is not None and hist["count"] >= 1
    doc = w.heartbeat_doc()
    assert doc["version"] == 2 and doc["skew"] == 0
    assert doc["lag_ms"]["p50"] is not None
    assert doc["lag_ms"]["p99"] >= doc["lag_ms"]["p50"] >= 0.0


# --------------------------------------------------- heartbeat substrate

def test_heartbeats_never_grow_the_event_log(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    store.publish("model-one", event="boot")
    log_bytes = os.path.getsize(store.events_path)
    for i in range(50):
        assert store.record_heartbeat({"node": "n-a", "seq": i})
    assert store.record_heartbeat({"node": "n-b"})
    # latest-wins sidecars: O(nodes) files, the event log untouched
    assert os.path.getsize(store.events_path) == log_bytes
    assert store.state()["events_log_bytes"] == log_bytes
    hbs = store.heartbeats()
    assert [h["node"] for h in hbs] == ["n-a", "n-b"]
    assert hbs[0]["seq"] == 49                 # only the newest beat kept
    assert all("ts" in h for h in hbs)
    assert store.state()["heartbeat_nodes"] == 2
    # replay sees exactly the published events, none of the heartbeats
    fresh = FleetStore(str(tmp_path), "m")
    assert [e["kind"] for e in fresh.events()] == ["publish"]

    # age filtering drops nodes that stopped reporting
    time.sleep(0.05)
    assert store.heartbeats(max_age_s=0.01) == []
    assert len(store.heartbeats(max_age_s=60.0)) == 2

    # a node id is required; junk ids are sanitized into a filename
    assert not store.record_heartbeat({"role": "replica"})
    assert store.record_heartbeat({"node": "../../../evil node"})
    hb_dir = os.path.join(str(tmp_path), "m", "heartbeats")
    names = os.listdir(hb_dir)
    assert all("/" not in n and " " not in n for n in names)

    # a torn sidecar (crash mid-beat) is skipped, not fatal
    torn = os.path.join(hb_dir, "torn.json")
    with open(torn, "w", encoding="utf-8") as f:
        f.write('{"node": "to')
    assert [h["node"] for h in store.heartbeats(max_age_s=60.0)
            if h["node"] == "torn"] == []


def test_read_only_replica_store_can_heartbeat(tmp_path):
    FleetStore(str(tmp_path), "m").publish("model-one")
    ro = FleetStore(str(tmp_path), "m", read_only=True)
    # publishing is fenced off for replica-role opens...
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        ro.publish("nope")
    # ...but heartbeats are observability, not replicated state
    assert ro.record_heartbeat({"node": "ro-replica", "version": 1})
    assert [h["node"] for h in ro.heartbeats()] == ["ro-replica"]


# ----------------------------------------------------------- ledger rollup

def test_ledger_serve_entries_carry_fleet_identity(tmp_path, capsys):
    from lightgbm_tpu import obs_ledger
    from lightgbm_tpu.config import Config
    path = str(tmp_path / "ledger.jsonl")
    cfg = Config.from_params({"objective": "binary", "verbosity": -1,
                              "obs_ledger": True, "obs_ledger_path": path})
    extra = {"fleet": {"role": "standby", "holder": "host-a:123",
                       "lease_epoch": 7}}
    entry = obs_ledger.record_run(cfg, "serve", 0, 0, extra=extra)
    assert entry is not None and entry["extra"]["fleet"]["role"] == "standby"
    obs_ledger.record_run(cfg, "serve", 0, 0)      # a fleet-less serve run

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import ledger as ledger_cli
    finally:
        sys.path.pop(0)
    assert ledger_cli.main(["list", "--path", path]) == 0
    out = capsys.readouterr().out
    # the list view distinguishes trainer/standby/replica runs
    assert "standby@7 host-a:123" in out
    assert "fleet" in out.lower()                  # column header
