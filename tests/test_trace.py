"""Span tracer, flight recorder, Chrome trace export (ISSUE 7 tentpole).

Covers the SpanTracer unit surface (nesting, trace-id inheritance, ring
bounds, mode gating), the zero-cost-when-off guarantee pinned
compile-budget style (a whole train with tracing off starts ZERO spans),
the traced-code refusal (trace_phase inside a jit trace records nothing),
the Chrome trace-event JSON schema (ph/ts/dur/pid/tid + per-tid nesting
consistency, Perfetto-loadable), the serve span chain (one HTTP /predict
-> queue_wait/coalesce/batch/session_dispatch/slice_back under ONE trace
id), Booster.dump_trace, the SIGUSR2 dump hook and the periodic
telemetry dump thread, and the cli --dump-trace flag end to end.
"""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs_trace import (
    NULL_SPAN,
    SpanTracer,
    install_signal_handlers,
    start_periodic_telemetry_dump,
    tracer,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARAMS = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1}


@pytest.fixture(autouse=True)
def _global_tracer_off():
    """Tests that flip the module tracer must not leak mode into the rest
    of the suite (trace_spans is process-global, like verbosity)."""
    yield
    tracer.configure("off")
    tracer.clear()


def _data(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float64)
    return X, y


# ---------------------------------------------------------------- tracer unit

def test_span_nesting_and_trace_id_inheritance():
    t = SpanTracer().configure("on")
    with t.span("outer", trace_id=7, rows=3):
        with t.span("inner"):          # inherits 7 from the stack
            pass
    with t.span("sibling"):            # fresh stack: no id to inherit
        pass
    by_name = {sp.name: sp for sp in t.events()}
    assert set(by_name) == {"outer", "inner", "sibling"}
    assert by_name["inner"].trace_id == 7
    assert by_name["outer"].trace_id == 7
    assert by_name["outer"].args == {"rows": 3}
    assert by_name["sibling"].trace_id is None
    # inner closed first and fits inside outer
    assert by_name["inner"].dur <= by_name["outer"].dur
    assert all(sp.dur >= 0 for sp in t.events())


def test_ring_is_bounded_and_keeps_newest():
    t = SpanTracer(capacity=8).configure("on")
    for i in range(20):
        t.record("s%d" % i, 0.0, 0.001)
    names = [sp.name for sp in t.events()]
    assert names == ["s%d" % i for i in range(12, 20)]
    t.configure("on", capacity=4)      # shrink keeps the newest tail
    assert [sp.name for sp in t.events()] == ["s16", "s17", "s18", "s19"]


def test_configure_rejects_unknown_mode():
    with pytest.raises(ValueError):
        SpanTracer().configure("everything")


def test_mode_gating_and_shared_noop_identity():
    t = SpanTracer()                   # default off
    assert t.span("x") is NULL_SPAN
    assert t.span("x", domain="serve") is NULL_SPAN
    assert t.phase_begin("x") is None
    t.configure("serve_only")
    assert t.span("x") is NULL_SPAN            # train domain stays off
    assert t.phase_begin("x") is None
    with t.span("s", domain="serve"):
        pass
    assert [sp.name for sp in t.events()] == ["s"]
    t.configure("off")
    assert t.span("s", domain="serve") is NULL_SPAN


def test_trace_id_header_round_trip_and_identity():
    from lightgbm_tpu.obs_trace import (format_trace_id, parse_trace_id)
    t = SpanTracer().configure("on")
    # ids are pid-salted so merged multi-process exports never collide
    tid = t.new_trace_id()
    assert (tid >> 40) == (os.getpid() & 0x3FFFFF)
    # header wire format: decimal string there, int back
    assert parse_trace_id(format_trace_id(tid)) == tid
    assert parse_trace_id(None) is None
    assert parse_trace_id("   ") is None
    assert parse_trace_id("client-abc") == "client-abc"   # opaque ids pass
    # current_trace_id reads the innermost open span on THIS thread
    assert t.current_trace_id() is None
    with t.span("outer", trace_id=99):
        assert t.current_trace_id() == 99
        with t.span("inner"):
            assert t.current_trace_id() == 99
    assert t.current_trace_id() is None
    # process identity lands in the chrome process_name meta (and ONLY
    # there — the schema gains no new keys)
    t.set_identity(role="replica", holder="host-1:42")
    assert t.identity() == {"pid": os.getpid(), "role": "replica",
                            "holder": "host-1:42"}
    pname = [m["args"]["name"] for m in t.chrome_trace()["traceEvents"]
             if m["ph"] == "M" and m["name"] == "process_name"]
    assert pname == ["lightgbm-tpu [replica host-1:42]"]
    _assert_chrome_schema(t.chrome_trace())
    t.set_identity(None, None)
    pname = [m["args"]["name"] for m in t.chrome_trace()["traceEvents"]
             if m["ph"] == "M" and m["name"] == "process_name"]
    assert pname == ["lightgbm-tpu"]


def test_new_trace_ids_are_unique_across_threads():
    t = SpanTracer()
    got = []

    def take():
        got.extend(t.new_trace_id() for _ in range(50))

    threads = [threading.Thread(target=take, name="trace-id-%d" % i)
               for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(set(got)) == 200


# --------------------------------------------------------- zero-cost-when-off

def test_off_path_starts_zero_spans_during_train():
    """The compile-budget-style overhead pin: with trace_spans off
    (default), a full train through every trace_phase site must not
    start a single span or touch the recorder."""
    assert tracer.mode == "off"
    before = tracer.spans_started
    X, y = _data()
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=4)
    assert tracer.spans_started == before
    assert tracer.events() == []


def test_trace_phase_refuses_inside_jit_trace():
    """trace_phase sites living in traced code (learner/boosting) must
    not record trace-time spans — only eager host executions count."""
    import jax
    import jax.numpy as jnp

    tracer.configure("on")
    tracer.clear()

    @jax.jit
    def f(x):
        with obs.trace_phase("unit/traced_region"):
            return x * 2.0

    f(jnp.arange(4.0)).block_until_ready()     # traces + runs: no span
    assert "unit/traced_region" not in {sp.name for sp in tracer.events()}
    with obs.trace_phase("unit/traced_region"):    # eager: records
        pass
    assert "unit/traced_region" in {sp.name for sp in tracer.events()}


def test_span_end_feeds_phase_histogram():
    tracer.configure("on")
    obs.telemetry.reset()
    with tracer.span("unit/hist_feed"):
        pass
    h = obs.telemetry.histogram("span_ms/unit/hist_feed")
    assert h is not None and h["count"] == 1


# --------------------------------------------------------- chrome trace JSON

def _assert_chrome_schema(doc):
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    assert doc["displayTimeUnit"] == "ms"
    xs, metas = [], []
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert isinstance(ev["name"], str) and ev["name"]
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            xs.append(ev)
        else:
            assert ev["name"] in ("process_name", "thread_name")
            assert ev["args"]["name"]
            metas.append(ev)
    # every tid with spans has a thread_name metadata event
    named = {m["tid"] for m in metas if m["name"] == "thread_name"}
    assert {e["tid"] for e in xs} <= named
    # nesting consistency per tid: spans either nest or are disjoint —
    # partial overlap would render as garbage in Perfetto
    for tid in {e["tid"] for e in xs}:
        evs = sorted((e for e in xs if e["tid"] == tid),
                     key=lambda e: (e["ts"], -e["dur"]))
        eps = 0.5   # rounding slack, microseconds
        for a, b in zip(evs, evs[1:]):
            a_end = a["ts"] + a["dur"]
            assert (b["ts"] + eps >= a_end           # disjoint
                    or b["ts"] + b["dur"] <= a_end + eps), \
                "partial overlap %s / %s" % (a["name"], b["name"])
    return xs


def test_chrome_trace_schema_multi_thread(tmp_path):
    t = SpanTracer().configure("on")
    with t.span("main/outer"):
        with t.span("main/inner"):
            pass

    def worker():
        with t.span("worker/span", trace_id=t.new_trace_id()):
            pass

    th = threading.Thread(target=worker, name="trace-test-worker")
    th.start()
    th.join()
    doc = t.chrome_trace()
    xs = _assert_chrome_schema(doc)
    assert {e["name"] for e in xs} == {"main/outer", "main/inner",
                                       "worker/span"}
    assert len({e["tid"] for e in xs}) == 2
    thread_names = {m["args"]["name"] for m in doc["traceEvents"]
                    if m["ph"] == "M" and m["name"] == "thread_name"}
    assert "trace-test-worker" in thread_names
    # the whole document must survive a json round-trip on disk
    p = tmp_path / "trace.json"
    n = t.dump(str(p))
    assert n == len(json.loads(p.read_text())["traceEvents"])


def test_booster_dump_trace(tmp_path):
    X, y = _data(seed=1)
    tracer.clear()
    bst = lgb.train(dict(PARAMS, trace_spans="on"),
                    lgb.Dataset(X, label=y), num_boost_round=4)
    p = tmp_path / "train_trace.json"
    n = bst.dump_trace(str(p))
    doc = json.loads(p.read_text())
    assert n == len(doc["traceEvents"])
    xs = _assert_chrome_schema(doc)
    names = {e["name"] for e in xs}
    assert "lgbtpu/train_block" in names       # engine block span
    assert "lgbtpu/fused_dispatch" in names    # fused host-side span


# ------------------------------------------------------------- serve chain

SERVE_CHAIN = ("serve/http_request", "serve/queue_wait", "serve/coalesce",
               "serve/batch", "serve/session_dispatch", "serve/slice_back")


def test_one_served_request_yields_full_span_chain(tmp_path):
    from urllib.request import Request, urlopen
    from lightgbm_tpu.serve import PredictServer

    X, y = _data(seed=2)
    bst = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                    num_boost_round=6)
    server = PredictServer(bst, port=0, buckets=(64,), warmup=True,
                           max_wait_ms=1.0)
    tracer.configure("serve_only")     # after warmup: only the request
    tracer.clear()
    host, port = server.address
    th = threading.Thread(target=server.serve_forever, daemon=True,
                          name="trace-test-http")
    th.start()
    try:
        body = json.dumps({"rows": X[:3].tolist()}).encode()
        req = Request("http://%s:%d/predict" % (host, port), data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read())["rows"] == 3
    finally:
        server.shutdown()
        th.join(timeout=10)
        server.close()
    spans = tracer.events()
    by_name = {}
    for sp in spans:
        by_name.setdefault(sp.name, sp)
    assert set(SERVE_CHAIN) <= set(by_name), sorted(by_name)
    # the whole chain carries the request's trace id
    rid = by_name["serve/http_request"].trace_id
    assert rid is not None
    for name in SERVE_CHAIN:
        assert by_name[name].trace_id == rid, name
    # chain crosses threads: handler thread != batcher worker thread
    assert by_name["serve/http_request"].tid != by_name["serve/batch"].tid
    # and the export is schema-valid
    xs = _assert_chrome_schema(tracer.chrome_trace())
    assert set(SERVE_CHAIN) <= {e["name"] for e in xs}


# ------------------------------------------------------------ dump surfaces

def test_sigusr2_dumps_trace(tmp_path):
    if not hasattr(signal, "SIGUSR2"):
        pytest.skip("platform without SIGUSR2")
    tracer.configure("on")
    with tracer.span("unit/sig"):
        pass
    trace_path = tmp_path / "sig_trace.json"
    tele_path = tmp_path / "sig_tele.json"
    old2 = signal.getsignal(signal.SIGUSR2)
    old1 = signal.getsignal(signal.SIGUSR1)
    try:
        installed = install_signal_handlers(telemetry_path=str(tele_path),
                                            trace_path=str(trace_path))
        assert "SIGUSR2" in installed and "SIGUSR1" in installed
        os.kill(os.getpid(), signal.SIGUSR2)
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not (
                trace_path.exists() and tele_path.exists()):
            time.sleep(0.01)
        doc = json.loads(trace_path.read_text())
        assert "unit/sig" in {e["name"] for e in doc["traceEvents"]}
        assert "counters" in json.loads(tele_path.read_text())
    finally:
        signal.signal(signal.SIGUSR2, old2)
        signal.signal(signal.SIGUSR1, old1)


def test_periodic_telemetry_dump(tmp_path):
    p = tmp_path / "periodic.json"
    stop = start_periodic_telemetry_dump(str(p), 0.05)
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not p.exists():
            time.sleep(0.01)
        assert p.exists()
        assert "counters" in json.loads(p.read_text())
    finally:
        stop.set()


# -------------------------------------------------------------------- cli

def test_cli_dump_trace_flag(tmp_path):
    from lightgbm_tpu import cli
    from lightgbm_tpu.cli import parse_args

    p = parse_args(["--dump-trace", "/tmp/t.json", "task=train"])
    assert p["dump_trace"] == "/tmp/t.json"
    p = parse_args(["--dump-trace=/tmp/u.json"])
    assert p["dump_trace"] == "/tmp/u.json"

    X, y = _data(n=200, seed=3)
    data = tmp_path / "train.csv"
    np.savetxt(data, np.column_stack([y, X]), delimiter=",")
    out = tmp_path / "cli_trace.json"
    model = tmp_path / "model.txt"
    cli.main(["task=train", "data=%s" % data, "objective=binary",
              "num_leaves=4", "num_iterations=2", "verbosity=-1",
              "trace_spans=on", "output_model=%s" % model,
              "--dump-trace", str(out)])
    doc = json.loads(out.read_text())
    assert any(e["ph"] == "X" for e in doc["traceEvents"])
