"""Distributed tree learners on the 8-device CPU mesh.

The reference tests multi-node behavior with in-process Dask workers over
localhost sockets (reference: tests/python_package_test/test_dask.py:26);
here the analog is an 8-virtual-CPU-device ``jax.sharding.Mesh``. On axon
terminals (where the TPU backend grabs the process at interpreter start)
these tests are driven through a clean-environment subprocess by
``test_parallel_launcher``; elsewhere they run directly.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from tests.conftest import clean_cpu_env

DIRECT = os.environ.get("LGB_TPU_MESH_SUBPROCESS") == "1"


def _mesh_ready():
    import jax
    return jax.default_backend() == "cpu" and len(jax.devices()) >= 8


needs_mesh = pytest.mark.skipif(
    "not config.getoption('collectonly', False) and not _mesh_ready()",
    reason="needs 8 CPU devices (run via test_parallel_launcher on axon)")


def _problem(rng, n=4000, f=10):
    X = rng.randn(n, f)
    w = rng.randn(f)
    y = (X @ w + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _train(X, y, **overrides):
    import lightgbm_tpu as lgb
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "metric": ["auc"],
              "tpu_part_chunk": 256, "tpu_hist_chunk": 256}
    params.update(overrides)
    return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)


@needs_mesh
@pytest.mark.parametrize("kind", ["data", "feature", "voting"])
def test_parallel_matches_serial(rng, kind):
    """Each distributed learner must produce a parity-quality model
    (reference analog: test_dask.py accuracy-vs-local assertions)."""
    from lightgbm_tpu.parallel import mesh as pm

    X, y = _problem(rng)
    serial = _train(X, y)
    (_, _, auc_s, _), = serial.eval_train()
    dist = _train(X, y, tree_learner=kind)
    cls = {"data": pm.DataParallelTreeLearner,
           "feature": pm.FeatureParallelTreeLearner,
           "voting": pm.VotingParallelTreeLearner}[kind]
    assert isinstance(dist.inner.learner, cls)
    (_, _, auc_d, _), = dist.eval_train()
    assert auc_d > 0.9
    # data-parallel computes the same global histograms -> same trees up
    # to f32 reduction order; feature/voting may differ on near-ties
    tol = 0.005 if kind == "data" else 0.03
    assert abs(auc_d - auc_s) < tol
    ps = serial.predict(X[:500])
    pd = dist.predict(X[:500])
    assert np.corrcoef(ps, pd)[0, 1] > 0.97


@needs_mesh
def test_data_parallel_uneven_rows(rng):
    """Row counts that don't divide the mesh force padding rows, which must
    never leak into histograms or predictions."""
    X, y = _problem(rng, n=4001)
    bst = _train(X, y, tree_learner="data")
    pred = bst.predict(X)
    assert pred.shape == (4001,)
    assert np.isfinite(pred).all()
    (_, _, auc, _), = bst.eval_train()
    assert auc > 0.9


@needs_mesh
def test_data_parallel_goss(rng):
    """GOSS sampling composes with the sharded learner (reference:
    goss.hpp under tree_learner=data)."""
    X, y = _problem(rng, n=4800)
    bst = _train(X, y, tree_learner="data", data_sample_strategy="goss",
                 top_rate=0.3, other_rate=0.2, learning_rate=0.3)
    (_, _, auc, _), = bst.eval_train()
    assert auc > 0.85


@needs_mesh
def test_sharded_valid_eval(rng):
    """Valid-set scoring during sharded training matches raw predictions."""
    import lightgbm_tpu as lgb

    X, y = _problem(rng, n=4000)
    Xv, yv = X[3000:], y[3000:]
    dtr = lgb.Dataset(X[:3000], label=y[:3000])
    dva = lgb.Dataset(Xv, label=yv, reference=dtr)
    res = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "tree_learner": "data",
                     "metric": ["binary_logloss"], "tpu_part_chunk": 256,
                     "tpu_hist_chunk": 256},
                    dtr, num_boost_round=6, valid_sets=[dva],
                    valid_names=["va"], callbacks=[lgb.record_evaluation(res)])
    pred = bst.predict(Xv)
    eps = 1e-7
    ll = -np.mean(yv * np.log(pred + eps) + (1 - yv) * np.log(1 - pred + eps))
    assert abs(ll - res["va"]["binary_logloss"][-1]) < 1e-3


@needs_mesh
def test_voting_wide_features(rng):
    """Voting must stay accurate when F >> 2*top_k (its comm stays
    O(top_k*B) while data-parallel's grows with F)."""
    n, f = 3000, 60
    X = rng.randn(n, f)
    w = np.zeros(f)
    w[:5] = rng.randn(5) * 3
    y = (X @ w + 0.3 * rng.randn(n) > 0).astype(np.float64)
    bst = _train(X, y, tree_learner="voting", top_k=8)
    (_, _, auc, _), = bst.eval_train()
    assert auc > 0.9


@pytest.mark.slow
def test_parallel_launcher():
    """On axon terminals, run this module's mesh tests in a subprocess with
    a clean CPU environment (the in-process backend cannot be switched)."""
    if _mesh_ready() or DIRECT:
        pytest.skip("mesh available in-process; tests run directly")
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        pytest.skip("no axon env and no CPU mesh — nothing to launch")
    env = clean_cpu_env(8)
    env["LGB_TPU_MESH_SUBPROCESS"] = "1"
    r = subprocess.run(
        [sys.executable, "-m", "pytest", os.path.abspath(__file__),
         "-q", "-x", "--no-header", "-p", "no:cacheprovider"],
        env=env, capture_output=True, text=True, timeout=3000,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, \
        "mesh subprocess failed:\n%s\n%s" % (r.stdout[-3000:], r.stderr[-2000:])
