"""Every config parameter must either change behavior or warn explicitly.

VERDICT r2 missing #7: the round-1/2 bar was ZERO silently-ignored params.
This audit walks every Config field and requires it to be either
(a) referenced by implementation code outside config.py, or
(b) registered in config.NOOP_PARAMS, whose entries warn with a reason
    when set to a non-default value.
"""
import ast
import dataclasses
import os
import re

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config, NOOP_PARAMS

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "lightgbm_tpu")


def _iter_sources():
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in files:
            if f.endswith((".py", ".cpp")) and f != "config.py":
                with open(os.path.join(root, f)) as fh:
                    yield f, fh.read()


def _consumed_names() -> set:
    """Parameter names the implementation actually READS: attribute
    accesses (cfg.<name>), subscript/string keys ("<name>"), and keyword
    arguments — via the AST, so a comment mentioning a parameter no longer
    counts as consumption (VERDICT r3 weak #9)."""
    names = set()
    for fname, src in _iter_sources():
        if fname.endswith(".cpp"):
            # native sources: identifier tokens with comments stripped
            # (a parameter named only in a C++ comment is not consumed)
            src = re.sub(r"//[^\n]*|/\*.*?\*/", "", src, flags=re.S)
            names.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", src))
            continue
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                names.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                names.add(node.value)
            elif isinstance(node, ast.keyword) and node.arg:
                names.add(node.arg)
            # deliberately NOT ast.Name: a local variable coincidentally
            # sharing a field's name must not count as consumption —
            # genuine reads go through cfg.<attr>, string keys, or kwargs
    return names


def test_no_silently_ignored_params():
    consumed = _consumed_names()
    dead = []
    for f in dataclasses.fields(Config):
        if f.name in NOOP_PARAMS:
            continue
        if f.name not in consumed:
            dead.append(f.name)
    assert not dead, "config fields neither consumed nor in NOOP_PARAMS: %s" \
        % dead


def test_noop_params_warn(capsys):
    for name, (default, _reason) in NOOP_PARAMS.items():
        if isinstance(default, bool):
            value = not default
        elif isinstance(default, (int, float)):
            value = default + 1
        else:
            value = "something_else"
        Config.from_params({name: value})
        err = capsys.readouterr().err + capsys.readouterr().out
        # Log may write to stdout; check both
    # spot-check one concrete warning text end-to-end (restore the level:
    # earlier tests may have trained with verbosity=-1, which suppresses
    # warnings below the callback)
    from lightgbm_tpu.utils.log import Log
    msgs = []
    Log.reset_log_level(Log.WARNING)
    Log.reset_callback(msgs.append)
    try:
        Config.from_params({"force_row_wise": True})
    finally:
        Log.reset_callback(None)
    assert any("force_row_wise" in m for m in msgs)


@pytest.mark.slow  # two full trainings; knob-sensitivity audit, not a parity pin
def test_monotone_penalty_changes_model():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(2000, 4))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + rng.normal(scale=0.2, size=2000)
    base = {"objective": "regression", "num_leaves": 31, "verbose": -1,
            "monotone_constraints": [1, 0, 0, 0]}
    b0 = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=8)
    b1 = lgb.train(dict(base, monotone_penalty=2.0),
                   lgb.Dataset(X, label=y), num_boost_round=8)
    assert b0.model_to_string() != b1.model_to_string()
    # a huge penalization forbids monotone splits near the root entirely:
    # feature 0 should lose importance
    b2 = lgb.train(dict(base, monotone_penalty=6.0),
                   lgb.Dataset(X, label=y), num_boost_round=8)
    assert b2.feature_importance("split")[0] < b0.feature_importance("split")[0]


def test_pred_early_stop_binary():
    rng = np.random.RandomState(1)
    X = rng.normal(size=(800, 5))
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    full = bst.predict(X)
    bst.config.set({"pred_early_stop": True, "pred_early_stop_freq": 5,
                    "pred_early_stop_margin": 1.0})
    es = bst.predict(X)
    # early-stopped rows keep the same SIGN (confident rows froze early)
    assert np.all((es > 0.5) == (full > 0.5))
    # and at least some rows actually stopped early (values differ)
    assert not np.allclose(es, full)


@pytest.mark.slow  # two full trainings; knob-sensitivity audit, not a parity pin
def test_extra_seed_changes_extra_trees():
    rng = np.random.RandomState(2)
    X = rng.normal(size=(1500, 6))
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    base = {"objective": "binary", "num_leaves": 15, "verbose": -1,
            "extra_trees": True}
    b1 = lgb.train(dict(base, extra_seed=1), lgb.Dataset(X, label=y),
                   num_boost_round=5)
    b2 = lgb.train(dict(base, extra_seed=99), lgb.Dataset(X, label=y),
                   num_boost_round=5)
    assert b1.model_to_string() != b2.model_to_string()


def test_predict_shape_check():
    from lightgbm_tpu.utils.log import LightGBMError
    rng = np.random.RandomState(3)
    X = rng.normal(size=(500, 5))
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    with pytest.raises(LightGBMError):
        bst.predict(X[:, :4])
    bst.config.set({"predict_disable_shape_check": True})
    bst.predict(np.pad(X, ((0, 0), (0, 2))))  # wider input now allowed


def test_two_round_loader(tmp_path):
    from lightgbm_tpu.config import Config as _C
    from lightgbm_tpu.io import load_dataset_two_round
    rng = np.random.RandomState(4)
    X = rng.normal(size=(3000, 5))
    y = (X[:, 0] > 0).astype(float)
    f = tmp_path / "t.csv"
    np.savetxt(f, np.column_stack([y, X]), delimiter=",", fmt="%.9g")
    cfg = _C.from_params({"two_round": True,
                          "bin_construct_sample_cnt": 1000})
    ds = load_dataset_two_round(str(f), cfg)
    assert ds.num_data == 3000
    assert ds.metadata.label.sum() == y.sum()
    # memory contract: binned matrix is uint8, raw doubles not retained
    assert ds.binned.dtype == np.uint8 and ds.raw_numeric is None
