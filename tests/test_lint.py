"""graftlint framework + rule-set tests (ISSUE 4 tentpole).

Fixture projects are written to tmp_path with the repo's directory shape
(lightgbm_tpu/, lightgbm_tpu/ops/, scripts/, bench.py) because several
rules scope by path. Every rule gets positive AND negative snippets; the
suppression tests prove inline ``# graftlint: disable`` and the baseline
each kill exactly their finding. The final tests run the real linter over
the real repo: zero unbaselined findings, under the ~5 s tier-1 budget.
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu import lint  # noqa: E402


def make_project(tmp_path, files):
    """Write {relpath: source} and lint it; returns the LintResult."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return lint.run(str(tmp_path))


def rules_hit(result):
    return sorted({f.rule for f in result.findings})


def lines_hit(result, rule):
    return sorted(f.line for f in result.findings if f.rule == rule)


# ------------------------------------------------------------ naked-timer

def test_naked_timer_positive(tmp_path):
    res = make_project(tmp_path, {
        "scripts/prof.py": """\
            import time
            from time import perf_counter as pc
            t0 = time.time()
            t1 = pc()
        """,
        "bench.py": """\
            import time
            t0 = time.perf_counter()
        """,
    })
    found = [(f.path, f.line) for f in res.findings
             if f.rule == "naked-timer"]
    assert ("scripts/prof.py", 3) in found       # time.time()
    assert ("scripts/prof.py", 4) in found       # aliased perf_counter
    assert ("bench.py", 2) in found


def test_naked_timer_negative(tmp_path):
    res = make_project(tmp_path, {
        # the two timing-impl modules are exempt
        "lightgbm_tpu/obs.py": "import time\nt0 = time.perf_counter()\n",
        "lightgbm_tpu/utils/timer.py":
            "import time\nt0 = time.perf_counter()\n",
        # obs.wall usage is the blessed pattern
        "scripts/good.py": """\
            from lightgbm_tpu import obs
            with obs.wall("phase", record=False) as w:
                pass
            print(w.seconds)
        """,
        # time.sleep is not a wall clock
        "scripts/sleepy.py": "import time\ntime.sleep(0.0)\n",
    })
    assert "naked-timer" not in rules_hit(res)


# ------------------------------------------------------------ host-sync

def test_host_sync_positive(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax
        import jax.numpy as jnp
        import numpy as np
        from functools import partial

        def helper(x):
            return float(jnp.sum(x))

        @jax.jit
        def kernel(x):
            y = helper(x)
            v = x.item()
            z = np.asarray(x)
            return y + v + z.sum()

        def make(seed):
            return partial(body, seed)

        def body(seed, x):
            x.block_until_ready()
            return x

        @jax.jit
        def loop_hot(x):
            def inner(i, c):
                return jnp.asarray(c.tolist())
            return jax.lax.fori_loop(0, 4, inner, x)
    """})
    got = lines_hit(res, "host-sync")
    assert 7 in got     # float(jnp...) in helper reachable from kernel
    assert 12 in got    # .item() in the jit body
    assert 13 in got    # np.asarray in the jit body
    assert 20 in got    # block_until_ready via partial-wrapped body
    assert 26 in got    # .tolist() in a nested def of a hot fn


def test_host_sync_negative(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        def host_side(x):
            return np.asarray(x)          # never reachable from a jit

        def configure(cfg):
            return int(cfg.seed), float(cfg.rate)   # static scalars

        @jax.jit
        def kernel(x, n):
            shape = int(x.shape[0])       # int() on a non-jnp expression
            return x * n + shape
    """})
    assert "host-sync" not in rules_hit(res)


def test_host_sync_scoped_name_resolution(tmp_path):
    """A hot function calling its LOCAL helper must not mark an unrelated
    same-named method hot — the FusedTrainer.flush false-positive class."""
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            def flush(v):
                return v + 1
            return flush(x)

        class Trainer:
            def flush(self, x):
                return np.asarray(x)      # host-side; same simple name
    """})
    assert "host-sync" not in rules_hit(res)


def test_host_sync_method_calls_resolve(tmp_path):
    """x.attr(...) calls from hot code DO reach methods of that name."""
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax

        class Comm:
            def psum(self, x):
                return x.item()

        @jax.jit
        def kernel(x, comm):
            return comm.psum(x)
    """})
    assert lines_hit(res, "host-sync") == [5]


# ------------------------------------------------------------ implicit-dtype

def test_implicit_dtype_positive(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax.numpy as jnp
        a = jnp.zeros((4,))
        b = jnp.arange(8)
        c = jnp.asarray([1, 2])
        d = jnp.ones((2, 2))
        e = jnp.full((3,), 0)
    """})
    assert lines_hit(res, "implicit-dtype") == [2, 3, 4, 5, 6]


def test_implicit_dtype_negative(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax.numpy as jnp
            a = jnp.zeros((4,), jnp.int32)          # positional dtype
            b = jnp.arange(8, dtype=jnp.uint8)      # kwarg dtype
            c = jnp.asarray([1], jnp.float32)
            d = jnp.full((3,), 0, jnp.int32)
            e = jnp.arange(2, 8, 2, jnp.int32)      # dtype at position 3
        """,
        # outside ops/ the rule does not apply
        "lightgbm_tpu/other.py":
            "import jax.numpy as jnp\nx = jnp.zeros((4,))\n",
    })
    assert "implicit-dtype" not in rules_hit(res)


# ------------------------------------------------------------ unnamed-pallas-call

def test_unnamed_pallas_call(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        from jax.experimental import pallas as pl
        bad = pl.pallas_call(lambda r: None, out_shape=None)
        good = pl.pallas_call(lambda r: None, out_shape=None, name="k")
    """})
    assert lines_hit(res, "unnamed-pallas-call") == [2]


# ------------------------------------------------------------ mutable-default

def test_mutable_default(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/m.py": """\
        def bad(x, acc=[]):
            return acc
        def bad2(*, table={}):
            return table
        def good(x, acc=(), n=0, s=None):
            return acc
    """})
    assert lines_hit(res, "mutable-default") == [1, 3]


# ------------------------------------------------------------ module-mutable-state

def test_module_mutable_state(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/m.py": """\
        CACHE = {}
        TABLE = {"a": 1}      # written only at module init: legal

        def put(k, v):
            CACHE[k] = v
    """})
    assert lines_hit(res, "module-mutable-state") == [1]


def test_module_mutable_state_obs_exempt(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/obs.py": """\
        REGISTRY = {}

        def register(k, v):
            REGISTRY[k] = v
    """})
    assert "module-mutable-state" not in rules_hit(res)


# ------------------------------------------------------------ suppression

def test_inline_disable_suppresses_exactly_its_rule(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()  # graftlint: disable=naked-timer
        b = time.time()  # graftlint: disable=implicit-dtype
        c = time.time()
    """})
    assert lines_hit(res, "naked-timer") == [3, 4]
    sup = [f.line for f in res.suppressed if f.rule == "naked-timer"]
    assert sup == [2]


def test_inline_disable_all_rules(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()  # graftlint: disable
    """})
    assert not res.findings
    assert len(res.suppressed) == 1


# ------------------------------------------------------------ baseline

def test_baseline_freezes_only_its_findings(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
    """})
    baseline = lint.baseline_from_findings(res.findings)
    new, old = lint.split_new_findings(res.findings, baseline)
    assert not new and len(old) == 1

    # an ADDITIONAL identical-text finding exceeds the count budget
    res2 = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
        a = time.time()
    """})
    new2, old2 = lint.split_new_findings(res2.findings, baseline)
    assert len(old2) == 1 and len(new2) == 1


def test_baseline_survives_line_renumbering(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
    """})
    baseline = lint.baseline_from_findings(res.findings)
    # same offending line, pushed down by an unrelated edit
    res2 = make_project(tmp_path, {"scripts/s.py": """\
        import time
        x = 1
        y = 2
        a = time.time()
    """})
    new, old = lint.split_new_findings(res2.findings, baseline)
    assert not new and len(old) == 1


def test_baseline_roundtrip(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
    """})
    path = str(tmp_path / "baseline.json")
    lint.save_baseline(path, lint.baseline_from_findings(res.findings))
    loaded = lint.load_baseline(path)
    new, old = lint.split_new_findings(res.findings, loaded)
    assert not new and len(old) == 1
    assert lint.load_baseline(str(tmp_path / "missing.json")) \
        == {"version": 1, "findings": []}


# ------------------------------------------------------------ framework

def test_rule_registry_and_selection(tmp_path):
    ids = set(lint.all_rules())
    assert {"naked-timer", "host-sync", "implicit-dtype",
            "unnamed-pallas-call", "mutable-default",
            "module-mutable-state"} <= ids
    with pytest.raises(ValueError):
        lint.run(str(tmp_path), rules=["no-such-rule"])


def test_finding_render_format(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py":
                                  "import time\na = time.time()\n"})
    (f,) = [x for x in res.findings if x.rule == "naked-timer"]
    assert f.render().startswith("scripts/s.py:2:")
    assert ": naked-timer " in f.render()


# ------------------------------------------------------------ the real repo

def test_repo_is_lint_clean():
    """python scripts/lint.py must exit 0: no unbaselined findings."""
    result = lint.run(REPO)
    baseline = lint.load_baseline(os.path.join(REPO, lint.BASELINE_NAME))
    new, _ = lint.split_new_findings(result.findings, baseline)
    assert not new, "\n" + "\n".join(f.render() for f in new)


def test_full_lint_is_fast():
    t0 = time.perf_counter()
    lint.run(REPO)
    assert time.perf_counter() - t0 < 5.0


def test_cli_json_exit_zero():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ok"] is True and payload["new"] == []
