"""graftlint framework + rule-set tests (ISSUE 4 tentpole).

Fixture projects are written to tmp_path with the repo's directory shape
(lightgbm_tpu/, lightgbm_tpu/ops/, scripts/, bench.py) because several
rules scope by path. Every rule gets positive AND negative snippets; the
suppression tests prove inline ``# graftlint: disable`` and the baseline
each kill exactly their finding. The final tests run the real linter over
the real repo: zero unbaselined findings, under the ~5 s tier-1 budget.
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu import lint  # noqa: E402


def make_project(tmp_path, files):
    """Write {relpath: source} and lint it; returns the LintResult."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return lint.run(str(tmp_path))


def rules_hit(result):
    return sorted({f.rule for f in result.findings})


def lines_hit(result, rule):
    return sorted(f.line for f in result.findings if f.rule == rule)


# ------------------------------------------------------------ naked-timer

def test_naked_timer_positive(tmp_path):
    res = make_project(tmp_path, {
        "scripts/prof.py": """\
            import time
            from time import perf_counter as pc
            t0 = time.time()
            t1 = pc()
        """,
        "bench.py": """\
            import time
            t0 = time.perf_counter()
        """,
    })
    found = [(f.path, f.line) for f in res.findings
             if f.rule == "naked-timer"]
    assert ("scripts/prof.py", 3) in found       # time.time()
    assert ("scripts/prof.py", 4) in found       # aliased perf_counter
    assert ("bench.py", 2) in found


def test_naked_timer_negative(tmp_path):
    res = make_project(tmp_path, {
        # the two timing-impl modules are exempt
        "lightgbm_tpu/obs.py": "import time\nt0 = time.perf_counter()\n",
        "lightgbm_tpu/utils/timer.py":
            "import time\nt0 = time.perf_counter()\n",
        # obs.wall usage is the blessed pattern
        "scripts/good.py": """\
            from lightgbm_tpu import obs
            with obs.wall("phase", record=False) as w:
                pass
            print(w.seconds)
        """,
        # time.sleep is not a wall clock
        "scripts/sleepy.py": "import time\ntime.sleep(0.0)\n",
    })
    assert "naked-timer" not in rules_hit(res)


# ------------------------------------------------------------ host-sync

def test_host_sync_positive(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax
        import jax.numpy as jnp
        import numpy as np
        from functools import partial

        def helper(x):
            return float(jnp.sum(x))

        @jax.jit
        def kernel(x):
            y = helper(x)
            v = x.item()
            z = np.asarray(x)
            return y + v + z.sum()

        def make(seed):
            return partial(body, seed)

        def body(seed, x):
            x.block_until_ready()
            return x

        @jax.jit
        def loop_hot(x):
            def inner(i, c):
                return jnp.asarray(c.tolist())
            return jax.lax.fori_loop(0, 4, inner, x)
    """})
    got = lines_hit(res, "host-sync")
    assert 7 in got     # float(jnp...) in helper reachable from kernel
    assert 12 in got    # .item() in the jit body
    assert 13 in got    # np.asarray in the jit body
    assert 20 in got    # block_until_ready via partial-wrapped body
    assert 26 in got    # .tolist() in a nested def of a hot fn


def test_host_sync_negative(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        def host_side(x):
            return np.asarray(x)          # never reachable from a jit

        def configure(cfg):
            return int(cfg.seed), float(cfg.rate)   # static scalars

        @jax.jit
        def kernel(x, n):
            shape = int(x.shape[0])       # int() on a non-jnp expression
            return x * n + shape
    """})
    assert "host-sync" not in rules_hit(res)


def test_host_sync_scoped_name_resolution(tmp_path):
    """A hot function calling its LOCAL helper must not mark an unrelated
    same-named method hot — the FusedTrainer.flush false-positive class."""
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            def flush(v):
                return v + 1
            return flush(x)

        class Trainer:
            def flush(self, x):
                return np.asarray(x)      # host-side; same simple name
    """})
    assert "host-sync" not in rules_hit(res)


def test_host_sync_method_calls_resolve(tmp_path):
    """x.attr(...) calls from hot code DO reach methods of that name."""
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax

        class Comm:
            def psum(self, x):
                return x.item()

        @jax.jit
        def kernel(x, comm):
            return comm.psum(x)
    """})
    assert lines_hit(res, "host-sync") == [5]


# ------------------------------------------------------------ implicit-dtype

def test_implicit_dtype_positive(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax.numpy as jnp
        a = jnp.zeros((4,))
        b = jnp.arange(8)
        c = jnp.asarray([1, 2])
        d = jnp.ones((2, 2))
        e = jnp.full((3,), 0)
    """})
    assert lines_hit(res, "implicit-dtype") == [2, 3, 4, 5, 6]


def test_implicit_dtype_negative(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax.numpy as jnp
            a = jnp.zeros((4,), jnp.int32)          # positional dtype
            b = jnp.arange(8, dtype=jnp.uint8)      # kwarg dtype
            c = jnp.asarray([1], jnp.float32)
            d = jnp.full((3,), 0, jnp.int32)
            e = jnp.arange(2, 8, 2, jnp.int32)      # dtype at position 3
        """,
        # outside ops/ the rule does not apply
        "lightgbm_tpu/other.py":
            "import jax.numpy as jnp\nx = jnp.zeros((4,))\n",
    })
    assert "implicit-dtype" not in rules_hit(res)


# ------------------------------------------------------------ unnamed-pallas-call

def test_unnamed_pallas_call(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        from jax.experimental import pallas as pl
        bad = pl.pallas_call(lambda r: None, out_shape=None)
        good = pl.pallas_call(lambda r: None, out_shape=None, name="k")
    """})
    assert lines_hit(res, "unnamed-pallas-call") == [2]


# ------------------------------------------------------------ mutable-default

def test_mutable_default(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/m.py": """\
        def bad(x, acc=[]):
            return acc
        def bad2(*, table={}):
            return table
        def good(x, acc=(), n=0, s=None):
            return acc
    """})
    assert lines_hit(res, "mutable-default") == [1, 3]


# ------------------------------------------------------------ module-mutable-state

def test_module_mutable_state(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/m.py": """\
        CACHE = {}
        TABLE = {"a": 1}      # written only at module init: legal

        def put(k, v):
            CACHE[k] = v
    """})
    assert lines_hit(res, "module-mutable-state") == [1]


def test_module_mutable_state_obs_exempt(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/obs.py": """\
        REGISTRY = {}

        def register(k, v):
            REGISTRY[k] = v
    """})
    assert "module-mutable-state" not in rules_hit(res)


# ------------------------------------------------------------ suppression

def test_inline_disable_suppresses_exactly_its_rule(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()  # graftlint: disable=naked-timer
        b = time.time()  # graftlint: disable=implicit-dtype
        c = time.time()
    """})
    assert lines_hit(res, "naked-timer") == [3, 4]
    sup = [f.line for f in res.suppressed if f.rule == "naked-timer"]
    assert sup == [2]


def test_inline_disable_all_rules(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()  # graftlint: disable
    """})
    assert not res.findings
    assert len(res.suppressed) == 1


# ------------------------------------------------------------ baseline

def test_baseline_freezes_only_its_findings(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
    """})
    baseline = lint.baseline_from_findings(res.findings)
    new, old = lint.split_new_findings(res.findings, baseline)
    assert not new and len(old) == 1

    # an ADDITIONAL identical-text finding exceeds the count budget
    res2 = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
        a = time.time()
    """})
    new2, old2 = lint.split_new_findings(res2.findings, baseline)
    assert len(old2) == 1 and len(new2) == 1


def test_baseline_survives_line_renumbering(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
    """})
    baseline = lint.baseline_from_findings(res.findings)
    # same offending line, pushed down by an unrelated edit
    res2 = make_project(tmp_path, {"scripts/s.py": """\
        import time
        x = 1
        y = 2
        a = time.time()
    """})
    new, old = lint.split_new_findings(res2.findings, baseline)
    assert not new and len(old) == 1


def test_baseline_roundtrip(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        import time
        a = time.time()
    """})
    path = str(tmp_path / "baseline.json")
    lint.save_baseline(path, lint.baseline_from_findings(res.findings))
    loaded = lint.load_baseline(path)
    new, old = lint.split_new_findings(res.findings, loaded)
    assert not new and len(old) == 1
    assert lint.load_baseline(str(tmp_path / "missing.json")) \
        == {"version": 1, "findings": []}


# ------------------------------------------------------------ metric-name

def test_metric_name_flags_ambiguous_sanitization(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        from lightgbm_tpu.obs import telemetry
        telemetry.count("serve requests")
        telemetry.gauge("queue-depth")
        telemetry.count("fleet/replica_polls")      # legal separators
        telemetry.observe("span_ms/" + "dyn", 1.0)  # dynamic: skipped
    """})
    assert lines_hit(res, "metric-name") == [2, 3]
    msgs = [f.message for f in res.findings if f.rule == "metric-name"]
    assert all("sanitizes ambiguously" in m for m in msgs)


def test_metric_name_flags_one_family_two_types(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/a.py": """\
            from lightgbm_tpu.obs import telemetry
            telemetry.gauge("fleet/skew")
        """,
        "lightgbm_tpu/b.py": """\
            from lightgbm_tpu.obs import telemetry
            telemetry.observe("fleet/skew", 2.0)
        """,
    })
    (f,) = [x for x in res.findings if x.rule == "metric-name"]
    # deterministic: the later site (file order) is the finding, the
    # earlier one is the cited first registration
    assert f.path == "lightgbm_tpu/b.py"
    assert "lgbtpu_fleet_skew" in f.message
    assert "one family, one type" in f.message
    assert "lightgbm_tpu/a.py:2" in f.message


def test_metric_name_counter_total_collides_with_gauge(tmp_path):
    # the hazard lives in the SUFFIXED family: counter "x" emits
    # lgbtpu_x_total, which a gauge literally named "x_total" collides
    # with even though the raw registry keys differ
    res = make_project(tmp_path, {"scripts/s.py": """\
        from lightgbm_tpu.obs import telemetry
        telemetry.count("ingest/rows")
        telemetry.gauge("ingest/rows_total")
    """})
    (f,) = [x for x in res.findings if x.rule == "metric-name"]
    assert "lgbtpu_ingest_rows_total" in f.message


def test_metric_name_negative(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py": """\
        from itertools import count
        from lightgbm_tpu.obs import telemetry
        ids = count(1)                       # not telemetry.count
        next(ids)
        telemetry.count("fleet/heartbeats_sent")
        telemetry.count("fleet/heartbeats_sent", 2)   # same type: fine
        telemetry.gauge("fleet/version_skew", 0)
        telemetry.observe("fleet/publish_adopt_lag_ms", 1.0)
        telemetry.add_time("wall/serve", 0.1)

        class Thing:
            def gauge(self, name, v):
                pass

        Thing().gauge("not a metric!", 1)    # receiver is not telemetry
    """})
    assert "metric-name" not in rules_hit(res)


# ------------------------------------------------------------ framework

def test_rule_registry_and_selection(tmp_path):
    ids = set(lint.all_rules())
    assert {"naked-timer", "host-sync", "implicit-dtype",
            "unnamed-pallas-call", "mutable-default",
            "module-mutable-state", "metric-name"} <= ids
    with pytest.raises(ValueError):
        lint.run(str(tmp_path), rules=["no-such-rule"])


def test_finding_render_format(tmp_path):
    res = make_project(tmp_path, {"scripts/s.py":
                                  "import time\na = time.time()\n"})
    (f,) = [x for x in res.findings if x.rule == "naked-timer"]
    assert f.render().startswith("scripts/s.py:2:")
    assert ": naked-timer " in f.render()


# ------------------------------------------------------------ the real repo

def test_repo_is_lint_clean():
    """python scripts/lint.py must exit 0: no unbaselined findings."""
    result = lint.run(REPO)
    baseline = lint.load_baseline(os.path.join(REPO, lint.BASELINE_NAME))
    new, _ = lint.split_new_findings(result.findings, baseline)
    assert not new, "\n" + "\n".join(f.render() for f in new)


def test_full_lint_is_fast():
    # best-of-three: a single wall-clock sample is at the mercy of whatever
    # else the machine is doing; the budget is about the linter, not the box.
    # 8s is ~2.5x the unloaded time on a slow CI box — loose enough that a
    # box running at 60% speed (observed across otherwise identical full-
    # suite runs) doesn't trip it, tight enough to catch a superlinear
    # regression in the graph engine.
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        lint.run(REPO)
        best = min(best, time.perf_counter() - t0)
        if best < 8.0:
            break
    assert best < 8.0


def test_cli_json_exit_zero():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ok"] is True and payload["new"] == []


# ------------------------------------------------------------ lock-discipline

def test_lock_discipline_microbatcher_closed_shape(tmp_path):
    """The pre-fix MicroBatcher race: _closed read by submit/worker,
    written by close, no lock anywhere. The rule must fire."""
    res = make_project(tmp_path, {"lightgbm_tpu/serve/b.py": """\
        import threading

        class Batcher:
            def __init__(self):
                self._closed = False
                self._thread = threading.Thread(target=self._worker)
                self._thread.start()

            def submit(self, x):
                if self._closed:
                    raise RuntimeError("closed")
                return x

            def _worker(self):
                while not self._closed:
                    pass

            def close(self):
                self._closed = True
    """})
    hits = [f for f in res.findings if f.rule == "lock-discipline"]
    assert hits, rules_hit(res)
    assert any("_closed" in f.message for f in hits)


def test_lock_discipline_pack_cache_shape(tmp_path):
    """The pre-fix Booster._pack_cache race: trainer mutates the
    version-keyed cache on the main thread while a server thread reads
    it through a typed attribute chain."""
    res = make_project(tmp_path, {"lightgbm_tpu/serve/s.py": """\
        import threading

        class Booster:
            def __init__(self):
                self._version = 0
                self._pack_cache = {}

            def train(self):
                self._version += 1
                self._pack_cache.clear()

            def pack(self):
                return self._pack_cache.get(self._version)

        class Server:
            def __init__(self, booster):
                self._b = booster
                self._thread = threading.Thread(target=self._serve)
                self._thread.start()

            def _serve(self):
                self._b.pack()

        def main():
            b = Booster()
            s = Server(b)
            b.train()
    """})
    hits = [f for f in res.findings if f.rule == "lock-discipline"]
    assert any("_pack_cache" in f.message for f in hits), \
        "\n".join(f.render() for f in res.findings)


def test_lock_discipline_locked_is_clean(tmp_path):
    """Same batcher shape with every access under one lock: clean."""
    res = make_project(tmp_path, {"lightgbm_tpu/serve/b.py": """\
        import threading

        class Batcher:
            def __init__(self):
                self._lock = threading.Lock()
                self._closed = False
                self._thread = threading.Thread(target=self._worker)
                self._thread.start()

            def submit(self, x):
                with self._lock:
                    if self._closed:
                        raise RuntimeError("closed")
                return x

            def _worker(self):
                while True:
                    with self._lock:
                        if self._closed:
                            return

            def close(self):
                with self._lock:
                    self._closed = True
    """})
    assert "lock-discipline" not in rules_hit(res)


def test_lock_discipline_guarded_by_annotation(tmp_path):
    """``# graftlint: guarded-by=<lock>`` blesses an access that holds
    the lock in a way the lexical scan can't see."""
    res = make_project(tmp_path, {"lightgbm_tpu/serve/b.py": """\
        import threading

        class Batcher:
            def __init__(self):
                self._lock = threading.Lock()
                self._closed = False
                self._thread = threading.Thread(target=self._worker)
                self._thread.start()

            def submit(self, x):
                with self._lock:
                    if self._closed:
                        raise RuntimeError("closed")
                return x

            def _worker(self):
                self._closed = True  # graftlint: guarded-by=_lock

            def close(self):
                with self._lock:
                    self._closed = True
    """})
    assert "lock-discipline" not in rules_hit(res)


def test_lock_discipline_executor_and_http_entries(tmp_path):
    """Thread roots beyond Thread(target=...): executor submissions and
    BaseHTTPRequestHandler do_* methods both count."""
    res = make_project(tmp_path, {"lightgbm_tpu/serve/w.py": """\
        import concurrent.futures
        from http.server import BaseHTTPRequestHandler

        class Work:
            def __init__(self):
                self.items = []
                self.ex = concurrent.futures.ThreadPoolExecutor()

            def kick(self):
                self.ex.submit(self.job)

            def job(self):
                self.items.append(1)

            def reset(self):
                self.items.clear()

        class App:
            def __init__(self):
                self.hits = []

            def bump_hits(self):
                self.hits.append(1)

            def drain_hits(self):
                self.hits.clear()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                self.app.bump_hits()
    """})
    hits = " ".join(f.message for f in res.findings
                    if f.rule == "lock-discipline")
    assert "items" in hits, "\n".join(f.render() for f in res.findings)
    assert "hits" in hits


def test_lock_discipline_init_only_writes_are_clean(tmp_path):
    """Attrs written only during construction are not shared-mutable
    state, even when threads read them."""
    res = make_project(tmp_path, {"lightgbm_tpu/serve/b.py": """\
        import threading

        class Batcher:
            def __init__(self):
                self._max_rows = 64
                self._thread = threading.Thread(target=self._worker)
                self._thread.start()

            def _worker(self):
                return self._max_rows
    """})
    assert "lock-discipline" not in rules_hit(res)


def test_lock_discipline_confined_receiver_is_clean(tmp_path):
    """The online-trainer shape: a worker thread builds a candidate
    object locally and drives arbitrary unguarded mutation on it. The
    receiver is freshly constructed in the worker's own frame, so its
    class surface is thread-confined — no finding, even though main
    code uses the same class."""
    res = make_project(tmp_path, {"lightgbm_tpu/online/t.py": """\
        import threading

        class Candidate:
            def __init__(self):
                self.weights = []

            def fit(self, x):
                self.weights.append(x)

        class Trainer:
            def __init__(self):
                self._lock = threading.Lock()
                self._out = None
                self._thread = threading.Thread(
                    target=self._worker, name="lgbtpu-w")
                self._thread.start()

            def _worker(self):
                c = Candidate()
                c.fit(1)
                with self._lock:
                    self._out = c

        def main():
            t = Trainer()
            c = Candidate()
            c.fit(2)
    """})
    assert "lock-discipline" not in rules_hit(res), \
        "\n".join(f.render() for f in res.findings)


def test_lock_discipline_self_held_receiver_still_fires(tmp_path):
    """Contrast for the confined-edge cut: the same candidate held on
    ``self`` and mutated from the worker IS shared — the cut only
    applies to receivers constructed in the calling frame."""
    res = make_project(tmp_path, {"lightgbm_tpu/online/t.py": """\
        import threading

        class Candidate:
            def __init__(self):
                self.weights = []

            def fit(self, x):
                self.weights.append(x)

        class Trainer:
            def __init__(self):
                self._cand = Candidate()
                self._thread = threading.Thread(
                    target=self._worker, name="lgbtpu-w")
                self._thread.start()

            def _worker(self):
                self._cand.fit(1)

        def main():
            t = Trainer()
            t._cand.fit(2)
    """})
    hits = [f for f in res.findings if f.rule == "lock-discipline"]
    assert any("weights" in f.message for f in hits), \
        "\n".join(f.render() for f in res.findings)


def test_lock_discipline_owned_class_annotation(tmp_path):
    """``# graftlint: owned`` on a class line exempts its fields: the
    ownership-transfer idiom (built by one thread, published through a
    locked handoff). The identical project without the annotation must
    fire on the same field."""
    src = """\
        import threading

        class Pack:{ann}
            def __init__(self):
                self.table = {{}}

            def put(self, k, v):
                self.table[k] = v

        class Publisher:
            def __init__(self, pack):
                self._pack = pack
                self._thread = threading.Thread(
                    target=self._build, name="lgbtpu-b")
                self._thread.start()

            def _build(self):
                self._pack.put("k", 1)

        def main():
            p = Pack()
            pub = Publisher(p)
            p.put("j", 2)
    """
    res = make_project(tmp_path / "owned", {
        "lightgbm_tpu/online/p.py": src.format(ann="  # graftlint: owned")})
    assert "lock-discipline" not in rules_hit(res), \
        "\n".join(f.render() for f in res.findings)
    res = make_project(tmp_path / "bare", {
        "lightgbm_tpu/online/p.py": src.format(ann="")})
    hits = [f for f in res.findings if f.rule == "lock-discipline"]
    assert any("table" in f.message for f in hits), \
        "\n".join(f.render() for f in res.findings)


# ------------------------------------------------------------ unnamed-thread

def test_unnamed_thread_positive(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/w.py": """\
        import threading
        from threading import Thread

        t1 = threading.Thread(target=print)
        t2 = Thread(target=print, daemon=True)
    """})
    assert lines_hit(res, "unnamed-thread") == [4, 5]


def test_unnamed_thread_negative(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/w.py": """\
        import threading

        t1 = threading.Thread(target=print, name="lgbtpu-worker")
        t2 = threading.Thread(None, print, "lgbtpu-pos-name")
        t3 = threading.Timer(1.0, print)    # not a Thread constructor
        local = threading.local()
    """})
    assert "unnamed-thread" not in rules_hit(res)


# ------------------------------------------------------------ tracer-leak

def test_tracer_leak_positive(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/learner.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            y = jnp.sum(x)
            if y > 0:
                return y
            while jnp.any(x > 0):
                x = x - 1
            assert jnp.all(x <= 0)
            return -y
    """})
    assert lines_hit(res, "tracer-leak") == [7, 9, 11]


def test_tracer_leak_param_evidence_via_subscript(tmp_path):
    """A param fed directly to a jnp call is a traced array; branching
    on an element of it leaks."""
    res = make_project(tmp_path, {"lightgbm_tpu/fused.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            s = jnp.sum(x)
            if x[0] > 0:
                return s
            return -s
    """})
    assert lines_hit(res, "tracer-leak") == [7]


def test_tracer_leak_negative(tmp_path):
    """Static shape/dtype tests, config scalars and config-struct attrs
    of array params stay legal; so does non-jit host code."""
    res = make_project(tmp_path, {"lightgbm_tpu/learner.py": """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x, depth, hp):
            s = jnp.sum(x)
            if x.shape[0] > 2:
                s = s + 1
            if x.ndim == 2:
                s = s + 1
            if depth > 3:
                s = s + 1
            if hp.max_delta_step > 0.0:
                s = s + 1
            return s

        def host_driver(x):
            if jnp.sum(x) > 0:
                return 1
            return 0
    """})
    assert "tracer-leak" not in rules_hit(res)


# ------------------------------------------------------------ dtype-promotion

def test_dtype_promotion_positive(tmp_path):
    res = make_project(tmp_path, {"lightgbm_tpu/ops/k.py": """\
        import jax.numpy as jnp

        def mix():
            x = jnp.zeros((4,), jnp.float32)
            y = jnp.ones((4,), jnp.float64)
            z = x + y
            i = jnp.arange(4, dtype=jnp.int64)
            j = jnp.zeros((4,), jnp.int32)
            k = i + j
            t = jnp.take(x, i)
            return z, k, t
    """})
    lines = lines_hit(res, "dtype-promotion")
    assert 6 in lines     # f32 meets f64
    assert 9 in lines     # i32 meets i64
    assert 10 in lines    # int64 indices


def test_dtype_promotion_negative(tmp_path):
    """Weak Python literals, same-width math and i32 indexing are
    clean; so is identical code outside ops/."""
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax.numpy as jnp

            def clean():
                x = jnp.zeros((4,), jnp.float32)
                y = x * 0.5
                i = jnp.arange(4, dtype=jnp.int32)
                t = jnp.take(x, i)
                f = x.astype(jnp.float64)
                g = f + 1.0
                return y + t, g.sum()
        """,
        "lightgbm_tpu/boosting2.py": """\
            import jax.numpy as jnp

            def hostside():
                x = jnp.zeros((4,), jnp.float32)
                y = jnp.ones((4,), jnp.float64)
                return x + y
        """,
    })
    assert "dtype-promotion" not in rules_hit(res)


# ------------------------------------------------------------ CLI modes

def test_cli_rules_validation():
    script = os.path.join(REPO, "scripts", "lint.py")
    out = subprocess.run([sys.executable, script, "--rules", "no-such"],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2
    assert "unknown rule" in out.stderr
    out = subprocess.run([sys.executable, script, "--rules", ""],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2
    assert "at least one rule" in out.stderr


def test_cli_changed_mode():
    script = os.path.join(REPO, "scripts", "lint.py")
    out = subprocess.run([sys.executable, script, "--changed"],
                         capture_output=True, text=True, cwd=REPO)
    # dirty checkout or clean: either way the mode must succeed
    assert out.returncode == 0, out.stdout + out.stderr
    assert "graftlint" in out.stdout


def test_new_rules_registered():
    ids = set(lint.all_rules())
    assert {"lock-discipline", "tracer-leak", "dtype-promotion",
            "pallas-interpret-thread", "aliased-ref-read",
            "recompile-hazard", "knob-contract"} <= ids


# ------------------------------------------------- pallas-interpret-thread

def test_interpret_thread_positive(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax
            from jax.experimental import pallas as pl

            _FROZEN = False

            def kern(x_ref, o_ref):
                o_ref[...] = x_ref[...]

            def launch_omitted(x):
                return pl.pallas_call(
                    kern, name="a",
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)

            def launch_literal(x):
                return pl.pallas_call(
                    kern, name="b",
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=True)(x)

            def launch_laundered(x):
                return pl.pallas_call(
                    kern, name="c",
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=_FROZEN)(x)
        """})
    assert len(lines_hit(res, "pallas-interpret-thread")) == 3


def test_interpret_thread_negative(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/cfg.py": """\
            import os
            _INTERPRET = os.environ.get("X", "") not in ("", "0")
        """,
        "lightgbm_tpu/ops/k.py": """\
            import jax
            from jax.experimental import pallas as pl
            from .cfg import _INTERPRET

            def kern(x_ref, o_ref):
                o_ref[...] = x_ref[...]

            def launch_param(x, interpret):
                return pl.pallas_call(
                    kern, name="a",
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=interpret)(x)

            def launch_config(x):
                return pl.pallas_call(
                    kern, name="b",
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=_INTERPRET)(x)

            def launch_expr(x):
                return pl.pallas_call(
                    kern, name="c",
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=jax.default_backend() != "tpu")(x)
        """,
        # perf-harness scripts stay free to hardwire the mode
        "scripts/pallas_probe.py": """\
            import jax
            from jax.experimental import pallas as pl

            def kern(x_ref, o_ref):
                o_ref[...] = x_ref[...]

            def launch(x):
                return pl.pallas_call(
                    kern, name="p", interpret=True,
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
        """})
    assert "pallas-interpret-thread" not in rules_hit(res)


def test_interpret_thread_suppression(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax
            from jax.experimental import pallas as pl

            def kern(x_ref, o_ref):
                o_ref[...] = x_ref[...]

            def launch(x):
                return pl.pallas_call(  # graftlint: disable=pallas-interpret-thread -- CPU-only helper
                    kern, name="a",
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
        """})
    assert "pallas-interpret-thread" not in rules_hit(res)
    assert any(f.rule == "pallas-interpret-thread" for f in res.suppressed)


# ------------------------------------------------------- aliased-ref-read

def test_aliased_ref_read_positive(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def kern(x_ref, o_ref):
                o_ref[0] = x_ref[0] * 2
                stale = x_ref[0]
                o_ref[1] = stale

            def launch(x, interpret):
                return pl.pallas_call(
                    kern, name="a", input_output_aliases={0: 0},
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=interpret)(x)
        """})
    assert lines_hit(res, "aliased-ref-read") == [7]


def test_aliased_ref_read_negative(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def kern_read_first(x_ref, o_ref):
                v = x_ref[0]
                o_ref[0] = v * 2

            def kern_other_region(sref, w_in, w_out, fb, sem):
                dst = sref[0]
                src = sref[1]
                wr = pltpu.make_async_copy(
                    fb.at[0], w_out.at[dst, pl.ds(0, 8), :], sem.at[0])
                wr.wait()
                rd = pltpu.make_async_copy(
                    w_in.at[src, pl.ds(0, 8), :], fb.at[0], sem.at[1])
                rd.wait()
                rd2 = pltpu.make_async_copy(
                    w_out.at[dst, pl.ds(0, 8), :], fb.at[0], sem.at[2])
                rd2.wait()

            def kern_varargs(sref, *refs):
                refs[1][0] = 1
                v = refs[0][0]

            def launch(x, scalars, work, interpret):
                a = pl.pallas_call(
                    kern_read_first, name="a",
                    input_output_aliases={0: 0},
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=interpret)(x)
                b = pl.pallas_call(
                    kern_other_region, name="b",
                    input_output_aliases={1: 0},
                    out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype)],
                    interpret=interpret)(scalars, work)
                c = pl.pallas_call(
                    kern_varargs, name="c",
                    input_output_aliases={1: 0},
                    out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype)],
                    interpret=interpret)(scalars, work)
                return a, b, c
        """})
    assert "aliased-ref-read" not in rules_hit(res)


def test_aliased_ref_read_interprocedural(tmp_path):
    """The hazard hides in a helper the kernel hands its refs to — the
    engine inlines the helper's ref events at the call site."""
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def _drain(src, acc):
                return src[0] + acc

            def kern(x_ref, o_ref):
                o_ref[0] = x_ref[0] * 2
                acc = _drain(x_ref, 0)
                o_ref[1] = acc

            def launch(x, interpret):
                return pl.pallas_call(
                    kern, name="a", input_output_aliases={0: 0},
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=interpret)(x)
        """})
    assert lines_hit(res, "aliased-ref-read") == [6]


def test_aliased_ref_read_suppression(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def kern(x_ref, o_ref):
                o_ref[0] = x_ref[0] * 2
                v = x_ref[0]  # graftlint: disable=aliased-ref-read -- proven tpu-only kernel
                o_ref[1] = v

            def launch(x, interpret):
                return pl.pallas_call(
                    kern, name="a", input_output_aliases={0: 0},
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    interpret=interpret)(x)
        """})
    assert "aliased-ref-read" not in rules_hit(res)
    assert any(f.rule == "aliased-ref-read" for f in res.suppressed)


# ------------------------------------------------------ PR 17 regressions

def test_pr17_bugs_verbatim_regression(tmp_path):
    """Both PR 17 latent bugs, re-introduced verbatim (the pre-fix
    ``partition_segment_fused`` pallas_call with no ``interpret=`` and
    the RMW drain tile reading ``work_in`` where only ``work_ref`` holds
    the freshly-written rows): each must be caught by its rule."""
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/partition.py": """\
            from functools import partial
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def _partition_kernel(sref, work_in, work_ref, lt_ref, tril,
                                  cin, pre, lstage, rstage, lfb, rfb, sem,
                                  *, ch, sb, width, num_bin):
                dst_plane = 1 - sref[0]
                dstart = sref[1]
                d = sref[2]
                wr = pltpu.make_async_copy(
                    lfb.at[0], work_ref.at[dst_plane, pl.ds(dstart, ch), :],
                    sem.at[3])
                wr.start()
                wr.wait()
                at = dstart + d - ch
                rd = pltpu.make_async_copy(
                    work_in.at[dst_plane, pl.ds(at, ch), :], lfb.at[0], sem.at[4])
                rd.start()
                rd.wait()
                lt_ref[0] = d

            def partition_segment_fused(work, scalars, ch, sb, width,
                                        num_bin):
                kern = partial(_partition_kernel, ch=ch, sb=sb, width=width,
                               num_bin=num_bin)
                grid_spec = pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=1,
                    grid=(1,),
                    in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)],
                    out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                               pl.BlockSpec(memory_space=pltpu.SMEM)],
                )
                work_out, lt = pl.pallas_call(
                    kern,
                    name="partition_segment_fused",
                    grid_spec=grid_spec,
                    out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                               jax.ShapeDtypeStruct((1,), jnp.int32)],
                    input_output_aliases={1: 0},
                    compiler_params=pltpu.CompilerParams(
                        dimension_semantics=("arbitrary",),
                        vmem_limit_bytes=100 * 1024 * 1024),
                )(scalars, work)
                return work_out, lt[0]
        """})
    # bug #1: the pallas_call never threads interpret=
    assert lines_hit(res, "pallas-interpret-thread") == [36]
    # bug #2: the drain tile reads work_in after work_ref was written
    assert lines_hit(res, "aliased-ref-read") == [20]


# ------------------------------------------------------- recompile-hazard

def test_recompile_hazard_positive(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/dyn.py": """\
            import jax
            import jax.numpy as jnp

            def grow(counts, work):
                n = int(jnp.sum(counts))
                buf = jnp.zeros((n, 4), jnp.float32)
                sz = counts.item()
                view = jax.lax.dynamic_slice_in_dim(work, 0, sz)
                return buf, view
        """})
    assert lines_hit(res, "recompile-hazard") == [6, 8]


def test_recompile_hazard_interprocedural(tmp_path):
    """The tainted value crosses a call boundary; the sink is flagged in
    the helper that builds the shape."""
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/dyn.py": """\
            import jax.numpy as jnp

            def helper(m):
                return jnp.ones((m, 2), jnp.float32)

            def via(x):
                k = x.item()
                return helper(k)
        """})
    assert lines_hit(res, "recompile-hazard") == [4]


def test_recompile_hazard_negative(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/dyn.py": """\
            import jax.numpy as jnp

            def static_shapes(work, cfg):
                n = work.shape[0]
                pad = (n + 127) // 128 * 128
                return jnp.zeros((pad, 4), jnp.float32)

            def rebound(x):
                n = x.item()
                n = 128
                return jnp.zeros((n, 4), jnp.float32)

            def dynamic_start_is_legal(work, start):
                import jax
                return jax.lax.dynamic_slice_in_dim(work, start, 128)
        """})
    assert "recompile-hazard" not in rules_hit(res)


def test_recompile_hazard_suppression(tmp_path):
    res = make_project(tmp_path, {
        "lightgbm_tpu/ops/dyn.py": """\
            import jax.numpy as jnp

            def once(counts):
                n = int(jnp.sum(counts))
                return jnp.zeros((n, 4), jnp.float32)  # graftlint: disable=recompile-hazard -- one-time setup
        """})
    assert "recompile-hazard" not in rules_hit(res)
    assert any(f.rule == "recompile-hazard" for f in res.suppressed)


# --------------------------------------------------------- knob-contract

def _knob_fixture(**overrides):
    files = {
        "lightgbm_tpu/config.py": """\
            class Log:
                @staticmethod
                def fatal(msg, *a):
                    raise ValueError(msg % a)

            class Config:
                tpu_foo_kernel: str = "auto"
                tpu_bar_rows: int = 4096
                tpu_flag: bool = True

                def _check(self):
                    if self.tpu_foo_kernel not in ("auto", "pallas", "xla"):
                        Log.fatal("bad %s", self.tpu_foo_kernel)
                    if self.tpu_bar_rows < 1:
                        Log.fatal("bad %d", self.tpu_bar_rows)
        """,
        "lightgbm_tpu/learner.py": """\
            def resolve(config, telemetry):
                def _rec(knob, value, reason):
                    telemetry.record("auto_resolution", knob=knob,
                                     value=value, reason=reason)
                if config.tpu_foo_kernel == "auto":
                    _rec("tpu_foo_kernel", "pallas", "mosaic present")
        """,
        "scripts/foo_bisect.py":
            '"""Hardware harness for tpu_foo_kernel."""\n',
        "README.md": "| `tpu_foo_kernel` | `tpu_bar_rows` | `tpu_flag` |\n",
    }
    files.update(overrides)
    return {k: v for k, v in files.items() if v is not None}


def _knob_msgs(res):
    return [f.message for f in res.findings if f.rule == "knob-contract"]


def test_knob_contract_clean(tmp_path):
    res = make_project(tmp_path, _knob_fixture())
    assert "knob-contract" not in rules_hit(res)


def test_knob_contract_missing_bisect(tmp_path):
    """Deleting an auto knob's bisect harness trips the rule — and only
    for the auto knob (fixed and bool knobs need no harness)."""
    res = make_project(tmp_path, _knob_fixture(**{
        "scripts/foo_bisect.py": None}))
    msgs = _knob_msgs(res)
    assert len(msgs) == 1 and "tpu_foo_kernel" in msgs[0] \
        and "_bisect.py" in msgs[0]


def test_knob_contract_missing_validation(tmp_path):
    res = make_project(tmp_path, _knob_fixture(**{
        "lightgbm_tpu/config.py": """\
            class Config:
                tpu_foo_kernel: str = "auto"
                tpu_bar_rows: int = 4096
                tpu_flag: bool = True

                def _check(self):
                    if self.tpu_foo_kernel not in ("auto", "pallas", "xla"):
                        raise ValueError(self.tpu_foo_kernel)
        """}))
    msgs = _knob_msgs(res)
    # tpu_bar_rows lost its clause; tpu_flag is bool and stays exempt
    assert len(msgs) == 1 and "tpu_bar_rows" in msgs[0] \
        and "validation" in msgs[0]


def test_knob_contract_missing_readme_row(tmp_path):
    res = make_project(tmp_path, _knob_fixture(**{
        "README.md": "| `tpu_foo_kernel` | `tpu_flag` |\n"}))
    msgs = _knob_msgs(res)
    assert len(msgs) == 1 and "tpu_bar_rows" in msgs[0] \
        and "README" in msgs[0]


def test_knob_contract_unreasoned_resolution(tmp_path):
    res = make_project(tmp_path, _knob_fixture(**{
        "lightgbm_tpu/learner.py": """\
            def resolve(config, telemetry):
                def _rec(knob, value, reason):
                    telemetry.record("auto_resolution", knob=knob,
                                     value=value, reason=reason)
                if config.tpu_foo_kernel == "auto":
                    _rec("tpu_foo_kernel", "pallas", "")
        """}))
    msgs = _knob_msgs(res)
    assert len(msgs) == 1 and "tpu_foo_kernel" in msgs[0] \
        and "reason" in msgs[0]


def test_knob_contract_missing_resolution(tmp_path):
    res = make_project(tmp_path, _knob_fixture(**{
        "lightgbm_tpu/learner.py": "def resolve(config):\n    pass\n"}))
    msgs = _knob_msgs(res)
    assert len(msgs) == 1 and "tpu_foo_kernel" in msgs[0] \
        and "auto-resolution" in msgs[0]


def test_knob_contract_suppression(tmp_path):
    base = _knob_fixture(**{"scripts/foo_bisect.py": None})
    base["lightgbm_tpu/config.py"] = base["lightgbm_tpu/config.py"].replace(
        'tpu_foo_kernel: str = "auto"',
        'tpu_foo_kernel: str = "auto"  '
        '# graftlint: disable=knob-contract -- harness lands next PR')
    res = make_project(tmp_path, base)
    assert "knob-contract" not in rules_hit(res)
    assert any(f.rule == "knob-contract" for f in res.suppressed)


# --------------------------------------------------- baseline drift gate

def test_stale_baseline_entries(tmp_path):
    p = tmp_path / "lightgbm_tpu" / "x.py"
    p.parent.mkdir(parents=True)
    p.write_text("import time\nt0 = time.time()\n")
    res = lint.run(str(tmp_path))
    baseline = lint.baseline_from_findings(res.findings)
    assert lint.stale_baseline_entries(str(tmp_path), baseline) == []
    p.write_text("def f():\n    return 0\n")
    stale = lint.stale_baseline_entries(str(tmp_path), baseline)
    assert [e["rule"] for e in stale] == ["naked-timer"]
    # a deleted file goes stale too
    p.unlink()
    assert len(lint.stale_baseline_entries(str(tmp_path), baseline)) == 1


def _cli(args, root, **kw):
    env = dict(os.environ, LGBTPU_LINT_ROOT=str(root))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py")] + args,
        capture_output=True, text=True, cwd=REPO, env=env, **kw)


def test_cli_baseline_drift_lifecycle(tmp_path):
    """Freeze -> fix -> the stale entry fails the run (baseline drift) ->
    --update-baseline prunes it and reports the pruned count."""
    p = tmp_path / "lightgbm_tpu" / "x.py"
    p.parent.mkdir(parents=True)
    p.write_text("import time\nt0 = time.time()\n")
    assert _cli([], tmp_path).returncode == 1       # unbaselined finding
    out = _cli(["--update-baseline"], tmp_path)
    assert out.returncode == 0 and "1 findings frozen" in out.stdout
    assert _cli([], tmp_path).returncode == 0       # frozen
    p.write_text("def f():\n    return 0\n")        # fixed upstream
    out = _cli([], tmp_path)
    assert out.returncode == 1
    assert "stale baseline entry" in out.stdout
    out = _cli(["--update-baseline"], tmp_path)
    assert out.returncode == 0 and "1 stale entry pruned" in out.stdout
    assert _cli([], tmp_path).returncode == 0


def test_cli_update_baseline_rejects_changed():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--update-baseline", "--changed"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2
    assert "full run" in out.stderr


def test_cli_list_rules_lists_every_rule():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--list-rules"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0
    listed = {}
    for line in out.stdout.splitlines():
        rid, _, desc = line.partition(" ")
        listed[rid] = desc.strip()
    assert set(listed) == set(lint.all_rules())
    for rid, rule in lint.all_rules().items():
        assert rule.description, rid
        assert listed[rid], rid
