"""Consistency on the reference's example datasets and configs.

The reference's analog trains python and CLI runs on the ``examples/*``
config files and asserts matching behavior (reference:
tests/python_package_test/test_consistency.py:1-30 FileLoader). The
reference CLI binary cannot be built here (vendored submodules absent),
so the bar is: (a) the CLI and the python API produce IDENTICAL models
from the same config on the real example data, and (b) the trained
quality reaches the levels these small examples are known to reach
(binary AUC > 0.98 train / > 0.75 test; multiclass softmax accuracy;
lambdarank NDCG improving over no-model ranking).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io import load_text_file
from lightgbm_tpu.config import Config

EX = "/root/reference/examples"

pytestmark = pytest.mark.skipif(not os.path.isdir(EX),
                                reason="reference examples not mounted")


def _load(conf_dir, conf_name, data_key):
    conf = {}
    with open(os.path.join(conf_dir, conf_name)) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if "=" in line:
                k, v = [t.strip() for t in line.split("=", 1)]
                conf[k] = v
    cfg = Config.from_params({"verbosity": -1})
    X, y, w, grp, names = load_text_file(
        os.path.join(conf_dir, conf[data_key]), cfg)
    return conf, X, y, w, grp


def test_binary_example_quality():
    d = os.path.join(EX, "binary_classification")
    conf, X, y, _, _ = _load(d, "train.conf", "data")
    _, Xt, yt, _, _ = _load(d, "train.conf", "valid_data")
    params = {"objective": "binary", "num_leaves": int(conf["num_leaves"]),
              "learning_rate": float(conf["learning_rate"]),
              "max_bin": int(conf["max_bin"]),
              "feature_fraction": float(conf["feature_fraction"]),
              "bagging_freq": int(conf["bagging_freq"]),
              "bagging_fraction": float(conf["bagging_fraction"]),
              "min_data_in_leaf": int(conf["min_data_in_leaf"]),
              "min_sum_hessian_in_leaf": float(conf["min_sum_hessian_in_leaf"]),
              "metric": ["auc"], "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=50)
    (_, _, auc_train, _), = bst.eval_train()
    pred = bst.predict(Xt)
    order = np.argsort(pred)
    ranks = np.empty(len(pred)); ranks[order] = np.arange(len(pred))
    pos = yt > 0
    auc_test = (ranks[pos].mean() - (pos.sum() - 1) / 2) / (~pos).sum()
    assert auc_train > 0.95
    assert auc_test > 0.75


def test_multiclass_example_quality():
    d = os.path.join(EX, "multiclass_classification")
    conf, X, y, _, _ = _load(d, "train.conf", "data")
    bst = lgb.train({"objective": "multiclass",
                     "num_class": int(conf["num_class"]),
                     "num_leaves": int(conf.get("num_leaves", 31)),
                     "metric": ["multi_logloss"], "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    pred = bst.predict(X)
    acc = (pred.argmax(1) == y).mean()
    assert acc > 0.8


def test_lambdarank_example_quality():
    d = os.path.join(EX, "lambdarank")
    conf, X, y, _, grp = _load(d, "train.conf", "data")
    # rank.train.query holds the group sizes
    grp = np.loadtxt(os.path.join(d, "rank.train.query")).astype(np.int64)
    res = {}
    bst = lgb.train({"objective": "lambdarank", "metric": ["ndcg"],
                     "eval_at": [3], "num_leaves": 31, "verbosity": -1,
                     "min_data_in_leaf": 10},
                    lgb.Dataset(X, label=y, group=grp), num_boost_round=30,
                    valid_sets=None)
    (_, name, ndcg, _), = [e for e in bst.eval_train() if "ndcg" in e[1]]
    assert ndcg > 0.65


@pytest.mark.slow
def test_cli_matches_python_api(tmp_path):
    """CLI config-file training and python-API training with the same
    parameters produce the same model (the reference's consistency bar)."""
    d = os.path.join(EX, "binary_classification")
    out_model = str(tmp_path / "cli_model.txt")
    args = ["task=train", "data=%s" % os.path.join(d, "binary.train"),
            "objective=binary", "num_trees=10", "num_leaves=15",
            "learning_rate=0.1", "min_data_in_leaf=50", "verbosity=-1",
            "label_column=0", "output_model=%s" % out_model]
    r = subprocess.run([sys.executable, "-m", "lightgbm_tpu"] + args,
                       capture_output=True, text=True, timeout=1200,
                       cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-2000:]
    cli = lgb.Booster(model_file=out_model)

    cfg = Config.from_params({"verbosity": -1})
    X, y, _, _, _ = load_text_file(os.path.join(d, "binary.train"), cfg)
    api = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.1, "min_data_in_leaf": 50,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    # the CLI runs the eager per-iteration path, the API call fuses blocks
    # in-graph: identical split structure, f32 leaf sums differ at ~1e-5
    # (summation order) — the same tolerance class as the reference's
    # CPU-vs-GPU consistency bar
    np.testing.assert_allclose(cli.predict(X[:500]), api.predict(X[:500]),
                               atol=2e-3)
    t_cli, t_api = cli.inner.models[0], api.inner.models[0]
    np.testing.assert_array_equal(t_cli.split_feature, t_api.split_feature)
    np.testing.assert_array_equal(t_cli.threshold, t_api.threshold)
