"""Best-split scan vs exhaustive naive search."""
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.ops.split import (
    FeatureMeta, SplitHyper, find_best_split, leaf_objective_value)


def _meta(num_bins, nan_missing=None, is_cat=None):
    f = len(num_bins)
    nb = np.asarray(num_bins, np.int32)
    nanm = np.zeros(f, bool) if nan_missing is None else np.asarray(nan_missing)
    cat = np.zeros(f, bool) if is_cat is None else np.asarray(is_cat)
    return FeatureMeta(
        num_bins=jnp.asarray(nb),
        movable_missing=jnp.asarray(nanm),
        missing_bin=jnp.asarray(np.where(nanm, nb - 1, 0).astype(np.int32)),
        is_categorical=jnp.asarray(cat),
        monotone=jnp.zeros(f, jnp.int8),
        penalty=jnp.ones(f, jnp.float32),
        cegb_coupled=jnp.zeros(f, jnp.float32),
    )


def _naive_best(hist, parent, num_bins, hp):
    """Exhaustive numerical threshold search, default-right only, no missing."""
    def gain(g, h):
        if h + hp.lambda_l2 <= 0:
            return 0.0
        tl1 = np.sign(g) * max(abs(g) - hp.lambda_l1, 0)
        return tl1 ** 2 / (h + hp.lambda_l2)
    pg = gain(parent[0], parent[1])
    best = (-np.inf, -1, -1)
    for f in range(hist.shape[0]):
        for t in range(num_bins[f] - 1):
            left = hist[f, : t + 1].sum(axis=0)
            right = parent - left
            if left[2] < hp.min_data_in_leaf or right[2] < hp.min_data_in_leaf:
                continue
            if left[1] < hp.min_sum_hessian_in_leaf or right[1] < hp.min_sum_hessian_in_leaf:
                continue
            imp = gain(left[0], left[1]) + gain(right[0], right[1]) - pg
            if imp > best[0]:
                best = (imp, f, t)
    return best


def test_matches_naive_numerical(rng):
    f, b = 5, 16
    num_bins = [16, 12, 8, 16, 5]
    hist = np.zeros((f, b, 3), np.float32)
    for i in range(f):
        nb = num_bins[i]
        hist[i, :nb, 0] = rng.randn(nb) * 3
        hist[i, :nb, 1] = rng.rand(nb) + 0.1
        hist[i, :nb, 2] = rng.randint(1, 50, nb)
    # make per-feature totals consistent with a shared parent
    parent = hist[0].sum(axis=0)
    for i in range(1, f):
        s = hist[i].sum(axis=0)
        hist[i] *= (parent / np.maximum(s, 1e-10))[None, :]
    hp = SplitHyper(min_data_in_leaf=3.0, lambda_l2=0.5)
    info = find_best_split(jnp.asarray(hist), jnp.asarray(parent),
                           _meta(num_bins), jnp.ones(f, bool), hp)
    exp_gain, exp_f, exp_t = _naive_best(hist, parent, num_bins, hp)
    assert abs(float(info.gain) - exp_gain) < 1e-2 * max(1, abs(exp_gain))
    assert int(info.feature) == exp_f
    assert int(info.bin) == exp_t


def test_min_data_blocks_split():
    f, b = 1, 4
    hist = np.zeros((f, b, 3), np.float32)
    hist[0, :, 0] = [5, -5, 4, -4]
    hist[0, :, 1] = 1.0
    hist[0, :, 2] = 5
    parent = hist[0].sum(axis=0)
    hp = SplitHyper(min_data_in_leaf=100.0)
    info = find_best_split(jnp.asarray(hist), jnp.asarray(parent),
                           _meta([4]), jnp.ones(1, bool), hp)
    assert float(info.gain) == -np.inf


def test_missing_direction():
    """NaN bin mass should be routed to whichever side improves gain."""
    f, b = 1, 5
    hist = np.zeros((f, b, 3), np.float32)
    # value bins 0..3, missing bin 4; negatives left, positives right,
    # missing gradient aligned with LEFT side
    hist[0, :, 0] = [-10, -8, 9, 8, -6]
    hist[0, :, 1] = [2, 2, 2, 2, 2]
    hist[0, :, 2] = [10, 10, 10, 10, 10]
    parent = hist[0].sum(axis=0)
    hp = SplitHyper(min_data_in_leaf=1.0)
    info = find_best_split(jnp.asarray(hist), jnp.asarray(parent),
                           _meta([5], nan_missing=[True]), jnp.ones(1, bool), hp)
    assert bool(info.default_left)
    tbl = np.asarray(info.go_left)
    assert tbl[4]  # missing goes left
    assert tbl[0] and tbl[1] and not tbl[2]


def test_feature_mask_respected():
    f, b = 2, 4
    hist = np.zeros((f, b, 3), np.float32)
    hist[:, :, 0] = [[9, -9, 9, -9], [1, -1, 1, -1]]
    hist[:, :, 1] = 1.0
    hist[:, :, 2] = 25.0
    parent = hist[0].sum(axis=0)
    hp = SplitHyper(min_data_in_leaf=1.0)
    mask = jnp.asarray([False, True])
    info = find_best_split(jnp.asarray(hist), jnp.asarray(parent),
                           _meta([4, 4]), mask, hp)
    assert int(info.feature) == 1


def test_categorical_onehot():
    f, b = 1, 4  # 3 categories + other bin
    hist = np.zeros((f, b, 3), np.float32)
    hist[0, :, 0] = [20, -10, -10, 0]
    hist[0, :, 1] = [5, 5, 5, 0.001]
    hist[0, :, 2] = [30, 30, 30, 1]
    parent = hist[0].sum(axis=0)
    hp = SplitHyper(min_data_in_leaf=1.0, min_sum_hessian_in_leaf=0.0,
                    has_categorical=True, max_cat_to_onehot=4)
    info = find_best_split(jnp.asarray(hist), jnp.asarray(parent),
                           _meta([4], is_cat=[True]), jnp.ones(1, bool), hp)
    assert int(info.kind) == 1
    assert int(info.bin) == 0  # category 0 isolated
    tbl = np.asarray(info.go_left)
    assert tbl[0] and not tbl[1] and not tbl[2]


def test_categorical_many_vs_many():
    f, b = 1, 9  # 8 categories + other
    hist = np.zeros((f, b, 3), np.float32)
    g = np.asarray([5, -5, 4, -4, 3, -3, 2, -2], np.float32)
    hist[0, :8, 0] = g
    hist[0, :8, 1] = 2.0
    hist[0, :8, 2] = 20.0
    parent = hist[0].sum(axis=0)
    hp = SplitHyper(min_data_in_leaf=1.0, min_data_per_group=1.0,
                    has_categorical=True, max_cat_to_onehot=2, cat_smooth=0.0,
                    cat_l2=0.0)
    info = find_best_split(jnp.asarray(hist), jnp.asarray(parent),
                           _meta([9], is_cat=[True]), jnp.ones(1, bool), hp)
    assert int(info.kind) in (2, 3)
    tbl = np.asarray(info.go_left)
    # optimal grouping separates positive-gradient from negative-gradient cats
    side_neg = set(np.flatnonzero(tbl))
    assert side_neg in ({1, 3, 5, 7}, {0, 2, 4, 6})


def test_monotone_constraint_blocks():
    f, b = 1, 4
    hist = np.zeros((f, b, 3), np.float32)
    # increasing feature with DECREASING response: +1 constraint must block
    hist[0, :, 0] = [-10, -5, 5, 10]   # grad = pred-target => left wants +, right -
    hist[0, :, 1] = 2.0
    hist[0, :, 2] = 20.0
    parent = hist[0].sum(axis=0)
    hp = SplitHyper(min_data_in_leaf=1.0, has_monotone=True)
    meta = _meta([4])._replace(monotone=jnp.asarray([1], jnp.int8))
    info = find_best_split(jnp.asarray(hist), jnp.asarray(parent), meta,
                           jnp.ones(1, bool), hp)
    assert float(info.gain) == -np.inf
