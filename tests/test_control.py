"""Fleet control-plane tests (ISSUE 20): the remote write surface, the
multi-endpoint read side, ingest forwarding, and snapshot bootstrap.

The contracts under test: a trainer with NO filesystem access to the
store drives the full lease -> fenced publish -> ingest/gate/compact
cycle over ``POST /fleet/*``, and a forged stale-epoch publish dies at
the store host with a 409 exactly as a local zombie dies at the store
lock — never written, never adopted; a replica following TWO endpoints
through a :class:`MultiEndpointStore` survives its primary going dark
mid-poll with exactly one version bump per applied publish (failover
changes which socket answers, never how many adopts happen); labeled
traffic hitting a node with no trainer is relayed to the lease holder
within a bounded ``X-Fleet-Hops`` chain, re-aiming once on a 409
``leader_hint``; and ``compact(snapshot_rows=N)`` folds buffer contents
into a versioned snapshot artifact from which a cold standby — local or
HTTP-only — replays BIT-identically to a full-log boot, including a cut
mid-shadow-window. The new ``partition``/``reorder`` chaos kinds are
exercised against this write surface with the same seeded determinism
as the PR-14 kinds.
"""
import json
import os
import shutil
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.fleet import FleetStore, IngestForwarder, \
    MultiEndpointStore, RemoteStore, RemoteWriteStore, ReplicaWatcher, \
    StaleLeaseError, TransportError, bootstrap_model, chaos  # noqa: E402
from lightgbm_tpu.fleet.chaos import FaultPlan  # noqa: E402
from lightgbm_tpu.fleet.control import EndpointSelector  # noqa: E402
from lightgbm_tpu.obs import telemetry  # noqa: E402
from lightgbm_tpu.online import OnlineTrainer  # noqa: E402
from lightgbm_tpu.serve import PredictServer  # noqa: E402
from lightgbm_tpu.utils.log import LightGBMError  # noqa: E402

from tests.conftest import clean_cpu_env  # noqa: E402

W = np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4])


def _data(n, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, len(W))
    y = (X @ W + 0.2 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _train(n=300, seed=0, rounds=6):
    X, y = _data(n, seed)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


def _trainer(bst, store, **kw):
    """Trainer with the gate wide open (threshold 2.0) so a refit
    candidate always banks a win — these tests exercise the control
    plane, not the gate's judgment."""
    kw.setdefault("trigger_rows", 10 ** 9)
    kw.setdefault("min_rows", 64)
    kw.setdefault("shadow_rows", 120)
    kw.setdefault("promote_threshold", 2.0)
    kw.setdefault("promote_patience", 2)
    kw.setdefault("start", False)
    return OnlineTrainer(bst, store=store, **kw)


def _host(store, bst=None, online=None, forwarder=None):
    """One in-process store-host endpoint: a PredictServer with the
    given FleetStore attached (and optionally a live trainer and/or an
    ingest forwarder), serving on an ephemeral port."""
    server = PredictServer(bst if bst is not None else _train(), port=0,
                           buckets=(16, 64), max_wait_ms=1.0,
                           online=online)
    server.fleet_store = store
    if forwarder is not None:
        server.ingest_forwarder = forwarder
    th = threading.Thread(target=server.serve_forever,
                          name="control-test-http", daemon=True)
    th.start()
    host, port = server.address
    return server, th, "http://%s:%d" % (host, port)


def _stop(server, thread):
    server.shutdown()
    thread.join(timeout=30)
    server.close()


# ------------------------------------------------------------ remote lease

def test_remote_lease_acquire_renew_release_epoch_bumps(tmp_path):
    """POST /fleet/lease round-trips the full lease lifecycle, and every
    acquisition bumps the fencing epoch — the remote client sees the
    SAME monotonic epochs a local holder would."""
    store = FleetStore(str(tmp_path), "m")
    server, th, base = _host(store)
    try:
        remote = RemoteWriteStore(base, timeout_s=10.0)
        assert remote.lease_state()["held"] is False
        e1 = remote.acquire_lease("t1", 30.0, url="http://t1:80")
        assert e1 == 1
        lease = remote.lease_state()
        assert lease["held"] and lease["holder"] == "t1"
        assert lease["epoch"] == 1 and lease["url"] == "http://t1:80"
        # a live lease refuses a second holder, over HTTP as locally
        assert remote.acquire_lease("t2", 30.0) is None
        assert remote.renew_lease("t1", e1, 30.0) is True
        # renewing with a forged epoch is refused
        assert remote.renew_lease("t1", e1 + 7, 30.0) is False
        assert remote.release_lease("t1", e1) is True
        assert remote.lease_state()["held"] is False
        # the epoch NEVER rewinds: next acquisition fences out epoch 1
        assert remote.acquire_lease("t2", 30.0) == 2
        # the host-side lease is the same record the local path sees
        assert store.lease_state()["holder"] == "t2"
    finally:
        _stop(server, th)


def test_remote_fenced_publish_forged_epoch_409_never_adopted(tmp_path):
    """The acceptance pin, in-process: a remote publish carrying a stale
    lease epoch is rejected 409 by the store host, raises the same
    StaleLeaseError the local fence raises, writes NOTHING, and a
    watching replica never adopts it."""
    store = FleetStore(str(tmp_path), "m")
    store.publish(_train().model_to_string(), event="boot")
    server, th, base = _host(store)
    try:
        # the replica, over plain read-only HTTP
        rb, applied = bootstrap_model(RemoteStore(base, timeout_s=10.0))
        watcher = ReplicaWatcher(rb, RemoteStore(base, timeout_s=10.0),
                                 applied_version=applied, start=False)
        v0 = rb.inner.model_version

        writer = RemoteWriteStore(base, timeout_s=10.0)
        epoch = writer.acquire_lease("t1", 30.0)
        writer.set_fence("t1", epoch)
        assert writer.publish(_train(seed=1).model_to_string()) == 2
        assert watcher.poll_once() is True
        assert rb.inner.model_version == v0 + 1

        # the lease moves on (crash + takeover): epoch bumps to 2
        assert writer.release_lease("t1", epoch)
        zombie = RemoteWriteStore(base, timeout_s=10.0)
        zombie.set_fence("t1", epoch)          # stale fence, forged on
        e2 = writer.acquire_lease("t2", 30.0)  # the wire by a dead node
        assert e2 == epoch + 1
        blocked0 = telemetry.counter("fleet/stale_publishes_blocked_remote")
        with pytest.raises(StaleLeaseError):
            zombie.publish(_train(seed=2).model_to_string())
        assert telemetry.counter(
            "fleet/stale_publishes_blocked_remote") == blocked0 + 1
        # nothing landed: same head version, and the replica sees no
        # newer publish to adopt
        assert store.latest_publish()["version"] == 2
        assert watcher.poll_once() is False
        assert rb.inner.model_version == v0 + 1

        # a torn upload (sha mismatch) dies BEFORE the fence check: 400
        # on the wire, CorruptArtifactError at the client, nothing written
        from lightgbm_tpu.fleet import CorruptArtifactError
        writer.set_fence("t2", e2)
        good = _train(seed=3).model_to_string()
        orig = writer._request

        def corrupting(path, data=None, no_retry=()):
            if path.endswith("/publish") and data is not None:
                body = json.loads(data.decode("utf-8"))
                body["model"] = body["model"] + "x"   # bytes != sha256
                data = json.dumps(body, sort_keys=True).encode("utf-8")
            return orig(path, data=data, no_retry=no_retry)

        writer._request = corrupting
        with pytest.raises(CorruptArtifactError):
            writer.publish(good)
        writer._request = orig
        assert store.latest_publish()["version"] == 2
    finally:
        _stop(server, th)


def test_remote_trainer_full_cycle_over_http(tmp_path):
    """OnlineTrainer(store=RemoteWriteStore(url)) runs the whole fleet
    cycle — lease, ingest persistence, gate appends, fenced publish —
    without touching the store's filesystem, and a second remote
    standby replays the identical state from the same endpoint."""
    store = FleetStore(str(tmp_path), "m")
    base_str = _train().model_to_string()
    store.publish(base_str, event="boot")
    server, th, base = _host(store)
    try:
        remote = RemoteWriteStore(base, timeout_s=10.0)
        tr = _trainer(lgb.Booster(model_str=base_str), remote,
                      lease_ttl_s=30.0)
        assert tr.try_acquire() is True
        tr.ingest(*_data(150, seed=5))
        assert tr.run_once() == "deferred"           # banks one win
        tr.ingest(*_data(60, seed=6))                # untrained tail
        st = tr.state()
        assert st["consumed_rows"] == 150 and st["win_streak"] == 1
        # everything the trainer persisted went over the wire
        assert sum(e["n"] for e in store.events("ingest")) == 210
        assert list(store.events("gate"))[-1]["wins"] == 1

        # remote standby: same endpoint, fresh booster, replayed state
        standby = _trainer(lgb.Booster(model_str=base_str),
                           RemoteWriteStore(base, timeout_s=10.0))
        assert standby.state()["consumed_rows"] == 150
        assert standby.state()["win_streak"] == 1
        assert standby.buffer.rows == tr.buffer.rows == 60
        Xa, ya = tr.buffer.shadow()
        Xb, yb = standby.buffer.shadow()
        np.testing.assert_array_equal(Xa, Xb)
        np.testing.assert_array_equal(ya, yb)

        # the banked win completes THROUGH the write surface
        tr.ingest(*_data(100, seed=7))
        assert tr.run_once() == "promoted"
        assert store.latest_publish()["version"] == 2
        assert store.latest_publish()["lease_epoch"] >= 1
    finally:
        _stop(server, th)


# -------------------------------------------------------- endpoint selector

def test_endpoint_selector_ranking_cooldown_and_switches():
    sel = EndpointSelector(["http://a", "http://b", "http://c"],
                           cooldown_base_s=0.05, cooldown_max_s=0.2)
    assert sel.current() == "http://a"
    # sticky current leads; liveness evidence ranks the rest
    sel.observe("http://b", head_version=3, heartbeat_age_s=1.0)
    sel.observe("http://c", head_version=5, heartbeat_age_s=9.0)
    assert sel.candidates() == ["http://a", "http://c", "http://b"]
    # equal heads: the fresher heartbeat wins the tie
    sel.observe("http://c", head_version=3, heartbeat_age_s=9.0)
    assert sel.candidates() == ["http://a", "http://b", "http://c"]
    # a failure cools the primary: it drops to the BACK, never vanishes
    sel.report_failure("http://a")
    cands = sel.candidates()
    assert cands[-1] == "http://a" and set(cands) == set(sel.urls)
    # success on the runner-up is a counted switch
    s0 = sel.state()["switches"]
    sel.report_success("http://b")
    assert sel.current() == "http://b"
    assert sel.state()["switches"] == s0 + 1
    # capped exponential: repeated failures double up to the cap
    for _ in range(8):
        sel.report_failure("http://a")
    assert sel.state()["endpoints"]["http://a"]["cooling_s"] <= 0.2
    # cooldown expires: the endpoint returns to the healthy pool
    time.sleep(0.25)
    assert "http://a" in sel.candidates()
    with pytest.raises(LightGBMError):
        EndpointSelector([])
    with pytest.raises(LightGBMError):
        EndpointSelector(["http://a", "http://a/"])


def test_multi_endpoint_failover_one_bump_per_publish(tmp_path):
    """The acceptance pin: a watcher following two endpoints through a
    MultiEndpointStore keeps adopting when its primary dies mid-poll —
    switching within the cooldown cap, with exactly one version bump per
    applied publish (failover must never double-adopt)."""
    store = FleetStore(str(tmp_path), "m")
    store.publish(_train().model_to_string(), event="boot")
    s1, t1, b1 = _host(FleetStore(str(tmp_path), "m"))
    s2, t2, b2 = _host(FleetStore(str(tmp_path), "m"))
    try:
        mstore = MultiEndpointStore([b1, b2], timeout_s=10.0,
                                    cooldown_base_s=0.05,
                                    cooldown_max_s=0.5)
        rb, applied = bootstrap_model(mstore)
        watcher = ReplicaWatcher(rb, mstore, applied_version=applied,
                                 start=False)
        v0 = rb.inner.model_version
        assert mstore.base_url == b1

        store.publish(_train(seed=1).model_to_string())
        assert watcher.poll_once() is True
        assert rb.inner.model_version == v0 + 1

        # kill the PRIMARY endpoint; the next poll sweeps to the
        # secondary inside the same call — no lost adoption window
        _stop(s1, t1)
        s1 = None
        switches0 = telemetry.counter("fleet/endpoint_switches")
        store.publish(_train(seed=2).model_to_string())
        assert watcher.poll_once() is True
        assert mstore.base_url == b2
        assert telemetry.counter("fleet/endpoint_switches") == switches0 + 1
        # exactly one bump per applied publish, across the failover
        st = watcher.state()
        assert rb.inner.model_version - v0 == st["swaps"] == 2
        # nothing new: poll is a no-op, still on the survivor
        assert watcher.poll_once() is False
        assert rb.inner.model_version == v0 + 2

        # both endpoints dark -> a real TransportError, not a hang
        _stop(s2, t2)
        s2 = None
        with pytest.raises(TransportError):
            mstore.latest_publish()
    finally:
        if s1 is not None:
            _stop(s1, t1)
        if s2 is not None:
            _stop(s2, t2)


# --------------------------------------------------------- ingest forwarding

def test_ingest_forwarding_relays_to_lease_holder(tmp_path):
    """Labeled traffic POSTed to a node with no trainer is relayed to
    the lease holder's /ingest and lands in ITS buffer; the response
    names the node that actually trained on the rows."""
    from urllib.request import Request, urlopen
    store = FleetStore(str(tmp_path), "m")
    bst = _train()
    leader_tr = _trainer(lgb.Booster(model_str=bst.model_to_string()),
                         None)
    ls, lt, lbase = _host(store, bst=bst, online=leader_tr)
    fstore = FleetStore(str(tmp_path), "m")
    fs, ft, fbase = _host(fstore,
                          forwarder=IngestForwarder(store=fstore,
                                                    timeout_s=10.0))
    try:
        assert store.acquire_lease("leader", 30.0, url=lbase) == 1
        X, y = _data(48, seed=9)
        body = json.dumps({"rows": X.tolist(),
                           "labels": y.tolist()}).encode()
        fwd0 = telemetry.counter("fleet/forwarded_rows")
        with urlopen(Request(fbase + "/ingest", data=body),
                     timeout=30) as resp:
            doc = json.loads(resp.read())
        assert doc["forwarded_to"] == lbase
        assert leader_tr.buffer.rows == 48
        assert telemetry.counter("fleet/forwarded_rows") == fwd0 + 48
    finally:
        _stop(fs, ft)
        _stop(ls, lt)


def test_ingest_forwarding_follows_leader_hint_and_bounds_hops(tmp_path):
    """A stale cached leader is corrected by the 409 leader_hint within
    the hop budget; a relay arriving AT the budget is refused (503 on
    the wire), so a cycling hint chain dies instead of looping."""
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen
    store = FleetStore(str(tmp_path), "m")
    bst = _train()
    leader_tr = _trainer(lgb.Booster(model_str=bst.model_to_string()),
                         None)
    ls, lt, lbase = _host(store, bst=bst, online=leader_tr)
    # a second trainer-less node: answers ingest with 409 + leader_hint
    ws, wt, wbase = _host(FleetStore(str(tmp_path), "m"))
    fstore = FleetStore(str(tmp_path), "m")
    fwd = IngestForwarder(store=fstore, timeout_s=10.0, max_hops=3)
    try:
        assert store.acquire_lease("leader", 30.0, url=lbase) == 1
        # prime the forwarder's cache with the WRONG node (a leader that
        # just moved): the 409 hint must re-aim the relay to the truth
        fwd._cached_leader = wbase
        fwd._cached_at = time.monotonic()  # graftlint: disable=naked-timer -- priming the forwarder's own monotonic cache stamp
        X, y = _data(32, seed=11)
        doc = fwd.forward("default", X.tolist(), y.tolist())
        assert doc["forwarded_to"] == lbase
        assert leader_tr.buffer.rows == 32

        # the hop budget: an incoming relay already at max_hops is
        # refused at the forwarder...
        with pytest.raises(TransportError):
            fwd.forward("default", X.tolist(), y.tolist(),
                        hops=fwd.max_hops)
        # ...and over the wire the host maps that to a 503
        fs, ft, fbase = _host(fstore, forwarder=fwd)
        try:
            body = json.dumps({"rows": X.tolist(),
                               "labels": y.tolist()}).encode()
            req = Request(fbase + "/ingest", data=body,
                          headers={"X-Fleet-Hops": str(fwd.max_hops)})
            with pytest.raises(HTTPError) as exc_info:
                urlopen(req, timeout=30)
            assert exc_info.value.code == 503
        finally:
            _stop(fs, ft)
        # no trainer + NO forwarder stays the PR-13 contract: 409 with
        # a leader_hint the client may chase itself
        body = json.dumps({"rows": X.tolist(),
                           "labels": y.tolist()}).encode()
        with pytest.raises(HTTPError) as exc_info:
            urlopen(Request(wbase + "/ingest", data=body), timeout=30)
        assert exc_info.value.code == 409
        hint = json.loads(exc_info.value.read()).get("leader_hint")
        assert hint == lbase
    finally:
        _stop(ws, wt)
        _stop(ls, lt)


# --------------------------------------------------------- snapshot bootstrap

def test_snapshot_bootstrap_bit_identity(tmp_path):
    """Satellite 4: compaction with snapshot_rows folds the retained
    ingest chunks into ONE snapshot artifact, the cut lands mid-shadow-
    window, and a standby booted from snapshot + tail is BIT-identical
    to a full-replay boot — same watermark, same streak, same buffers,
    and the banked win refits to the SAME model string. A second
    standby boots the same snapshot over HTTP only."""
    base = _train()
    base_str = base.model_to_string()
    orig = str(tmp_path / "orig")
    full = str(tmp_path / "full")
    store = FleetStore(orig, "m")
    tr = _trainer(lgb.Booster(model_str=base_str), store)
    for seed in (1, 2, 3):
        tr.ingest(*_data(30, seed=seed))
    assert tr.run_once() == "deferred"      # wins=1, watermark=90
    for seed in (4, 5):
        tr.ingest(*_data(25, seed=seed))    # 50 untrained rows on top
    assert tr.buffer.shadow_rows == 110 and tr.buffer.rows == 50
    shutil.copytree(orig, full)

    summary = store.compact(watermark=90, wins=1,
                            keep_rows=tr.buffer.shadow_capacity,
                            snapshot_rows=tr.buffer.shadow_capacity)
    snap = summary.get("snapshot")
    assert isinstance(snap, dict) and snap["rows"] == 110
    assert os.path.exists(store.snapshot_path(snap["id"]))
    # the log itself holds NO ingest lines any more — they live in the
    # snapshot blob; replay offsets come from the compact record
    kinds = [e["kind"] for e in store.events()]
    assert kinds.count("ingest") == 0 and kinds[0] == "compact"

    # three cold boots: snapshot+tail (local), snapshot+tail (HTTP),
    # and the untouched full log
    bst_s = lgb.Booster(model_str=base_str)
    bst_f = lgb.Booster(model_str=base_str)
    tr_s = _trainer(bst_s, FleetStore(orig, "m"))
    tr_f = _trainer(bst_f, FleetStore(full, "m"))
    server, th, base_url = _host(FleetStore(orig, "m"))
    try:
        tr_r = _trainer(lgb.Booster(model_str=base_str),
                        RemoteWriteStore(base_url, timeout_s=10.0))
        for a in (tr_s, tr_r):
            assert a.state()["consumed_rows"] \
                == tr_f.state()["consumed_rows"] == 90
            assert a.state()["win_streak"] \
                == tr_f.state()["win_streak"] == 1
            assert a.buffer.rows == tr_f.buffer.rows == 50
            assert a.buffer.shadow_rows == tr_f.buffer.shadow_rows == 110
            Xa, ya = a.buffer.shadow()
            Xf, yf = tr_f.buffer.shadow()
            np.testing.assert_array_equal(Xa, Xf)
            np.testing.assert_array_equal(ya, yf)
        # the banked win completes identically on both boot paths: the
        # SAME fresh rows trigger the SAME refit over the SAME buffers
        X6, y6 = _data(100, seed=6)
        tr_s.ingest(X6, y6)
        tr_f.ingest(X6, y6)
        assert tr_s.run_once() == "promoted"
        assert tr_f.run_once() == "promoted"
        assert bst_s.model_to_string() == bst_f.model_to_string()
    finally:
        _stop(server, th)


def test_snapshot_corruption_degrades_not_crashes(tmp_path):
    """A missing/corrupt snapshot blob costs the buffered rows it held,
    never misaligns replay: the standby boots with empty buffers at the
    compact record's row_base instead of crashing or double-counting."""
    base_str = _train().model_to_string()
    store = FleetStore(str(tmp_path), "m")
    tr = _trainer(lgb.Booster(model_str=base_str), store)
    for seed in (1, 2):
        tr.ingest(*_data(30, seed=seed))
    summary = store.compact(watermark=0, wins=0, keep_rows=200,
                            snapshot_rows=200)
    sid = summary["snapshot"]["id"]
    with open(store.snapshot_path(sid), "r+b") as f:
        f.write(b"}corrupt{")
    fails0 = telemetry.counter("fleet/snapshot_load_failures")
    tr2 = _trainer(lgb.Booster(model_str=base_str),
                   FleetStore(str(tmp_path), "m"))
    assert telemetry.counter("fleet/snapshot_load_failures") == fails0 + 1
    assert tr2.buffer.rows == 0
    # offsets stayed intact: new ingest lands PAST the snapshot rows
    tr2.ingest(*_data(10, seed=3))
    assert tr2.buffer.total_rows == 10


# ------------------------------------------------------------ chaos kinds

def test_chaos_partition_darkens_write_surface_then_heals(tmp_path):
    """The new ("partition", n) kind: n CONSECUTIVE transport failures
    from one scheduled action. A retrying remote publish rides out a
    window shorter than its retry budget; a window longer than the
    budget surfaces as TransportError — and the next call, with the
    window drained, goes straight through."""
    store = FleetStore(str(tmp_path), "m")
    server, th, base = _host(store)
    try:
        remote = RemoteWriteStore(base, timeout_s=10.0, retries=4,
                                  backoff_base_s=0.01, backoff_max_s=0.05)
        with chaos.inject(FaultPlan(
                {"transport/request": [("partition", 3)]})) as plan:
            assert remote.publish(_train(seed=1).model_to_string()) == 1
            assert plan.injected()["transport/request"] == 3
        # a window wider than the retry budget: the call fails...
        with chaos.inject(FaultPlan(
                {"transport/request": [("partition", 8)]})):
            with pytest.raises(TransportError):
                remote.publish(_train(seed=2).model_to_string())
        # ...and with the partition healed the surface works again
        assert remote.publish(_train(seed=2).model_to_string()) == 2
        assert store.latest_publish()["version"] == 2
    finally:
        _stop(server, th)


def test_chaos_partition_seeded_mix_is_deterministic():
    """seeded(kinds=KINDS_ALL) schedules the new kinds from the same
    integer seed: two builds produce byte-identical plans, and the
    legacy default mix is untouched by the new kinds."""
    def drain(plan):
        out = []
        while True:
            act = plan.next_action("transport/request")
            if act is None:
                return out
            # drop exception INSTANCES from the comparison (two builds
            # allocate distinct objects); every seeded parameter stays
            out.append(tuple(x for x in act
                             if not isinstance(x, Exception)))

    a = drain(FaultPlan.seeded(7, {"transport/request": 40},
                               kinds=FaultPlan.KINDS_ALL))
    b = drain(FaultPlan.seeded(7, {"transport/request": 40},
                               kinds=FaultPlan.KINDS_ALL))
    assert a == b and len(a) == 40
    kinds = {act[0] for act in a}
    assert "partition" in kinds and "reorder" in kinds
    legacy = drain(FaultPlan.seeded(7, {"transport/request": 40}))
    assert {act[0] for act in legacy} <= {"raise", "torn", "sleep"}


def test_chaos_reorder_delays_append_past_successor(tmp_path):
    """The new ("reorder",) kind against the write surface: one remote
    ingest append is parked and lands AFTER its successor. The log holds
    both chunks (reordered), and a replaying standby still reconstructs
    every row — the delayed-write race costs ordering, never data."""
    store = FleetStore(str(tmp_path), "m")
    base_str = _train().model_to_string()
    store.publish(base_str, event="boot")
    server, th, base = _host(store)
    try:
        remote = RemoteWriteStore(base, timeout_s=10.0)
        Xa, ya = _data(30, seed=1)
        Xb, yb = _data(20, seed=2)
        with chaos.inject(FaultPlan({"store/append": [("reorder",)]})):
            remote.append_ingest(Xa, ya)     # parked, not yet in the log
            assert sum(e["n"] for e in store.events("ingest")) == 0
            remote.append_ingest(Xb, yb)     # lands, then drains A
        chunks = [e["n"] for e in store.events("ingest")]
        assert chunks == [20, 30]            # successor first
        # replay tolerates the swap: all 50 rows, nothing duplicated
        tr = _trainer(lgb.Booster(model_str=base_str),
                      FleetStore(str(tmp_path), "m"))
        assert tr.buffer.total_rows == 50 and tr.buffer.rows == 50
    finally:
        _stop(server, th)


# ------------------------------------------------------- multi-process pin

_HOST_CHILD = textwrap.dedent("""
    import sys, tempfile, threading
    sys.path.insert(0, %(repo)r)
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.fleet import FleetStore
    from lightgbm_tpu.serve import PredictServer

    rng = np.random.RandomState(0)
    X = rng.randn(200, 6)
    y = (X @ np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4]) > 0
         ).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    # the store lives in THIS process's private tempdir: the parent
    # never learns the path, only the port — no shared filesystem
    store = FleetStore(tempfile.mkdtemp(prefix="lgbtpu_ctl_child_"), "m")
    server = PredictServer(bst, port=0, buckets=(16, 64), max_wait_ms=1.0)
    server.fleet_store = store
    print("PORT %%d" %% server.address[1], flush=True)
    server.serve_forever()
""")


@pytest.mark.slow
def test_remote_write_surface_no_shared_filesystem(tmp_path):
    """The acceptance pin, multi-process: the store host runs in a
    CHILD process over a private tempdir the parent never sees; the
    parent — trainer and replica both — converges end-to-end over HTTP
    alone (remote lease -> fenced publish -> replica adopt), and a
    forged stale-epoch publish is 409'd and never adopted."""
    script = tmp_path / "host_child.py"
    script.write_text(_HOST_CHILD % {"repo": REPO})
    proc = subprocess.Popen(
        [sys.executable, str(script)], env=clean_cpu_env(4),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT "), (line, proc.stderr.read()
                                          if proc.poll() is not None
                                          else "")
        base = "http://127.0.0.1:%d" % int(line.split()[1])

        writer = RemoteWriteStore(base, timeout_s=30.0)
        epoch = writer.acquire_lease("remote-trainer", 60.0)
        assert epoch == 1
        writer.set_fence("remote-trainer", epoch)
        model_v1 = _train(seed=1).model_to_string()
        assert writer.publish(model_v1, event="boot") == 1

        # the replica: HTTP only, adopts the remote trainer's publish
        rb, applied = bootstrap_model(RemoteStore(base, timeout_s=30.0))
        assert applied == 1
        # compare through one load/serialize round trip: adoption
        # re-serializes (normalized feature names), bytes-on-wire don't
        assert rb.model_to_string() \
            == lgb.Booster(model_str=model_v1).model_to_string()
        watcher = ReplicaWatcher(rb, RemoteStore(base, timeout_s=30.0),
                                 applied_version=applied, start=False)
        v0 = rb.inner.model_version
        assert writer.publish(_train(seed=2).model_to_string()) == 2
        assert watcher.poll_once() is True
        assert rb.inner.model_version == v0 + 1

        # takeover bumps the epoch; the old holder's forged publish is
        # fenced off at the host and the replica never sees a v3
        assert writer.release_lease("remote-trainer", epoch)
        assert writer.acquire_lease("trainer-2", 60.0) == epoch + 1
        zombie = RemoteWriteStore(base, timeout_s=30.0)
        zombie.set_fence("remote-trainer", epoch)
        with pytest.raises(StaleLeaseError):
            zombie.publish(_train(seed=3).model_to_string())
        assert watcher.poll_once() is False
        assert rb.inner.model_version == v0 + 1
        assert writer.lease_state()["holder"] == "trainer-2"
    finally:
        proc.kill()
        proc.wait(timeout=30)
