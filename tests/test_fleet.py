"""Fleet-serving subsystem tests (ISSUE 11): durable store semantics,
replay-on-boot, promotion hysteresis, auto-rollback on live regression,
multi-replica model distribution, and per-tenant fair queuing.

The contracts under test: every store append is one atomic JSONL line
(a SIGKILL mid-write costs at most one partial line, skipped on read);
artifacts land via ``os.replace`` BEFORE their publish event so a
watcher never reads a torn model; every applied publish — promotion or
rollback — is exactly ONE version bump on the serving booster; and a
flooding tenant sheds only itself while quota-respecting tenants keep
being admitted in weighted fair-share order.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
from urllib.request import Request, urlopen

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.fleet import FleetStore, ReplicaWatcher, \
    bootstrap_model  # noqa: E402
from lightgbm_tpu.obs import telemetry  # noqa: E402
from lightgbm_tpu.online import ModelRegistry, OnlineTrainer  # noqa: E402
from lightgbm_tpu.serve import MicroBatcher, PredictServer  # noqa: E402
from lightgbm_tpu.serve.batcher import QueueFullError  # noqa: E402
from lightgbm_tpu.utils.log import LightGBMError  # noqa: E402

from tests.conftest import clean_cpu_env  # noqa: E402

W = np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4])


def _data(n, seed=0, flip=0.0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, len(W))
    y = (X @ W + 0.2 * rng.randn(n) > 0).astype(np.float64)
    if flip:
        m = rng.rand(n) < flip
        y[m] = 1.0 - y[m]
    return X, y


def _train(n=300, seed=0, rounds=6):
    X, y = _data(n, seed)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


def _post(url, obj, timeout=30, headers=None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = Request(url, data=json.dumps(obj).encode(), headers=hdrs)
    with urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=30):
    with urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _start_server(server):
    th = threading.Thread(target=server.serve_forever,
                          name="fleet-test-http", daemon=True)
    th.start()
    return th


def _degraded_factory(bst):
    """Candidate factory returning a maximally wrong model (every leaf
    pinned at +1e3 logit) — promotable only because the test sets a
    generous gate threshold."""
    src = bst.model_to_string()

    def degraded(X, y):
        cand = lgb.Booster(model_str=src)
        for t in cand.inner.models:
            t.leaf_value[:] = 1e3
        cand.inner._bump_model_version()
        return cand
    return degraded


# ---------------------------------------------------------------- store

def test_store_roundtrip_and_corrupt_line_skip(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    X, y = _data(3, seed=1)
    store.append_ingest(X, y)
    store.append_gate("rejected", 0, 3, {"current": 1.0})
    v = store.publish("hello model", event="boot")
    assert v == 1
    # a corrupt line mid-log (bad JSON) and a torn final line (the
    # SIGKILL-mid-append shape: no trailing newline) are both skipped
    with open(store.events_path, "a", encoding="utf-8") as f:
        f.write('{"v": 1, "kind": "gate", oops}\n')
        f.write('{"v": 1, "kind": "ing')
    fresh = FleetStore(str(tmp_path), "m")
    events = list(fresh.events())
    assert [e["kind"] for e in events] == ["ingest", "gate", "publish"]
    ing = events[0]
    assert ing["n"] == 3
    np.testing.assert_allclose(np.asarray(ing["rows"]), X)
    np.testing.assert_allclose(np.asarray(ing["labels"]), y)
    assert events[1]["result"] == "rejected"
    latest = fresh.latest_publish()
    assert latest["version"] == 1 and latest["event"] == "boot"
    assert fresh.load_model(1) == "hello model"
    assert fresh.state()["ingest_rows_persisted"] == 0  # per-process counter
    assert store.state()["ingest_rows_persisted"] == 3


def test_store_versions_monotonic_across_processes(tmp_path):
    a = FleetStore(str(tmp_path), "m")
    assert a.publish("one") == 1
    assert a.publish("two", event="rollback") == 2
    # a second store over the same directory (a restarted trainer)
    # resumes the version sequence instead of reissuing tokens
    b = FleetStore(str(tmp_path), "m")
    assert b.publish("three") == 3
    assert [p["version"] for p in b.publishes()] == [1, 2, 3]
    for ver, txt in ((1, "one"), (2, "two"), (3, "three")):
        assert os.path.exists(b.artifact_path(ver))
        assert b.load_model(ver) == txt
    with pytest.raises(LightGBMError):
        a.publish("x", event="nope")
    for bad in ("", "a/b", ".hidden"):
        with pytest.raises(LightGBMError):
            FleetStore(str(tmp_path), bad)


# -------------------------------------------------------------- replica

def test_bootstrap_and_replica_one_bump_per_publish(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    assert bootstrap_model(store) == (None, 0)
    bst = _train(seed=0)
    bst2 = _train(seed=4, rounds=4)
    Xq = _data(16, seed=9)[0]
    store.publish(bst.model_to_string(), event="boot")
    rb, ver = bootstrap_model(store)
    assert ver == 1
    np.testing.assert_allclose(rb.predict(Xq), bst.predict(Xq),
                               rtol=1e-6, atol=1e-8)
    w = ReplicaWatcher(rb, store, applied_version=ver, start=False)
    assert w.poll_once() is False               # nothing newer yet
    v0 = rb.inner.model_version
    store.publish(bst2.model_to_string(), event="promotion")
    assert w.poll_once() is True
    # the whole-model invariant: one applied publish == one version bump
    assert rb.inner.model_version == v0 + 1
    assert w.applied_version == 2
    np.testing.assert_allclose(rb.predict(Xq), bst2.predict(Xq),
                               rtol=1e-6, atol=1e-8)
    assert w.poll_once() is False               # idempotent
    assert rb.inner.model_version == v0 + 1
    # a rollback is just another publish: replicas converge on the
    # newest token and the restored model distributes identically
    store.publish(bst.model_to_string(), event="rollback")
    assert w.poll_once() is True
    assert rb.inner.model_version == v0 + 2
    np.testing.assert_allclose(rb.predict(Xq), bst.predict(Xq),
                               rtol=1e-6, atol=1e-8)
    st = w.state()
    assert st["applied_version"] == 3 and st["swaps"] == 2
    assert st["poll_errors"] == 0
    # a late-booting second replica skips straight to the newest version
    rb2, ver2 = bootstrap_model(store)
    assert ver2 == 3
    np.testing.assert_allclose(rb2.predict(Xq), bst.predict(Xq),
                               rtol=1e-6, atol=1e-8)


def test_replica_background_thread_applies_and_survives_errors(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    bst = _train(seed=0)
    store.publish(bst.model_to_string(), event="boot")
    rb, ver = bootstrap_model(store)
    with ReplicaWatcher(rb, store, poll_interval_s=0.05,
                        applied_version=ver) as w:
        # a torn/garbage artifact must not kill the poller thread
        bad = store.publish("not a model", event="promotion")
        deadline = time.time() + 30
        while w.state()["poll_errors"] == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert w.state()["poll_errors"] >= 1
        assert w.applied_version == ver         # nothing applied
        os.remove(store.artifact_path(bad))     # heal: newest valid wins
        store.publish(bst.model_to_string(), event="promotion")
        while w.applied_version < bad + 1 and time.time() < deadline:
            time.sleep(0.02)
        assert w.applied_version == bad + 1
    assert not w.state()["running"]


# ----------------------------------------------------- trainer + store

def test_trainer_persists_ingest_gates_and_publishes(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    bst = _train()
    tr = OnlineTrainer(bst, trigger_rows=10**6, min_rows=32,
                       promote_threshold=1.5, store=store, start=False)
    X, y = _data(200, seed=1)
    tr.ingest(X, y)
    ing = list(store.events("ingest"))
    assert sum(e["n"] for e in ing) == 200      # persisted before the push
    assert tr.run_once() == "promoted"
    gates = list(store.events("gate"))
    assert len(gates) == 1
    assert gates[0]["result"] == "promoted"
    assert gates[0]["consumed_rows"] == 200     # the replay watermark
    latest = store.latest_publish()
    assert latest["version"] == 1 and latest["event"] == "promotion"
    # the published artifact IS the model now serving
    Xq = _data(16, seed=9)[0]
    np.testing.assert_allclose(
        lgb.Booster(model_str=store.load_model(1)).predict(Xq),
        bst.predict(Xq), rtol=1e-6, atol=1e-8)
    assert tr.state()["store"]["last_published_version"] == 1


def test_replay_watermark_splits_trained_from_buffered(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    bst = _train()
    kw = dict(trigger_rows=10**6, min_rows=64, shadow_rows=10**6,
              promote_threshold=1.5)
    tr1 = OnlineTrainer(bst, store=store, start=False, **kw)
    tr1.ingest(*_data(100, seed=2))
    assert tr1.run_once() in ("promoted", "rejected")   # watermark -> 100
    tr1.ingest(*_data(40, seed=3))                      # untrained tail
    assert tr1.buffer.rows == 40
    # "restart": a fresh trainer over the same store resumes mid-window
    tr2 = OnlineTrainer(_train(), store=store, start=False, **kw)
    assert tr2.buffer.rows == 40                # trained rows NOT re-buffered
    assert tr2.buffer.total_rows == 140
    assert tr2.buffer.shadow_rows == 140        # but all judge promotions
    st = tr2.state()
    assert st["consumed_rows"] == 100
    assert st["replayed_rows"] == 140
    # replay=False cold-starts (watermark state still resumes from gates)
    tr3 = OnlineTrainer(_train(), store=store, replay=False,
                        start=False, **kw)
    assert tr3.buffer.rows == 0 and tr3.state()["replayed_rows"] == 0


def test_replay_splits_chunk_straddling_watermark(tmp_path):
    # synthetic log: one 50-row chunk, watermark at 30 — only the
    # 20-row untrained tail may re-enter the training buffer
    store = FleetStore(str(tmp_path), "m")
    store.append_ingest(*_data(50, seed=5))
    store.append_gate("rejected", 0, 30)
    tr = OnlineTrainer(_train(), trigger_rows=10**6, min_rows=64,
                       shadow_rows=10**6, store=store, start=False)
    assert tr.buffer.rows == 20
    assert tr.buffer.shadow_rows == 50
    assert tr.state()["consumed_rows"] == 30


# ------------------------------------------------ hysteresis + rollback

def test_promote_patience_defers_then_promotes():
    bst = _train()
    v0 = bst.inner.model_version
    tr = OnlineTrainer(bst, trigger_rows=10**6, min_rows=32,
                       promote_threshold=2.0, promote_patience=2,
                       start=False)
    d0 = telemetry.counter("online/deferrals")
    tr.ingest(*_data(100, seed=1))
    # first shadow win is banked, not acted on: no swap yet
    assert tr.run_once() == "deferred"
    assert bst.inner.model_version == v0
    assert tr.state()["win_streak"] == 1
    assert telemetry.counter("online/deferrals") == d0 + 1
    tr.ingest(*_data(100, seed=2))
    # second consecutive win completes the streak: single-bump promotion
    assert tr.run_once() == "promoted"
    assert bst.inner.model_version == v0 + 1
    assert tr.state()["win_streak"] == 0


def test_rejection_breaks_win_streak():
    bst = _train()
    behavior = {"degrade": False}
    good = _degraded_factory(bst)               # built lazily below

    def factory(X, y):
        if behavior["degrade"]:
            return good(X, y)
        return lgb.Booster(model_str=bst.model_to_string()).refit(X, y)

    tr = OnlineTrainer(bst, trigger_rows=10**6, min_rows=32,
                       promote_threshold=2.0, promote_patience=2,
                       candidate_factory=factory, start=False)
    tr.ingest(*_data(100, seed=1))
    assert tr.run_once() == "deferred"
    behavior["degrade"] = True                  # force a shadow loss
    tr.ingest(*_data(100, seed=2))
    assert tr.run_once() == "rejected"
    assert tr.state()["win_streak"] == 0        # the loss reset the streak
    behavior["degrade"] = False
    tr.ingest(*_data(100, seed=3))
    assert tr.run_once() == "deferred"          # counting starts over


def test_replay_resumes_win_streak_toward_promotion(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    store.append_gate("deferred", 1, 0)         # one banked win on disk
    bst = _train()
    v0 = bst.inner.model_version
    tr = OnlineTrainer(bst, trigger_rows=10**6, min_rows=32,
                       promote_threshold=2.0, promote_patience=2,
                       store=store, start=False)
    assert tr.state()["win_streak"] == 1        # hysteresis state resumed
    tr.ingest(*_data(100, seed=1))
    # the restarted trainer's next win completes the dead process's streak
    assert tr.run_once() == "promoted"
    assert bst.inner.model_version == v0 + 1


def test_watch_confirms_good_promotion():
    bst = _train()
    tr = OnlineTrainer(bst, trigger_rows=10**6, min_rows=32,
                       promote_threshold=2.0, rollback_threshold=1.5,
                       rollback_min_rows=32, start=False)
    tr.ingest(*_data(100, seed=1))
    assert tr.run_once() == "promoted"
    st = tr.state()
    assert st["watch_armed"] and st["watch_rows"] == 0
    assert tr.watch_once() is None              # not enough live rows yet
    v1 = bst.inner.model_version
    c0 = telemetry.counter("online/watch_confirms")
    tr.ingest(*_data(40, seed=2))               # fresh post-swap traffic
    assert tr.watch_once() is False             # live loss fine: confirmed
    assert bst.inner.model_version == v1        # no extra swap
    assert telemetry.counter("online/watch_confirms") == c0 + 1
    st = tr.state()
    assert not st["watch_armed"] and st["auto_rollbacks"] == 0
    assert st["can_rollback"]                   # manual rollback still open
    assert tr.watch_once() is None              # one verdict per promotion


def test_auto_rollback_restores_model_and_publishes(tmp_path):
    store = FleetStore(str(tmp_path), "m")
    bst = _train()
    v0 = bst.inner.model_version
    s0 = bst.model_to_string()
    Xq = _data(16, seed=9)[0]
    p0 = np.asarray(bst.predict(Xq))
    tr = OnlineTrainer(bst, trigger_rows=10**6, min_rows=32,
                       promote_threshold=10**9,  # gate waves anything in
                       rollback_threshold=1.2, rollback_min_rows=32,
                       candidate_factory=_degraded_factory(bst),
                       store=store, start=False)
    tr.ingest(*_data(100, seed=1))
    assert tr.run_once() == "promoted"          # the bad model is live
    assert bst.inner.model_version == v0 + 1
    assert store.latest_publish()["event"] == "promotion"
    a0 = telemetry.counter("online/auto_rollbacks")
    tr.ingest(*_data(50, seed=2))               # live traffic exposes it
    assert tr.watch_once() is True
    # exactly one version bump each way: promote, then restore
    assert bst.inner.model_version == v0 + 2
    assert bst.model_to_string() == s0
    np.testing.assert_allclose(bst.predict(Xq), p0, rtol=1e-9)
    assert telemetry.counter("online/auto_rollbacks") == a0 + 1
    st = tr.state()
    assert st["auto_rollbacks"] == 1 and st["last_rollback_ts"] > 0
    assert not st["watch_armed"] and not st["can_rollback"]
    # the rollback distributed as a publish under a NEW version token
    pubs = store.publishes()
    assert [p["event"] for p in pubs] == ["promotion", "rollback"]
    assert [p["version"] for p in pubs] == [1, 2]
    # a replica that saw neither event converges straight to the
    # restored model with exactly one swap
    rb = lgb.Booster(model_str=s0)
    rv0 = rb.inner.model_version
    w = ReplicaWatcher(rb, store, start=False)
    assert w.poll_once() is True
    assert rb.inner.model_version == rv0 + 1
    np.testing.assert_allclose(rb.predict(Xq), p0, rtol=1e-9)


# ------------------------------------------------- per-tenant fairness

class _SlowSession:
    """MicroBatcher-shaped fake: dispatch sleeps, predictions are row
    sums (so slicing bugs would show)."""

    buckets = (64,)

    def __init__(self, delay=0.05):
        self.delay = delay

    def dispatch(self, X):
        time.sleep(self.delay)
        return [(np.asarray(X).sum(axis=1), len(X))]

    def finalize(self, raw, raw_score=False):
        return np.asarray(raw)


def _tag(order, name):
    return lambda _f: order.append(name)


def test_fair_queue_interleaves_equal_weight_tenants():
    b = MicroBatcher(_SlowSession(0.15), max_batch_rows=8, max_wait_ms=1.0)
    order = []
    try:
        warm = b.submit(np.ones((8, 4)))        # occupies the worker
        warm.add_done_callback(_tag(order, "warm"))
        time.sleep(0.05)
        futs = []
        for i in range(3):                      # a's backlog, then b's
            futs.append(b.submit(np.ones((8, 4)), tenant="a"))
            futs[-1].add_done_callback(_tag(order, "a"))
        for i in range(3):
            futs.append(b.submit(np.ones((8, 4)), tenant="b"))
            futs[-1].add_done_callback(_tag(order, "b"))
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60), 4.0)
        # start-time fair queuing drains equal-weight backlogs
        # alternately even though a's requests all arrived first
        assert order == ["warm", "a", "b", "a", "b", "a", "b"]
        stats = b.tenant_stats()
        assert stats["a"]["served_rows"] == 24
        assert stats["b"]["served_requests"] == 3
        assert stats["a"]["queue_rows"] == 0
    finally:
        b.close()


def test_fair_queue_weighted_shares():
    b = MicroBatcher(_SlowSession(0.15), max_batch_rows=8, max_wait_ms=1.0,
                     tenant_weights={"heavy": 3.0})
    order = []
    try:
        warm = b.submit(np.ones((8, 4)))
        warm.add_done_callback(_tag(order, "warm"))
        time.sleep(0.05)
        futs = []
        for i in range(4):
            futs.append(b.submit(np.ones((8, 4)), tenant="heavy"))
            futs[-1].add_done_callback(_tag(order, "heavy"))
        for i in range(2):
            futs.append(b.submit(np.ones((8, 4)), tenant="light"))
            futs[-1].add_done_callback(_tag(order, "light"))
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60), 4.0)
        # weight 3 tenant drains ~3 rows per light row over the backlog
        assert order == ["warm", "heavy", "light", "heavy", "heavy",
                         "heavy", "light"]
        assert b.tenant_stats()["heavy"]["weight"] == 3.0
    finally:
        b.close()


def test_tenant_quota_sheds_only_the_flooder():
    b = MicroBatcher(_SlowSession(0.2), max_batch_rows=8, max_wait_ms=1.0,
                     tenant_quota_rows=8, overload="shed")
    try:
        futs = [b.submit(np.ones((8, 4)), tenant="noisy")]  # worker busy
        time.sleep(0.05)
        futs.append(b.submit(np.ones((8, 4)), tenant="noisy"))  # quota full
        with pytest.raises(QueueFullError):
            b.submit(np.ones((8, 4)), tenant="noisy")
        # the polite tenant is untouched by the flooder's quota
        futs.append(b.submit(np.ones((8, 4)), tenant="polite"))
        # per-tenant oversize carve-out: a request alone bigger than the
        # quota is admitted when that tenant's queue is empty
        futs.append(b.submit(np.ones((32, 4)), tenant="big"))
        stats = b.tenant_stats()
        assert stats["noisy"]["shed"] == 1
        assert stats["noisy"]["shed_rows"] == 8
        assert stats["polite"]["shed"] == 0
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60), 4.0)
    finally:
        b.close()


# ------------------------------------------------------ healthz surface

def test_healthz_reports_tenants_promotions_and_fleet(tmp_path):
    bst = _train(seed=7)
    server = PredictServer(bst, port=0, buckets=(64,), max_wait_ms=1.0,
                           tenant_quota_rows=4096,
                           online=dict(trigger_rows=10**6, min_rows=32))
    store = FleetStore(str(tmp_path), "default")
    store.publish(bst.model_to_string(), event="boot")
    server.fleet_watcher = ReplicaWatcher(bst, store, applied_version=1,
                                          start=False)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    th = _start_server(server)
    try:
        Xq = _data(5, seed=14)[0]
        # tenant via header and via payload both land in the stats
        code, _ = _post(base + "/predict", {"rows": Xq.tolist()},
                        headers={"X-Tenant": "acme"})
        assert code == 200
        code, _ = _post(base + "/predict", {"rows": Xq.tolist(),
                                            "tenant": "beta"})
        assert code == 200
        health = _get(base + "/healthz")
        assert set(health["tenants"]) >= {"acme", "beta"}
        for t in ("acme", "beta"):
            assert health["tenants"][t]["queue_rows"] == 0
            assert health["tenants"][t]["shed"] == 0
        # per-model promotion/rollback timestamps are hoisted for ops
        assert health["promotions"]["default"]["last_promotion_ts"] == 0.0
        assert health["promotions"]["default"]["last_rollback_ts"] == 0.0
        served = health["models"]["default"]["tenants"]
        assert served["acme"]["served_rows"] == 5
        # replica-mode watcher state rides along
        assert health["fleet"]["applied_version"] == 1
        assert health["fleet"]["swaps"] == 0
    finally:
        server.shutdown()
        th.join(timeout=10)
        server.close()


# ------------------------------------------------------------ e2e slow

def test_rollback_on_regression_e2e_under_load(tmp_path):
    """Satellite 3: a deliberately degraded model is promoted under
    closed-loop predict load; the live watch rolls it back automatically,
    restoring the prior model with exactly one version bump each way and
    publishing the rollback under a new version token."""
    store = FleetStore(str(tmp_path), "default")
    bst = _train(seed=8)
    v0 = bst.inner.model_version
    s0 = bst.model_to_string()
    Xq = _data(8, seed=15)[0]
    p0 = np.asarray(bst.predict(Xq))
    tr = OnlineTrainer(bst, trigger_rows=256, min_rows=64,
                       shadow_rows=1024, promote_threshold=10**9,
                       rollback_threshold=1.2, rollback_min_rows=64,
                       candidate_factory=_degraded_factory(bst),
                       store=store, start=True)
    registry = ModelRegistry()
    registry.register("default", bst, buckets=(64,), max_wait_ms=1.0,
                      online=tr)
    server = PredictServer(registry=registry, port=0)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    th = _start_server(server)
    failures = []
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                code, out = _post(base + "/predict", {"rows": Xq.tolist()})
                if code != 200 or len(out["predictions"]) != 8:
                    failures.append(out)
            except Exception as exc:  # noqa: BLE001 - collected for assert
                failures.append(repr(exc))

    clients = [threading.Thread(target=client, name="fleet-e2e-%d" % i)
               for i in range(2)]
    for c in clients:
        c.start()
    try:
        def wait_for(pred, what, timeout=60):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if pred(tr.state()):
                    return
                time.sleep(0.05)
            pytest.fail("timed out waiting for %s: %s" % (what, tr.state()))

        # phase 1: enough labeled traffic to trigger one train cycle —
        # the degraded candidate sails through the wide-open gate
        X, y = _data(300, seed=21)
        code, _ = _post(base + "/ingest", {"rows": X.tolist(),
                                           "labels": y.tolist()})
        assert code == 200
        wait_for(lambda s: s["promotions"] == 1, "promotion")
        # phase 2: fresh labeled traffic feeds the live watch (stays
        # below trigger_rows so no second cycle races the verdict)
        X2, y2 = _data(100, seed=22)
        code, _ = _post(base + "/ingest", {"rows": X2.tolist(),
                                           "labels": y2.tolist()})
        assert code == 200
        wait_for(lambda s: s["auto_rollbacks"] == 1, "auto rollback")
    finally:
        stop.set()
        for c in clients:
            c.join(timeout=30)
        server.shutdown()
        th.join(timeout=10)
        server.close()
    assert not failures, failures[:3]
    # one bump up (promotion), one bump down (restore) — and the served
    # model is byte-identical to the pre-promotion one
    assert bst.inner.model_version == v0 + 2
    assert bst.model_to_string() == s0
    np.testing.assert_allclose(np.asarray(bst.predict(Xq)), p0, rtol=1e-9)
    pubs = store.publishes()
    assert [p["event"] for p in pubs] == ["promotion", "rollback"]
    assert [p["version"] for p in pubs] == [1, 2]
    health_rollback = tr.state()["last_rollback_ts"]
    assert health_rollback > 0


_CRASH_CHILD = textwrap.dedent("""
    import os, signal, sys
    sys.path.insert(0, %(repo)r)
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.fleet import FleetStore
    from lightgbm_tpu.online import OnlineTrainer

    W = np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4])

    def data(n, seed):
        rng = np.random.RandomState(seed)
        X = rng.randn(n, len(W))
        y = (X @ W + 0.2 * rng.randn(n) > 0).astype(np.float64)
        return X, y

    store = FleetStore(sys.argv[1], "m")
    bst = lgb.Booster(model_file=sys.argv[2])
    tr = OnlineTrainer(bst, trigger_rows=10**9, min_rows=64,
                       shadow_rows=10**6, promote_threshold=2.0,
                       promote_patience=2, store=store, start=False)
    tr.ingest(*data(150, seed=5))
    result = tr.run_once()          # banks one win: "deferred" on disk
    assert result == "deferred", result
    tr.ingest(*data(60, seed=6))    # mid-shadow-window, never trained
    print("READY", flush=True)
    os.kill(os.getpid(), signal.SIGKILL)
""")


@pytest.mark.slow
def test_sigkill_crash_recovery_resumes_shadow_window(tmp_path):
    """Satellite 2: SIGKILL a serving-trainer subprocess mid-shadow-
    window; a restarted trainer over the same store resumes the buffer,
    the shadow window and the pending-promotion (win-streak) state."""
    model_path = str(tmp_path / "seed.txt")
    store_dir = str(tmp_path / "fleet")
    _train().save_model(model_path)
    script = tmp_path / "crash_child.py"
    script.write_text(_CRASH_CHILD % {"repo": REPO})
    proc = subprocess.run(
        [sys.executable, str(script), store_dir, model_path],
        env=clean_cpu_env(4), capture_output=True, text=True, timeout=600)
    assert "READY" in proc.stdout, (proc.stdout, proc.stderr)
    assert proc.returncode == -signal.SIGKILL
    # what the dead process persisted, straight from the log
    store = FleetStore(store_dir, "m")
    assert sum(e["n"] for e in store.events("ingest")) == 210
    gates = list(store.events("gate"))
    assert len(gates) == 1 and gates[0]["wins"] == 1
    assert gates[0]["consumed_rows"] == 150
    # restart: replay rebuilds exactly the pre-kill in-memory state
    bst = lgb.Booster(model_file=model_path)
    v0 = bst.inner.model_version
    tr = OnlineTrainer(bst, trigger_rows=10**9, min_rows=64,
                       shadow_rows=10**6, promote_threshold=2.0,
                       promote_patience=2, store=store, start=False)
    st = tr.state()
    assert tr.buffer.rows == 60                 # only the untrained tail
    assert tr.buffer.shadow_rows == 210         # full window resumed
    assert st["consumed_rows"] == 150
    assert st["replayed_rows"] == 210
    assert st["win_streak"] == 1                # pending promotion resumed
    # and the resumed streak completes: the next win promotes
    X, y = _data(100, seed=7)
    tr.ingest(X, y)
    assert tr.run_once() == "promoted"
    assert bst.inner.model_version == v0 + 1
