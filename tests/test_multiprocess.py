"""Real multi-process distributed training over jax.distributed.

Two OS processes (4 virtual CPU devices each -> one 8-device global mesh)
drive the full distributed path end to end: per-rank sharded file loading
(load_dataset_sharded), global array assembly from process-local shards,
the data-parallel tree learner's reduce-scatter/argmax-sync collectives,
and per-rank score tracking. Reference analog: the Dask harness that spins
up in-process workers over localhost sockets (test_dask.py:26,
dask.py:333).

Identical binning + globally-reduced histograms make the distributed model
structurally identical to single-process training on the same file, so
rank 0's saved model is compared against a single-process run.
"""
import socket
import subprocess
import sys

import numpy as np
import pytest

from tests.conftest import clean_cpu_env

_WORKER = r"""
import sys
import numpy as np
import jax

rank = int(sys.argv[1])
port = sys.argv[2]
path = sys.argv[3]
out = sys.argv[4]
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2,
                           process_id=rank)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io import load_dataset_sharded

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "tree_learner": "data"}
ds = load_dataset_sharded(path, Config.from_params(params))
assert ds.shard_info[:2] == (rank, 2), ds.shard_info
wrap = lgb.Dataset(None)
wrap._constructed = ds
bst = lgb.train(dict(params), wrap, num_boost_round=8)
if rank == 0:
    bst.save_model(out)
print("rank", rank, "done", flush=True)
"""

_REF = r"""
import sys
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io import load_dataset_sharded

path, out = sys.argv[1], sys.argv[2]
params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
ds = load_dataset_sharded(path, Config.from_params(params), rank=0, world=1)
wrap = lgb.Dataset(None)
wrap._constructed = ds
bst = lgb.train(dict(params), wrap, num_boost_round=8)
bst.save_model(out)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_data_parallel(tmp_path, rng):
    n, f = 4000, 8
    X = rng.randn(n, f)
    w = rng.randn(f)
    y = (X @ w + 0.3 * rng.randn(n) > 0).astype(np.float64)
    path = tmp_path / "train.csv"
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.7g")
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    model_out = tmp_path / "model.txt"

    port = _free_port()
    env = clean_cpu_env(4)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(r), str(port), str(path),
         str(model_out)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for r in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-4000:]}"
    assert model_out.exists()

    refscript = tmp_path / "ref.py"
    refscript.write_text(_REF)
    ref_out = tmp_path / "ref.txt"
    ref = subprocess.run(
        [sys.executable, str(refscript), str(path), str(ref_out)],
        env=clean_cpu_env(8), capture_output=True, text=True, timeout=900)
    assert ref.returncode == 0, ref.stdout + ref.stderr

    import lightgbm_tpu as lgb
    pd = lgb.Booster(model_file=str(model_out)).predict(X)
    ps = lgb.Booster(model_file=str(ref_out)).predict(X)
    assert np.corrcoef(pd, ps)[0, 1] > 0.995
    assert pd[y > 0].mean() > pd[y <= 0].mean()
