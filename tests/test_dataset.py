"""BinnedDataset construction tests (reference: src/io/dataset.cpp Construct)."""
import numpy as np

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata, construct_dataset


def _make_X(n=1000, f=10, seed=0):
    rng = np.random.RandomState(seed)
    return rng.normal(size=(n, f))


def test_basic_construction():
    X = _make_X()
    y = np.random.RandomState(1).normal(size=1000)
    ds = construct_dataset(X, Config(), label=y)
    assert ds.num_data == 1000
    assert ds.num_features == 10
    assert ds.binned.shape == (1000, 10)
    assert ds.binned.dtype == np.uint8
    assert ds.metadata.label is not None and len(ds.metadata.label) == 1000

def test_trivial_feature_dropped():
    X = _make_X()
    X[:, 3] = 5.0  # constant
    ds = construct_dataset(X, Config(), label=np.zeros(1000))
    assert ds.num_features == 9
    assert 3 not in ds.used_feature_indices

def test_reference_binning_reused():
    X = _make_X()
    ds = construct_dataset(X, Config(), label=np.zeros(1000))
    X2 = _make_X(seed=5)
    ds2 = construct_dataset(X2, Config(), reference=ds)
    assert ds2.bin_mappers is ds.bin_mappers
    # same value -> same bin under both datasets
    v = X[0:1, :]
    b1 = [m.value_to_bin(v[:, i])[0] for i, m in zip(range(10), ds.bin_mappers)]
    b2 = [m.value_to_bin(v[:, i])[0] for i, m in zip(range(10), ds2.bin_mappers)]
    assert b1 == b2

def test_group_metadata_sizes():
    md = Metadata(10, group=np.array([4, 3, 3]))
    assert md.num_queries == 3
    assert md.query_boundaries.tolist() == [0, 4, 7, 10]
    assert md.query_id.tolist() == [0]*4 + [1]*3 + [2]*3

def test_group_metadata_per_row_ids():
    md = Metadata(6, group=np.array([7, 7, 7, 9, 9, 9]))
    assert md.num_queries == 2
    assert md.query_boundaries.tolist() == [0, 3, 6]

def test_categorical_feature():
    rng = np.random.RandomState(2)
    X = _make_X()
    X[:, 0] = rng.randint(0, 5, size=1000)
    ds = construct_dataset(X, Config(), label=np.zeros(1000), categorical_feature=[0])
    from lightgbm_tpu.ops.binning import BIN_CATEGORICAL
    assert ds.bin_mappers[0].bin_type == BIN_CATEGORICAL

def test_uint16_for_large_max_bin():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(5000, 2))
    ds = construct_dataset(X, Config.from_params({"max_bin": 1000, "min_data_in_bin": 1}),
                           label=np.zeros(5000))
    assert ds.binned.dtype == np.uint16
    assert ds.max_bins_per_feature > 256

def test_group_sizes_vector_of_ones():
    # regression: [1,1,1] is a sizes vector (3 singleton queries), not qids
    md = Metadata(3, group=np.array([1, 1, 1]))
    assert md.num_queries == 3

def test_non_contiguous_qids_rejected():
    import pytest
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        Metadata(6, group=np.array([7, 9, 7, 9, 7, 9]))
