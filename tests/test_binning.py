"""BinMapper unit tests (reference behavior: src/io/bin.cpp FindBin)."""
import numpy as np
import pytest

from lightgbm_tpu.ops.binning import (
    BIN_CATEGORICAL,
    MISSING_NAN,
    MISSING_NONE,
    MISSING_ZERO,
    find_bin,
)


def test_distinct_values_get_own_bins():
    vals = np.array([1.0, 2.0, 3.0] * 50)
    m = find_bin(vals, len(vals), max_bin=255, min_data_in_bin=1)
    assert not m.is_trivial
    b = m.value_to_bin(np.array([1.0, 2.0, 3.0]))
    assert len(set(b.tolist())) == 3
    # ordering preserved
    assert b[0] < b[1] < b[2]

def test_monotone_binning():
    rng = np.random.RandomState(0)
    vals = rng.normal(size=10000)
    m = find_bin(vals, len(vals), max_bin=63, min_data_in_bin=3)
    assert m.num_bins <= 63
    x = np.sort(rng.normal(size=100))
    b = m.value_to_bin(x)
    assert np.all(np.diff(b) >= 0)

def test_equal_density():
    rng = np.random.RandomState(1)
    vals = rng.uniform(1.0, 2.0, size=100000)  # all positive, no zeros
    m = find_bin(vals, len(vals), max_bin=100, min_data_in_bin=1)
    b = m.value_to_bin(vals)
    counts = np.bincount(b, minlength=m.num_bins)
    nonzero = counts[counts > 0]
    # equal-density: bin populations within ~4x of each other
    assert nonzero.max() < 6 * max(1, nonzero.mean())

def test_zero_bin():
    vals = np.concatenate([np.zeros(500), np.random.RandomState(2).normal(size=500)])
    m = find_bin(vals, len(vals), max_bin=32, min_data_in_bin=1)
    zb = m.value_to_bin(np.array([0.0]))[0]
    assert zb == m.default_bin
    # most frequent bin is the zero bin here
    assert m.most_freq_bin == zb

def test_nan_missing():
    vals = np.concatenate([np.random.RandomState(3).normal(size=900), np.full(100, np.nan)])
    m = find_bin(vals, len(vals), max_bin=16, min_data_in_bin=1, use_missing=True)
    assert m.missing_type == MISSING_NAN
    nb = m.value_to_bin(np.array([np.nan]))[0]
    assert nb == m.missing_bin == m.num_bins - 1

def test_no_missing_handling():
    vals = np.concatenate([np.random.RandomState(3).normal(size=900), np.full(100, np.nan)])
    m = find_bin(vals, len(vals), max_bin=16, min_data_in_bin=1, use_missing=False)
    assert m.missing_type == MISSING_NONE
    # NaN maps like zero
    assert m.value_to_bin(np.array([np.nan]))[0] == m.value_to_bin(np.array([0.0]))[0]

def test_zero_as_missing():
    vals = np.concatenate([np.zeros(500), np.random.RandomState(4).normal(size=500)])
    m = find_bin(vals, len(vals), max_bin=16, min_data_in_bin=1, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO
    assert m.value_to_bin(np.array([np.nan]))[0] == m.value_to_bin(np.array([0.0]))[0] == m.missing_bin

def test_trivial_feature():
    m = find_bin(np.full(100, 7.0), 100, max_bin=255, min_data_in_bin=1)
    assert m.is_trivial

def test_categorical():
    rng = np.random.RandomState(5)
    vals = rng.choice([3, 7, 11, 500], p=[0.5, 0.3, 0.15, 0.05], size=1000).astype(float)
    m = find_bin(vals, len(vals), max_bin=255, min_data_in_bin=1, bin_type=BIN_CATEGORICAL)
    assert m.bin_type == BIN_CATEGORICAL
    assert m.num_bins == 5  # 4 cats + other
    b = m.value_to_bin(np.array([3.0, 7.0, 11.0, 500.0, 999.0, np.nan]))
    assert b[0] == 0  # most frequent first
    assert b[4] == m.missing_bin and b[5] == m.missing_bin
    # round trip
    assert int(m.categories[b[1]]) == 7

def test_categorical_cut_to_max_bin():
    rng = np.random.RandomState(6)
    vals = rng.randint(0, 100, size=5000).astype(float)
    m = find_bin(vals, len(vals), max_bin=16, min_data_in_bin=1, bin_type=BIN_CATEGORICAL)
    assert m.num_bins <= 16

def test_max_bin_respected():
    rng = np.random.RandomState(7)
    vals = rng.normal(size=100000)
    for mb in (16, 64, 255):
        m = find_bin(vals, len(vals), max_bin=mb, min_data_in_bin=3)
        assert m.num_bins <= mb

def test_zero_as_missing_all_positive_reserves_zero_bin():
    # regression: zeros must not share a bin with the smallest real values
    vals = np.concatenate([np.zeros(500), np.random.RandomState(9).uniform(1, 2, 500)])
    m = find_bin(vals, len(vals), max_bin=16, min_data_in_bin=1, zero_as_missing=True)
    assert m.value_to_bin(np.array([0.0]))[0] != m.value_to_bin(np.array([1.01]))[0]
    assert m.sparse_rate == 0.5
