"""Run ledger (ISSUE 10): round-trip, regression gate, knob preresolution.

Pins the self-calibration contract from the ROADMAP: one JSONL entry per
train run carrying machine identity + dataset shape + config fingerprint
+ every resolved auto knob, and a second train with an identical
(machine, shape, config) key pre-resolves all ``tpu_*`` auto knobs from
the ledger — ZERO new auto_resolution records — while producing the
bit-identical model. Plus the gate/compare/CLI surfaces behind
``scripts/check.sh --ledger``.
"""
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs, obs_ledger  # noqa: E402
from lightgbm_tpu.config import Config  # noqa: E402

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "tpu_iter_block": 5}


# NOT test_retrace.py's (600, 8): these suites share the cross-Booster
# block cache, and retrace's "first train" must stay genuinely cold
def _data(n=620, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.1 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _params(path, **over):
    p = dict(PARAMS, obs_ledger=True, obs_ledger_path=str(path))
    p.update(over)
    return p


# ------------------------------------------------------------------ round trip

def test_entry_round_trip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    cfg = Config.from_params(_params(path))
    obs.telemetry.reset()
    entry = obs_ledger.record_run(cfg, "train", 600, 8, extra={"x": 1})
    assert entry is not None
    read = list(obs_ledger.read_entries(path))
    assert len(read) == 1
    e = read[0]
    assert e["kind"] == "train"
    assert e["dataset"] == {"rows": 600, "features": 8}
    assert e["config_fp"] == obs_ledger.config_fingerprint(cfg)
    assert e["extra"] == {"x": 1}
    assert "device_cost" in e and "machine" in e
    # appends accumulate; corrupt lines are skipped, not fatal
    with open(path, "a") as f:
        f.write("{truncated garbage\n")
    obs_ledger.append(path, entry)
    assert len(list(obs_ledger.read_entries(path))) == 2


def test_fingerprint_ignores_volatile_fields(tmp_path):
    base = _params(str(tmp_path / "l.jsonl"))
    a = Config.from_params(base)
    b = Config.from_params(dict(base, verbosity=2,
                                output_model="elsewhere.txt",
                                obs_ledger_path="other.jsonl"))
    c = Config.from_params(dict(base, num_leaves=31))
    assert obs_ledger.config_fingerprint(a) == \
        obs_ledger.config_fingerprint(b)
    assert obs_ledger.config_fingerprint(a) != \
        obs_ledger.config_fingerprint(c)


# ------------------------------------------------------------- preresolution

def test_second_train_preresolves_all_tpu_auto_knobs(tmp_path):
    """The acceptance pin: run 1 records every resolved tpu_* auto knob;
    run 2 (same machine, shape, config) applies them from the ledger —
    zero NEW auto_resolution records — and trains the identical model."""
    path = str(tmp_path / "ledger.jsonl")
    X, y = _data()
    p = _params(path)

    obs.telemetry.reset()
    ds1 = lgb.Dataset(X, label=y)
    b1 = lgb.train(dict(p), ds1, num_boost_round=5)
    first = {r["knob"]: r["value"]
             for r in obs.telemetry.records("auto_resolution")}
    assert first, "first run resolved no auto knobs"
    assert all(k.startswith("tpu_") for k in first)
    entries = list(obs_ledger.read_entries(path))
    assert len(entries) == 1
    assert entries[0]["resolved_knobs"] == first

    obs.telemetry.reset()
    ds2 = lgb.Dataset(X, label=y)
    b2 = lgb.train(dict(p), ds2, num_boost_round=5)
    assert obs.telemetry.records("auto_resolution") == [], \
        "second identical train re-resolved auto knobs"
    pre = {r["knob"]: r["value"]
           for r in obs.telemetry.records("ledger_preresolution")}
    assert pre == first
    assert obs.telemetry.counter("ledger/preresolved_knobs") >= len(first)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X))
    # run 2's own entry still carries the full knob set forward
    entries = list(obs_ledger.read_entries(path))
    assert entries[-1]["resolved_knobs"] == first


def test_goss_and_mxu_knobs_preresolve(tmp_path):
    """ISSUE 17 pin: on a GOSS config tpu_goss_compact resolves through
    the bisect-gated path (not the structural no-GOSS branch) and, with
    tpu_hist_mxu, preresolves from the ledger on run 2 — zero NEW
    auto_resolution records for either knob."""
    path = str(tmp_path / "ledger.jsonl")
    X, y = _data(n=640, f=11, seed=4)   # keep the shared block cache cold
    p = _params(path, boosting="goss", top_rate=0.3, other_rate=0.2)

    obs.telemetry.reset()
    lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=5)
    first = {r["knob"]: r for r in obs.telemetry.records("auto_resolution")}
    assert first["tpu_goss_compact"]["value"] == "off"
    assert "goss_bisect" in first["tpu_goss_compact"]["reason"]
    assert first["tpu_hist_mxu"]["value"] == "off"
    assert "hist_mxu_bisect" in first["tpu_hist_mxu"]["reason"]

    obs.telemetry.reset()
    lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=5)
    assert obs.telemetry.records("auto_resolution") == [], \
        "second identical GOSS train re-resolved auto knobs"
    pre = {r["knob"]: r["value"]
           for r in obs.telemetry.records("ledger_preresolution")}
    assert pre == {k: r["value"] for k, r in first.items()}
    assert {"tpu_goss_compact", "tpu_hist_mxu"} <= set(pre)


@pytest.mark.slow  # two fresh-resolution trainings; the preresolve hit
# path itself stays tier-1 (test_second_train_preresolves_all_tpu_auto_knobs)
def test_preresolve_ignores_mismatched_key(tmp_path):
    """Different shape or different config fingerprint: no preresolution,
    knobs resolve fresh."""
    path = str(tmp_path / "ledger.jsonl")
    X, y = _data()
    p = _params(path)
    obs.telemetry.reset()
    lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=5)

    # different dataset shape
    X2, y2 = _data(n=700, f=9, seed=1)
    obs.telemetry.reset()
    lgb.train(dict(p), lgb.Dataset(X2, label=y2), num_boost_round=5)
    assert obs.telemetry.records("auto_resolution"), \
        "shape mismatch must resolve fresh"
    assert obs.telemetry.records("ledger_preresolution") == []

    # different (non-volatile) config
    obs.telemetry.reset()
    lgb.train(dict(_params(path, num_leaves=31)), lgb.Dataset(X, label=y),
              num_boost_round=5)
    assert obs.telemetry.records("auto_resolution")


def test_preresolve_sanitizes_corrupt_values(tmp_path):
    """A tampered ledger (invalid kernel name, negative chunk) must not
    reach the learner: bad values fall back to fresh auto resolution."""
    path = str(tmp_path / "ledger.jsonl")
    X, y = _data()
    p = _params(path)
    lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=5)
    entries = list(obs_ledger.read_entries(path))
    bad = dict(entries[0])
    bad["resolved_knobs"] = {"tpu_partition_kernel": "evil",
                             "tpu_part_chunk": -5,
                             "tpu_hist_chunk": "4096"}
    obs_ledger.append(path, bad)
    obs.telemetry.reset()
    bst = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=5)
    assert bst.inner.iter_ == 5
    assert obs.telemetry.records("ledger_preresolution") == []
    assert obs.telemetry.records("auto_resolution")


def test_off_mode_writes_nothing_and_costs_nothing(tmp_path):
    """obs_ledger=False (default): no file, no ledger counters, and —
    via the compile-budget harness — zero compiles on a warm second
    train (the ledger path must add no device work either way)."""
    path = str(tmp_path / "never.jsonl")
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    lgb.train(dict(PARAMS), ds, num_boost_round=5)     # warm every cache
    obs.telemetry.reset()
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    assert not os.path.exists(path)
    assert obs.telemetry.counter("ledger/entries_written") == 0
    jc = bst.telemetry()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc


# -------------------------------------------------------------------- gating

def _entry(cfg, rows, features, train_s, kind="bench"):
    e = obs_ledger.build_entry(cfg, kind, rows, features,
                               extra={"train_s": train_s})
    return e


def test_gate_passes_then_fails_on_regression(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    cfg = Config.from_params(_params(path))
    # 0 entries: pass (fresh machine must not fail CI)
    ok, msg = obs_ledger.gate(path, cfg, 600, 8, "extra.train_s", 0.25)
    assert ok and "nothing to compare" in msg
    obs_ledger.append(path, _entry(cfg, 600, 8, 10.0))
    ok, _ = obs_ledger.gate(path, cfg, 600, 8, "extra.train_s", 0.25)
    assert ok  # 1 entry: still pass
    obs_ledger.append(path, _entry(cfg, 600, 8, 11.0))
    ok, msg = obs_ledger.gate(path, cfg, 600, 8, "extra.train_s", 0.25)
    assert ok, msg  # +10% within 25% tolerance
    obs_ledger.append(path, _entry(cfg, 600, 8, 20.0))
    ok, msg = obs_ledger.gate(path, cfg, 600, 8, "extra.train_s", 0.25)
    assert not ok, msg  # 11 -> 20 is +82%: fail
    # entries under a different key never enter the comparison
    other = Config.from_params(_params(path, num_leaves=31))
    obs_ledger.append(path, _entry(other, 600, 8, 1.0))
    ok, msg = obs_ledger.gate(path, cfg, 600, 8, "extra.train_s", 0.25)
    assert not ok, "foreign-key entry leaked into the gate"


def test_metric_value_dotted_paths():
    e = {"extra": {"train_s": 2.5},
         "telemetry": {"timers": {"fused/device_wait": 1.25}}}
    assert obs_ledger.metric_value(e, "extra.train_s") == 2.5
    assert obs_ledger.metric_value(
        e, "telemetry.timers.fused/device_wait") == 1.25
    assert obs_ledger.metric_value(e, "extra.missing") is None


# ----------------------------------------------------------------------- CLI

def test_cli_list_show_gate(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import ledger as ledger_cli
    finally:
        sys.path.pop(0)
    path = str(tmp_path / "ledger.jsonl")
    cfg = Config.from_params(_params(path))
    obs_ledger.append(path, _entry(cfg, 600, 8, 5.0))
    assert ledger_cli.main(["list", "--path", path]) == 0
    assert ledger_cli.main(["show", "--path", path]) == 0
    # the CLI gate uses its own fixed CI key; foreign entries -> pass
    assert ledger_cli.main(["gate", "--path", path]) == 0


@pytest.mark.slow  # subprocess gate (check.sh --ledger pair), per the marker's charter
def test_cli_train_then_gate(tmp_path):
    """The check.sh --ledger pair end-to-end: train appends a gated
    entry, gate compares (first run: pass on no prior)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import ledger as ledger_cli
    finally:
        sys.path.pop(0)
    path = str(tmp_path / "ledger.jsonl")
    rc = ledger_cli.main(["train", "--path", path,
                          "--rows", "400", "--features", "6"])
    assert rc == 0
    kinds = [e["kind"] for e in obs_ledger.read_entries(path)]
    assert "bench" in kinds      # the gated entry
    assert ledger_cli.main(["gate", "--path", path,
                            "--rows", "400", "--features", "6"]) == 0
    # second run: two bench entries, gate now actually compares
    assert ledger_cli.main(["train", "--path", path,
                            "--rows", "400", "--features", "6"]) == 0
    assert ledger_cli.main(["gate", "--path", path, "--rows", "400",
                            "--features", "6",
                            "--tolerance", "1000"]) == 0
