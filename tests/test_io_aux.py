"""Auxiliary IO/subsystem surface: binary dataset cache, snapshots, forced
bins, pandas inputs, plotting, timers.

Reference analogs: Dataset::SaveBinaryFile/LoadFromBinFile, gbdt.cpp:277
snapshot_freq, dataset_loader.cpp GetForcedBins, basic.py _data_from_pandas,
plotting.py, common.h:931 global_timer.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _xy(rng, n=1200, f=6):
    X = rng.randn(n, f)
    y = (X[:, 0] > 0).astype(np.float64)
    return X, y


def test_binary_dataset_roundtrip(tmp_path, rng):
    X, y = _xy(rng)
    d = lgb.Dataset(X, label=y, weight=np.abs(rng.randn(len(y))) + 0.5)
    p = str(tmp_path / "train.bin.npz")
    d.save_binary(p)
    d2 = lgb.Dataset(p)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    b1 = lgb.train(dict(params), d, num_boost_round=3)
    b2 = lgb.train(dict(params), d2, num_boost_round=3)
    np.testing.assert_allclose(b1.predict(X[:100]), b2.predict(X[:100]))


def test_snapshot_freq_resume(tmp_path, rng):
    X, y = _xy(rng)
    out = str(tmp_path / "m.txt")
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "snapshot_freq": 2, "output_model": out},
              lgb.Dataset(X, label=y), num_boost_round=4,
              valid_sets=[lgb.Dataset(X[:100], label=y[:100])])
    snap = out + ".snapshot_iter_2"
    assert os.path.exists(snap)
    resumed = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=2, init_model=snap)
    assert resumed.inner.num_trees() == 4


def test_forced_bins(tmp_path, rng):
    X, y = _xy(rng)
    fb = str(tmp_path / "forced.json")
    with open(fb, "w") as f:
        json.dump([{"feature": 0, "bin_upper_bound": [-0.5, 0.5]}], f)
    ds = lgb.Dataset(X, label=y,
                     params={"forcedbins_filename": fb}).construct()
    ub = ds.bin_mappers[0].upper_bounds
    assert -0.5 in ub and 0.5 in ub


def test_pandas_dataframe_with_categoricals(rng):
    pd = pytest.importorskip("pandas")
    n = 900
    df = pd.DataFrame({
        "num": rng.randn(n),
        "cat": pd.Categorical(rng.choice(["x", "y", "z"], n)),
    })
    y = ((df["num"] > 0) & (df["cat"] == "x")).astype(float).values
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(df, label=y), num_boost_round=6)
    ds = lgb.Dataset(df, label=y).construct()
    from lightgbm_tpu.ops.binning import BIN_CATEGORICAL
    inner = ds.inner_feature_index(1)
    assert ds.bin_mappers[inner].bin_type == BIN_CATEGORICAL
    pred = bst.predict(lgb.basic._to_2d(df))
    assert ((pred > 0.5) == y).mean() > 0.95


def test_plotting_smoke(rng):
    mpl = pytest.importorskip("matplotlib")
    mpl.use("Agg")
    X, y = _xy(rng)
    res = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "metric": ["auc"]},
                    lgb.Dataset(X, label=y), num_boost_round=3,
                    valid_sets=[lgb.Dataset(X[:200], label=y[:200])],
                    callbacks=[lgb.record_evaluation(res)])
    assert lgb.plot_importance(bst) is not None
    assert lgb.plot_metric(res) is not None


def test_phase_timers(rng):
    from lightgbm_tpu.utils.timer import global_timer
    X, y = _xy(rng, n=600)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
              lgb.Dataset(X, label=y), num_boost_round=2,
              valid_sets=[lgb.Dataset(X[:100], label=y[:100])])
    rep = global_timer.report()
    assert "boosting iteration" in rep and "dataset construction" in rep


def test_native_parser_matches_python(tmp_path, rng):
    """native/parser.cpp via ctypes vs numpy (reference: src/io/parser.cpp
    + fast_double_parser). Skips when no compiler is available."""
    from lightgbm_tpu.io_native import parse_file

    X = rng.randn(500, 7)
    p = str(tmp_path / "t.tsv")
    np.savetxt(p, X, delimiter="\t", fmt="%.6g")
    out = parse_file(p)
    if out is None:
        pytest.skip("native parser unavailable (no g++)")
    M, fmt = out
    assert fmt == "tsv"
    np.testing.assert_allclose(M, np.genfromtxt(p, delimiter="\t"))


@pytest.mark.slow  # two full trainings; accuracy comparison, not a parity pin
def test_quantized_gradients_accuracy(rng):
    """int8 quantized-gradient histograms (LightGBM 4.x quantized training
    analog) must track the exact path's accuracy."""
    n = 20000
    X = rng.randn(n, 10)
    y = (X @ rng.randn(10) + 0.3 * rng.randn(n) > 0).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "metric": ["auc"]}
    exact = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=10)
    quant = lgb.train(dict(base, use_quantized_grad=True),
                      lgb.Dataset(X, label=y), num_boost_round=10)
    (_, _, auc_e, _), = exact.eval_train()
    (_, _, auc_q, _), = quant.eval_train()
    assert auc_q > auc_e - 0.01
