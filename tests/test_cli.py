"""CLI train/predict on LightGBM-style config files (model: reference
tests/python_package_test/test_consistency.py which drives examples/*
configs)."""
import os

import numpy as np
import pytest

from lightgbm_tpu.cli import Application, parse_args
from lightgbm_tpu.io import detect_format, load_text_file
from lightgbm_tpu.config import Config


@pytest.fixture
def tiny_csv(tmp_path, rng):
    n = 400
    X = rng.randn(n, 5)
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    data = np.column_stack([y, X])
    path = tmp_path / "train.csv"
    np.savetxt(path, data, delimiter=",", fmt="%.6f")
    return str(path)


def test_detect_format():
    assert detect_format(["1,2,3"]) == "csv"
    assert detect_format(["1\t2\t3"]) == "tsv"
    assert detect_format(["1 2:0.5 7:1.2"]) == "libsvm"


def test_load_tsv_with_query(tmp_path, rng):
    n = 60
    X = rng.randn(n, 3)
    y = rng.randint(0, 3, n)
    np.savetxt(tmp_path / "rank.tsv", np.column_stack([y, X]), delimiter="\t",
               fmt="%.5f")
    np.savetxt(tmp_path / "rank.tsv.query", np.asarray([20, 20, 20]), fmt="%d")
    Xl, yl, w, group, _ = load_text_file(str(tmp_path / "rank.tsv"), Config())
    assert Xl.shape == (n, 3)
    assert group.tolist() == [20, 20, 20]


def test_load_libsvm(tmp_path):
    p = tmp_path / "data.svm"
    p.write_text("1 0:1.5 3:2.0\n0 1:0.5\n1 2:1.0 3:-1\n")
    X, y, _, _, _ = load_text_file(str(p), Config())
    assert X.shape == (3, 4)
    assert y.tolist() == [1, 0, 1]
    assert X[0, 0] == 1.5 and X[1, 1] == 0.5 and X[2, 3] == -1


def test_cli_train_predict(tmp_path, tiny_csv):
    conf = tmp_path / "train.conf"
    model = tmp_path / "model.txt"
    conf.write_text(
        "task = train\n"
        "objective = binary\n"
        "data = %s\n"
        "num_iterations = 10\n"
        "num_leaves = 7\n"
        "min_data_in_leaf = 5\n"
        "output_model = %s\n"
        "verbosity = -1\n" % (tiny_csv, model))
    Application(parse_args(["config=%s" % conf])).run()
    assert model.exists()

    out = tmp_path / "pred.txt"
    Application(parse_args([
        "task=predict", "data=%s" % tiny_csv, "input_model=%s" % model,
        "output_result=%s" % out, "verbosity=-1"])).run()
    preds = np.loadtxt(out)
    assert preds.shape == (400,)
    assert (preds >= 0).all() and (preds <= 1).all()


def test_cli_key_value_overrides(tmp_path, tiny_csv):
    model = tmp_path / "m.txt"
    Application(parse_args([
        "task=train", "objective=binary", "data=%s" % tiny_csv,
        "num_trees=5", "num_leaves=4", "min_data_in_leaf=5",
        "output_model=%s" % model, "verbosity=-1"])).run()
    from lightgbm_tpu.basic import Booster
    bst = Booster(model_file=str(model))
    assert bst.num_trees() == 5
