"""Config registry tests (reference behavior: src/io/config.cpp Config::Set)."""
import pytest

from lightgbm_tpu.config import Config, resolve_aliases
from lightgbm_tpu.utils.log import LightGBMError


def test_defaults():
    c = Config()
    assert c.num_leaves == 31
    assert c.learning_rate == 0.1
    assert c.max_bin == 255
    assert c.objective == "regression"

def test_aliases():
    c = Config.from_params({"n_estimators": 50, "eta": 0.3, "min_child_samples": 5,
                            "reg_alpha": 1.0, "reg_lambda": 2.0, "subsample": 0.8,
                            "colsample_bytree": 0.7, "num_leaf": 15})
    assert c.num_iterations == 50
    assert c.learning_rate == 0.3
    assert c.min_data_in_leaf == 5
    assert c.lambda_l1 == 1.0
    assert c.lambda_l2 == 2.0
    assert c.bagging_fraction == 0.8
    assert c.feature_fraction == 0.7
    assert c.num_leaves == 15

def test_canonical_wins_over_alias():
    r = resolve_aliases({"num_iterations": 10, "n_estimators": 99})
    assert r["num_iterations"] == 10

def test_string_coercion():
    c = Config.from_params({"num_leaves": "63", "learning_rate": "0.05",
                            "boost_from_average": "false", "metric": "l2,l1"})
    assert c.num_leaves == 63
    assert c.learning_rate == 0.05
    assert c.boost_from_average is False
    assert c.metric == ["l2", "l1"]

def test_goss_boosting_normalized():
    c = Config.from_params({"boosting": "goss"})
    assert c.boosting == "gbdt"
    assert c.data_sample_strategy == "goss"

def test_invalid_params_raise():
    with pytest.raises(LightGBMError):
        Config.from_params({"num_leaves": 1})
    with pytest.raises(LightGBMError):
        Config.from_params({"bagging_fraction": 0.0})

def test_multiclass_requires_num_class():
    with pytest.raises(LightGBMError):
        Config.from_params({"objective": "multiclass"})
    c = Config.from_params({"objective": "multiclass", "num_class": 3})
    assert c.num_class == 3

def test_constructor_validates_and_normalizes():
    c = Config(boosting="goss")
    assert c.boosting == "gbdt" and c.data_sample_strategy == "goss"
    with pytest.raises(LightGBMError):
        Config(num_leaves=1)
