"""Telemetry subsystem: obs primitives, run counters, exposure surfaces.

Covers the registry round-trip, the dataset device-cache hit/miss/
invalidation counters over repeated trains, the auto-knob resolution
records, CallbackEnv.telemetry during log_evaluation, the bit-parity
guarantee (telemetry never perturbs trained trees), the utils.log
thread-default regression, and the "no naked time.time() walls" grep over
the migrated timing harnesses.
"""
import json
import os
import re
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import Telemetry, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + X[:, 1] > 1).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1}


# ---------------------------------------------------------------- registry

def test_registry_snapshot_roundtrip():
    t = Telemetry()
    t.count("a/b")
    t.count("a/b", 3)
    t.gauge("g", np.int64(7))          # numpy scalars must serialize
    t.add_time("t", 0.25)
    with t.timed("t"):
        pass
    t.record("ev", knob="k", value=np.float32(1.5))
    t.record("dd", dedupe_key=("x", 1), v=1)
    t.record("dd", dedupe_key=("x", 1), v=1)   # deduped
    t.record("dd", dedupe_key=("x", 2), v=2)
    snap = t.snapshot(include_global_timer=False)
    parsed = json.loads(json.dumps(snap))      # must survive json round-trip
    assert parsed["counters"]["a/b"] == 4
    assert parsed["gauges"]["g"] == 7
    assert parsed["timers"]["t"] >= 0.25
    assert parsed["timer_calls"]["t"] == 2
    assert parsed["records"]["ev"] == [{"knob": "k", "value": 1.5}]
    assert len(parsed["records"]["dd"]) == 2
    t.reset()
    empty = t.snapshot(include_global_timer=False)
    assert empty["counters"] == {} and empty["records"] == {}


def test_wall_and_sync_primitives():
    import jax.numpy as jnp
    with obs.wall("obs_test/block", record=False) as w:
        x = jnp.arange(8.0) * 2
        got = obs.sync(x)
    assert w.seconds > 0
    assert got is not None and got.shape == (1,)
    assert obs.sync({"host": 3}) is None       # no device leaves -> no-op


def test_ab_interleaved_protocol():
    import jax
    import jax.numpy as jnp

    def make(k):
        @jax.jit
        def f():
            def body(c, _):
                return c * 1.0000001 + 1.0, None   # changing carry
            out, _ = jax.lax.scan(body, jnp.float32(0), None, length=k * 50)
            return out.reshape(1)
        return f

    with pytest.raises(ValueError):
        obs.ab_interleaved([("x", make)], k=1)
    res = obs.ab_interleaved([("x", make)], reps=2, k=3)
    assert set(res) == {"x"} and np.isfinite(res["x"])


# ------------------------------------------------------------ histograms

def test_histogram_buckets_and_percentiles():
    h = obs.Histogram(bounds=(1.0, 2.0, 4.0, 8.0))
    for v in (0.5, 1.0, 3.0, 3.5, 100.0):
        h.observe(v)
    assert h.count == 5 and h.sum == 108.0
    # le-inclusive buckets: 1.0 lands in le=1, 100 overflows to +Inf
    assert h.cumulative() == [(1.0, 2), (2.0, 2), (4.0, 4), (8.0, 4),
                              ("+Inf", 5)]
    assert h.percentile(0.0) == 0.0 or h.percentile(0.0) <= 1.0
    assert 2.0 <= h.percentile(0.6) <= 4.0    # interpolated in (2, 4]
    assert h.percentile(1.0) == 8.0           # overflow clamps to top bound
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["buckets"][-1] == ["+Inf", 5]
    assert set(snap) >= {"p50", "p90", "p99", "p999", "sum"}
    json.dumps(snap)
    # cumulative counts never decrease (Prometheus invariant)
    cums = [c for _, c in h.cumulative()]
    assert cums == sorted(cums)


def test_registry_histograms_in_snapshot():
    t = Telemetry()
    for v in (1.0, 5.0, 50.0):
        t.observe("lat_ms", v)
    snap = t.snapshot(include_global_timer=False)
    assert snap["histograms"]["lat_ms"]["count"] == 3
    assert t.histogram("lat_ms")["count"] == 3
    assert t.histogram("nope") is None
    t.reset()
    assert t.snapshot(include_global_timer=False)["histograms"] == {}


_PROM_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{le="[^"]+"\})? (-?[0-9.eE+\-]+|[0-9]+)$')
_PROM_TYPE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram)$")


def parse_prometheus(text):
    """Strict line parser for text exposition 0.0.4: returns
    {family: type} and {sample_name(+labels): float}."""
    families, samples = {}, {}
    assert text.endswith("\n")
    for line in text.rstrip("\n").split("\n"):
        m = _PROM_TYPE.match(line)
        if m:
            assert m.group(1) not in families, "duplicate family"
            families[m.group(1)] = m.group(2)
            continue
        m = _PROM_SAMPLE.match(line)
        assert m, "unparseable exposition line: %r" % line
        samples[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return families, samples


def test_prometheus_text_renders_all_kinds():
    t = Telemetry()
    t.count("serve/requests", 3)
    t.gauge("serve/queue_depth", 2)
    t.gauge("layout", "rows-major")            # non-numeric: skipped
    t.add_time("wall/serve", 0.5)
    t.observe("serve/latency_ms", 3.0)
    t.observe("serve/latency_ms", 700.0)
    text = obs.prometheus_text(t)
    families, samples = parse_prometheus(text)
    assert families["lgbtpu_serve_requests_total"] == "counter"
    assert families["lgbtpu_serve_queue_depth"] == "gauge"
    assert families["lgbtpu_wall_serve_seconds_total"] == "counter"
    assert families["lgbtpu_serve_latency_ms"] == "histogram"
    assert "lgbtpu_layout" not in families
    assert samples["lgbtpu_serve_requests_total"] == 3
    assert samples["lgbtpu_wall_serve_calls_total"] == 1
    assert samples['lgbtpu_serve_latency_ms_bucket{le="+Inf"}'] == 2
    assert samples["lgbtpu_serve_latency_ms_count"] == 2
    assert samples["lgbtpu_serve_latency_ms_sum"] == 703.0
    # cumulative bucket series is monotone in le order
    buckets = [(k, v) for k, v in samples.items()
               if k.startswith("lgbtpu_serve_latency_ms_bucket")]
    vals = [v for _, v in buckets]
    assert vals == sorted(vals) and vals[-1] == 2


def test_prometheus_name_collision_first_family_wins():
    t = Telemetry()
    t.count("a/b", 1)
    t.count("a.b", 5)          # sanitizes to the same family name
    families, samples = parse_prometheus(obs.prometheus_text(t))
    assert families["lgbtpu_a_b_total"] == "counter"
    # keys render in sorted order, so "a.b" is emitted first and wins
    assert samples["lgbtpu_a_b_total"] == 5


def test_fleet_exposition_round_trips_every_family(tmp_path):
    """ISSUE 15: after a fleet e2e run (trainer + replica + one publish
    + heartbeats) EVERY counter and histogram family in the snapshot
    round-trips through the strict exposition parser — including the
    new ``lgbtpu_fleet_*`` convergence families."""
    from lightgbm_tpu.fleet import FleetStore, ReplicaWatcher
    from lightgbm_tpu.online import OnlineTrainer

    X, y = _data(n=300)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=4)
    telemetry.reset()
    store = FleetStore(str(tmp_path), "default")
    trainer = OnlineTrainer(bst, trigger_rows=10**9, min_rows=64,
                            shadow_rows=10**6, promote_threshold=2.0,
                            promote_patience=2, store=store,
                            holder_id="obs-trainer", start=False)
    store.publish(bst.model_to_string(), event="boot")
    replica = lgb.Booster(model_str=bst.model_to_string())
    w = ReplicaWatcher(replica, store, node_id="obs-replica", start=False)
    assert w.poll_once()
    assert trainer.maybe_heartbeat(force=True)
    assert w.maybe_heartbeat(force=True)
    trainer.close()

    snap = telemetry.snapshot(include_global_timer=False)
    families, samples = parse_prometheus(obs.prometheus_text())
    # the run actually exercised the new convergence families
    for fam, kind in (("lgbtpu_fleet_replica_polls_total", "counter"),
                      ("lgbtpu_fleet_replica_swaps_total", "counter"),
                      ("lgbtpu_fleet_heartbeats_recorded_total", "counter"),
                      ("lgbtpu_fleet_publish_adopt_lag_ms", "histogram"),
                      ("lgbtpu_fleet_version_skew", "gauge"),
                      ("lgbtpu_fleet_applied_version", "gauge"),
                      ("lgbtpu_fleet_events_log_bytes", "gauge")):
        assert families.get(fam) == kind, (fam, families.get(fam))
    assert samples["lgbtpu_fleet_replica_swaps_total"] == 1
    assert samples["lgbtpu_fleet_heartbeats_recorded_total"] == 2
    assert samples["lgbtpu_fleet_publish_adopt_lag_ms_count"] == 1
    # completeness: every snapshot counter/histogram surfaced as a
    # correctly-typed family (first-family-wins may merge same-name
    # kin, but nothing may go missing or change type)
    for name in snap["counters"]:
        assert families.get(obs._prom_name(name) + "_total") == \
            "counter", name
    for name in snap["histograms"]:
        fam = obs._prom_name(name)
        assert families.get(fam) == "histogram", name
        assert samples[fam + "_count"] == \
            snap["histograms"][name]["count"], name


def test_compile_listener_install_is_idempotent():
    import jax
    import jax.numpy as jnp

    obs.install_compile_listener()
    # simulate a module re-import losing the module-global flag: the
    # sentinel on jax.monitoring must still prevent a second listener
    obs._compile_listener_installed = False
    obs.install_compile_listener()
    assert obs._compile_listener_installed
    telemetry.reset()

    @jax.jit
    def _fresh(x):
        return x * 3.0 + 1.0

    _fresh(np.arange(11.0)).block_until_ready()
    c = telemetry.snapshot(include_global_timer=False)["counters"]
    # a doubled listener would count 2 per compile
    assert c.get("jit/backend_compiles", 0) == 1


# ------------------------------------------------------------- hot path

def test_train_telemetry_counters_and_auto_records():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    telemetry.reset()
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=4)
    snap = bst.telemetry()
    json.dumps(snap)                           # acceptance: serializable
    c = snap["counters"]
    # dataset device caches: first train uploads (misses), no hits yet
    assert c["dataset/device_bins/miss"] >= 1
    assert c["dataset/device_bins/upload_bytes"] > 0
    # fused pipeline dispatched and flushed at train end
    assert c["fused/blocks_dispatched"] >= 1
    assert c["fused/iters_dispatched"] == 4
    assert c["fused/flush/train_end"] == 1
    # per-tree growth + launch accounting
    assert c["tree/trees"] == 4
    assert c["tree/leaves"] == c["tree/splits"] + c["tree/trees"]
    assert c["learner/partition_launches"] == c["tree/splits"]
    assert c["learner/hist_launches"] >= c["tree/splits"]
    # phase timers nonzero after a CPU train
    assert snap["timers"].get("fused/dispatch", 0) > 0
    assert snap["timers"].get("fused/logs_transfer", 0) > 0
    # one auto-resolution record per auto knob (ISSUE 10 added the
    # chunk knobs so the run ledger can preresolve the full set; ISSUE 16
    # added the forest-serving kernel knob; ISSUE 17 the GOSS compaction
    # and MXU histogram knobs)
    knobs = {r["knob"]: r for r in snap["records"]["auto_resolution"]}
    assert set(knobs) == {"tpu_partition_kernel", "tpu_hist_kernel",
                          "tpu_work_layout", "tpu_resident_state",
                          "tpu_part_chunk", "tpu_hist_chunk",
                          "tpu_split_kernel", "tpu_forest_kernel",
                          "tpu_goss_compact", "tpu_hist_mxu"}
    for r in knobs.values():
        assert r["configured"] == "auto" and r["value"] and r["reason"]
    assert "traffic/work_layout" in snap["gauges"]


def test_second_train_hits_device_cache_and_bump_invalidates():
    X, y = _data(seed=1)
    ds = lgb.Dataset(X, label=y)
    binned = ds.construct(dict(PARAMS))
    lgb.train(dict(PARAMS), ds, num_boost_round=3)
    telemetry.reset()
    lgb.train(dict(PARAMS), ds, num_boost_round=3)
    c = telemetry.snapshot(include_global_timer=False)["counters"]
    assert c.get("dataset/device_bins/hit", 0) > 0      # acceptance bar
    assert c.get("dataset/device_bins/miss", 0) == 0
    # bump_version invalidates: next train re-uploads
    binned.bump_version()
    binned.metadata.bump_version()
    telemetry.reset()
    lgb.train(dict(PARAMS), ds, num_boost_round=3)
    c = telemetry.snapshot(include_global_timer=False)["counters"]
    assert c.get("dataset/device_bins/miss", 0) >= 1


def test_read_api_flush_reasons():
    X, y = _data(seed=2)
    ds = lgb.Dataset(X, label=y)
    telemetry.reset()
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
    bst.num_trees()
    c = telemetry.snapshot(include_global_timer=False)["counters"]
    # train() itself flushed at train_end; num_trees after that finds no
    # in-flight block, so no fused/flush/num_trees is counted
    assert c["fused/flush/train_end"] == 1
    assert "fused/flush/num_trees" not in c
    # model_to_string mid-block: drive the fused trainer manually
    telemetry.reset()
    bst2 = lgb.Booster(dict(PARAMS, tpu_iter_block=8), ds)
    bst2.inner.train_block(4)                  # dispatch, leave in flight
    bst2.inner.model_to_string()
    c = telemetry.snapshot(include_global_timer=False)["counters"]
    assert c.get("fused/flush/model_to_string", 0) == 1


def test_callback_env_carries_telemetry():
    X, y = _data(seed=3)
    ds = lgb.Dataset(X, label=y)
    seen = []

    def spy(env):
        seen.append(env.telemetry)

    spy.order = 20
    lgb.train(dict(PARAMS), ds, num_boost_round=3, valid_sets=[ds],
              valid_names=["train"],
              callbacks=[lgb.log_evaluation(period=1), spy])
    assert len(seen) == 3
    assert all(t is telemetry for t in seen)
    # positional 6-field construction stays valid (telemetry defaults None)
    env = lgb.callback.CallbackEnv(None, {}, 0, 0, 1, None)
    assert env.telemetry is None


def test_telemetry_is_bit_parity_neutral():
    """Counters/tracing must not perturb training: two identical trains
    (one snapshotted mid-flight via a callback, one not) produce
    bit-identical predictions."""
    X, y = _data(n=300, seed=4)
    p1 = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                   num_boost_round=5).predict(X)
    telemetry.reset()
    p2 = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                   num_boost_round=5).predict(X)
    np.testing.assert_array_equal(p1, p2)


# ------------------------------------------------------------- surfaces

def test_cli_dump_telemetry_flag(tmp_path):
    from lightgbm_tpu.cli import parse_args
    p = parse_args(["--dump-telemetry", "/tmp/t.json", "task=train"])
    assert p["dump_telemetry"] == "/tmp/t.json"
    p = parse_args(["--dump-telemetry=/tmp/u.json"])
    assert p["dump_telemetry"] == "/tmp/u.json"

    # end-to-end: train task writes the snapshot JSON
    from lightgbm_tpu import cli
    X, y = _data(n=200, seed=5)
    data = tmp_path / "train.csv"
    np.savetxt(data, np.column_stack([y, X]), delimiter=",")
    out = tmp_path / "telemetry.json"
    model = tmp_path / "model.txt"
    cli.main(["task=train", "data=%s" % data, "objective=binary",
              "num_leaves=4", "num_iterations=2", "verbosity=-1",
              "output_model=%s" % model,
              "--dump-telemetry", str(out)])
    snap = json.loads(out.read_text())
    assert snap["counters"]["tree/trees"] >= 2


# ---------------------------------------------------------------- log.py

def test_log_level_default_is_process_global():
    from lightgbm_tpu.utils import log as L
    old = L._default_level
    try:
        L.Log.reset_log_level(L.Log.DEBUG)
        seen = {}

        def worker():
            seen["level"] = L._get_level()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        # regression: thread-local default lost main-thread verbosity
        assert seen["level"] == L.Log.DEBUG
    finally:
        L.Log.reset_log_level(old)


def test_log_sink_global_with_thread_override():
    from lightgbm_tpu.utils import log as L
    lines, thread_lines = [], []

    class _Logger:                      # register_logger wants .info()
        def info(self, m):
            lines.append(m)

    lgb.register_logger(_Logger())
    try:
        L.Log.reset_log_level(L.Log.INFO)
        L.Log.info("main")

        def worker():
            L.Log.info("inherit")                   # global sink
            L.set_thread_log_level(L.Log.WARNING)   # per-thread override
            L.Log.info("suppressed")
            L.set_thread_log_level(None)
            L.set_thread_log_sink(lambda m: thread_lines.append(m))
            L.Log.info("threaded")
            L.set_thread_log_sink(None, clear=True)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    finally:
        L.Log.reset_callback(None)
        L.Log.reset_log_level(L.Log.INFO)
    joined = "".join(lines)
    assert "main" in joined and "inherit" in joined
    assert "suppressed" not in joined
    assert "threaded" not in joined
    assert any("threaded" in m for m in thread_lines)


# The naked-walls grep that lived here is superseded by graftlint's
# naked-timer rule (lightgbm_tpu/lint/rules.py), which covers ALL of
# lightgbm_tpu/, scripts/ and bench.py — see tests/test_lint.py.
