"""Histogram kernel vs naive reference (SURVEY.md §4: 'add real unit tests
for kernels (histogram vs naive reference)')."""
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import build_histogram_jit, build_histogram_np


def test_histogram_matches_naive(rng):
    n, f, b = 5000, 7, 32
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    ghc = rng.randn(n, 3).astype(np.float32)
    dev = np.asarray(build_histogram_jit(jnp.asarray(bins), jnp.asarray(ghc), b))
    ref = build_histogram_np(bins, ghc, b)
    np.testing.assert_allclose(dev, ref, rtol=1e-4, atol=1e-3)


def test_histogram_chunked_equals_single(rng):
    n, f, b = 3000, 4, 16
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    ghc = rng.randn(n, 3).astype(np.float32)
    a = np.asarray(build_histogram_jit(jnp.asarray(bins), jnp.asarray(ghc), b, 512))
    c = np.asarray(build_histogram_jit(jnp.asarray(bins), jnp.asarray(ghc), b, 4096))
    np.testing.assert_allclose(a, c, rtol=1e-5, atol=1e-4)


def test_histogram_masked_rows_zero_out(rng):
    n, f, b = 1000, 3, 8
    bins = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    ghc = rng.randn(n, 3).astype(np.float32)
    mask = (rng.rand(n) < 0.5).astype(np.float32)
    dev = np.asarray(build_histogram_jit(
        jnp.asarray(bins), jnp.asarray(ghc * mask[:, None]), b))
    ref = build_histogram_np(bins[mask > 0], ghc[mask > 0], b)
    np.testing.assert_allclose(dev, ref, rtol=1e-4, atol=1e-3)
