"""convert_model C++ codegen must predict EXACTLY like the python model.

The reference treats its generated if-else code as a model-correctness
regression harness (tests/cpp_test on gbdt_model_text.cpp ToIfElse); here
the generated source is compiled with g++ and driven through ctypes.
"""
import ctypes
import os
import shutil
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="g++ not available")


def _compile(src_path, tmp_path):
    so = os.path.join(tmp_path, "model.so")
    r = subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-std=c++14",
                        "-o", so, src_path],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    lib = ctypes.CDLL(so)
    dptr = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
    for fn in ("PredictRaw", "Predict"):
        getattr(lib, fn).argtypes = [dptr, dptr]
        getattr(lib, fn).restype = None
    return lib


def _predict_all(lib, X, k, raw=False):
    out = np.zeros(k)
    res = np.zeros((len(X), k))
    fn = lib.PredictRaw if raw else lib.Predict
    for i, row in enumerate(np.ascontiguousarray(X, np.float64)):
        fn(row, out)
        res[i] = out
    return res


@needs_gxx
def test_binary_codegen_exact(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.normal(size=(1200, 6))
    X[rng.rand(1200, 6) < 0.05] = np.nan          # exercise missing handling
    y = (np.nansum(X[:, :2], axis=1) > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    src = tmp_path / "model.cpp"
    src.write_text(bst.inner.to_if_else_cpp())
    lib = _compile(str(src), str(tmp_path))
    # raw scores are pure f64 on both sides: exact
    raw = _predict_all(lib, X[:300], 1, raw=True)[:, 0]
    np.testing.assert_allclose(raw, bst.predict(X[:300], raw_score=True),
                               rtol=0, atol=1e-10)
    # the python transform runs in f32 on device; allow that rounding
    got = _predict_all(lib, X[:300], 1)[:, 0]
    np.testing.assert_allclose(got, bst.predict(X[:300]), atol=2e-6)


@needs_gxx
@pytest.mark.slow
def test_multiclass_codegen_exact(tmp_path):
    rng = np.random.RandomState(1)
    X = rng.normal(size=(1500, 5))
    y = np.digitize(X[:, 0], [-0.5, 0.5]).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    src = tmp_path / "model.cpp"
    src.write_text(bst.inner.to_if_else_cpp())
    lib = _compile(str(src), str(tmp_path))
    raw = _predict_all(lib, X[:200], 3, raw=True)
    np.testing.assert_allclose(raw, bst.predict(X[:200], raw_score=True),
                               rtol=0, atol=1e-10)
    got = _predict_all(lib, X[:200], 3)
    np.testing.assert_allclose(got, bst.predict(X[:200]), atol=2e-6)


@needs_gxx
def test_linear_codegen_exact(tmp_path):
    """convert_model used to Log.fatal on linear trees; the generated C++
    now emits the per-leaf linear terms (with the NaN constant fallback)
    and must round-trip exactly against the f64 host predict."""
    rng = np.random.RandomState(3)
    n = 1500
    X = rng.normal(size=(n, 4))
    y = 0.3 * X[:, 0] - 0.1 * X[:, 1] + 0.02 * rng.normal(size=n)
    X[rng.rand(n) < 0.1, 0] = np.nan          # exercise the NaN fallback
    p = {"objective": "regression", "num_leaves": 8, "verbose": -1,
         "linear_tree": True, "linear_lambda": 0.01}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=5)
    assert any(t.is_linear for t in bst.inner.models)
    src = tmp_path / "model.cpp"
    src.write_text(bst.inner.to_if_else_cpp())
    lib = _compile(str(src), str(tmp_path))
    raw = _predict_all(lib, X[:300], 1, raw=True)[:, 0]
    np.testing.assert_allclose(raw, bst.predict(X[:300], raw_score=True),
                               rtol=0, atol=1e-10)


@needs_gxx
def test_categorical_codegen_exact(tmp_path):
    rng = np.random.RandomState(2)
    n = 1500
    Xc = rng.randint(0, 8, size=(n, 1)).astype(np.float64)
    Xn = rng.normal(size=(n, 3))
    X = np.column_stack([Xc, Xn])
    y = ((Xc[:, 0] % 3 == 0) ^ (Xn[:, 0] > 0)).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                     "categorical_feature": [0], "min_data_per_group": 5,
                     "cat_smooth": 1.0},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    src = tmp_path / "model.cpp"
    src.write_text(bst.inner.to_if_else_cpp())
    lib = _compile(str(src), str(tmp_path))
    raw = _predict_all(lib, X[:300], 1, raw=True)[:, 0]
    np.testing.assert_allclose(raw, bst.predict(X[:300], raw_score=True),
                               rtol=0, atol=1e-10)
