"""Linear trees, CEGB penalties, monotone constraint methods.

Reference: src/treelearner/linear_tree_learner.cpp (Eigen per-leaf ridge),
cost_effective_gradient_boosting.hpp:66 DetlaGain,
monotone_constraints.hpp:327/:463 Basic/Intermediate.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.mark.slow
def test_linear_tree_beats_plain_on_piecewise_linear(rng):
    n = 3000
    X = rng.rand(n, 4) * 4
    y = 2.0 * X[:, 0] + 2 * np.sin(3 * X[:, 1]) + 0.1 * rng.randn(n)
    base = {"objective": "regression", "num_leaves": 8, "verbosity": -1,
            "metric": ["l2"], "learning_rate": 0.2, "min_data_in_leaf": 20}
    plain = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=12)
    lin_p = dict(base, linear_tree=True, linear_lambda=0.01)
    linear = lgb.train(lin_p, lgb.Dataset(X, label=y,
                                          params={"linear_tree": True}),
                       num_boost_round=12)
    (_, _, l2_plain, _), = plain.eval_train()
    (_, _, l2_lin, _), = linear.eval_train()
    assert l2_lin < l2_plain * 0.8
    # predict consistency with the training-time scores
    tr = np.asarray(linear.inner.train_score.score)
    np.testing.assert_allclose(linear.predict(X, raw_score=True), tr,
                               atol=1e-4)
    # text round trip preserves the linear leaves
    re = lgb.Booster(model_str=linear.model_to_string())
    np.testing.assert_allclose(re.predict(X[:200]), linear.predict(X[:200]),
                               atol=1e-10)
    assert any(t.is_linear for t in linear.inner.models)


def test_linear_tree_nan_fallback(rng):
    n = 2000
    X = rng.rand(n, 3) * 2
    y = X[:, 0] * 3 + 0.05 * rng.randn(n)
    X[rng.rand(n) < 0.1, 0] = np.nan
    p = {"objective": "regression", "num_leaves": 6, "verbosity": -1,
         "linear_tree": True, "min_data_in_leaf": 10}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params={"linear_tree": True}),
                    num_boost_round=5)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()


@pytest.mark.slow  # two full trainings; behavioral comparison, not a parity pin
def test_cegb_coupled_penalty_shrinks_feature_set(rng):
    n, f = 2500, 12
    X = rng.randn(n, f)
    w = np.concatenate([[3.0, 2.0, 1.5], np.full(f - 3, 0.3)])
    y = (X @ w > 0).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    plain = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=8)
    cegb = lgb.train(dict(base, cegb_penalty_feature_coupled=[5.0] * f),
                     lgb.Dataset(X, label=y), num_boost_round=8)
    used_plain = int((plain.feature_importance() > 0).sum())
    used_cegb = int((cegb.feature_importance() > 0).sum())
    assert used_cegb <= used_plain
    assert used_cegb < f


@pytest.mark.slow  # two full trainings; behavioral comparison, not a parity pin
def test_cegb_split_penalty_shrinks_trees(rng):
    n = 2500
    X = rng.randn(n, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1}
    plain = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=5)
    cegb = lgb.train(dict(base, cegb_penalty_split=0.002),
                     lgb.Dataset(X, label=y), num_boost_round=5)
    leaves_plain = sum(t.num_leaves for t in plain.inner.models)
    leaves_cegb = sum(t.num_leaves for t in cegb.inner.models)
    assert leaves_cegb < leaves_plain


def test_monotone_intermediate(rng):
    n = 3000
    X = rng.rand(n, 3)
    y = 2 * X[:, 0] + 0.5 * np.sin(8 * X[:, 1]) + 0.1 * rng.randn(n)
    grid = np.tile(np.linspace(0.02, 0.98, 25)[:, None], (1, 3)) * 0 + 0.5
    grid[:, 0] = np.linspace(0.02, 0.98, 25)
    for method in ("basic", "intermediate"):
        p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
             "monotone_constraints": [1, 0, 0],
             "monotone_constraints_method": method,
             "min_data_in_leaf": 10}
        bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=10)
        pred = bst.predict(grid)
        assert np.all(np.diff(pred) >= -1e-6), method
