"""Serving subsystem tests (ISSUE 5): PredictSession / MicroBatcher /
PredictServer parity, pad-slice exactness, batching semantics.

Parity baseline is the per-tree HOST walk (Tree.predict in float64). The
device path accumulates in float32, so session-vs-host parity is asserted
to tight tolerances; what IS exact is everything the serve layer itself
adds — padding to a bucket then slicing back, and batcher-vs-session
(same compiled program over row-independent routing) — and those are
asserted bit-identical.
"""
import os
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs  # noqa: E402
from lightgbm_tpu.serve import (  # noqa: E402
    MicroBatcher,
    PredictServer,
    PredictSession,
)

TOL = dict(rtol=1e-5, atol=1e-6)


def _data(n=700, f=10, seed=0, nan_frac=0.0, cat=False, classes=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    if cat:
        X[:, 0] = rng.randint(0, 6, size=n)
    if classes:
        y = (np.digitize(X[:, 1], [-0.5, 0.5])).astype(np.float64)
    else:
        y = (X[:, 1] + 0.25 * rng.randn(n) > 0).astype(np.float64)
    if nan_frac:
        mask = rng.rand(n, f) < nan_frac
        mask[:, 0] = False if cat else mask[:, 0]
        X[mask] = np.nan
    return X, y


def _train(X, y, extra=None, rounds=12):
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "tpu_iter_block": 4}
    params.update(extra or {})
    ds = lgb.Dataset(X, label=y,
                     categorical_feature=params.pop("categorical_feature", []))
    return lgb.train(params, ds, num_boost_round=rounds), ds


def _host_predict(bst, X, raw=False):
    """Per-tree host walk reference (float64 end to end except the shared
    output transform)."""
    g = bst.inner
    K = g.num_tree_per_iteration
    score = np.zeros((len(X), K), np.float64)
    for i, t in enumerate(g.models):
        score[:, i % K] += t.predict(X)
    score = score + g.init_scores[None, :K]
    if not raw and g.objective is not None:
        score = np.asarray(g.objective.convert_output(jnp.asarray(score)))
    return score.ravel() if K == 1 else score


# ------------------------------------------------------------------- parity

def test_session_parity_nan_missing_rows():
    X, y = _data(nan_frac=0.15, seed=1)
    bst, _ = _train(X, y)
    sess = PredictSession(bst)
    np.testing.assert_allclose(sess.predict(X), _host_predict(bst, X), **TOL)
    np.testing.assert_allclose(sess.predict(X, raw_score=True),
                               _host_predict(bst, X, raw=True), **TOL)


def test_session_parity_multiclass():
    X, y = _data(seed=2, classes=3)
    bst, _ = _train(X, y, {"objective": "multiclass", "num_class": 3})
    sess = PredictSession(bst)
    out = sess.predict(X)
    assert out.shape == (len(X), 3)
    np.testing.assert_allclose(out, _host_predict(bst, X), **TOL)


def test_session_parity_categorical():
    X, y = _data(seed=3, cat=True)
    bst, _ = _train(X, y, {"categorical_feature": [0]})
    sess = PredictSession(bst)
    np.testing.assert_allclose(sess.predict(X), _host_predict(bst, X), **TOL)


def test_session_matches_booster_device_path():
    """Booster.predict >= DEVICE_PREDICT_MIN_ROWS rows routes through the
    session — same numbers as a standalone session over the same model."""
    X, y = _data(n=900, seed=4)
    bst, _ = _train(X, y)
    sess = PredictSession(bst)
    np.testing.assert_array_equal(sess.predict(X), bst.predict(X))


# -------------------------------------------------------- pad/slice + buckets

def test_pad_slice_exact_non_bucket_aligned():
    """Rows are routed independently, so padding to the bucket and slicing
    back must be EXACT: an unaligned-N predict equals the same rows from a
    full-bucket predict, bit for bit."""
    X, y = _data(n=640, seed=5)
    bst, _ = _train(X, y)
    sess = PredictSession(bst, buckets=(256, 640))
    full = sess.predict(X[:256])          # exactly one bucket, no padding
    part = sess.predict(X[:77])           # 77 -> padded to 256
    np.testing.assert_array_equal(part, full[:77])
    a = sess.predict(X[:300], raw_score=True)   # 300 -> bucket 640
    b = sess.predict(X[:640], raw_score=True)   # exactly the 640 bucket
    np.testing.assert_array_equal(a, b[:300])


def test_bucket_ladder_and_chunking():
    X, y = _data(n=900, seed=6)
    bst, _ = _train(X, y)
    sess = PredictSession(bst, buckets=(128, 256))
    assert sess.bucket_for(1) == 128
    assert sess.bucket_for(129) == 256
    assert sess.bucket_for(10_000) == 256   # beyond the ladder: top rung
    # 900 rows over a 256-top ladder -> 4 chunks, still correct
    np.testing.assert_allclose(sess.predict(X), _host_predict(bst, X), **TOL)


# --------------------------------------------------------------- micro-batch

def test_batcher_bit_identical_to_session():
    X, y = _data(n=600, seed=7)
    bst, _ = _train(X, y)
    sess = PredictSession(bst, buckets=(64, 256))
    base = sess.predict(X[:64])           # one full bucket, no padding
    results = {}
    with MicroBatcher(sess, max_batch_rows=64, max_wait_ms=20.0) as mb:
        def post(i):
            results[i] = mb.submit(X[i:i + 1]).result(timeout=60)
        threads = [threading.Thread(target=post, args=(i,)) for i in range(64)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    got = np.concatenate([results[i] for i in range(64)])
    np.testing.assert_array_equal(got, base)
    assert obs.telemetry.counter("serve/batches") >= 1


def test_batcher_coalesces_into_few_batches():
    X, y = _data(n=600, seed=8)
    bst, _ = _train(X, y)
    sess = PredictSession(bst, buckets=(256,))
    sess.warmup([1])
    before = obs.telemetry.counter("serve/batches")
    with MicroBatcher(sess, max_batch_rows=256, max_wait_ms=50.0) as mb:
        futs = [mb.submit(X[i:i + 1]) for i in range(40)]
        outs = [f.result(timeout=60) for f in futs]
    batches = obs.telemetry.counter("serve/batches") - before
    assert 1 <= batches < 40, "40 submits should coalesce, got %d" % batches
    np.testing.assert_array_equal(np.concatenate(outs), sess.predict(X[:40]))


class _InstantSession:
    """Dispatch-free fake: batcher-discipline tests must not depend on
    model math or compile time."""

    buckets = (64,)

    def __init__(self, delay=0.0):
        self.delay = delay

    def dispatch(self, X):
        if self.delay:
            import time as _time
            _time.sleep(self.delay)
        return [(np.asarray(X).sum(axis=1), len(X))]

    def finalize(self, raw, raw_score=False):
        return np.asarray(raw)


def test_dispatch_mode_validated():
    with pytest.raises(ValueError):
        MicroBatcher(_InstantSession(), dispatch_mode="sideways")


def test_continuous_dispatch_cuts_queue_wait():
    """ISSUE 16 tentpole B: coalesce parks a lone request for the full
    max_wait_ms company window; continuous dispatches it immediately.
    Same requests, same session — queue wait (and end-to-end latency)
    must collapse, and the serve/queue_wait_ms histogram must record it
    in both modes."""
    import time as _time

    waits, qw50 = {}, {}
    for mode in ("coalesce", "continuous"):
        obs.telemetry.reset()
        with MicroBatcher(_InstantSession(), max_wait_ms=200.0,
                          dispatch_mode=mode) as mb:
            t0 = _time.monotonic()
            for _ in range(3):
                np.testing.assert_allclose(
                    mb.submit(np.ones((2, 4))).result(timeout=60), 4.0)
            waits[mode] = _time.monotonic() - t0
        h = obs.telemetry.histogram("serve/queue_wait_ms")
        assert h is not None and h["count"] == 3, h
        qw50[mode] = h["p50"]
    assert waits["coalesce"] > 0.45, \
        "coalesce should pay ~3x200ms company wait, took %.3fs" \
        % waits["coalesce"]
    assert waits["continuous"] < waits["coalesce"] / 3, waits
    assert qw50["continuous"] < qw50["coalesce"] / 3, qw50


def test_continuous_close_delivers_launched_tile():
    """Graceful drain: a tile already launched when close() lands is
    DELIVERED (its futures resolve with results), and both serving
    threads are joined."""
    import time as _time

    mb = MicroBatcher(_InstantSession(delay=0.2),
                      dispatch_mode="continuous")
    fut = mb.submit(np.ones((4, 4)))
    _time.sleep(0.05)                    # worker picked it; dispatch busy
    mb.close(timeout=30)
    np.testing.assert_allclose(fut.result(timeout=1), 4.0)
    assert not mb._thread.is_alive()
    assert not mb._deliver_thread.is_alive()


def test_continuous_batcher_bit_identical_to_session():
    """The continuous discipline changes WHEN tiles seal, never what a
    row scores: concurrent single-row submits equal the sealed-bucket
    session answer bit for bit (same contract as the coalesce test
    above, which now runs both modes via the default)."""
    X, y = _data(n=600, seed=7)
    bst, _ = _train(X, y)
    sess = PredictSession(bst, buckets=(64, 256))
    base = sess.predict(X[:64])
    results = {}
    with MicroBatcher(sess, max_batch_rows=64,
                      dispatch_mode="continuous") as mb:
        def post(i):
            results[i] = mb.submit(X[i:i + 1]).result(timeout=60)
        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(64)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    got = np.concatenate([results[i] for i in range(64)])
    np.testing.assert_array_equal(got, base)


def test_continuous_shed_and_block_admission_preserved():
    """Admission control is mode-independent: a full queue sheds under
    continuous dispatch exactly as it did under coalesce."""
    from lightgbm_tpu.serve.batcher import QueueFullError

    mb = MicroBatcher(_InstantSession(delay=0.2), max_batch_rows=8,
                      max_queue_rows=8, overload="shed",
                      dispatch_mode="continuous")
    try:
        futs = [mb.submit(np.ones((8, 4)))]      # worker busy dispatching
        import time as _time
        _time.sleep(0.05)
        futs.append(mb.submit(np.ones((8, 4))))  # fills the queue bound
        with pytest.raises(QueueFullError):
            mb.submit(np.ones((8, 4)))
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=60), 4.0)
    finally:
        mb.close()


def test_batcher_propagates_worker_exceptions():
    X, y = _data(seed=9)
    bst, _ = _train(X, y)
    sess = PredictSession(bst)
    with MicroBatcher(sess) as mb:
        fut = mb.submit(np.zeros((2, 2, 2)))   # 3-D batch: dispatch raises
        with pytest.raises(Exception):
            fut.result(timeout=60)
        # worker survives the failed batch and keeps serving
        ok = mb.submit(X[:1]).result(timeout=60)
        assert ok.shape == (1,)


def test_batcher_close_is_clean_and_idempotent():
    X, y = _data(seed=10)
    bst, _ = _train(X, y)
    sess = PredictSession(bst)
    mb = MicroBatcher(sess)
    assert mb.submit(X[:3]).result(timeout=60).shape == (3,)
    mb.close()
    mb.close()
    with pytest.raises(RuntimeError):
        mb.submit(X[:1])
    assert not mb._thread.is_alive()


# ------------------------------------------------------------ binned fast path

def test_binned_fast_path_matches_raw_routing():
    X, y = _data(seed=11)
    bst, ds = _train(X, y)
    sess = PredictSession(bst)
    binned = sess.predict_binned(ds)
    np.testing.assert_allclose(binned, sess.predict(X), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(binned, _host_predict(bst, X), rtol=1e-4,
                               atol=1e-5)


# ------------------------------------------------------- model-version safety

def test_session_tracks_model_updates_and_rollback():
    X, y = _data(n=600, seed=12)
    bst, _ = _train(X, y, rounds=6)
    sess = PredictSession(bst)
    np.testing.assert_allclose(sess.predict(X), _host_predict(bst, X), **TOL)
    bst.update()                      # continued training -> version bump
    np.testing.assert_allclose(sess.predict(X), _host_predict(bst, X), **TOL)
    bst.inner.rollback_one_iter()     # rollback -> version bump
    np.testing.assert_allclose(sess.predict(X), _host_predict(bst, X), **TOL)


# ------------------------------------------------------------------ HTTP API

def test_http_server_roundtrip():
    import json
    from urllib.request import Request, urlopen

    X, y = _data(seed=13)
    bst, _ = _train(X, y)
    server = PredictServer(bst, port=0, buckets=(64,), warmup=True,
                           max_wait_ms=1.0)
    host, port = server.address
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        body = json.dumps({"rows": X[:5].tolist()}).encode()
        req = Request("http://%s:%d/predict" % (host, port), data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        np.testing.assert_allclose(np.asarray(out["predictions"]),
                                   _host_predict(bst, X[:5]), **TOL)
        assert out["rows"] == 5
        with urlopen("http://%s:%d/healthz" % (host, port), timeout=30) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok"
        # /healthz carries substance: registry + per-model state (the
        # single-model server registers its booster as "default")
        assert health["model_count"] == 1
        assert health["uptime_s"] >= 0
        assert health["queue_rows"] == 0
        info = health["models"]["default"]
        assert info["model_version"] == bst.inner.model_version
        assert info["queue_rows"] == 0 and info["age_s"] >= 0
        assert info["online"] is None
        assert health["model_version"] == bst.inner.model_version
        assert health["buckets"] == [64]
        with urlopen("http://%s:%d/telemetry" % (host, port), timeout=30) as r:
            snap = json.loads(r.read())
        assert snap["counters"].get("serve/requests", 0) >= 1
    finally:
        server.shutdown()
        thread.join(timeout=10)
        server.close()


def test_http_metrics_prometheus_and_telemetry_histograms():
    """GET /metrics parses as Prometheus text exposition (incl. latency
    histogram buckets); GET /telemetry carries the histogram sections."""
    import json
    from urllib.request import Request, urlopen
    from test_obs import parse_prometheus

    X, y = _data(seed=15)
    bst, _ = _train(X, y)
    server = PredictServer(bst, port=0, buckets=(64,), warmup=True,
                           max_wait_ms=1.0)
    host, port = server.address
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="serve-test-metrics")
    thread.start()
    try:
        body = json.dumps({"rows": X[:4].tolist()}).encode()
        req = Request("http://%s:%d/predict" % (host, port), data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read())["rows"] == 4
        with urlopen("http://%s:%d/metrics" % (host, port), timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode("utf-8")
        families, samples = parse_prometheus(text)
        assert families["lgbtpu_serve_requests_total"] == "counter"
        assert families["lgbtpu_serve_latency_ms"] == "histogram"
        assert samples['lgbtpu_serve_latency_ms_bucket{le="+Inf"}'] >= 1
        assert samples["lgbtpu_serve_latency_ms_count"] >= 1
        assert samples["lgbtpu_serve_batch_rows_count"] >= 1
        with urlopen("http://%s:%d/telemetry" % (host, port), timeout=30) as r:
            snap = json.loads(r.read())
        hists = snap["histograms"]
        assert hists["serve/latency_ms"]["count"] >= 1
        assert hists["serve/latency_ms"]["buckets"][-1][0] == "+Inf"
        assert hists["serve/batch_rows"]["count"] >= 1
    finally:
        server.shutdown()
        thread.join(timeout=10)
        server.close()


def test_batcher_latency_stats_from_histogram():
    X, y = _data(seed=16)
    bst, _ = _train(X, y)
    sess = PredictSession(bst, buckets=(64,))
    with MicroBatcher(sess, max_wait_ms=1.0) as mb:
        assert mb.latency_stats()["count"] == 0
        for i in range(5):
            mb.submit(X[i:i + 1]).result(timeout=60)
        stats = mb.latency_stats()
    assert stats["count"] == 5
    assert 0 < stats["p50_s"] <= stats["p90_s"] <= stats["p99_s"] \
        <= stats["p999_s"]
    # gauges derived from the same buckets land in the registry
    assert obs.telemetry.snapshot()["gauges"]["serve/latency_p50_ms"] > 0


# ------------------------------------------------------------------ counters

def test_serve_counters_and_latency_gauges():
    X, y = _data(seed=14)
    bst, _ = _train(X, y)
    obs.telemetry.reset()
    sess = PredictSession(bst, buckets=(64,))
    sess.predict(X[:10])
    with MicroBatcher(sess, max_wait_ms=1.0) as mb:
        mb.submit(X[:7]).result(timeout=60)
    snap = obs.telemetry.snapshot()
    c = snap["counters"]
    assert c["serve/requests"] == 2
    assert c["serve/rows"] == 17
    assert c["serve/pack_build"] == 1
    assert c["serve/batches"] == 1
    assert c["serve/dispatches"] >= 2
    assert "serve/queue_depth" in snap["gauges"]
    assert "serve/latency_p50_ms" in snap["gauges"]
    assert "serve/latency_p99_ms" in snap["gauges"]
    assert snap["timers"].get("wall/serve/request", 0) > 0


# ------------------------------------------------------- concurrency stress

def test_batcher_submit_close_race_no_hung_futures():
    """N threads hammer submit() across close(): every accepted Future
    resolves (result or the deterministic closed-drain error), stragglers
    raise RuntimeError at submit, and nothing hangs. Pre-fix, a submit
    slipping between close()'s flag flip and the worker's stop marker
    left its Future pending forever."""
    import time as _time

    X, y = _data(n=300)
    bst, _ = _train(X, y, rounds=4)
    sess = PredictSession(bst, buckets=(64,))
    sess.warmup((64,))
    for _trial in range(3):
        mb = MicroBatcher(sess, max_batch_rows=64, max_wait_ms=0.5)
        futures, rejected = [], []

        def hammer():
            while True:
                try:
                    futures.append(mb.submit(X[:3]))
                except RuntimeError:
                    rejected.append(1)
                    return

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        _time.sleep(0.05)
        mb.close(timeout=30)
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert not mb._thread.is_alive()
        assert rejected, "close() raced in after every hammer thread died?"
        for fut in futures:
            # .exception() raises TimeoutError if the Future hung
            exc = fut.exception(timeout=30)
            assert exc is None or "closed" in str(exc)


def test_train_while_serve_sees_whole_versions():
    """A serve thread predicts while the main thread keeps training.
    Every served batch must equal the model at SOME iteration count
    between the counts observed before and after the predict — a torn
    pack (half-committed iteration, stale-version cache entry) matches
    no whole iteration and fails."""
    X, y = _data(n=300, seed=21)
    bst, _ = _train(X, y, rounds=2)
    sess = PredictSession(bst, buckets=(64,))
    Xq = np.ascontiguousarray(X[:24])
    observed = []
    stop = threading.Event()

    def serve():
        while not stop.is_set() and len(observed) < 400:
            n0 = len(bst.inner.models)
            out = np.asarray(sess.raw_scores(Xq), np.float64).ravel()
            n1 = len(bst.inner.models)
            observed.append((n0, out, n1))

    th = threading.Thread(target=serve)
    th.start()
    try:
        for _ in range(10):
            bst.update()
    finally:
        stop.set()
        th.join(timeout=60)
    assert not th.is_alive()
    assert observed
    # prefix raw sums of the final (append-only) model reconstruct the
    # exact serving surface at every historical iteration count
    per_tree = np.array([t.predict(Xq) for t in bst.inner.models])
    prefix = np.vstack([np.zeros((1, len(Xq))), np.cumsum(per_tree, axis=0)])
    for n0, out, n1 in observed:
        ok = any(np.allclose(out, prefix[j], rtol=1e-4, atol=1e-5)
                 for j in range(n0, n1 + 1))
        assert ok, ("served batch matches no whole model between "
                    "%d and %d trees" % (n0, n1))
