"""Runtime retrace / compile-budget detector (ISSUE 4 tentpole).

obs.track_jit wraps every training-path jit entry point, turning
compiled-cache growth into ``jit/compiles/<name>`` telemetry counters.
These tests pin the contract the round-5 "dispatch soup" regression
violated: a first train pays a bounded number of compilations, and a
second train at identical shapes/config pays ZERO — every jit entry must
hit its cache (fused path: the cross-Booster _BLOCK_CACHE).
"""
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs  # noqa: E402

#: first-train ceiling for TRACKED entry-point compiles. The fused path
#: compiles run_block once; the eager path adds learner/build, grads,
#: score_add and assign_leaves. Anything near double this is a retrace
#: leak, not workload growth.
PER_TRAIN_COMPILE_BUDGET = 8


def _data(n=600, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.1 * rng.randn(n) > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "tpu_iter_block": 5}


# ------------------------------------------------------------ track_jit unit

def test_track_jit_counts_traces():
    obs.telemetry.reset()
    calls = []

    @jax.jit
    def f(x):
        calls.append(None)
        return x * 2

    g = obs.track_jit("test/f", f)
    g(jnp.ones((4,)))
    assert obs.jit_compiles().get("test/f") == 1
    g(jnp.ones((4,)))                      # cache hit: no growth
    assert obs.jit_compiles().get("test/f") == 1
    g(jnp.ones((8,)))                      # new shape: retrace
    assert obs.jit_compiles().get("test/f") == 2


def test_track_jit_delegates_attributes():
    @jax.jit
    def f(x):
        return x + 1

    g = obs.track_jit("test/delegate", f)
    lowered = g.lower(jnp.ones((2,)))      # PjitFunction API passes through
    assert lowered is not None
    # re-wrapping re-labels instead of stacking wrappers
    h = obs.track_jit("test/relabel", g)
    assert h._fn is f


def test_snapshot_exposes_jit_compiles():
    obs.telemetry.reset()

    @jax.jit
    def f(x):
        return x - 1

    obs.track_jit("test/snap", f)(jnp.ones((2,)))
    snap = obs.telemetry.snapshot()
    jc = snap["jit_compiles"]
    assert jc["per_function"] == {"test/snap": 1}
    assert jc["total"] == 1
    assert jc["backend_compiles"] >= 1     # global listener saw the compile


# ------------------------------------------------------------ train budgets

def test_first_train_within_compile_budget():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    obs.telemetry.reset()
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    jc = bst.telemetry()["jit_compiles"]
    assert jc["total"] >= 1, "no tracked jit entry point ran"
    assert jc["total"] <= PER_TRAIN_COMPILE_BUDGET, jc
    assert "fused/run_block" in jc["per_function"], jc


def test_second_identical_train_compiles_nothing():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    lgb.train(dict(PARAMS), ds, num_boost_round=5)       # warm every cache
    obs.telemetry.reset()
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    jc = bst.telemetry()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc


# ---------------------------------------------------------- serving budgets

def test_second_same_bucket_predict_zero_compiles():
    """The serving contract: once a bucket is warm, repeat predicts in that
    bucket pay ZERO tracked compiles, ZERO backend compiles, and ZERO host
    re-packs — regardless of the exact row count within the bucket."""
    from lightgbm_tpu.serve import PredictSession
    X, y = _data(n=1000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    sess = PredictSession(bst, buckets=(1024,))
    sess.predict(X[:600], raw_score=True)    # warm: pack upload + compile
    obs.telemetry.reset()
    sess.predict(X[:600], raw_score=True)    # same bucket, same N
    sess.predict(X[:600], raw_score=True)
    sess.predict(X[:1000], raw_score=True)   # same bucket, different N
    jc = obs.telemetry.snapshot()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc
    assert obs.telemetry.counter("serve/pack_build") == 0
    assert obs.telemetry.counter("serve/bucket_hit") == 3


def test_second_same_shape_linear_predict_zero_compiles():
    """Linear models ride the same bucket contract: the coefficient-table
    gather + dot adds no per-call retrace, so a second same-shape predict
    on a linear model pays ZERO compiles and ZERO re-packs."""
    from lightgbm_tpu.serve import PredictSession
    rng = np.random.RandomState(3)
    X = rng.randn(1000, 5)
    y = 0.3 * X[:, 0] - 0.1 * X[:, 1] + 0.02 * rng.randn(1000)
    p = {"objective": "regression", "num_leaves": 8, "verbosity": -1,
         "linear_tree": True, "linear_lambda": 0.01}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=dict(p)),
                    num_boost_round=4)
    assert any(t.is_linear for t in bst.inner.models)
    sess = PredictSession(bst, buckets=(1024,))
    sess.predict(X[:600])                    # warm: pack upload + compile
    obs.telemetry.reset()
    sess.predict(X[:600])                    # same bucket, same N
    sess.predict(X[:1000])                   # same bucket, different N
    jc = obs.telemetry.snapshot()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc
    assert obs.telemetry.counter("serve/pack_build") == 0
    assert obs.telemetry.counter("serve/bucket_hit") == 2


def test_forest_kernel_same_bucket_zero_compiles():
    """ISSUE 16: the forest-at-once path rides the same bucket contract —
    after the first dispatch warms a rung, repeat forest predicts pay
    ZERO tracked compiles, ZERO backend compiles, and ZERO node-table
    rebuilds (the serve/forest_build counter)."""
    from lightgbm_tpu.serve import PredictSession
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    sess = PredictSession(bst, buckets=(256,), forest="on")
    sess.predict(X[:200], raw_score=True)    # warm: table build + compile
    obs.telemetry.reset()
    sess.predict(X[:200], raw_score=True)    # same bucket, same N
    sess.predict(X[:256], raw_score=True)    # same bucket, different N
    jc = obs.telemetry.snapshot()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc
    assert obs.telemetry.counter("serve/forest_build") == 0
    assert obs.telemetry.counter("serve/forest_dispatches") == 2


def test_warmup_ladder_compile_budget():
    """warmup() pre-compiles the ladder: at most one predict compile per
    rung, and a second warmup compiles nothing new."""
    from lightgbm_tpu.serve import PredictSession
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=5)
    rungs = (96, 192, 384)
    sess = PredictSession(bst, buckets=rungs)
    obs.telemetry.reset()
    sess.warmup()
    jc = obs.telemetry.snapshot()["jit_compiles"]["per_function"]
    assert jc.get("serve/predict_bucket", 0) <= len(rungs), jc
    obs.telemetry.reset()
    sess.warmup()
    jc = obs.telemetry.snapshot()["jit_compiles"]
    assert jc["total"] == 0, jc


def test_bench_json_carries_jit_compiles():
    """bench.py embeds telemetry.snapshot(); the jit_compiles section must
    be json-serializable and present."""
    import json
    X, y = _data(300, 6)
    ds = lgb.Dataset(X, label=y)
    obs.telemetry.reset()
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
    snap = json.loads(json.dumps(bst.telemetry()))
    assert "jit_compiles" in snap
    assert snap["jit_compiles"]["total"] >= 0
