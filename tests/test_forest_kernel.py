"""Forest-at-once serving kernel (ISSUE 16 tentpole).

``ops/forest.py`` packs the ensemble into BIN-space split-major tables
and evaluates the WHOLE forest per row tile in one pallas launch;
``serve/session.PredictSession`` routes to it behind the
``tpu_forest_kernel`` knob with the per-depth-gather ``_predict_bucket``
retained verbatim as the oracle. The contract these tests pin (the PR-12
discipline): under the CPU interpreter the kernel is BIT-IDENTICAL to
the oracle — ``a.tobytes() == b.tobytes()``, not allclose — for every
model class (plain binary, NaN-missing routing, categorical splits,
multiclass, linear leaves, linear + NaN), across chunked multi-tile
dispatches, and the knob's auto default resolves to "off" until
``scripts/forest_bisect.py`` validates the Mosaic lowering on hardware.

Feature grids are quantized to 1/64 (f32-exact, including the 1/128 bin
midpoints) so BIN-space routing vs raw-threshold routing cannot split a
row on representation error.
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs  # noqa: E402
from lightgbm_tpu.serve import PredictSession  # noqa: E402
from lightgbm_tpu.utils.log import LightGBMError  # noqa: E402


def _grid(rng, n, f):
    # 1/64 grid: every value and every bin-boundary midpoint is f32-exact
    return np.round(rng.randn(n, f) * 16) / 64.0


def _model(params, cat=False, nan=False, classes=0, rounds=10,
           n=800, f=10, n_query=300, seed=7):
    rng = np.random.RandomState(seed)
    X = _grid(rng, n, f)
    if cat:
        X[:, 0] = rng.randint(0, 6, size=n)
    if classes:
        y = np.digitize(X[:, 1], [-0.5, 0.5]).astype(np.float64)
    else:
        y = (X[:, 1] + 0.25 * _grid(rng, n, 1)[:, 0] > 0) \
            .astype(np.float64)
    if nan:
        m = rng.rand(n, f) < 0.15
        if cat:
            m[:, 0] = False
        X[m] = np.nan
    p = dict(params)
    p.setdefault("verbosity", -1)
    p.setdefault("num_leaves", 15)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0] if cat else [])
    bst = lgb.train(p, ds, num_boost_round=rounds)
    Xq = _grid(rng, n_query, f)
    if cat:
        Xq[:, 0] = rng.randint(0, 6, size=n_query)
    if nan:
        mq = rng.rand(n_query, f) < 0.15
        if cat:
            mq[:, 0] = False
        Xq[mq] = np.nan
    return bst, Xq


CLASSES = {
    "binary": dict(params={"objective": "binary"}),
    "nan_missing": dict(params={"objective": "binary"}, nan=True),
    "categorical": dict(params={"objective": "binary"}, cat=True),
    "multiclass": dict(params={"objective": "multiclass", "num_class": 3},
                       classes=3),
    "linear": dict(params={"objective": "regression",
                           "linear_tree": True}),
    "linear_nan": dict(params={"objective": "regression",
                               "linear_tree": True}, nan=True),
}


# --------------------------------------------------------------- bit parity

@pytest.mark.parametrize("name", sorted(CLASSES))
def test_forest_kernel_bit_parity(name):
    """Kernel raw scores are byte-identical to the per-depth-gather
    oracle's for every model class (interpret-mode contract)."""
    bst, Xq = _model(**CLASSES[name])
    a = PredictSession(bst, buckets=(256,), forest="off").raw_scores(Xq)
    b = PredictSession(bst, buckets=(256,), forest="on").raw_scores(Xq)
    assert a.shape == b.shape
    assert a.tobytes() == b.tobytes(), \
        "%s: max |diff| = %g over %d/%d rows" \
        % (name, np.abs(a - b).max(), (np.abs(a - b) > 0).sum(), a.size)


def test_forest_kernel_multi_tile_chunked_dispatch():
    """A request past the top rung chunks into several dispatches, each
    padded to its covering bucket and spanning multiple kernel tiles —
    parity must survive the seams."""
    bst, _ = _model(**CLASSES["binary"])
    rng = np.random.RandomState(11)
    Xq = _grid(rng, 700, 10)       # 3 chunks at bucket 256, last padded
    a = PredictSession(bst, buckets=(256,), forest="off").raw_scores(Xq)
    b = PredictSession(bst, buckets=(256,), forest="on").raw_scores(Xq)
    assert a.tobytes() == b.tobytes()


def test_forest_kernel_final_predictions_match():
    """The full predict path (init score + output transform + squeeze)
    rides the same parity: final probabilities byte-match the oracle's."""
    bst, Xq = _model(**CLASSES["binary"])
    a = PredictSession(bst, buckets=(256,), forest="off").predict(Xq)
    b = PredictSession(bst, buckets=(256,), forest="on").predict(Xq)
    assert a.tobytes() == b.tobytes()


# ------------------------------------------------------------- eligibility

def test_forest_ineligible_falls_back_to_oracle():
    """A booster without its training Dataset (model round-tripped
    through a string) has no bin mappers to pack BIN tables from: a
    forest="on" session must warn once, fall back to the oracle, and
    still answer correctly."""
    bst, Xq = _model(**CLASSES["binary"])
    ref = PredictSession(bst, buckets=(256,)).predict(Xq)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    obs.telemetry.reset()
    sess = PredictSession(loaded, buckets=(256,), forest="on")
    out = sess.predict(Xq)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-7)
    assert obs.telemetry.counter("serve/forest_dispatches") == 0
    recs = obs.telemetry.snapshot()["records"]
    assert "forest_ineligible" in recs, recs.keys()


# ------------------------------------------------------------ knob plumbing

def test_forest_knob_auto_resolves_off():
    """The PR-12 discipline: parity is proven under interpret only, so
    auto stays off until forest_bisect.py validates hardware — and the
    resolution record names the script."""
    bst, _ = _model(**CLASSES["binary"])
    assert bst.inner._forest_knob() == "off"
    recs = {r["knob"]: r
            for r in bst.telemetry()["records"]["auto_resolution"]}
    rec = recs["tpu_forest_kernel"]
    assert rec["value"] == "off"
    assert "forest_bisect" in rec["reason"]


def test_forest_knob_explicit_on_reaches_session():
    params = dict(CLASSES["binary"]["params"], tpu_forest_kernel="on")
    bst, Xq = _model(params=params)
    assert bst.inner._forest_knob() == "on"
    obs.telemetry.reset()
    sess = PredictSession(bst, buckets=(256,))   # no override: follow knob
    sess.predict(Xq)
    assert obs.telemetry.counter("serve/forest_dispatches") >= 1


def test_forest_session_override_validated():
    bst, _ = _model(**CLASSES["binary"])
    with pytest.raises(LightGBMError):
        PredictSession(bst, forest="sideways")


def test_forest_config_value_validated():
    bst_params = {"objective": "binary", "verbosity": -1,
                  "tpu_forest_kernel": "sideways"}
    rng = np.random.RandomState(0)
    X = _grid(rng, 200, 5)
    y = (X[:, 0] > 0).astype(np.float64)
    with pytest.raises(LightGBMError):
        lgb.train(bst_params, lgb.Dataset(X, label=y), num_boost_round=2)


# --------------------------------------------------------- compile budgets

def test_forest_second_same_bucket_predict_zero_compiles():
    """The serving contract extends to the forest path: once a rung is
    warm, repeat forest predicts pay ZERO tracked compiles, ZERO backend
    compiles, and ZERO table rebuilds."""
    bst, Xq = _model(**CLASSES["binary"])
    sess = PredictSession(bst, buckets=(256,), forest="on")
    sess.predict(Xq[:200])            # warm: table build + compile
    obs.telemetry.reset()
    sess.predict(Xq[:200])            # same bucket, same N
    sess.predict(Xq[:256])            # same bucket, different N
    jc = obs.telemetry.snapshot()["jit_compiles"]
    assert jc["total"] == 0, jc
    assert jc["backend_compiles"] == 0, jc
    assert obs.telemetry.counter("serve/forest_build") == 0
    assert obs.telemetry.counter("serve/forest_dispatches") == 2
