"""Online-training subsystem tests (ISSUE 8): TrafficBuffer semantics,
shadow-scoring promotion gate, atomic hot swap, multi-tenant registry
routing, admission control, graceful drain, and the closed-loop e2e demo
(concurrent predict + labeled ingestion + background promotion).

The promotion contract under test: a served batch always scores against
exactly ONE whole model version (never a half-committed swap), a
REJECTED candidate leaves the serving pack byte-identical
(``PredictSession.pack_fingerprint``), and a promotion is a single
version-token bump.
"""
import json
import os
import sys
import threading
import time
from urllib.error import HTTPError
from urllib.request import Request, urlopen

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs import telemetry  # noqa: E402
from lightgbm_tpu.online import ModelRegistry, OnlineTrainer  # noqa: E402
from lightgbm_tpu.online.buffer import TrafficBuffer  # noqa: E402
from lightgbm_tpu.serve import MicroBatcher, PredictServer, \
    PredictSession  # noqa: E402
from lightgbm_tpu.serve.batcher import QueueFullError  # noqa: E402

W = np.array([1.2, -0.8, 0.5, 0.0, 0.3, -0.4])


def _data(n, seed=0, flip=0.0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, len(W))
    y = (X @ W + 0.2 * rng.randn(n) > 0).astype(np.float64)
    if flip:
        m = rng.rand(n) < flip
        y[m] = 1.0 - y[m]
    return X, y


def _train(n=300, seed=0, rounds=6):
    X, y = _data(n, seed)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


def _post(url, obj, timeout=30):
    req = Request(url, data=json.dumps(obj).encode(),
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=30):
    with urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


# --------------------------------------------------------------- buffer

def test_buffer_bounded_drop_oldest_and_shadow_window():
    buf = TrafficBuffer(capacity_rows=100, shadow_rows=50)
    for i in range(5):
        buf.push(np.full((30, 2), i, np.float64), np.full(30, i))
    # 150 rows pushed into a 100-row buffer: the 30-row oldest chunk
    # drops once (120 rows, over), leaving 4 chunks = 120?  No: drops
    # until <= capacity with at least one chunk -> 90 rows remain.
    assert buf.rows == 90
    assert buf.dropped_rows == 60
    assert buf.total_rows == 150
    # shadow window slides independently: 50-row cap -> newest chunks
    assert buf.shadow_rows <= 60  # one chunk may straddle the cap
    Xs, ys = buf.shadow()
    assert set(np.unique(ys)) <= {3.0, 4.0}
    # draining the training buffer leaves the shadow window intact
    X, y = buf.take_training()
    assert len(y) == 90 and buf.rows == 0
    assert buf.take_training() is None
    assert buf.shadow() is not None
    # a single chunk larger than the whole buffer is kept whole
    buf.push(np.zeros((200, 2)), np.zeros(200))
    assert buf.rows == 200


def test_buffer_validates_shapes():
    buf = TrafficBuffer()
    with pytest.raises(ValueError):
        buf.push(np.zeros((3, 2)), np.zeros(4))
    with pytest.raises(ValueError):
        buf.push(np.zeros((2, 2, 2)), np.zeros(2))
    assert buf.push(np.zeros((0, 2)), np.zeros(0)) == 0
    # a 1-D row is a single-row batch
    assert buf.push(np.zeros(4), [1.0]) == 1


# ------------------------------------------------------- trainer cycle

def test_run_once_skips_below_min_rows_and_restores_buffer():
    bst = _train()
    tr = OnlineTrainer(bst, trigger_rows=1000, min_rows=64, start=False)
    X, y = _data(20, seed=3)
    tr.ingest(X, y)
    assert tr.run_once() == "skipped"
    # the drained-but-insufficient rows go back for the next cycle
    assert tr.buffer.rows == 20
    assert tr.state()["last_result"] == "skipped"


def test_refit_promotion_bumps_version_once_and_serves_new_model():
    bst = _train()
    sess = PredictSession(bst, buckets=(64,))
    Xq = _data(32, seed=9)[0]
    before = np.asarray(sess.predict(Xq))
    v0 = bst.inner.model_version
    tr = OnlineTrainer(bst, mode="refit", trigger_rows=100, min_rows=32,
                       shadow_rows=256, start=False)
    promos0 = telemetry.counter("online/promotions")
    Xn, yn = _data(200, seed=4)
    tr.ingest(Xn, yn)
    assert tr.run_once() == "promoted"
    # adopt is a SINGLE version-token bump on the served booster
    assert bst.inner.model_version == v0 + 1
    assert telemetry.counter("online/promotions") == promos0 + 1
    st = tr.state()
    assert st["promotions"] == 1 and st["can_rollback"]
    assert st["last_losses"]["candidate"] <= st["last_losses"]["current"]
    # the resident session picks the refit leaves up on its next dispatch
    after = np.asarray(sess.predict(Xq))
    assert not np.allclose(before, after)
    ref = np.asarray(bst.predict(Xq))
    np.testing.assert_allclose(after, ref, rtol=1e-5, atol=1e-6)


def test_shadow_gate_rejects_degraded_candidate_pack_identical():
    bst = _train(seed=1)
    sess = PredictSession(bst, buckets=(64,))
    sess.predict(_data(8, seed=5)[0])          # make the pack resident
    fp0 = sess.pack_fingerprint()
    v0 = bst.inner.model_version

    def degraded(X, y):
        cand = lgb.Booster(model_str=bst.model_to_string())
        for t in cand.inner.models:
            t.leaf_value[:] = 1e3              # maximally wrong leaves
        cand.inner._bump_model_version()
        return cand

    tr = OnlineTrainer(bst, trigger_rows=100, min_rows=32,
                       candidate_factory=degraded, start=False)
    rej0 = telemetry.counter("online/rejections")
    Xn, yn = _data(200, seed=6)
    tr.ingest(Xn, yn)
    assert tr.run_once() == "rejected"
    assert telemetry.counter("online/rejections") == rej0 + 1
    assert bst.inner.model_version == v0
    # the promotion contract: a rejected candidate leaves the serving
    # pack byte-identical
    assert sess.pack_fingerprint() == fp0
    st = tr.state()
    assert st["rejections"] == 1 and st["last_result"] == "rejected"
    assert st["last_losses"]["candidate"] > st["last_losses"]["current"]


def test_shadow_decay_weighted_loss_matches_manual():
    """online_shadow_decay=d weights the shadow window by recency
    (newest row weight 1, each step back x d); d=1.0 (the default) is
    bit-identical to the uniform mean it replaces."""
    from lightgbm_tpu.online.trainer import _CandidateBuilder, _EPS
    bst = _train(seed=5)
    src = bst.model_to_string()
    Xs, ys = _data(50, seed=7)
    cand = lgb.Booster(model_str=src)
    p = np.clip(np.asarray(bst.predict(Xs), np.float64), _EPS, 1.0 - _EPS)
    per_row = -(ys * np.log(p) + (1 - ys) * np.log(1 - p))

    uni = _CandidateBuilder("refit", src, {}, 1, None)
    cur_u, cand_u = uni.score_pair(cand, Xs, ys)
    assert cur_u == cand_u                     # same model on both sides
    assert cur_u == float(np.mean(per_row))    # default: exact uniform mean

    dec = _CandidateBuilder("refit", src, {}, 1, None, shadow_decay=0.9)
    cur_d, _ = dec.score_pair(cand, Xs, ys)
    w = 0.9 ** np.arange(len(ys) - 1, -1, -1, dtype=np.float64)
    np.testing.assert_allclose(cur_d, np.average(per_row, weights=w),
                               rtol=1e-12)
    assert cur_d != cur_u


def test_shadow_decay_flips_promotion_under_drift():
    """The point of the decayed window: after a concept flip, the stale
    majority of the shadow window outvotes the drifted tail under uniform
    weighting (candidate rejected) while a decayed window follows the
    live traffic (candidate promoted)."""
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    X_new, y_new = _data(200, seed=11)
    y_drift = 1.0 - y_new                      # inverted concept
    cand_src = lgb.train(params, lgb.Dataset(X_new, label=y_drift),
                         num_boost_round=6)

    def run(decay):
        bst = _train(seed=1)                   # incumbent: original concept
        cand = lgb.Booster(model_str=cand_src.model_to_string())
        tr = OnlineTrainer(bst, trigger_rows=10_000, min_rows=32,
                           shadow_rows=1024, shadow_decay=decay,
                           candidate_factory=lambda X, y: cand, start=False)
        X_old, y_old = _data(600, seed=12)     # stale majority first...
        tr.ingest(X_old, y_old)
        tr.ingest(X_new, y_drift)              # ...drifted tail newest
        return tr.run_once()

    assert run(1.0) == "rejected"
    assert run(0.95) == "promoted"


def test_shadow_decay_validated_and_surfaced():
    from lightgbm_tpu.utils.log import LightGBMError
    bst = _train(seed=6)
    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(LightGBMError):
            OnlineTrainer(bst, shadow_decay=bad, start=False)
    tr = OnlineTrainer(bst, shadow_decay=0.98, start=False)
    assert tr.state()["shadow_decay"] == 0.98


def test_promote_threshold_zero_rejects_everything():
    bst = _train(seed=2)
    tr = OnlineTrainer(bst, trigger_rows=100, min_rows=32,
                       promote_threshold=0.0, start=False)
    Xn, yn = _data(200, seed=7)
    tr.ingest(Xn, yn)
    assert tr.run_once() == "rejected"


def test_continue_mode_adds_rounds():
    bst = _train(seed=3, rounds=4)
    n0 = len(bst.inner.models)
    tr = OnlineTrainer(bst, mode="continue", continue_rounds=2,
                       trigger_rows=100, min_rows=32, start=False)
    Xn, yn = _data(256, seed=8)
    tr.ingest(Xn, yn)
    assert tr.run_once() == "promoted"
    assert len(bst.inner.models) == n0 + 2


def test_rollback_restores_previous_model():
    bst = _train(seed=4)
    # generous gate: this test is about the swap mechanics, not scoring
    tr = OnlineTrainer(bst, trigger_rows=100, min_rows=32,
                       promote_threshold=1.25, start=False)
    s_before = bst.model_to_string()
    Xn, yn = _data(200, seed=9)
    tr.ingest(Xn, yn)
    assert tr.run_once() == "promoted"
    assert bst.model_to_string() != s_before
    assert tr.rollback()
    assert bst.model_to_string() == s_before
    assert not tr.rollback()                  # token is single-use
    # the trainer's snapshot cache rewinds with the swap
    Xn2, yn2 = _data(200, seed=10)
    tr.ingest(Xn2, yn2)
    assert tr.run_once() in ("promoted", "rejected")


def test_worker_thread_triggers_on_row_count():
    bst = _train(seed=5)
    tr = OnlineTrainer(bst, trigger_rows=128, min_rows=64,
                       shadow_rows=256, start=True)
    try:
        v0 = bst.inner.model_version
        Xn, yn = _data(256, seed=11)
        tr.ingest(Xn, yn)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = tr.state()
            if st["trains"] >= 1:
                break
            time.sleep(0.05)
        st = tr.state()
        assert st["trains"] >= 1, st
        assert st["errors"] == 0, st["last_error"]
        if st["promotions"]:
            assert bst.inner.model_version > v0
    finally:
        tr.close(timeout=30)
    assert tr.state()["running"] is False


def test_atomic_swap_every_batch_is_one_whole_version():
    """Serve threads hammer the session while the main thread promotes
    repeatedly: every observed output must equal the model at one whole
    version — a torn swap matches neither side."""
    bst = _train(seed=6)
    sess = PredictSession(bst, buckets=(64,))
    Xq = np.ascontiguousarray(_data(16, seed=12)[0])
    expected = {bst.inner.model_version: np.asarray(bst.predict(Xq))}
    observed = []
    stop = threading.Event()

    def serve():
        while not stop.is_set() and len(observed) < 300:
            v0 = bst.inner.model_version
            out = np.asarray(sess.predict(Xq), np.float64)
            v1 = bst.inner.model_version
            observed.append((v0, out, v1))

    th = threading.Thread(target=serve, name="online-test-serve")
    th.start()
    try:
        tr = OnlineTrainer(bst, trigger_rows=64, min_rows=32,
                           shadow_rows=128, start=False)
        for i in range(4):
            Xn, yn = _data(96, seed=20 + i)
            tr.ingest(Xn, yn)
            tr.run_once()
            expected[bst.inner.model_version] = np.asarray(bst.predict(Xq))
    finally:
        stop.set()
        th.join(timeout=60)
    assert not th.is_alive() and observed
    assert len(expected) >= 2          # at least one promotion happened
    for v0, out, v1 in observed:
        ok = any(v in expected and np.allclose(out, expected[v],
                                               rtol=1e-5, atol=1e-6)
                 for v in range(v0, v1 + 1))
        assert ok, "batch matches no whole version in [%d, %d]" % (v0, v1)


# ---------------------------------------------------- admission control

class _SlowSession:
    """MicroBatcher-shaped fake: dispatch sleeps, predictions are row
    sums (so slicing bugs would show)."""

    buckets = (64,)

    def __init__(self, delay=0.05):
        self.delay = delay

    def dispatch(self, X):
        time.sleep(self.delay)
        return [(np.asarray(X).sum(axis=1), len(X))]

    def finalize(self, raw, raw_score=False):
        return np.asarray(raw)


def test_admission_control_shed_raises_and_counts():
    shed0 = telemetry.counter("serve/shed")
    b = MicroBatcher(_SlowSession(0.2), max_batch_rows=8, max_wait_ms=1.0,
                     max_queue_rows=8, overload="shed")
    try:
        futs = [b.submit(np.ones((8, 4)))]     # occupies the worker
        time.sleep(0.05)
        futs.append(b.submit(np.ones((8, 4)))) # fills the queue
        with pytest.raises(QueueFullError):
            for _ in range(20):
                futs.append(b.submit(np.ones((8, 4))))
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=30), 4.0)
    finally:
        b.close()
    assert telemetry.counter("serve/shed") >= shed0 + 1


def test_admission_control_block_waits_and_completes():
    b = MicroBatcher(_SlowSession(0.02), max_batch_rows=8, max_wait_ms=1.0,
                     max_queue_rows=8, overload="block")
    try:
        futs = [b.submit(np.full((4, 4), i, np.float64))
                for i in range(12)]
        for i, f in enumerate(futs):
            np.testing.assert_allclose(f.result(timeout=60), 4.0 * i)
    finally:
        b.close()


def test_oversize_single_request_admitted_when_queue_empty():
    b = MicroBatcher(_SlowSession(0.0), max_batch_rows=64, max_wait_ms=1.0,
                     max_queue_rows=8, overload="shed")
    try:
        f = b.submit(np.ones((32, 4)))         # larger than the bound
        assert len(f.result(timeout=30)) == 32
    finally:
        b.close()


# ------------------------------------------------- multi-tenant serving

def _start_server(server):
    th = threading.Thread(target=server.serve_forever,
                          name="online-test-http", daemon=True)
    th.start()
    return th


def test_multi_tenant_routing_healthz_and_ingest_409():
    clf = _train(seed=7)
    Xr = _data(200, seed=13)[0]
    reg_bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1, "min_data_in_leaf": 5},
                        lgb.Dataset(Xr, label=Xr @ W),
                        num_boost_round=4)
    registry = ModelRegistry()
    registry.register("clf", clf, buckets=(64,))
    registry.register("reg", reg_bst, buckets=(64,))
    server = PredictServer(registry=registry, port=0)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    th = _start_server(server)
    try:
        Xq = _data(5, seed=14)[0]
        code, out = _post(base + "/predict/clf", {"rows": Xq.tolist()})
        assert code == 200 and out["rows"] == 5
        np.testing.assert_allclose(out["predictions"],
                                   np.asarray(clf.predict(Xq)),
                                   rtol=1e-5, atol=1e-6)
        # routing via request body
        code, out2 = _post(base + "/predict", {"rows": Xq.tolist(),
                                               "model": "reg"})
        assert code == 200
        np.testing.assert_allclose(out2["predictions"],
                                   np.asarray(reg_bst.predict(Xq)),
                                   rtol=1e-4, atol=1e-5)
        # two models, no "default": an id is required
        with pytest.raises(HTTPError) as ei:
            _post(base + "/predict", {"rows": Xq.tolist()})
        assert ei.value.code == 404
        with pytest.raises(HTTPError) as ei:
            _post(base + "/predict/nope", {"rows": Xq.tolist()})
        assert ei.value.code == 404
        assert "nope" in json.loads(ei.value.read())["error"]
        # ingest without online training on the target model
        with pytest.raises(HTTPError) as ei:
            _post(base + "/ingest/clf", {"rows": Xq.tolist(),
                                         "labels": [1] * 5})
        assert ei.value.code == 409
        assert sorted(_get(base + "/models")["models"]) == ["clf", "reg"]
        health = _get(base + "/healthz")
        assert health["status"] == "ok"
        assert health["model_count"] == 2
        assert set(health["models"]) == {"clf", "reg"}
        for m in health["models"].values():
            assert m["model_version"] >= 1
            assert m["queue_rows"] == 0
            assert m["online"] is None
        assert health["uptime_s"] >= 0
        assert health["queue_rows"] == 0
    finally:
        server.shutdown()
        th.join(timeout=10)
        server.close()


def test_graceful_drain_503_and_queued_work_completes():
    registry = ModelRegistry()
    from lightgbm_tpu.online.registry import RegistryEntry

    class _B:                                   # booster stub for /healthz
        class inner:
            model_version = 1
    sess = _SlowSession(0.3)
    batcher = MicroBatcher(sess, max_batch_rows=8, max_wait_ms=1.0)
    registry.add_entry(RegistryEntry("default", _B(), sess, batcher))
    server = PredictServer(registry=registry, port=0)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    th = _start_server(server)
    drain0 = telemetry.counter("serve/drain_rejected")
    results = {}

    def post_slow(key):
        try:
            results[key] = _post(base + "/predict",
                                 {"rows": [[1.0] * 4] * 8})
        except HTTPError as exc:
            results[key] = (exc.code, json.loads(exc.read()))

    try:
        # A occupies the worker; B sits in the queue and holds the
        # drain window open until the worker reaches it
        t1 = threading.Thread(target=post_slow, args=("inflight",))
        t1.start()
        time.sleep(0.1)
        t2 = threading.Thread(target=post_slow, args=("queued",))
        t2.start()
        time.sleep(0.05)
        drainer = threading.Thread(target=server.begin_shutdown,
                                   name="online-test-drain")
        drainer.start()
        time.sleep(0.05)                    # drain flag is up
        post_slow("during_drain")
        t1.join(timeout=30)
        t2.join(timeout=30)
        drainer.join(timeout=30)
        assert results["during_drain"][0] == 503
        for key in ("inflight", "queued"):  # admitted work finished
            assert results[key][0] == 200, results[key]
            np.testing.assert_allclose(results[key][1]["predictions"], 4.0)
        assert telemetry.counter("serve/drain_rejected") >= drain0 + 1
        th.join(timeout=10)                 # serve_forever returned
        assert not th.is_alive()
    finally:
        server.close()


def test_e2e_concurrent_predict_ingest_promotion_zero_failures():
    """The acceptance demo: live /predict traffic + labeled /ingest with
    a background trainer; at least one promotion lands, no request ever
    fails, and /metrics exposes the online counter families."""
    bst = _train(seed=8)
    server = PredictServer(bst, port=0, buckets=(64,), max_wait_ms=1.0,
                           online=dict(trigger_rows=64, min_rows=32,
                                       shadow_rows=256))
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    th = _start_server(server)
    failures = []
    stop = threading.Event()
    Xq = _data(8, seed=15)[0]

    def predict_loop():
        while not stop.is_set():
            try:
                code, out = _post(base + "/predict", {"rows": Xq.tolist()})
                if code != 200 or len(out["predictions"]) != 8:
                    failures.append(out)
            except Exception as exc:        # noqa: BLE001 - record all
                failures.append(repr(exc))
            time.sleep(0.005)

    preds = [threading.Thread(target=predict_loop,
                              name="online-e2e-pred-%d" % i)
             for i in range(2)]
    for p in preds:
        p.start()
    try:
        deadline = time.monotonic() + 90
        seed = 30
        while time.monotonic() < deadline:
            Xn, yn = _data(48, seed=seed)
            seed += 1
            code, _ = _post(base + "/ingest", {"rows": Xn.tolist(),
                                               "labels": yn.tolist()})
            assert code == 200
            st = _get(base + "/healthz")["models"]["default"]["online"]
            if st["promotions"] >= 1:
                break
            time.sleep(0.1)
        assert st["promotions"] >= 1, st
        assert st["errors"] == 0, st["last_error"]
    finally:
        stop.set()
        for p in preds:
            p.join(timeout=30)
        server.shutdown()
        th.join(timeout=10)
        server.close()
    assert not failures, failures[:3]
    # the whole counter family is on /metrics (pre-touched at trainer
    # start so dashboards see the series even before the first cycle)
    from lightgbm_tpu import obs
    text = obs.prometheus_text()
    for name in ("lgbtpu_online_promotions_total",
                 "lgbtpu_online_rejections_total",
                 "lgbtpu_serve_shed_total",
                 "lgbtpu_serve_queue_depth_rows"):
        assert name in text, name


@pytest.mark.slow
def test_cli_sigterm_drains_dumps_and_exits_zero(tmp_path):
    """task=serve wired end to end: SIGTERM -> drain -> telemetry dump
    -> exit 0."""
    import signal
    import subprocess

    bst = _train(seed=9)
    model = tmp_path / "m.txt"
    bst.save_model(str(model))
    dump = tmp_path / "telemetry.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu.cli", "task=serve",
         "input_model=%s" % model, "serve_port=0", "verbosity=1",
         "--dump-telemetry", str(dump)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and port is None:
            line = proc.stdout.readline()
            if "http://" in line:
                port = int(line.rsplit(":", 1)[1].split()[0].strip("/"))
        assert port, "server never reported its address"
        base = "http://127.0.0.1:%d" % port
        code, out = _post(base + "/predict",
                          {"rows": _data(3, seed=16)[0].tolist()})
        assert code == 200 and out["rows"] == 3
        assert _get(base + "/healthz")["status"] == "ok"
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    snap = json.loads(dump.read_text())
    assert snap["counters"].get("serve/requests", 0) >= 1
    assert snap["counters"].get("serve/drain_begin", 0) >= 1
