"""Test harness: run on a virtual 8-device CPU mesh.

The reference tests multi-node behavior with in-process Dask workers
(reference: tests/python_package_test/test_dask.py:26). Here the analog is
8 virtual CPU devices via XLA host-platform device count; distributed tests
build a jax.sharding.Mesh over them.
"""
import os

# Hard-force the CPU host platform: the axon sitecustomize registers the TPU
# backend regardless of JAX_PLATFORMS unless its trigger env var is absent.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
# persistent compile cache: the jitted tree builder dominates test wall-clock
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lgb_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
