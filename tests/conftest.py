"""Test harness: run on a virtual 8-device CPU mesh.

The reference tests multi-node behavior with in-process Dask workers
(reference: tests/python_package_test/test_dask.py:26). Here the analog is
8 virtual CPU devices via XLA host-platform device count; distributed tests
build a jax.sharding.Mesh over them.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
