"""Test harness: run on a virtual 8-device CPU mesh.

The reference tests multi-node behavior with in-process Dask workers
(reference: tests/python_package_test/test_dask.py:26). Here the analog is
8 virtual CPU devices via XLA host-platform device count; distributed tests
build a jax.sharding.Mesh over them.

Caveat: the axon sitecustomize registers its TPU backend at interpreter
start (before conftest runs), so on an axon-attached terminal the env
settings below do NOT take effect and the suite runs on the real device;
tests that genuinely need the 8-device mesh use the ``cpu_mesh_devices``
fixture (skipped on non-mesh backends) and are additionally driven through
a clean-environment subprocess by tests/test_parallel.py's launcher.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
# persistent compile cache: the jitted tree builder dominates test wall-clock
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lgb_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
# jaxlib 0.4.37's CPU backend intermittently segfaults/aborts when
# DESERIALIZING tiny cached executables (trivial jit_add/broadcast-class
# programs; reproducible ~1-in-2 once such entries exist). Only the big
# block programs (>~140 KB serialized) are worth caching anyway, so gate
# writes on entry size — and sweep undersized entries that earlier runs
# already wrote, or every later suite run rolls the same dice.
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "65536")


def _sweep_small_cache_entries() -> None:
    d = os.environ["JAX_COMPILATION_CACHE_DIR"]
    floor = int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"])
    if not os.path.isdir(d):
        return
    for name in os.listdir(d):
        if not name.endswith("-cache"):
            continue
        path = os.path.join(d, name)
        try:
            if os.path.getsize(path) < floor:
                os.unlink(path)
                atime = os.path.join(d, name[:-len("-cache")] + "-atime")
                if os.path.exists(atime):
                    os.unlink(atime)
        except OSError:
            pass  # concurrent suite run; the survivor sweeps next time


_sweep_small_cache_entries()

import jax  # noqa: E402

# The axon sitecustomize registers its TPU plugin at interpreter start and
# the JAX_PLATFORMS env var does NOT override it — but the config API does
# (the backend initializes lazily at first use). LIGHTGBM_TPU_TEST_CPU=1
# forces the suite onto the local CPU mesh; it is OFF by default because
# on this 1-core host local execution measured SLOWER than the tunnel
# (35-45 min vs ~25) — on any multi-core host, set it.
if os.environ.get("LIGHTGBM_TPU_TEST_CPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    """The 8 virtual CPU devices; skips when the env forcing could not take
    effect (axon terminals — see module docstring)."""
    import jax

    devs = jax.devices()
    if jax.default_backend() != "cpu" or len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh (JAX_PLATFORMS=cpu + "
                    "xla_force_host_platform_device_count=8)")
    return devs


def clean_cpu_env(n_devices: int = 8) -> dict:
    """Environment for subprocesses that must run on the virtual CPU mesh
    even under an axon terminal (whose sitecustomize grabs the backend at
    interpreter start)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={n_devices}")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return env
