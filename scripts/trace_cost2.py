"""Split per-train-call fixed cost into trace / lower / compile / run."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import lightgbm_tpu as lgb
from lightgbm_tpu.fused import FusedTrainer
from bench import make_higgs_like

N = int(os.environ.get("PROF_N", 2_000_000))
X, y = make_higgs_like(N)
params = {
    "objective": "binary", "num_leaves": 255, "max_bin": 255,
    "learning_rate": 0.1, "verbosity": -1, "tpu_iter_block": 20,
}
ds = lgb.Dataset(X, label=y)
ds.construct()

bst = lgb.train(dict(params), ds, num_boost_round=1)  # warm small pieces

from lightgbm_tpu.basic import Booster
with obs.wall("trace_cost2/init", record=False) as w:
    b2 = Booster(params=dict(params), train_set=ds)
print(f"Booster init: {w.seconds:.1f}s")
g = b2.inner
ft = FusedTrainer(g)
with obs.wall("trace_cost2/block_fn", record=False) as w:
    fn = ft._block_fn(20)
print(f"_block_fn build (no trace): {w.seconds:.1f}s")
args = (g.train_score.score, jnp.asarray(g._cegb_used), g._key, jnp.int32(0))
with obs.wall("trace_cost2/trace", record=False) as w:
    lowered = fn.trace(*args)
print(f"jit trace: {w.seconds:.1f}s")
with obs.wall("trace_cost2/lower", record=False) as w:
    low = lowered.lower()
print(f"lower: {w.seconds:.1f}s")
with obs.wall("trace_cost2/compile", record=False) as w:
    comp = low.compile()
print(f"compile (persistent cache): {w.seconds:.1f}s")
with obs.wall("trace_cost2/run", record=False) as w:
    out = comp(*args)
    obs.sync(out)
print(f"run block of 20: {w.seconds:.1f}s")
with obs.wall("trace_cost2/run2", record=False) as w:
    out = comp(*args)
    obs.sync(out)
print(f"run block of 20 (2nd): {w.seconds:.1f}s")
