"""Split per-train-call fixed cost into trace / lower / compile / run."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import lightgbm_tpu as lgb
from lightgbm_tpu.fused import FusedTrainer
from bench import make_higgs_like

N = int(os.environ.get("PROF_N", 2_000_000))
X, y = make_higgs_like(N)
params = {
    "objective": "binary", "num_leaves": 255, "max_bin": 255,
    "learning_rate": 0.1, "verbosity": -1, "tpu_iter_block": 20,
}
ds = lgb.Dataset(X, label=y)
ds.construct()

bst = lgb.train(dict(params), ds, num_boost_round=1)  # warm small pieces

from lightgbm_tpu.basic import Booster
t0 = time.time()
b2 = Booster(params=dict(params), train_set=ds)
print(f"Booster init: {time.time()-t0:.1f}s")
g = b2.inner
ft = FusedTrainer(g)
t0 = time.time()
fn = ft._block_fn(20)
print(f"_block_fn build (no trace): {time.time()-t0:.1f}s")
args = (g.train_score.score, jnp.asarray(g._cegb_used), g._key, jnp.int32(0))
t0 = time.time()
lowered = fn.trace(*args)
print(f"jit trace: {time.time()-t0:.1f}s")
t0 = time.time()
low = lowered.lower()
print(f"lower: {time.time()-t0:.1f}s")
t0 = time.time()
comp = low.compile()
print(f"compile (persistent cache): {time.time()-t0:.1f}s")
t0 = time.time()
out = comp(*args)
jax.block_until_ready(out)
print(f"run block of 20: {time.time()-t0:.1f}s")
t0 = time.time()
out = comp(*args)
jax.block_until_ready(out)
print(f"run block of 20 (2nd): {time.time()-t0:.1f}s")
