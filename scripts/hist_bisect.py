"""Device-profiled bisect of the pallas hist kernel's per-chunk cost.

The hardware harness behind the ``tpu_hist_kernel`` (pallas vs xla
segment histograms) and ``tpu_hist_chunk`` (rows per segment-histogram
launch) auto knobs: their learner defaults are the chunk/kernel points
this bisect measured on v5e.
"""
import collections
import glob
import gzip
import json
import os
import shutil
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

from lightgbm_tpu.ops.partition import pack_rows, work_spec

N = 2_000_000
F = 28
B = 255
CH = 4096
LO_W = 4
SH = (B + LO_W - 1) // LO_W
NCH = 5
REPS = int(os.environ.get("HREPS", 10))

rng = np.random.RandomState(0)
bins = rng.randint(0, B, size=(N, F)).astype(np.uint8)
ghc = rng.randn(N, 3).astype(np.float32)
guard, width = work_spec(F, False, "pallas", 1024, 4096)
pad = ((guard, guard), (0, 0))
w0 = pack_rows(jnp.pad(jnp.asarray(bins), pad), jnp.pad(jnp.asarray(ghc), pad))
w0 = jnp.pad(w0, ((0, 0), (0, width - w0.shape[1])))
work = jnp.stack([w0, jnp.zeros_like(w0)])


def make_kernel(variant):
    f32 = jnp.float32
    i32 = jnp.int32

    def kern(sref, work_in, acc_ref, cin, sem):
        plane = sref[0]
        start = sref[1]
        cnt = sref[2]
        astart = (start // 32) * 32
        head = start - astart
        tot = head + cnt
        nchunks = jnp.maximum((tot + CH - 1) // CH, 1)
        acc_ref[...] = jnp.zeros((F * SH, LO_W * NCH), f32)

        def start_in(i, slot):
            pltpu.make_async_copy(
                work_in.at[plane, pl.ds(astart + i * CH, CH), :],
                cin.at[slot], sem.at[slot]).start()

        start_in(0, 0)
        sub_i = jax.lax.broadcasted_iota(i32, (CH, 1), 0)
        iota_sh = jax.lax.broadcasted_iota(i32, (CH, SH), 1)
        jl = jax.lax.broadcasted_iota(i32, (CH, LO_W * NCH), 1) // NCH

        def word(gb, o):
            return jax.lax.bitcast_convert_type(
                gb[:, o:o + 1] + gb[:, o + 1:o + 2] * 256
                + gb[:, o + 2:o + 3] * 65536
                + gb[:, o + 3:o + 4] * 16777216, f32)

        def body(i, carry):
            slot = jax.lax.rem(i, 2)
            pltpu.make_async_copy(
                work_in.at[plane, pl.ds(astart + i * CH, CH), :],
                cin.at[slot], sem.at[slot]).wait()

            @pl.when(i + 1 < nchunks)
            def _():
                start_in(i + 1, 1 - slot)

            cw = cin[slot].astype(i32)
            bi = cw[:, :F]
            hi = bi // LO_W
            lo = bi - hi * LO_W
            gb = cw[:, F:F + 12]
            pos = sub_i + i * CH
            valid = ((pos >= head) & (pos < tot)).astype(f32)
            g = word(gb, 0) * valid
            h = word(gb, 4) * valid
            c = word(gb, 8) * valid
            g_hi = g.astype(jnp.bfloat16)
            g_lo = (g - g_hi.astype(f32)).astype(jnp.bfloat16)
            h_hi = h.astype(jnp.bfloat16)
            h_lo = (h - h_hi.astype(f32)).astype(jnp.bfloat16)
            chs = jnp.concatenate(
                [g_hi, g_lo, h_hi, h_lo, c.astype(jnp.bfloat16)], axis=1)
            tiled = jnp.concatenate([chs] * LO_W, axis=1)

            if variant == "preamble":
                acc_ref[0:8, 0:1] += jnp.sum(tiled[:, 0:1], axis=0,
                                             keepdims=True) \
                    + jnp.sum(hi[:, 0:1] + lo[:, 0:1], axis=0, keepdims=True) \
                    .astype(f32)
                return carry
            for f in range(F):
                hioh = (hi[:, f:f + 1] == iota_sh).astype(jnp.bfloat16)
                logf = jnp.where(lo[:, f:f + 1] == jl, tiled, jnp.bfloat16(0))
                if variant == "onehots":
                    acc_ref[0:8, 0:1] += (
                        jnp.sum(hioh[:, 0:1].astype(f32), axis=0,
                                keepdims=True)
                        + jnp.sum(logf[:, 0:1].astype(f32), axis=0,
                                  keepdims=True))
                    continue
                ps = jax.lax.dot_general(
                    hioh, logf, (((0,), (0,)), ((), ())),
                    preferred_element_type=f32)
                if variant == "dots":
                    acc_ref[0:8, 0:1] += ps[0:8, 0:1]
                else:
                    acc_ref[f * SH:(f + 1) * SH, :] += ps
            return carry

        jax.lax.fori_loop(0, nchunks, body, 0)

    return kern


def profile(variant):
    kern = make_kernel(variant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        scratch_shapes=[pltpu.VMEM((2, CH, width), jnp.uint8),
                        pltpu.SemaphoreType.DMA((2,))],
    )

    @jax.jit
    def chain(work):
        def body(i, acc):
            a, = pl.pallas_call(
                kern, name="hist_bisect", grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct((F * SH, LO_W * NCH),
                                                jnp.float32)],
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("arbitrary",),
                    vmem_limit_bytes=100 * 1024 * 1024),
            )(jnp.stack([jnp.int32(0), jnp.int32(guard), jnp.int32(N)]), work)
            return acc + a[0, 0] + i.astype(jnp.float32)
        return jax.lax.fori_loop(0, REPS, body, jnp.float32(0))

    jax.block_until_ready(chain(work))
    tdir = "/tmp/jaxtrace_hb"
    shutil.rmtree(tdir, ignore_errors=True)
    with jax.profiler.trace(tdir):
        jax.block_until_ready(chain(work))
    path = sorted(glob.glob(tdir + "/plugins/profile/*/*.trace.json.gz"))[-1]
    data = json.load(gzip.open(path, "rt"))
    events = data["traceEvents"]
    pids = {e["pid"]: e["args"].get("name", "") for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tot = collections.Counter()
    cnt = collections.Counter()
    for e in events:
        if e.get("ph") != "X":
            continue
        if "TPU" not in pids.get(e["pid"], ""):
            continue
        tot[e["name"]] += e.get("dur", 0)
        cnt[e["name"]] += 1
    best = max(((d, n) for n, d in tot.items() if "call" in n),
               default=(0, "?"))
    per_chunk = best[0] / REPS / ((N + CH - 1) // CH)
    print("%-10s kernel: %8.1f us/call  %6.2f us/chunk  %5.2f ns/row"
          % (variant, best[0] / REPS, per_chunk, best[0] / REPS / N * 1e3))


for v in (sys.argv[1:] or ["full", "preamble", "onehots", "dots"]):
    profile(v)
