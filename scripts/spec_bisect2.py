"""Isolate the 375us: start from the known-fast signature, add one diff at
a time."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from lightgbm_tpu import obs
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

REPS = 254
N = 1 << 20
W = 128
work = jnp.zeros((2, N, W), jnp.uint8)
table = jnp.zeros((1, 255), jnp.float32)


def bench(name, with_table, four_scalars, write_out2, use_dma):
    def kern(*refs):
        if with_table:
            sref, w_in, tref, w_ref, o_ref, sem = refs
        else:
            sref, w_in, w_ref, o_ref, sem = refs
        if write_out2:
            o_ref[...] = jnp.zeros((256, W), jnp.uint8)
        if use_dma:
            cp = pltpu.make_async_copy(w_in.at[0, pl.ds(0, 256), :],
                                       o_ref.at[...], sem)
            cp.start()
            cp.wait()

    in_specs = [pl.BlockSpec(memory_space=pltpu.HBM)]
    if with_table:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )

    @jax.jit
    def chain(work, cnt):
        def body(i, carry):
            work, acc = carry
            if four_scalars:
                scalars = jnp.stack([jax.lax.rem(i, 2), jnp.int32(1024),
                                     cnt, jax.lax.rem(i, 28)])
            else:
                scalars = jnp.stack([i.astype(jnp.int32)])
            args = (scalars, work, table) if with_table else (scalars, work)
            w2, o = pl.pallas_call(
                kern, name="spec_bisect2", grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                           jax.ShapeDtypeStruct((256, W), jnp.uint8)],
                input_output_aliases={1: 0},
            )(*args)
            return w2, acc + jnp.sum(o.astype(jnp.int32))
        return jax.lax.fori_loop(0, REPS, body, (work, jnp.int32(0)))

    obs.sync(chain(work, jnp.int32(256)))
    best = 1e9
    for _ in range(2):
        with obs.wall("spec_bisect2/stage", record=False) as w:
            obs.sync(chain(work, jnp.int32(256)))
        best = min(best, w.seconds)
    print("%-48s %7.1f us/call" % (name, best / REPS * 1e6))


bench("fast baseline (dma copy, 1 scalar)", False, False, False, True)
bench("+ 4 scalars", False, True, False, True)
bench("+ table input", True, True, False, True)
bench("no dma, no write", False, False, False, False)
bench("no dma, write out2", False, False, True, False)
