"""Correctness + speed of the fused Pallas partition kernel vs the XLA path."""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu.ops.partition import (pack_rows, partition_segment,
                                        partition_segment_fused, unpack_ghc)

CH = 2048


def check(n, start_off, cnt, seed=0):
    rng = np.random.RandomState(seed)
    F, B = 28, 256
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)), jnp.uint8)
    ghc = jnp.asarray(rng.randn(n, 3).astype(np.float32))
    guard = CH + 64
    work0 = pack_rows(jnp.pad(bins, ((guard, guard), (0, 0))),
                      jnp.pad(ghc, ((guard, guard), (0, 0))))
    work = jnp.stack([work0, jnp.zeros_like(work0)])
    work128 = jnp.pad(work, ((0, 0), (0, 0), (0, 128 - work.shape[2])))
    table = jnp.asarray(rng.rand(B) < 0.4)
    feat = jnp.int32(rng.randint(F))
    start = jnp.int32(guard + start_off)
    cntj = jnp.int32(cnt)

    w_ref, lt_ref = jax.jit(partial(partition_segment, ch=CH))(
        work, jnp.int32(0), start, cntj, feat, table)
    w_pal, lt_pal = jax.jit(partial(partition_segment_fused, ch=CH))(
        work128, jnp.int32(0), start, cntj, feat, table)
    lt_ref, lt_pal = int(lt_ref), int(lt_pal)
    assert lt_ref == lt_pal, (lt_ref, lt_pal)
    # left segments must match exactly (stable); right segments are
    # chunk-reversed in both, so compare as row SETS via sorted bytes
    a = np.asarray(w_ref[1])[guard + start_off: guard + start_off + cnt]
    b = np.asarray(w_pal[1])[guard + start_off: guard + start_off + cnt, :w_ref.shape[2]]
    np.testing.assert_array_equal(a[:lt_ref], b[:lt_ref])
    ra = a[lt_ref:]
    rb = b[lt_ref:]
    order_a = np.lexsort(ra.T)
    order_b = np.lexsort(rb.T)
    np.testing.assert_array_equal(ra[order_a], rb[order_b])
    print(f"ok n={n} cnt={cnt} lt={lt_ref}")


# trusted wall per PERF.md discipline: warm once, then time one call
# ended by a forced 1-element transfer (obs.timed_sync)
timed = obs.timed_sync


def chain(make, K=4):
    f1, fK = make(1), make(K)
    t1 = min(timed(f1), timed(f1)); tK = min(timed(fK), timed(fK))
    return (tK - t1) / (K - 1)


def bench(n):
    rng = np.random.RandomState(0)
    F, B = 28, 256
    bins = jnp.asarray(rng.randint(0, B, size=(n, F)), jnp.uint8)
    ghc = jnp.asarray(rng.randn(n, 3).astype(np.float32))
    guard = CH + 64
    work0 = pack_rows(jnp.pad(bins, ((guard, guard), (0, 0))),
                      jnp.pad(ghc, ((guard, guard), (0, 0))))
    work = jnp.stack([work0, jnp.zeros_like(work0)])
    work128 = jnp.pad(work, ((0, 0), (0, 0), (0, 128 - work.shape[2])))
    table = jnp.asarray(rng.rand(B) < 0.5)

    for name, fn, wk in (("xla", partition_segment, work),
                         ("pallas", partition_segment_fused, work128)):
        def make(k, fn=fn, work=wk):
            @jax.jit
            def f(work):
                def body(carry, _):
                    w, c = carry
                    w2, lt = fn(w, c % 2, jnp.int32(guard), jnp.int32(n),
                                jnp.int32(3), table, ch=CH)
                    return (w2, 1 - c), None
                (w, _), _ = jax.lax.scan(body, (work, jnp.int32(0)), None, length=k)
                return w[0, 0, 0]
            return lambda: f(work)
        per = chain(make, K=4)
        nch = (n + CH - 1) // CH
        print(f"{name} n={n}: {per*1e3:.2f} ms ({n/per/1e6:.0f} M rows/s, "
              f"{per/nch*1e6:.1f} us/chunk)")


if __name__ == "__main__":
    check(10000, 0, 10000)
    check(10000, 1000, 3000, seed=1)
    check(5000, 100, 1, seed=2)
    check(300000, 7, 299000, seed=3)
    bench(2_000_000)
